"""Fused Lloyd accumulate: distance + argmin + cluster sums in one kernel.

The XLA path (ops/kmeans_ops._accumulate) materializes the (n, k) distance
matrix and an (n, k) one-hot in HBM each iteration — 2*n*k*4 bytes of
traffic on top of reading X.  This kernel streams X once per iteration:
for each row block, it computes the (bn, k) distances in VMEM, reduces
min/argmin on the VPU, forms the block one-hot in VMEM, and accumulates
``one_hot.T @ x`` into the (k, d) sums output, exploiting the TPU grid's
sequential execution for safe read-modify-write accumulation (the pallas
accumulate pattern).  HBM traffic per iteration drops from
O(n*d + 2*n*k) to O(n*d + k*d).

Precision tiers (``mode``) — shared vocabulary in ops/pallas/_tiers.py
(Mosaic only lowers Precision.HIGHEST/DEFAULT, so split tiers are
implemented by hand with bf16 hi/lo splits):

- ``highest``: both matmuls f32 Precision.HIGHEST.  Parity default.
- ``high``: distance cross-term single-pass bf16 (the tier contract —
  kmeans_ops._assign_prec — runs the assignment matmul at bf16: argmin is
  decision-only); cluster sums via an *exact-split* trick: the unweighted
  one-hot is 0/1 — exactly representable in bf16 — so ``one_hot.T @
  (w*x)`` with (w*x) split into bf16 hi+lo needs only TWO bf16 passes
  and is accurate to ~f32, meeting the XLA "high" tier's error envelope.
- ``default``: bf16 assignment + SINGLE-pass bf16 sums — the XLA default
  tier's ~1e-3 error envelope at its speed.

Caller contract (see ``lloyd_accumulate_pallas``): rows padded to the block
size with weight 0; k and d padded to lane multiples (128) by the wrapper —
dummy centers get +inf-like coordinates so no row ever selects them.  The
single-shot path pads INSIDE one jitted program (pad + kernel + slice),
so progcache sees one program per input signature instead of a spray of
eager padding dispatches per call (ISSUE 9 satellite).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas import _dbuf
from oap_mllib_tpu.ops.pallas._tiers import (
    LANE,
    check_mode,
    dot_bf16,
    dot_f32,
    kernel_launch,
    pad_to,
    split_bf16,
)
from oap_mllib_tpu.utils import progcache

_BLOCK_ROWS = 512


def _cross_term(x, c, mode):
    """x @ c.T (bn, k) at the requested precision tier.

    "high" and "default" share the single-pass bf16 path: the tier
    definition (kmeans_ops._assign_prec) runs the ASSIGNMENT matmul at
    bf16 for both — argmin is a discrete decision, and the tiers differ
    only in the cluster-sums accuracy (which this kernel's exact-split
    sums exceed in both modes)."""
    dn = (((1,), (1,)), ((), ()))
    if mode == "highest":
        return dot_f32(x, c, dn)
    # high/default: single-pass bf16 — argmin only flips on near-ties
    return dot_bf16(x.astype(jnp.bfloat16), c.astype(jnp.bfloat16), dn)


def _cluster_sums(one_hot01, wx, mode):
    """one_hot.T @ (w*x) (k, d).  one_hot is exactly 0/1 in bf16, so the
    split tiers lose nothing on it; "high" hi/lo-splits wx for ~f32
    accuracy (2 bf16 passes); "default" is single-pass all-bf16 — the
    same error envelope as the XLA default tier (~1e-3)."""
    dn = (((0,), (0,)), ((), ()))
    if mode == "highest":
        return dot_f32(one_hot01, wx, dn)
    oh = one_hot01.astype(jnp.bfloat16)  # exact
    if mode == "default":
        return dot_bf16(oh, wx.astype(jnp.bfloat16), dn)
    wx_hi, wx_lo = split_bf16(wx)
    return dot_bf16(oh, wx_hi, dn) + dot_bf16(oh, wx_lo, dn)


def _tile_update(x, w, c, mode, need_cost):
    """One resident tile's full fused update: assignment + moment
    accumulation with the one-hot/centered intermediates living and
    dying in VMEM (never HBM).  Shared by the grid kernel, the
    double-buffered walk kernel, and the schedule-identical XLA
    fallback, so the three cannot drift a bit.  Returns
    ``(sums_inc (k, d), counts_inc (1, k), cost_inc | None)``."""
    k = c.shape[0]
    c_sq = jnp.sum(c * c, axis=1)[None, :]  # (1, k)
    cross = _cross_term(x, c, mode)  # (bn, k)  <- MXU

    if need_cost:
        # squared distances via the matmul identity (MXU)
        x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # (bn, 1)
        d2 = jnp.maximum(x_sq + c_sq - 2.0 * cross, 0.0)
        assign = jnp.argmin(d2, axis=1)  # (bn,)
        min_d2 = jnp.min(d2, axis=1, keepdims=True)  # (bn, 1)
    else:
        # loop mode: argmin is invariant to the per-row |x|^2 term, so
        # rank on the half-score x.c - c_sq/2 (argMAX) — no d2 assembly,
        # no maximum, no min pass (cost is dead inside the Lloyd loop:
        # the caller recomputes it at "highest" after convergence).
        # NB keep the (bn, k) term on the LEFT of the subtract: with the
        # broadcast (1, k) operand first, Mosaic's lowering allocates a
        # ~32 MB scoped-vmem temp and fails to compile (argmax of
        # cross - c_sq/2 selects the same center, same first-index
        # tie-break as argmin of the negation)
        assign = jnp.argmax(cross - 0.5 * c_sq, axis=1)  # (bn,)

    # unweighted 0/1 one-hot (VPU compare against 2-D iota); weights fold
    # into w*x so the one-hot stays exactly representable in bf16
    col_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1)
    one_hot = jnp.where(col_ids == assign[:, None], 1.0, 0.0)  # (bn, k)

    sums_inc = _cluster_sums(one_hot, w * x, mode)
    if mode == "highest":
        # strict-parity tier: exact f32 VPU reduction
        counts_inc = jnp.sum(one_hot * w, axis=0, keepdims=True)
    else:
        # fast tiers: counts as (1, bn) @ (bn, k) bf16 matmuls with
        # f32 accumulation — the one-hot is exact 0/1 and w rides a
        # hi/lo split, so counts stay ~f32-exact for ANY weights
        # while the two VPU passes over (bn, k) disappear (measured
        # -1.1 ms/iter at 1M x 256 k=1000).  NB bf16 single-pass at
        # this shape compiles where the f32-HIGHEST variant blew
        # Mosaic's scoped vmem (see the assignment note above).
        oh = one_hot.astype(jnp.bfloat16)
        w_hi, w_lo = split_bf16(w)
        dn = (((1,), (0,)), ((), ()))
        counts_inc = dot_bf16(w_hi.T, oh, dn) + dot_bf16(w_lo.T, oh, dn)
    cost_inc = jnp.sum(min_d2 * w) if need_cost else None
    return sums_inc, counts_inc, cost_inc


def _make_kernel(mode, need_cost=True):
    def _kernel(x_ref, w_ref, c_ref, sums_ref, counts_ref, cost_ref):
        """One grid step: process a (bn, d) row block against all k centers."""
        # zero accumulators on the first block (sequential TPU grid)
        @pl.when(pl.program_id(0) == 0)
        def _init():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)
            cost_ref[0, 0] = jnp.float32(0.0)

        sums_inc, counts_inc, cost_inc = _tile_update(
            x_ref[:], w_ref[:], c_ref[:], mode, need_cost
        )
        sums_ref[:] += sums_inc
        counts_ref[:] += counts_inc
        if need_cost:
            cost_ref[0, 0] += cost_inc

    return _kernel


def _pallas_accumulate(x, w, centers, mode="highest", interpret=False,
                       need_cost=True, block_rows=_BLOCK_ROWS):
    """Raw pallas_call on pre-padded operands (traced inside the jitted
    wrappers below — no jit of its own)."""
    n, d = x.shape
    k = centers.shape[0]
    grid = (n // block_rows,)
    sums, counts, cost = pl.pallas_call(
        _make_kernel(mode, need_cost),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, centers)
    return sums, counts, cost


# -- double-buffered walk (explicit DMA overlap; ROADMAP item 4) -------------


def _make_dbuf_kernel(mode, need_cost, tile_rows, depth, num_tiles):
    def _kernel(x_hbm, w_hbm, c_ref, sums_ref, counts_ref, cost_ref,
                xbuf, wbuf, xsem, wsem):
        """Single-invocation walk: x/w stay in HBM, each (tile_rows, d)
        tile streams into the rotation buffer while the previous tile's
        fused update runs — the accumulators are VMEM-resident for the
        whole walk."""
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        cost_ref[0, 0] = jnp.float32(0.0)
        c = c_ref[:]

        def body(t, views):
            x, w = views
            sums_inc, counts_inc, cost_inc = _tile_update(
                x, w, c, mode, need_cost
            )
            sums_ref[:] += sums_inc
            counts_ref[:] += counts_inc
            if need_cost:
                cost_ref[0, 0] += cost_inc

        _dbuf.tile_walk(
            [x_hbm, w_hbm], [xbuf, wbuf], [xsem, wsem],
            tile_rows, num_tiles, depth, body,
        )

    return _kernel


def _pallas_accumulate_dbuf(x, w, centers, mode, interpret, need_cost,
                            tile_rows, depth):
    """Raw double-buffered pallas_call on pre-padded operands (rows a
    multiple of ``tile_rows``)."""
    n, d = x.shape
    k = centers.shape[0]
    num_tiles = n // tile_rows
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            has_side_effects=True
        )
    sums, counts, cost = pl.pallas_call(
        _make_dbuf_kernel(mode, need_cost, tile_rows, depth, num_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=_dbuf.rotation_scratch(
            depth, [(tile_rows, d), (tile_rows, 1)]
        ),
        interpret=interpret,
        **kwargs,
    )(x, w, centers)
    return sums, counts, cost


def _xla_walk(x_p, w_p, c_p, mode, need_cost, tile_rows):
    """Schedule-identical XLA fallback for the double-buffered walk: a
    ``lax.scan`` over the SAME (tile_rows, d) tiles in the SAME order
    through the SAME ``_tile_update``, so the CPU tier-1 suite exercises
    the exact program structure (and bits) the DMA kernel produces."""
    n, d = x_p.shape
    k = c_p.shape[0]
    num_tiles = n // tile_rows
    xt = x_p.reshape(num_tiles, tile_rows, d)
    wt = w_p.reshape(num_tiles, tile_rows, 1)

    def step(carry, tile):
        sums, counts, cost = carry
        xi, wi = tile
        sums_inc, counts_inc, cost_inc = _tile_update(
            xi, wi, c_p, mode, need_cost
        )
        cost = cost + cost_inc if need_cost else cost
        return (sums + sums_inc, counts + counts_inc, cost), None

    init = (
        jnp.zeros((k, d), jnp.float32),
        jnp.zeros((1, k), jnp.float32),
        jnp.float32(0.0),
    )
    (sums, counts, cost), _ = jax.lax.scan(step, init, (xt, wt))
    return sums, counts, cost.reshape(1, 1)


def _accumulate_walk_any(x_p, w_p, c_p, mode, interpret, need_cost,
                         tile_rows, depth):
    """Backend dispatch for the walk on pre-padded operands: the DMA
    kernel on TPU (or under interpret), the schedule-identical XLA scan
    elsewhere."""
    if interpret or jax.default_backend() == "tpu":
        return _pallas_accumulate_dbuf(
            x_p, w_p, c_p, mode, interpret, need_cost, tile_rows, depth
        )
    return _xla_walk(x_p, w_p, c_p, mode, need_cost, tile_rows)


@functools.partial(
    jax.jit,
    static_argnames=("mode", "interpret", "need_cost", "tile_rows", "depth"),
)
def _walk_jit(x, weights, centers, mode, interpret, need_cost, tile_rows,
              depth):
    k, d = centers.shape[0], x.shape[1]
    x_p, w_p, c_p = _pad_operands_traced(
        x, weights, centers, block_rows=tile_rows
    )
    sums, counts, cost = _accumulate_walk_any(
        x_p, w_p, c_p, mode, interpret, need_cost, tile_rows, depth
    )
    return sums[:k, :d], counts[0, :k], cost[0, 0]


def lloyd_accumulate_walk(
    x: jax.Array,
    weights: jax.Array,
    centers: jax.Array,
    mode: str = "highest",
    interpret: bool = False,
    tile_rows: int = _BLOCK_ROWS,
    depth: int = 2,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Double-buffered fused accumulate: same contract (and bits) as
    :func:`lloyd_accumulate_pallas`, with explicit DMA/compute overlap
    and tunable geometry (ops/pallas/autotune.py)."""
    mode = check_mode(mode)
    _dbuf.check_depth(depth)
    progcache.note(
        "kmeans.pallas_walk",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, weights, centers), mode, interpret,
         tile_rows, depth),
    )
    with kernel_launch("kmeans.accumulate_walk"):
        return _walk_jit(
            x, weights, centers, mode, interpret, True, int(tile_rows),
            int(depth),
        )


@functools.partial(jax.jit, static_argnames=("mode", "interpret", "need_cost"))
def _call(x, w, centers, mode="highest", interpret=False, need_cost=True):
    return _pallas_accumulate(x, w, centers, mode, interpret, need_cost)


def _pad_operands_traced(x, weights, centers, block_rows=_BLOCK_ROWS):
    """Padding math shared by the jitted wrappers (traced, never eager):
    rows to the row-block multiple, k and d to lane multiples.  Dummy
    centers sit at 1e15 so no real row selects them; dummy feature
    columns of real centers are 0 (matching padded x columns)."""
    n, d = x.shape
    k = centers.shape[0]
    n_pad = pad_to(max(n, block_rows), block_rows)
    d_pad = pad_to(d, LANE)
    k_pad = pad_to(k, LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(x.astype(jnp.float32))
    w_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(weights.astype(jnp.float32))
    c_p = jnp.full((k_pad, d_pad), 1e15, jnp.float32).at[:k, :d].set(
        centers.astype(jnp.float32)
    )
    c_p = c_p.at[:k, d:].set(0.0)
    return x_p, w_p, c_p


def _pad_operands(x, weights, centers, block_rows=_BLOCK_ROWS):
    """One compiled program per shape signature for the loop entry's pad
    step — previously ~6 eager dispatches per call.  Built through the
    program-cache registry (R1: jit lives in a get_or_build builder)."""
    fn = progcache.get_or_build(
        "kmeans.pallas_pad", (block_rows,),
        lambda: jax.jit(
            functools.partial(_pad_operands_traced, block_rows=block_rows)
        ),
    )
    return fn(x, weights, centers)


def _accum_any(x_p, w_p, centers, mode, interpret, need_cost, tile_rows,
               depth):
    """Kernel-variant dispatch on pre-padded operands: the grid-pipelined
    kernel at depth < 2, the explicit double-buffered walk (DMA kernel on
    TPU/interpret, schedule-identical XLA scan elsewhere) at depth >= 2.
    All variants share ``_tile_update``, so this choice never moves a
    result bit — only the overlap."""
    if depth >= 2:
        return _accumulate_walk_any(
            x_p, w_p, centers, mode, interpret, need_cost, tile_rows, depth
        )
    return _pallas_accumulate(
        x_p, w_p, centers, mode, interpret, need_cost, tile_rows
    )


@functools.partial(
    jax.jit,
    static_argnames=("mode", "interpret", "need_cost", "tile_rows", "depth"),
)
def _accumulate_jit(x, weights, centers, mode, interpret, need_cost,
                    tile_rows=_BLOCK_ROWS, depth=0):
    """Single-shot fused accumulate: pad + kernel + slice in ONE jitted
    program.  The old path ran ``_pad_operands`` eagerly before a jitted
    kernel call — roughly six XLA dispatches of padding scatter/concat per
    invocation that the program cache could not see (``lloyd_run_pallas``
    pads once outside its loop and never had the problem)."""
    k, d = centers.shape[0], x.shape[1]
    x_p, w_p, c_p = _pad_operands_traced(
        x, weights, centers, block_rows=tile_rows
    )
    sums, counts, cost = _accum_any(
        x_p, w_p, c_p, mode, interpret, need_cost, tile_rows, depth
    )
    return sums[:k, :d], counts[0, :k], cost[0, 0]


def _norm_geometry(tile_rows, depth):
    """Normalize optional tuned geometry to the static (tile_rows, depth)
    pair the jitted entries key on: None -> the hand-picked defaults
    (grid kernel at the 512-row block)."""
    tile_rows = _BLOCK_ROWS if tile_rows is None else int(tile_rows)
    depth = 0 if depth is None else int(depth)
    if depth >= 2:
        _dbuf.check_depth(depth)
    return tile_rows, depth


def lloyd_accumulate_pallas(
    x: jax.Array,
    weights: jax.Array,
    centers: jax.Array,
    mode: str = "highest",
    interpret: bool = False,
    tile_rows: int = None,
    depth: int = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Drop-in replacement for ops.kmeans_ops._accumulate (f32 only).

    One registry-tracked jitted program per input signature (padding
    included — see ``_accumulate_jit``).  ``tile_rows``/``depth`` carry
    tuned geometry (ops/pallas/autotune.py); depth >= 2 routes to the
    double-buffered walk, bit-identical by construction.
    """
    mode = check_mode(mode)
    tile_rows, depth = _norm_geometry(tile_rows, depth)
    progcache.note(
        "kmeans.pallas_accumulate",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, weights, centers), mode, interpret,
         tile_rows, depth),
    )
    with kernel_launch("kmeans.accumulate"):
        return _accumulate_jit(
            x, weights, centers, mode, interpret, True, tile_rows, depth
        )


@functools.partial(
    jax.jit,
    static_argnames=("max_iter", "mode", "interpret", "tile_rows", "depth"),
)
def _lloyd_loop_padded(x_p, w_p, c_p, max_iter, tol, mode="highest",
                       interpret=False, tile_rows=_BLOCK_ROWS, depth=0):
    """while_loop over the fused kernel on pre-padded operands."""
    tol_sq = tol * tol

    def cond(state):
        _, it, converged = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _ = state
        sums, counts, _ = _accum_any(
            x_p, w_p, centers, mode, interpret, False, tile_rows, depth
        )
        counts_col = counts[0][:, None]  # (k_pad, 1)
        new_centers = jnp.where(
            counts_col > 0, sums / jnp.maximum(counts_col, 1e-30), centers
        )
        moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged

    state = (c_p, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    centers, n_iter, _ = jax.lax.while_loop(cond, body, state)
    # final cost + counts w.r.t. the returned centers, always at full
    # precision — the user-facing objective should not carry the fast
    # tiers' distance error
    _, counts, cost = _accum_any(
        x_p, w_p, centers, "highest", interpret, True, tile_rows, depth
    )
    return centers, n_iter, cost[0, 0], counts[0]


def lloyd_run_pallas(x, weights, init_centers, max_iter, tol,
                     mode: str = "highest", interpret: bool = False,
                     tile_rows: int = None, depth: int = None):
    """Fused-kernel Lloyd loop; same contract as ops.kmeans_ops.lloyd_run
    (f32, adds per-cluster counts). Pads once outside the loop (one
    compiled pad program), slices the result back.  Tuned geometry rides
    ``tile_rows``/``depth`` (depth >= 2 = the double-buffered walk)."""
    mode = check_mode(mode)
    tile_rows, depth = _norm_geometry(tile_rows, depth)
    d = x.shape[1]
    k = init_centers.shape[0]
    with kernel_launch("kmeans.lloyd_loop"):
        x_p, w_p, c_p = _pad_operands(
            x, weights, init_centers, block_rows=tile_rows
        )
        centers, n_iter, cost, counts = _lloyd_loop_padded(
            x_p, w_p, c_p, max_iter, jnp.asarray(tol, jnp.float32), mode,
            interpret, tile_rows, depth,
        )
    return centers[:k, :d], n_iter, cost, counts[:k]
