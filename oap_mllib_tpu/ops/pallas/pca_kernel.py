"""Fused streaming PCA moments: centered Gram + column sums in one kernel.

The XLA covariance pass (ops/pca_ops._covariance_jit and the streamed
``_gram_chunk``) materializes the centered, mask-scaled copy ``xc = (x -
mean) * mask`` in HBM before the Gram matmul — an extra O(n*d) write +
read per pass on top of streaming X.  This kernel fuses center + mask +
Gram per row tile in VMEM: each (bn, d) block is centered on the VPU,
contracted on the MXU into the (d, d) Gram accumulator, and its raw
masked column sums + weighted row count accumulate alongside (the
"X-tile -> X^T X partial + colsum in VMEM" shape of ISSUE 9) —
exploiting the TPU grid's sequential execution for read-modify-write
accumulation exactly like the K-Means kernel.  HBM traffic per pass
drops from O(2*n*d + d^2) to O(n*d + d^2).

Two-pass numerics are preserved: the covariance wrapper
(ops/pca_ops.covariance) first runs the kernel with ``need_gram=False``
(column sums only — the mean pass), then with the mean and
``need_gram=True`` (the centered Gram pass).  The raw-moment one-pass
form stays banned (catastrophic cancellation — see pca_ops).

Precision tiers (``mode``, shared vocabulary in ops/pallas/_tiers.py):
``highest`` = f32 Precision.HIGHEST Gram (parity tier; column sums and
the row count ALWAYS reduce f32 on the VPU at every tier); ``high`` =
hand-rolled bf16_3x — both Gram operands hi/lo-split, three bf16 passes,
~1e-5 of full f32; ``default`` = single-pass all-bf16 with f32
accumulation (~1e-3).  Policy aliases (f32/tf32/bf16) map through
``check_mode``, which is what prices the bf16 compute policy ON Pallas
(utils/precision.kernel_tier — the ISSUE 9 workaround retirement).

Caller contract (``pca_moments_pallas``): rows pad to the 512-row block
with mask 0, d pads to lane multiples with zero columns (zero in x, mean
and therefore in every output slice).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas._tiers import (
    LANE,
    check_mode,
    kernel_launch,
    pad_to,
    tiered_dot,
)
from oap_mllib_tpu.utils import progcache

_BLOCK_ROWS = 512


def _make_kernel(mode, need_gram):
    def _kernel(x_ref, m_ref, mean_ref, gram_ref, colsum_ref, count_ref):
        """One grid step: fold a (bn, d) row block into the moments."""
        @pl.when(pl.program_id(0) == 0)
        def _init():
            gram_ref[:] = jnp.zeros_like(gram_ref)
            colsum_ref[:] = jnp.zeros_like(colsum_ref)
            count_ref[0, 0] = jnp.float32(0.0)

        x = x_ref[:]  # (bn, d)
        m = m_ref[:]  # (bn, 1)
        xm = x * m
        # raw masked column sums + weighted row count: always exact f32
        # VPU reductions (the mean numerator must not carry tier rounding)
        colsum_ref[:] += jnp.sum(xm, axis=0, keepdims=True)
        count_ref[0, 0] += jnp.sum(m)
        if need_gram:
            xc = (x - mean_ref[:]) * m  # centered in f32, masked
            # (d, d) += xc^T @ xc — contract the row axis on the MXU at
            # the requested tier (hi/lo splits round xc ONCE per operand)
            gram_ref[:] += tiered_dot(
                xc, xc, (((0,), (0,)), ((), ())), mode
            )

    return _kernel


def _pallas_moments(x, m, mean, mode, interpret, need_gram):
    """Raw pallas_call on pre-padded operands (traced inside the jitted
    wrappers — no jit of its own)."""
    n, d = x.shape
    grid = (n // _BLOCK_ROWS,)
    gram, colsum, count = pl.pallas_call(
        _make_kernel(mode, need_gram),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, m, mean)
    return gram, colsum, count


def _pad_rows_cols(x, mask, mean):
    """Pad rows to the block multiple (mask 0) and d to the lane multiple
    (zero columns — zero in x AND mean, so they vanish from every
    output).  Traced only (inside the jitted wrappers)."""
    n, d = x.shape
    n_pad = pad_to(max(n, _BLOCK_ROWS), _BLOCK_ROWS)
    d_pad = pad_to(d, LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(
        x.astype(jnp.float32)
    )
    m_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(
        mask.astype(jnp.float32)
    )
    mean_p = jnp.zeros((1, d_pad), jnp.float32).at[0, :d].set(
        mean.astype(jnp.float32)
    )
    return x_p, m_p, mean_p


def moments_traced(x, mask, mean, mode, interpret, need_gram):
    """Traced pad + kernel + slice (no jit of its own) — the seam the
    streamed per-chunk accumulators jit around (ops/stream_ops)."""
    d = x.shape[1]
    x_p, m_p, mean_p = _pad_rows_cols(x, mask, mean)
    gram, colsum, count = _pallas_moments(
        x_p, m_p, mean_p, mode, interpret, need_gram
    )
    return gram[:d, :d], colsum[0, :d], count[0, 0]


@functools.partial(
    jax.jit, static_argnames=("mode", "interpret", "need_gram")
)
def _moments_jit(x, mask, mean, mode, interpret, need_gram):
    """Pad + kernel + slice in ONE jitted program (the
    kmeans_kernel._accumulate_jit pattern — progcache sees one program
    per input signature, never eager padding dispatches)."""
    return moments_traced(x, mask, mean, mode, interpret, need_gram)


def pca_moments_pallas(
    x: jax.Array,
    mask: jax.Array,
    mean: jax.Array = None,
    mode: str = "highest",
    interpret: bool = False,
    need_gram: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused PCA moments over one table/chunk: returns (gram (d, d),
    colsum (d,), wcount scalar), all f32.

    ``gram`` is the CENTERED masked Gram ``((x - mean) * mask)^T @ ...``
    (zeros when ``need_gram=False`` — the mean pass, which skips the MXU
    work entirely); ``colsum``/``wcount`` are the raw masked column sums
    and total mask weight, tier-independent f32.  ``mean=None`` means a
    zero vector (pass-1 usage).
    """
    mode = check_mode(mode)
    if mean is None:
        mean = jnp.zeros((x.shape[1],), jnp.float32)
    progcache.note(
        "pca.pallas_moments",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, mask), mode, interpret, need_gram),
    )
    with kernel_launch("pca.moments"):
        return _moments_jit(x, mask, mean, mode, interpret, need_gram)


@functools.partial(jax.jit, static_argnames=("mode", "interpret"))
def _covariance_pallas_jit(x, mask, n_rows, mode, interpret):
    """Both covariance passes — colsum/mean then centered Gram — over ONE
    padded copy of the table, in one jitted program.  Numerics match
    pca_ops._covariance_jit's two-pass mean-centered form (the raw-moment
    form stays banned; see that docstring)."""
    d = x.shape[1]
    x_p, m_p, zero_mean = _pad_rows_cols(
        x, mask, jnp.zeros((d,), jnp.float32)
    )
    _, colsum, _ = _pallas_moments(
        x_p, m_p, zero_mean, mode, interpret, need_gram=False
    )
    mean_p = colsum / n_rows  # (1, d_pad); padded columns stay 0
    gram, _, _ = _pallas_moments(
        x_p, m_p, mean_p, mode, interpret, need_gram=True
    )
    cov = gram[:d, :d] / jnp.maximum(n_rows - 1.0, 1.0)
    # numerical symmetry guard before eigh (same as the XLA pass)
    return 0.5 * (cov + cov.T), mean_p[0, :d]


def covariance_pallas(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array,
    mode: str = "highest", interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused-kernel replacement for pca_ops._covariance_jit: (cov (d, d),
    mean (d,)) — same two-pass centered numerics, one padded table copy,
    no HBM-materialized centered temp."""
    mode = check_mode(mode)
    progcache.note(
        "pca.pallas_covariance",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, mask), mode, interpret),
    )
    with kernel_launch("pca.covariance"):
        return _covariance_pallas_jit(x, mask, n_rows, mode, interpret)


def pallas_gram_preferred(d: int, precision: str) -> bool:
    """Shape/tier rule for pca_kernel="auto": the fused kernel holds the
    full (d, d) Gram block in VMEM, so past ~4M padded elements (16 MB
    f32) Mosaic cannot place it — those fits stay on the XLA pass.  All
    three tiers qualify (the kernel ships the same hand-rolled hi/lo
    split tiers as the K-Means kernel, so the bf16 policy prices ON
    Pallas — the ISSUE 9 workaround retirement)."""
    d_pad = pad_to(d, LANE)
    if d_pad * d_pad > (1 << 22):  # 16 MB per f32 VMEM block
        return False
    return precision in ("highest", "high", "default")
