"""Fused streaming PCA moments: centered Gram + column sums in one kernel.

The XLA covariance pass (ops/pca_ops._covariance_jit and the streamed
``_gram_chunk``) materializes the centered, mask-scaled copy ``xc = (x -
mean) * mask`` in HBM before the Gram matmul — an extra O(n*d) write +
read per pass on top of streaming X.  This kernel fuses center + mask +
Gram per row tile in VMEM: each (bn, d) block is centered on the VPU,
contracted on the MXU into the (d, d) Gram accumulator, and its raw
masked column sums + weighted row count accumulate alongside (the
"X-tile -> X^T X partial + colsum in VMEM" shape of ISSUE 9) —
exploiting the TPU grid's sequential execution for read-modify-write
accumulation exactly like the K-Means kernel.  HBM traffic per pass
drops from O(2*n*d + d^2) to O(n*d + d^2).

Two-pass numerics are preserved: the covariance wrapper
(ops/pca_ops.covariance) first runs the kernel with ``need_gram=False``
(column sums only — the mean pass), then with the mean and
``need_gram=True`` (the centered Gram pass).  The raw-moment one-pass
form stays banned (catastrophic cancellation — see pca_ops).

Precision tiers (``mode``, shared vocabulary in ops/pallas/_tiers.py):
``highest`` = f32 Precision.HIGHEST Gram (parity tier; column sums and
the row count ALWAYS reduce f32 on the VPU at every tier); ``high`` =
hand-rolled bf16_3x — both Gram operands hi/lo-split, three bf16 passes,
~1e-5 of full f32; ``default`` = single-pass all-bf16 with f32
accumulation (~1e-3).  Policy aliases (f32/tf32/bf16) map through
``check_mode``, which is what prices the bf16 compute policy ON Pallas
(utils/precision.kernel_tier — the ISSUE 9 workaround retirement).

Caller contract (``pca_moments_pallas``): rows pad to the 512-row block
with mask 0, d pads to lane multiples with zero columns (zero in x, mean
and therefore in every output slice).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas import _dbuf
from oap_mllib_tpu.ops.pallas._tiers import (
    LANE,
    check_mode,
    kernel_launch,
    pad_to,
    tiered_dot,
)
from oap_mllib_tpu.utils import progcache

_BLOCK_ROWS = 512


def _tile_moments(x, m, mean, mode, need_gram):
    """One resident tile's moment update — center + mask + Gram with the
    centered intermediate living and dying in VMEM.  Shared by the grid
    kernel, the double-buffered walk, and the schedule-identical XLA
    fallback.  Returns (gram_inc | None, colsum_inc (1, d), count_inc)."""
    xm = x * m
    # raw masked column sums + weighted row count: always exact f32
    # VPU reductions (the mean numerator must not carry tier rounding)
    colsum_inc = jnp.sum(xm, axis=0, keepdims=True)
    count_inc = jnp.sum(m)
    gram_inc = None
    if need_gram:
        xc = (x - mean) * m  # centered in f32, masked
        # (d, d) += xc^T @ xc — contract the row axis on the MXU at
        # the requested tier (hi/lo splits round xc ONCE per operand)
        gram_inc = tiered_dot(xc, xc, (((0,), (0,)), ((), ())), mode)
    return gram_inc, colsum_inc, count_inc


def _make_kernel(mode, need_gram):
    def _kernel(x_ref, m_ref, mean_ref, gram_ref, colsum_ref, count_ref):
        """One grid step: fold a (bn, d) row block into the moments."""
        @pl.when(pl.program_id(0) == 0)
        def _init():
            gram_ref[:] = jnp.zeros_like(gram_ref)
            colsum_ref[:] = jnp.zeros_like(colsum_ref)
            count_ref[0, 0] = jnp.float32(0.0)

        gram_inc, colsum_inc, count_inc = _tile_moments(
            x_ref[:], m_ref[:], mean_ref[:], mode, need_gram
        )
        colsum_ref[:] += colsum_inc
        count_ref[0, 0] += count_inc
        if need_gram:
            gram_ref[:] += gram_inc

    return _kernel


def _pallas_moments(x, m, mean, mode, interpret, need_gram,
                    block_rows=_BLOCK_ROWS):
    """Raw pallas_call on pre-padded operands (traced inside the jitted
    wrappers — no jit of its own)."""
    n, d = x.shape
    grid = (n // block_rows,)
    gram, colsum, count = pl.pallas_call(
        _make_kernel(mode, need_gram),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, m, mean)
    return gram, colsum, count


# -- double-buffered walk (explicit DMA overlap; ROADMAP item 4) -------------


def _make_dbuf_kernel(mode, need_gram, tile_rows, depth, num_tiles):
    def _kernel(x_hbm, m_hbm, mean_ref, gram_ref, colsum_ref, count_ref,
                xbuf, mbuf, xsem, msem):
        gram_ref[:] = jnp.zeros_like(gram_ref)
        colsum_ref[:] = jnp.zeros_like(colsum_ref)
        count_ref[0, 0] = jnp.float32(0.0)
        mean = mean_ref[:]

        def body(t, views):
            x, m = views
            gram_inc, colsum_inc, count_inc = _tile_moments(
                x, m, mean, mode, need_gram
            )
            colsum_ref[:] += colsum_inc
            count_ref[0, 0] += count_inc
            if need_gram:
                gram_ref[:] += gram_inc

        _dbuf.tile_walk(
            [x_hbm, m_hbm], [xbuf, mbuf], [xsem, msem],
            tile_rows, num_tiles, depth, body,
        )

    return _kernel


def _pallas_moments_dbuf(x, m, mean, mode, interpret, need_gram,
                         tile_rows, depth):
    n, d = x.shape
    num_tiles = n // tile_rows
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.TPUCompilerParams(
            has_side_effects=True
        )
    gram, colsum, count = pl.pallas_call(
        _make_dbuf_kernel(mode, need_gram, tile_rows, depth, num_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=_dbuf.rotation_scratch(
            depth, [(tile_rows, d), (tile_rows, 1)]
        ),
        interpret=interpret,
        **kwargs,
    )(x, m, mean)
    return gram, colsum, count


def _xla_walk(x_p, m_p, mean_p, mode, need_gram, tile_rows):
    """Schedule-identical XLA fallback: ``lax.scan`` over the same tiles
    in the same order through the same ``_tile_moments``."""
    n, d = x_p.shape
    num_tiles = n // tile_rows
    xt = x_p.reshape(num_tiles, tile_rows, d)
    mt = m_p.reshape(num_tiles, tile_rows, 1)

    def step(carry, tile):
        gram, colsum, count = carry
        xi, mi = tile
        gram_inc, colsum_inc, count_inc = _tile_moments(
            xi, mi, mean_p, mode, need_gram
        )
        gram = gram + gram_inc if need_gram else gram
        return (gram, colsum + colsum_inc, count + count_inc), None

    init = (
        jnp.zeros((d, d), jnp.float32),
        jnp.zeros((1, d), jnp.float32),
        jnp.float32(0.0),
    )
    (gram, colsum, count), _ = jax.lax.scan(step, init, (xt, mt))
    return gram, colsum, count.reshape(1, 1)


def _moments_any(x_p, m_p, mean_p, mode, interpret, need_gram, tile_rows,
                 depth):
    """Kernel-variant dispatch on pre-padded operands (the kmeans_kernel
    ``_accum_any`` pattern): grid pipeline at depth < 2, double-buffered
    walk at depth >= 2 (DMA kernel on TPU/interpret, XLA scan
    elsewhere)."""
    if depth >= 2:
        if interpret or jax.default_backend() == "tpu":
            return _pallas_moments_dbuf(
                x_p, m_p, mean_p, mode, interpret, need_gram, tile_rows,
                depth,
            )
        return _xla_walk(x_p, m_p, mean_p, mode, need_gram, tile_rows)
    return _pallas_moments(
        x_p, m_p, mean_p, mode, interpret, need_gram, tile_rows
    )


def _pad_rows_cols(x, mask, mean, block_rows=_BLOCK_ROWS):
    """Pad rows to the block multiple (mask 0) and d to the lane multiple
    (zero columns — zero in x AND mean, so they vanish from every
    output).  Traced only (inside the jitted wrappers)."""
    n, d = x.shape
    n_pad = pad_to(max(n, block_rows), block_rows)
    d_pad = pad_to(d, LANE)
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(
        x.astype(jnp.float32)
    )
    m_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(
        mask.astype(jnp.float32)
    )
    mean_p = jnp.zeros((1, d_pad), jnp.float32).at[0, :d].set(
        mean.astype(jnp.float32)
    )
    return x_p, m_p, mean_p


def _norm_geometry(tile_rows, depth):
    """None -> the hand-picked defaults (grid kernel, 512-row block)."""
    tile_rows = _BLOCK_ROWS if tile_rows is None else int(tile_rows)
    depth = 0 if depth is None else int(depth)
    if depth >= 2:
        _dbuf.check_depth(depth)
    return tile_rows, depth


def moments_traced(x, mask, mean, mode, interpret, need_gram,
                   tile_rows=None, depth=None):
    """Traced pad + kernel + slice (no jit of its own) — the seam the
    streamed per-chunk accumulators jit around (ops/stream_ops)."""
    tile_rows, depth = _norm_geometry(tile_rows, depth)
    d = x.shape[1]
    x_p, m_p, mean_p = _pad_rows_cols(x, mask, mean, block_rows=tile_rows)
    gram, colsum, count = _moments_any(
        x_p, m_p, mean_p, mode, interpret, need_gram, tile_rows, depth
    )
    return gram[:d, :d], colsum[0, :d], count[0, 0]


@functools.partial(
    jax.jit,
    static_argnames=("mode", "interpret", "need_gram", "tile_rows", "depth"),
)
def _moments_jit(x, mask, mean, mode, interpret, need_gram,
                 tile_rows=_BLOCK_ROWS, depth=0):
    """Pad + kernel + slice in ONE jitted program (the
    kmeans_kernel._accumulate_jit pattern — progcache sees one program
    per input signature, never eager padding dispatches)."""
    return moments_traced(
        x, mask, mean, mode, interpret, need_gram, tile_rows, depth
    )


def pca_moments_pallas(
    x: jax.Array,
    mask: jax.Array,
    mean: jax.Array = None,
    mode: str = "highest",
    interpret: bool = False,
    need_gram: bool = True,
    tile_rows: int = None,
    depth: int = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused PCA moments over one table/chunk: returns (gram (d, d),
    colsum (d,), wcount scalar), all f32.

    ``gram`` is the CENTERED masked Gram ``((x - mean) * mask)^T @ ...``
    (zeros when ``need_gram=False`` — the mean pass, which skips the MXU
    work entirely); ``colsum``/``wcount`` are the raw masked column sums
    and total mask weight, tier-independent f32.  ``mean=None`` means a
    zero vector (pass-1 usage).
    """
    mode = check_mode(mode)
    tile_rows, depth = _norm_geometry(tile_rows, depth)
    if mean is None:
        mean = jnp.zeros((x.shape[1],), jnp.float32)
    progcache.note(
        "pca.pallas_moments",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, mask), mode, interpret, need_gram,
         tile_rows, depth),
    )
    with kernel_launch("pca.moments"):
        return _moments_jit(
            x, mask, mean, mode, interpret, need_gram, tile_rows, depth
        )


@functools.partial(
    jax.jit, static_argnames=("mode", "interpret", "tile_rows", "depth")
)
def _covariance_pallas_jit(x, mask, n_rows, mode, interpret,
                           tile_rows=_BLOCK_ROWS, depth=0):
    """Both covariance passes — colsum/mean then centered Gram — over ONE
    padded copy of the table, in one jitted program.  Numerics match
    pca_ops._covariance_jit's two-pass mean-centered form (the raw-moment
    form stays banned; see that docstring)."""
    d = x.shape[1]
    x_p, m_p, zero_mean = _pad_rows_cols(
        x, mask, jnp.zeros((d,), jnp.float32), block_rows=tile_rows
    )
    _, colsum, _ = _moments_any(
        x_p, m_p, zero_mean, mode, interpret, False, tile_rows, depth
    )
    mean_p = colsum / n_rows  # (1, d_pad); padded columns stay 0
    gram, _, _ = _moments_any(
        x_p, m_p, mean_p, mode, interpret, True, tile_rows, depth
    )
    cov = gram[:d, :d] / jnp.maximum(n_rows - 1.0, 1.0)
    # numerical symmetry guard before eigh (same as the XLA pass)
    return 0.5 * (cov + cov.T), mean_p[0, :d]


def covariance_pallas(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array,
    mode: str = "highest", interpret: bool = False,
    tile_rows: int = None, depth: int = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused-kernel replacement for pca_ops._covariance_jit: (cov (d, d),
    mean (d,)) — same two-pass centered numerics, one padded table copy,
    no HBM-materialized centered temp.  ``tile_rows``/``depth`` carry
    tuned geometry (depth >= 2 = the double-buffered walk)."""
    mode = check_mode(mode)
    tile_rows, depth = _norm_geometry(tile_rows, depth)
    progcache.note(
        "pca.pallas_covariance",
        (progcache.backend_fingerprint(),
         progcache.array_key(x, mask), mode, interpret, tile_rows, depth),
    )
    with kernel_launch("pca.covariance"):
        return _covariance_pallas_jit(
            x, mask, n_rows, mode, interpret, tile_rows, depth
        )


def pallas_gram_preferred(d: int, precision: str) -> bool:
    """Shape/tier rule for pca_kernel="auto": the fused kernel holds the
    full (d, d) Gram block in VMEM, so past ~4M padded elements (16 MB
    f32) Mosaic cannot place it — those fits stay on the XLA pass.  All
    three tiers qualify (the kernel ships the same hand-rolled hi/lo
    split tiers as the K-Means kernel, so the bf16 policy prices ON
    Pallas — the ISSUE 9 workaround retirement)."""
    d_pad = pad_to(d, LANE)
    if d_pad * d_pad > (1 << 22):  # 16 MB per f32 VMEM block
        return False
    return precision in ("highest", "high", "default")
