"""Ring-overlapped cross-device reduction of per-pass moment buffers.

Every model-sharded and multi-chip pass used to finish with SEPARATE XLA
psums of its moment buffers — the K-Means accumulate alone paid three
(centroid sums, counts, cost), each a standalone allreduce serialized
behind the pass's compute (the pattern *Communication-Avoiding Linear
Algebraic Kernel K-Means on GPUs* — PAPERS.md, arXiv:2601.17136 —
identifies as the dominant distributed-Lloyd cost, and the map-reduce
partial-sums formulation of arXiv:1610.05601 makes overlappable).  This
module replaces them with ONE ring reduction of the PACKED moments:

- **Schedule** (shared by both backends, so numerics cannot diverge):
  bandwidth-optimal ring allreduce — the buffer splits into ``world``
  row segments; W-1 reduce-scatter steps rotate partial segments around
  the ring (each device adds the arriving segment into its running
  copy), then W-1 all-gather steps rotate the fully-reduced segments
  back.  Per-link traffic is 2·(W-1)/W of the buffer — the optimum —
  and each segment's additions happen in a fixed ring order, so results
  are deterministic and identical on every device.
- **TPU backend**: a Pallas kernel drives the rotation with
  ``pltpu.make_async_remote_copy`` ICI DMAs (SNIPPETS [1] pattern: HBM
  ``memory_space=ANY`` operands, VMEM communication buffers and DMA
  semaphores in scratch, a neighbor barrier before first contact,
  ``collective_id`` compiler param).  The segment ADD of ring step s
  overlaps the in-flight DMA of the opposite-direction half (the
  buffer's columns split into a clockwise and a counter-clockwise half,
  the guide's bi-directional ring), so both ICI links carry traffic
  while the VPU folds — the communication-overlap half of ISSUE 9.
- **Everywhere else** (CPU pseudo-cluster, interpret-mode tests, and
  the parity reference on TPU): the identical schedule expressed as
  ``collective.ppermute`` steps — same segment rotation, same addition
  order, so the CPU tier-1 suite exercises the exact reduction the TPU
  kernel performs.

Fallback contract: a mesh with fewer than 2 devices on the reduce axis
routes to a plain ``collective.psum`` (the pre-ring path) — resolved
STATICALLY at program build (kmeans_ops.ring_enabled), so single-device
fits never trace ring code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from oap_mllib_tpu.ops.pallas._tiers import LANE, note_emitted, pad_to
from oap_mllib_tpu.parallel import collective


def _rot(i, s: int, world: int):
    """(i - s) mod world for a traced non-negative ``i`` and static s —
    offset into the positive range first (lax.rem keeps the dividend's
    sign, so a bare ``(i - s) % world`` could go negative)."""
    return lax.rem(i - s + 2 * world, world)


# -- ppermute schedule (CPU / parity path) -----------------------------------


def _ring_dir_ppermute(buf, axis_name: str, world: int, me, sign: int):
    """One direction's ring over one column half: ``world - 1``
    reduce-scatter + ``world - 1`` all-gather ppermute steps.  ``sign``
    +1 sends clockwise (to the right neighbor), -1 counter-clockwise —
    the same rotation the TPU kernel's two DMA directions drive, so the
    per-segment addition order is identical across backends."""
    seg = buf.shape[0] // world
    acc = buf.reshape(world, seg, buf.shape[1])
    perm = [(i, (i + sign) % world) for i in range(world)]
    for s in range(world - 1):  # reduce-scatter: rotate + add
        send_idx = _rot(me, sign * s, world)
        recv_idx = _rot(me, sign * (s + 1), world)
        b = lax.dynamic_index_in_dim(acc, send_idx, 0, keepdims=False)
        recv = collective.ppermute(b, axis_name, perm)
        cur = lax.dynamic_index_in_dim(acc, recv_idx, 0, keepdims=False)
        acc = lax.dynamic_update_index_in_dim(acc, cur + recv, recv_idx, 0)
    for s in range(world - 1):  # all-gather: rotate the reduced segments
        send_idx = _rot(me, sign * (s - 1), world)
        recv_idx = _rot(me, sign * s, world)
        b = lax.dynamic_index_in_dim(acc, send_idx, 0, keepdims=False)
        recv = collective.ppermute(b, axis_name, perm)
        acc = lax.dynamic_update_index_in_dim(acc, recv, recv_idx, 0)
    return acc.reshape(world * seg, buf.shape[1])


def _ring_ppermute(x, axis_name: str, world: int):
    """The bi-directional ring schedule as ppermute steps: the clockwise
    half of the columns and the counter-clockwise half rotate in
    opposite directions (the TPU kernel's two-link schedule), then
    reassemble.  ``x`` is the (seg * world, cols) padded buffer with an
    even column split; returns the fully-summed buffer (identical on
    every rank)."""
    half = x.shape[1] // 2
    me = lax.axis_index(axis_name)
    cw = _ring_dir_ppermute(x[:, :half], axis_name, world, me, 1)
    ccw = _ring_dir_ppermute(x[:, half:], axis_name, world, me, -1)
    return jnp.concatenate([cw, ccw], axis=1)


# -- Pallas remote-DMA kernel (TPU path) -------------------------------------


def _make_ring_kernel(axis_name: str, world: int, seg: int, cols: int):
    half = cols // 2  # bi-directional: column halves travel opposite ways

    def _kernel(x_ref, out_ref, comm, send_sem, recv_sem, copy_sem):
        # x_ref/out_ref live in ANY (HBM); comm is the (2 dirs, 2 slots,
        # seg, half) VMEM rotation buffer; semaphores index [dir, slot].
        me = lax.axis_index(axis_name)
        right = lax.rem(me + 1, world)
        left = lax.rem(me + world - 1, world)

        # local copy input -> output (the running accumulator)
        cp = pltpu.make_async_copy(x_ref, out_ref, copy_sem)
        cp.start()
        cp.wait()

        # neighbor barrier: nobody DMAs into a peer's comm buffer before
        # that peer has entered the kernel
        barrier = pltpu.get_barrier_semaphore()
        for nb in (left, right):
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=(nb,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        pltpu.semaphore_wait(barrier, 2)

        def load(idx, dir_, slot):
            # acc segment -> VMEM staging half (dir 0 = clockwise carries
            # columns [:half], dir 1 = counter-clockwise carries [half:])
            c0 = dir_ * half
            cp = pltpu.make_async_copy(
                out_ref.at[pl.ds(idx * seg, seg), pl.ds(c0, half)],
                comm.at[dir_, slot],
                copy_sem,
            )
            cp.start()
            cp.wait()

        def store(idx, dir_, slot, add: bool):
            c0 = dir_ * half
            tgt = out_ref.at[pl.ds(idx * seg, seg), pl.ds(c0, half)]
            if add:
                # fold the arrived segment into the running copy: pull
                # current to the spare slot, add on the VPU, push back —
                # the fold of one direction overlaps the other
                # direction's in-flight DMA
                spare = 1 - slot
                cp = pltpu.make_async_copy(tgt, comm.at[dir_, spare], copy_sem)
                cp.start()
                cp.wait()
                comm[dir_, spare] = comm[dir_, spare] + comm[dir_, slot]
                cp2 = pltpu.make_async_copy(comm.at[dir_, spare], tgt, copy_sem)
                cp2.start()
                cp2.wait()
            else:
                cp = pltpu.make_async_copy(comm.at[dir_, slot], tgt, copy_sem)
                cp.start()
                cp.wait()

        def ring_step(send_idx_cw, send_idx_ccw, recv_idx_cw, recv_idx_ccw,
                      add: bool):
            # stage both directions, fire both remote DMAs (opposite ICI
            # links), then fold — adds overlap the other link's transfer
            load(send_idx_cw, 0, 0)
            load(send_idx_ccw, 1, 0)
            rdma_cw = pltpu.make_async_remote_copy(
                src_ref=comm.at[0, 0],
                dst_ref=comm.at[0, 1],
                send_sem=send_sem.at[0],
                recv_sem=recv_sem.at[0],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma_ccw = pltpu.make_async_remote_copy(
                src_ref=comm.at[1, 0],
                dst_ref=comm.at[1, 1],
                send_sem=send_sem.at[1],
                recv_sem=recv_sem.at[1],
                device_id=(left,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma_cw.start()
            rdma_ccw.start()
            rdma_cw.wait()
            store(recv_idx_cw, 0, 1, add)
            rdma_ccw.wait()
            store(recv_idx_ccw, 1, 1, add)
            # per-step neighbor barrier: slot reuse in the next step must
            # not race a slow peer's in-flight read (conservative — the
            # overlap win is within a step, across the two directions)
            for nb in (left, right):
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=(nb,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            pltpu.semaphore_wait(barrier, 2)

        # same index schedule as _ring_dir_ppermute (sign +1 = cw half,
        # sign -1 = ccw half) — numerics identical across backends
        for s in range(world - 1):  # reduce-scatter
            ring_step(
                _rot(me, s, world), _rot(me, -s, world),
                _rot(me, s + 1, world), _rot(me, -(s + 1), world),
                add=True,
            )
        for s in range(world - 1):  # all-gather
            ring_step(
                _rot(me, s - 1, world), _rot(me, -(s - 1), world),
                _rot(me, s, world), _rot(me, -s, world),
                add=False,
            )

    return _kernel


def _ring_pallas(x, axis_name: str, world: int):
    """shard_map-body entry for the TPU remote-DMA kernel; ``x`` is the
    (seg * world, cols) padded buffer with cols an even lane multiple."""
    seg = x.shape[0] // world
    cols = x.shape[1]
    return pl.pallas_call(
        _make_ring_kernel(axis_name, world, seg, cols),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 2, seg, cols // 2), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=7, has_side_effects=True,
        ),
    )(x)


# -- dispatch ----------------------------------------------------------------


def ring_allreduce(x, axis_name: str, world: int, interpret: bool = False,
                   segments: int = 1):
    """Sum an identically-shaped per-device 2-D f32 buffer across
    ``axis_name`` with the ring schedule; call INSIDE shard_map/jit
    bodies (the collective.psum seam's in-jit contract).  ``world`` is
    the static axis size.  ``world < 2`` falls back to the psum path —
    the clean degradation the acceptance contract requires.  The
    ``interpret`` static forces the ppermute schedule (tier-1's CPU leg
    runs it regardless, by backend).

    ``segments`` > 1 is the segmented-start epilogue (ROADMAP item 4,
    tuned by ops/pallas/autotune.py): the rows split into ``segments``
    INDEPENDENT ring reductions, each fenced on only its own row slice.
    A segment's reduce-scatter may therefore dispatch while the local
    walk is still accumulating later rows, and a consumer of an early
    segment's output may start before the last segment's all-gather
    completes — the data dependence is per segment, which is exactly
    what lets the XLA scheduler overlap the ring with the surrounding
    pass.  Row-disjoint segments mean the set of additions per row is
    unchanged; only the rotation's starting owner moves, so results
    stay within the ring parity envelope (<= 1e-5) and the trace-time
    census is unchanged (one ``ring.allreduce`` per call, zero
    standalone psums)."""
    note_emitted("ring.allreduce")
    if world < 2:
        return collective.psum(x, axis_name)
    segments = max(1, int(segments))
    rows, cols = x.shape
    rows_pad = pad_to(max(rows, world * segments), world * segments)
    use_pallas = jax.default_backend() == "tpu" and not interpret
    # even lane-multiple columns on BOTH paths so the bi-directional
    # halves split at the same column — cross-backend bit identity
    cols_pad = pad_to(max(cols, 2 * LANE), 2 * LANE)
    xp = x.astype(jnp.float32)
    if rows_pad != rows or cols_pad != cols:
        xp = jnp.zeros((rows_pad, cols_pad), jnp.float32).at[
            :rows, :cols
        ].set(xp)
    ring_one = _ring_pallas if use_pallas else _ring_ppermute
    if segments == 1:
        out = ring_one(xp, axis_name, world)
    else:
        seg_rows = rows_pad // segments
        out = jnp.concatenate(
            [
                ring_one(
                    xp[g * seg_rows : (g + 1) * seg_rows], axis_name, world
                )
                for g in range(segments)
            ],
            axis=0,
        )
    return out[:rows, :cols]


# -- eager/hosted entry for the streamed multi-host reductions ---------------


def stacked_ring_fn(mesh, axis_name: str, interpret: bool = False,
                    segments: int = 1):
    """Registry-cached jitted ring program for host-driven paths
    (ops/stream_ops): takes a (world, rows, cols) f32 array sharded one
    slot per device over ``axis_name`` (each process contributes its
    per-pass moments in its first local slot, zeros elsewhere) and
    returns it with every slot holding the full sum.  ``segments`` is
    the segmented-start epilogue knob (see :func:`ring_allreduce`)."""
    from oap_mllib_tpu.utils import progcache
    from oap_mllib_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    world = mesh.shape[axis_name]
    segments = max(1, int(segments))

    def build():
        def body(blk):  # (1, rows, cols) per device slot
            return ring_allreduce(
                blk[0], axis_name, world, interpret, segments=segments
            )[None]

        return jax.jit(
            shard_map(
                body, mesh=mesh,
                in_specs=P(axis_name, None, None),
                out_specs=P(axis_name, None, None),
                check_vma=False,
            )
        )

    key = (
        progcache.mesh_fingerprint(mesh), axis_name, world, interpret,
        segments,
    )
    return progcache.get_or_build("ring.stacked", key, build)
