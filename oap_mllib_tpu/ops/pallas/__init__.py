"""Pallas TPU kernels for the hot ops.

Hand-fused kernels where XLA's automatic fusion leaves HBM bandwidth on
the table.  Each kernel has the same contract as its XLA counterpart in
ops/ and is opt-in via config (``use_pallas``) with automatic fallback
off-TPU (interpret mode keeps them testable on the CPU pseudo-cluster).
"""

from oap_mllib_tpu.ops.pallas.kmeans_kernel import lloyd_accumulate_pallas

__all__ = ["lloyd_accumulate_pallas"]
