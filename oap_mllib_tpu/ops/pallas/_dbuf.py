"""Shared double-buffered tile-walk plumbing for the Pallas kernel plane.

The grid-pipelined kernels (kmeans/pca/als ``pallas_call`` grids) lean on
the Mosaic pipeline to stage the next block while the current one
computes.  The communication-avoiding restructure (ROADMAP item 4, the
rank-k-update formulation of arXiv:2601.17136) makes that overlap
explicit instead: inputs stay in HBM (``memory_space=ANY``), each kernel
walks its tiles with a *rotating* VMEM buffer of static ``depth``, and
the DMA for tile ``t + depth - 1`` is in flight while tile ``t``
computes — the SNIPPETS [1] async-copy pattern applied within a rank.
Accumulators live in VMEM for the whole walk, so intermediates (the
K-Means one-hot, the centered PCA tile) never round-trip HBM.

This module owns the two pieces every kernel shares, so the rotation
arithmetic cannot drift between them:

- :func:`rotation_scratch` — the ``scratch_shapes`` entries for one
  walk: a ``(depth, *tile)`` VMEM buffer plus a ``(depth,)`` DMA
  semaphore per input.
- :func:`tile_walk` — the in-kernel driver: warm-up starts for the
  first ``depth - 1`` tiles, then a ``fori_loop`` that prefetches tile
  ``t + depth - 1`` into its rotation slot, waits tile ``t``'s DMA, and
  hands the resident views to the kernel's tile body.  Tiles are
  visited strictly in order, so the accumulation order — and therefore
  every result bit — matches the grid-pipelined kernels and the
  schedule-identical XLA fallbacks (``lax.scan`` over the same tiles in
  the same order; see each kernel's ``_xla_walk``).

Depth is a tuned knob (ops/pallas/autotune.py): 2 = classic double
buffering, 3+ trades VMEM for slack against DMA-latency jitter.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEPTHS = (2, 3, 4)  # supported rotation depths (1 means "use the grid kernel")


def check_depth(depth: int) -> int:
    depth = int(depth)
    if depth not in DEPTHS:
        raise ValueError(
            f"rotation depth must be one of {DEPTHS}, got {depth!r}"
        )
    return depth


def rotation_scratch(depth: int, tile_shapes):
    """``scratch_shapes`` for one rotating walk over ``len(tile_shapes)``
    inputs: the VMEM rotation buffers first, then one (depth,) DMA
    semaphore array per input (kernel scratch refs arrive in this
    order)."""
    shapes = [
        pltpu.VMEM((depth,) + tuple(ts), jnp.float32) for ts in tile_shapes
    ]
    shapes += [pltpu.SemaphoreType.DMA((depth,)) for _ in tile_shapes]
    return shapes


def tile_walk(inputs, bufs, sems, tile, num_tiles, depth, body, axes=None):
    """Drive one double-buffered walk inside a kernel body.

    ``inputs`` are HBM (``ANY``) refs, ``bufs``/``sems`` the matching
    rotation scratch from :func:`rotation_scratch`, ``tile`` the static
    tile extent along each input's walk axis (``axes``, default 0 —
    the ALS solve walks axis 1), ``num_tiles`` the static tile count.
    ``body(t, views)`` receives the tile index and the resident
    ``(tile, ...)`` views; it mutates the kernel's accumulator refs.

    The start/wait pair rebuilds the same copy descriptor (the async
    copy contract), keyed by rotation slot ``t % depth``.
    """
    if axes is None:
        axes = (0,) * len(inputs)

    def _dma(ref, buf, sem, ax, slot, t):
        if ax == 0:
            src = ref.at[pl.ds(t * tile, tile)]
        else:
            src = ref.at[:, pl.ds(t * tile, tile)]
        return pltpu.make_async_copy(src, buf.at[slot], sem.at[slot])

    def _start(t):
        slot = lax.rem(t, depth)
        for ref, buf, sem, ax in zip(inputs, bufs, sems, axes):
            _dma(ref, buf, sem, ax, slot, t).start()

    def _wait(t):
        slot = lax.rem(t, depth)
        for ref, buf, sem, ax in zip(inputs, bufs, sems, axes):
            _dma(ref, buf, sem, ax, slot, t).wait()

    # warm-up: fill the pipeline with the first depth-1 tiles
    for t in range(min(depth - 1, num_tiles)):
        _start(jnp.int32(t))

    def _step(t, carry):
        nxt = t + depth - 1

        @pl.when(nxt < num_tiles)
        def _prefetch():
            _start(nxt)

        _wait(t)
        slot = lax.rem(t, depth)
        body(t, [buf[slot] for buf in bufs])
        return carry

    lax.fori_loop(0, num_tiles, _step, jnp.int32(0))
