"""Streamed (bounded-HBM) block-parallel ALS: out-of-core composed with
the mesh.

`ops/als_stream.py` bounds a SINGLE device's HBM by walking host-resident
grouped edge layouts through the chip in chunks; `ops/als_block.py`
shards the fit over the mesh but keeps every rank's grouped layouts
device-resident.  This module is their composition — the round-4 review
gap ("out-of-core ALS does not compose with the mesh"): each rank keeps
its OWN block's grouped layouts in HOST memory (the reference's
executors likewise hold only their partition in RAM, OneDAL.scala
:92-166) and streams them through its device per half-iteration, while
the inter-rank structure stays exactly the in-memory block path's:

- **replicated item layout**: user update fully local; item update
  accumulates a per-rank (n_items, (r+1)(r+2)) flat moment carry and
  psums it once at solve time (the same single-allreduce collapse of the
  reference's gather -> step2Master -> bcast -> all2all chain,
  ALSDALImpl.cpp:336-431).
- **sharded (2-D) item layout**: both factor sides block-sharded; each
  half-iteration all_gathers the OTHER side's factors once into a
  replicated table, then streams chunks against it (the same
  per-iteration collective payload as als_block_run_grouped_2d — the
  gather just lives between chunk launches instead of inside one
  shard_map program).

Per-device HBM is O(chunk + factors + moments):

- chunk: one (world*gc, Pw) slice of each grouped array per launch,
  gc from the shared ``_GROUPED_BUDGET_ELEMS`` budget
  (als_stream.groups_per_chunk);
- factors: this rank's X block + one replicated source-side table
  (Y, or the all_gathered other side);
- moments: (upb, (r+1)(r+2)) for the user side; item side
  (n_items, (r+1)(r+2)) replicated / (ipb, (r+1)(r+2)) sharded.

Host memory per process is O(its blocks' padded nnz).  Multi-process
worlds first REDISTRIBUTE the triples so each process holds exactly its
blocks' edges — a chunked fixed-shape allgather over DCN
(``_redistribute_triples``; bounded host transient of
O(processes x chunk), the alltoall(lengths)+alltoallv idiom of the
reference's shuffle, ALSShuffle.cpp:92-109, in its simplest
fixed-shape form).

Math parity: the per-chunk moment kernel IS the in-memory kernel
(als_ops.grouped_block_moments) and the solves consume summed moments
identically — streamed-vs-in-memory factors match to fp tolerance on
every layout (chunked segment-sums only reorder additions).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats
from oap_mllib_tpu.ops.als_block import (
    _global_max,
    _global_sum,
    _group_sizes,
    _group_sizes_2d,
    _pad_groups,
)
from oap_mllib_tpu.ops.als_ops import (
    _factor_gram,
    build_grouped_edges,
    grouped_block_moments,
    regularized_solve,
    resolve_solve_kernel,
    unpack_flat_moments,
)
from oap_mllib_tpu.ops.als_stream import groups_per_chunk
from oap_mllib_tpu.parallel import collective
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.timing import tick
from oap_mllib_tpu.utils.jax_compat import shard_map


def owned_blocks(mesh: Mesh, axis: str) -> List[int]:
    """Data-axis block indices whose device(s) live in THIS process
    (all blocks in single-process worlds; with a model axis, a block is
    owned if any of its model-replica devices is local)."""
    ax = mesh.axis_names.index(axis)
    arr = np.moveaxis(np.asarray(mesh.devices, dtype=object), ax, 0)
    arr = arr.reshape(arr.shape[0], -1)
    pidx = jax.process_index()
    return [
        b for b in range(arr.shape[0])
        if any(d.process_index == pidx for d in arr[b])
    ]


# chunk rows for the multi-process triple redistribution: 1M rows of
# (u, i, r) f64 = 24 MB local, x processes transient on receive
_REDIST_CHUNK_ROWS = 1 << 20


def _gathered_triple_chunks(keys, other, ratings):
    """Yield globally-gathered (keys, other, ratings) host chunks: each
    process contributes its local triples, padded to a globally equal
    chunk count so the allgather stays fixed-shape.  Ratings ride f64
    exactly (f32 embeds exactly); ids ride f64 exactly up to 2^53 (the
    ChunkSource id contract)."""
    from jax.experimental import multihost_utils

    n_local = len(keys)
    n_max = int(_global_max([n_local])[0])
    for lo in range(0, max(n_max, 1), _REDIST_CHUNK_ROWS):
        hi = min(lo + _REDIST_CHUNK_ROWS, n_max)
        blob = np.full((hi - lo, 3), -1.0, np.float64)
        if lo < n_local:
            m = min(hi, n_local) - lo
            blob[:m, 0] = keys[lo : lo + m]
            blob[:m, 1] = other[lo : lo + m]
            blob[:m, 2] = ratings[lo : lo + m]
        g = np.asarray(multihost_utils.process_allgather(blob)).reshape(-1, 3)
        g = g[g[:, 0] >= 0]
        yield (
            g[:, 0].astype(np.int64),
            g[:, 1].astype(np.int64),
            g[:, 2].astype(np.float32),
        )


def _own_mask(world: int, owned: List[int]) -> np.ndarray:
    own = np.zeros((world,), bool)
    own[np.asarray(owned, np.int64)] = True
    return own


def _block_of(k: np.ndarray, kpb: int, world: int,
              offsets: "Optional[np.ndarray]" = None) -> np.ndarray:
    """Block id of each key: the uniform ``k // kpb`` division, or the
    boundary lookup when capability-weighted offsets are in play
    (parallel/balance.plan_block_offsets).  ``offsets=None`` keeps the
    exact integer-division mapping so homogeneous fits stay
    bit-identical to the pre-offsets layout."""
    if offsets is None:
        return np.minimum(k // kpb, world - 1)
    # offsets[b] <= k < offsets[b+1] selects block b; the clip guards
    # stray out-of-range ids the same way the uniform min() does
    return np.minimum(
        np.searchsorted(offsets[1:], k, side="right"), world - 1
    )


def _cat(parts, dtype):
    return np.concatenate(parts) if parts else np.zeros((0,), dtype)


def _redistribute_triples(
    keys: np.ndarray,      # the side's PARTITION ids
    other: np.ndarray,
    ratings: np.ndarray,
    kpb: int,
    world: int,
    owned: List[int],
    offsets: "Optional[np.ndarray]" = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Multi-process edge redistribution by block of ``keys``: returns
    the (keys, other, ratings) triples belonging to THIS process's
    blocks.  Identity when single-process (the caller's triples are
    already the whole dataset).  ``offsets`` switches the uniform block
    mapping to capability-weighted boundaries (see _block_of)."""
    if jax.process_count() == 1:
        return (
            np.asarray(keys, np.int64),
            np.asarray(other, np.int64),
            np.asarray(ratings, np.float32),
        )
    own = _own_mask(world, owned)
    ku, ko, kr = [], [], []
    for k, o, r in _gathered_triple_chunks(keys, other, ratings):
        mine = own[_block_of(k, kpb, world, offsets)]
        ku.append(k[mine])
        ko.append(o[mine])
        kr.append(r[mine])
    return _cat(ku, np.int64), _cat(ko, np.int64), _cat(kr, np.float32)


def _redistribute_triples_2d(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    kpb_u: int,
    kpb_i: int,
    world: int,
    owned: List[int],
):
    """Both keyed edge sets from ONE gathered sweep (the 2-D layout
    needs user-block AND item-block copies; sweeping the global edges
    twice would double the dominant DCN prep traffic).  Returns
    ((users, items, ratings) for my user blocks,
     (items, users, ratings) for my item blocks)."""
    if jax.process_count() == 1:
        u = np.asarray(users, np.int64)
        i = np.asarray(items, np.int64)
        r = np.asarray(ratings, np.float32)
        return (u, i, r), (i, u, r)
    own = _own_mask(world, owned)
    au, ai = ([], [], []), ([], [], [])
    for u, i, r in _gathered_triple_chunks(users, items, ratings):
        mu = own[np.minimum(u // kpb_u, world - 1)]
        au[0].append(u[mu]); au[1].append(i[mu]); au[2].append(r[mu])
        mi = own[np.minimum(i // kpb_i, world - 1)]
        ai[0].append(i[mi]); ai[1].append(u[mi]); ai[2].append(r[mi])
    return (
        (_cat(au[0], np.int64), _cat(au[1], np.int64),
         _cat(au[2], np.float32)),
        (_cat(ai[0], np.int64), _cat(ai[1], np.int64),
         _cat(ai[2], np.float32)),
    )


@dataclasses.dataclass
class StreamedBlockLayouts:
    """Host-resident per-owned-block grouped layouts + the shapes every
    rank agreed on (group sizes / padded group counts are GLOBAL so the
    compiled programs see one static shape)."""

    by_user: Dict[int, tuple]   # block -> (src, conf, valid, dst), padded
    by_item: Dict[int, tuple]
    upb: int
    ipb: int                    # 0 in the replicated layout
    n_items: int
    offsets_u: np.ndarray
    offsets_i: Optional[np.ndarray]
    gc_u: int                   # groups per uploaded chunk, user side
    gc_i: int
    g_u: int                    # padded per-rank group count (== across ranks)
    g_i: int
    item_sharded: bool
    owned: List[int]


def prepare_streamed_block_layouts(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    mesh: Mesh,
    r: int,
    *,
    item_sharded: bool,
    sizes=None,
    offsets=None,
) -> StreamedBlockLayouts:
    """Build the host-side grouped layouts for the streamed block fit.

    Triples are this process's LOCAL edges (multi-process worlds
    redistribute by block first); each owned block gets the same two
    grouped layouts the in-memory block path builds
    (als_block.prepare_grouped_inputs / _2d), except they STAY on host.
    ``sizes`` is the block guard's (p_u, p_i, nnz_global) tuple when the
    guard ran (models/als._block_dispatch) — threaded through so the
    build uses exactly the layout the guard priced, like the in-memory
    preps; otherwise group sizes derive from global stats here.  Either
    way every process compiles identical static shapes.

    ``offsets`` is the capability-weighted user-block layout
    (parallel/balance.block_offsets): ``(world + 1,)`` boundaries that
    replace the uniform ``ceil(n/world)`` split, mirroring the
    in-memory path's als_block.prepare_block_inputs.  ``upb`` becomes
    the widest block and every consumer downstream is boundary-generic
    (block-local rebasing, factor placement, checkpoint resharding).
    Only valid on the replicated-item layout — the 2-D sharded layout's
    identity mapping requires uniform blocks — and ``None`` keeps the
    uniform arithmetic bit-identical."""
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    owned = owned_blocks(mesh, axis)
    if offsets is not None and item_sharded:
        raise ValueError(
            "weighted block offsets require the replicated-item layout "
            "(the 2-D identity mapping needs uniform blocks)"
        )
    # integer ceil, matching the guards' kpb (a float ceil could differ
    # at large n and desynchronize the priced vs built layout)
    kpb_u = max(1, -(-n_users // world))
    if offsets is not None:
        offsets_u = np.asarray(offsets, np.int64)
        upb = max(1, int(np.max(np.diff(offsets_u))))
        off_w = offsets_u
    else:
        upb = kpb_u
        offsets_u = np.minimum(np.arange(world + 1) * kpb_u, n_users)
        off_w = None
    if item_sharded:
        kpb_i = max(1, -(-n_items // world))
        ipb = kpb_i
        offsets_i = np.minimum(np.arange(world + 1) * kpb_i, n_items)
    else:
        kpb_i = ipb = 0
        offsets_i = None

    if sizes is not None:
        p_u, p_i, _ = sizes
    else:
        nnz_global = int(_global_sum([len(users)])[0])
        if item_sharded:
            p_u, p_i = _group_sizes_2d(nnz_global, world, upb, ipb)
        else:
            p_u, p_i = _group_sizes(nnz_global, world, upb, n_items)

    by_user: Dict[int, tuple] = {}
    by_item: Dict[int, tuple] = {}
    if item_sharded:
        # both keyed copies from ONE gathered sweep (the reference's
        # transposed per-rank table, ALSDALImpl.cpp:192-214, as a role
        # swap of the same exchange)
        (uu, ui, ur), (iu, io, ir) = _redistribute_triples_2d(
            users, items, ratings, kpb_u, kpb_i, world, owned
        )
    else:
        uu, ui, ur = _redistribute_triples(
            users, items, ratings, kpb_u, world, owned, off_w
        )
    ublock = _block_of(uu, kpb_u, world, off_w)
    for b in owned:
        sel = ublock == b
        # block-local rebase: the weighted layout subtracts the block's
        # planned boundary, the uniform layout the exact b*kpb product
        # (bit-identical to the pre-offsets arithmetic)
        lo = int(offsets_u[b]) if off_w is not None else b * kpb_u
        # user side: dst = block-local user, src = global item id (the
        # padded-Y row under the identity mapping — als_block
        # prepare_block_inputs note — so the SAME layout serves both
        # item layouts' user updates)
        by_user[b] = build_grouped_edges(
            uu[sel] - lo, ui[sel], ur[sel], upb, p_u
        )
        if not item_sharded:
            # replicated item side: dst = global item, src = LOCAL user
            # (indexes this rank's x block), exactly like
            # als_block.prepare_grouped_inputs
            by_item[b] = build_grouped_edges(
                ui[sel], uu[sel] - lo, ur[sel], n_items, p_i
            )
    if item_sharded:
        iblock = np.minimum(iu // kpb_i, world - 1)
        for b in owned:
            sel = iblock == b
            # dst = block-local item, src = global user id (padded-X row)
            by_item[b] = build_grouped_edges(
                iu[sel] - b * kpb_i, io[sel], ir[sel], ipb, p_i
            )

    # one static shape everywhere: pad group counts to the global max,
    # then to a multiple of the chunk size
    gc_u = groups_per_chunk(p_u, r)
    gc_i = groups_per_chunk(p_i, r)
    gu_local = max((g[0].shape[0] for g in by_user.values()), default=0)
    hi_local = max((g[0].shape[0] for g in by_item.values()), default=0)
    gu, hi = (int(v) for v in _global_max([gu_local, hi_local]))
    g_u = max(gc_u, -(-max(gu, 1) // gc_u) * gc_u)
    g_i = max(gc_i, -(-max(hi, 1) // gc_i) * gc_i)
    i_ndst = ipb if item_sharded else n_items
    for b in owned:
        by_user[b] = _pad_groups(by_user[b], g_u, upb)
        by_item[b] = _pad_groups(by_item[b], g_i, i_ndst)

    return StreamedBlockLayouts(
        by_user=by_user, by_item=by_item, upb=upb, ipb=ipb,
        n_items=n_items, offsets_u=offsets_u, offsets_i=offsets_i,
        gc_u=gc_u, gc_i=gc_i, g_u=g_u, g_i=g_i,
        item_sharded=item_sharded, owned=owned,
    )


def _chunk_placer(mesh: Mesh, axis: str, owned: List[int]):
    """Host-chunk -> block-sharded device array.  The local stack is the
    owned blocks' slices in block order (exactly the addressable portion
    of the P(axis, ...) sharding)."""

    def place(per_block: Dict[int, np.ndarray], sl: slice, world: int):
        local = np.concatenate([per_block[b][sl] for b in owned])
        shape = (world * (local.shape[0] // len(owned)),) + local.shape[1:]
        sharding = NamedSharding(
            mesh, P(axis, *([None] * (local.ndim - 1)))
        )
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, local, shape
            )
        return jax.device_put(local, sharding)

    return place


def _make_programs(mesh: Mesh, axis: str, implicit: bool,
                   policy: str = "f32", solve_kernel: str = "xla"):
    """The four compiled building blocks, registry-cached per (mesh
    fingerprint, axis, implicit, precision policy, solve kernel) —
    utils/progcache — so repeat fits on one mesh reuse the jitted
    closures instead of rebuilding (and re-tracing) them every call;
    within a fit they already cached compilations across chunks and
    iterations."""
    key = (
        progcache.mesh_fingerprint(mesh), axis, implicit, policy,
        solve_kernel,
    )
    return progcache.get_or_build(
        "als_block_stream.programs", key,
        lambda: _build_programs(mesh, axis, implicit, policy, solve_kernel),
    )


def _build_programs(mesh: Mesh, axis: str, implicit: bool,
                    policy: str = "f32", solve_kernel: str = "xla"):
    """Build the four jitted building blocks (cached above)."""
    sh2 = P(axis, None)
    sh1 = P(axis)
    rep = P()

    def accum_local(m, src, conf, valid, gdst, factors, alpha):
        # m block: (n_loc, width); factors: FULL replicated table
        mm = grouped_block_moments(
            src, conf, valid, factors, alpha, implicit, policy
        )
        gb = mm.shape[0]
        return m + jax.ops.segment_sum(
            mm.reshape(gb, -1), gdst, num_segments=m.shape[0],
            indices_are_sorted=True,
        )

    accum_local_fn = jax.jit(
        shard_map(
            accum_local, mesh=mesh,
            in_specs=(sh2, sh2, sh2, sh2, sh1, rep, rep),
            out_specs=sh2, check_vma=False,
        ),
        donate_argnums=(0,),
    )

    def accum_item_rep(m, src, conf, valid, gdst, x_blk, alpha):
        # m block: (1, n_items, width); x_blk: this rank's (upb, r);
        # src = LOCAL user ids
        mm = grouped_block_moments(
            src, conf, valid, x_blk, alpha, implicit, policy
        )
        gb = mm.shape[0]
        return m + jax.ops.segment_sum(
            mm.reshape(gb, -1), gdst, num_segments=m.shape[1],
            indices_are_sorted=True,
        )[None]

    accum_item_rep_fn = jax.jit(
        shard_map(
            accum_item_rep, mesh=mesh,
            in_specs=(P(axis, None, None), sh2, sh2, sh2, sh1, sh2, rep),
            out_specs=P(axis, None, None), check_vma=False,
        ),
        donate_argnums=(0,),
    )

    def solve_local(m, f_full, reg):
        # one side's local solve from summed flat moments (the shared
        # regularized_solve); f_full replicated, padded rows zero so its
        # Gram is exact
        r = f_full.shape[1]
        a, b, n_reg = unpack_flat_moments(m, r)
        eye = jnp.eye(r, dtype=f_full.dtype)
        gram = _factor_gram(f_full, solve_kernel) if implicit else None
        return regularized_solve(
            a, b, n_reg, reg, eye, gram, solve_kernel
        ).astype(f_full.dtype)

    solve_local_fn = jax.jit(
        shard_map(
            solve_local, mesh=mesh, in_specs=(sh2, rep, rep),
            out_specs=sh2, check_vma=False,
        )
    )

    def solve_item_rep(m, x_blk, reg):
        # m block: (1, n_items, width) -> psum = the in-memory path's one
        # item-update allreduce; X Gram psums block Grams (exact: padded
        # rows are zero)
        r = x_blk.shape[1]
        a, b, n_reg = unpack_flat_moments(collective.psum(m[0], axis), r)
        eye = jnp.eye(r, dtype=x_blk.dtype)
        gram = (
            collective.psum(
                _factor_gram(x_blk, solve_kernel),
                axis,
            )
            if implicit else None
        )
        return regularized_solve(
            a, b, n_reg, reg, eye, gram, solve_kernel
        ).astype(x_blk.dtype)

    solve_item_rep_fn = jax.jit(
        shard_map(
            solve_item_rep, mesh=mesh,
            in_specs=(P(axis, None, None), sh2, rep),
            out_specs=rep, check_vma=False,
        )
    )

    replicate = jax.jit(
        lambda a: a, out_shardings=NamedSharding(mesh, P())
    )
    return (accum_local_fn, accum_item_rep_fn, solve_local_fn,
            solve_item_rep_fn, replicate)


def als_block_run_streamed(
    lay: StreamedBlockLayouts,
    x0: jax.Array,   # (world * upb, r) block-sharded user factors
    y0: jax.Array,   # (n_items, r) replicated OR (world * ipb, r) sharded
    max_iter: int,
    reg: float,
    alpha: float,
    mesh: Mesh,
    *,
    implicit: bool,
    timings=None,
    policy: str = "f32",
    checkpoint=None,
) -> Tuple[jax.Array, jax.Array]:
    """Streamed block-parallel ALS over the mesh (both feedback modes,
    both item layouts).  Returns (X blocks, Y) in the same forms as the
    in-memory runners (als_block_run_grouped / _grouped_2d).  Chunk
    placement runs through the prefetch pipeline: each rank's NEXT chunk
    stages onto the mesh while the current chunk's sharded accumulate
    executes (staging is rank-local, so lookahead cannot desynchronize
    the collective launch order — every rank still issues the same
    accum/solve sequence).  The stage/transfer/compute split lands in
    ``timings`` under ``als_iterations/``.

    ``checkpoint`` (utils/checkpoint.py) is the elastic-worlds channel
    for the production topology: every rank writes ITS blocks' valid
    factor rows (global row ids + values) per interval, and restore
    re-buckets whatever shards the relaunched world read onto the LIVE
    block layout through one collective resharding pass
    (parallel/shuffle.reshard_factor_rows) — the full table never
    materializes on one host, whether the world shrank, grew, or merely
    re-blocked."""
    cfg = get_config()
    axis = cfg.data_axis
    world = mesh.shape[axis]
    r = x0.shape[1]
    width = (r + 1) * (r + 2)
    dtype = x0.dtype
    stats = PrefetchStats()
    elapsed = tick()
    place = _chunk_placer(mesh, axis, lay.owned)
    (accum_local_fn, accum_item_rep_fn, solve_local_fn,
     solve_item_rep_fn, replicate) = _make_programs(
        mesh, axis, implicit, policy, resolve_solve_kernel(r, dtype, cfg)
    )
    alpha_j = jnp.asarray(alpha, dtype)
    reg_j = jnp.asarray(reg, dtype)
    sh2 = NamedSharding(mesh, P(axis, None))
    sh3 = NamedSharding(mesh, P(axis, None, None))
    mesh_fp = progcache.mesh_fingerprint(mesh)

    def _zeros_fn(shape, sharding):
        # registry-cached: a fresh jit(lambda) per fit would recompile
        # the (tiny) init program every call
        return progcache.get_or_build(
            "als_block_stream.zeros",
            (mesh_fp, shape, str(np.dtype(dtype))),
            lambda: jax.jit(
                lambda: jnp.zeros(shape, dtype), out_shardings=sharding
            ),
        )

    zeros_u = _zeros_fn((world * lay.upb, width), sh2)
    if lay.item_sharded:
        zeros_i = _zeros_fn((world * lay.ipb, width), sh2)
    else:
        zeros_i = _zeros_fn((world, lay.n_items, width), sh3)

    def stream_side(by_side, g_total, gc, accum, m, *factor_args):
        su = {b: by_side[b][0] for b in lay.owned}
        cu = {b: by_side[b][1] for b in lay.owned}
        vu = {b: by_side[b][2] for b in lay.owned}
        gu = {b: by_side[b][3] for b in lay.owned}

        def stage(lo):
            sl = slice(lo, lo + gc)
            with stats.transfer():
                return (
                    place(su, sl, world),
                    place(cu, sl, world),
                    place(vu, sl, world),
                    place(gu, sl, world),
                )

        step_key = (
            mesh_fp, (gc, su[lay.owned[0]].shape[1] if lay.owned else 0),
            tuple(getattr(m, "shape", ())), implicit, policy,
        )
        pf = Prefetcher(
            range(0, g_total, gc), stage=stage, stats=stats, retire=True
        )
        with pf:
            for su_c, cu_c, vu_c, gu_c in pf:
                with progcache.launch(
                    "als_block_stream.accum", step_key, timings,
                    "als_iterations", record_execute=False,
                ):
                    m = accum(
                        m, su_c, cu_c, vu_c, gu_c, *factor_args, alpha_j
                    )
        return m

    x_blk, y = x0, y0
    start_it = 0
    ckpt_layout = None
    if checkpoint is not None:
        from oap_mllib_tpu.parallel.shuffle import reshard_factor_rows
        from oap_mllib_tpu.utils import checkpoint as ckpt_mod

        ckpt_layout = {
            "offsets_u": [int(v) for v in lay.offsets_u],
            "upb": int(lay.upb),
            "item_sharded": bool(lay.item_sharded),
        }
        if lay.item_sharded:
            ckpt_layout["offsets_i"] = [int(v) for v in lay.offsets_i]
            ckpt_layout["ipb"] = int(lay.ipb)
        resume = checkpoint.restore()
        if resume.found:
            start_it = min(int(resume.step), max_iter)
            # the collective resharding pass runs on EVERY restore (same
            # code path for same-world and resized worlds; values travel
            # as exact bit patterns, so a same-layout round trip is
            # bit-identical)
            nproc, rank = jax.process_count(), jax.process_index()
            ids_u, vals_u = ckpt_mod.sharded_rows_from_result(
                resume, "x", nproc, rank
            )
            x_blk = reshard_factor_rows(
                ids_u, vals_u, mesh, lay.offsets_u, lay.upb
            )
            if lay.item_sharded:
                ids_i, vals_i = ckpt_mod.sharded_rows_from_result(
                    resume, "y", nproc, rank
                )
                y = reshard_factor_rows(
                    ids_i, vals_i, mesh, lay.offsets_i, lay.ipb
                )
            else:
                y = jnp.asarray(
                    ckpt_mod.replicated_from_result(resume, "y", lay.n_items)
                )
            if resume.layout != ckpt_layout:
                checkpoint.mark_resharded()

        def _write_state(step: int) -> None:
            sharded = {
                "x": ckpt_mod.local_factor_rows(
                    x_blk, lay.offsets_u, lay.upb
                )
            }
            arrays = {}
            if lay.item_sharded:
                sharded["y"] = ckpt_mod.local_factor_rows(
                    y, lay.offsets_i, lay.ipb
                )
            else:
                arrays["y"] = np.asarray(y)
            checkpoint.maybe_write(
                step, arrays, sharded=sharded, layout=ckpt_layout,
            )

    for it in range(start_it, max_iter):
        # -- user update: stream by-user chunks against the (gathered)
        # item table, solve locally
        y_full = replicate(y) if lay.item_sharded else y
        m_u = stream_side(
            lay.by_user, lay.g_u, lay.gc_u, accum_local_fn, zeros_u(),
            y_full,
        )
        x_blk = solve_local_fn(m_u, y_full, reg_j)
        # -- item update
        if lay.item_sharded:
            x_full = replicate(x_blk)
            m_i = stream_side(
                lay.by_item, lay.g_i, lay.gc_i, accum_local_fn,
                zeros_i(), x_full,
            )
            y = solve_local_fn(m_i, x_full, reg_j)
        else:
            m_i = stream_side(
                lay.by_item, lay.g_i, lay.gc_i, accum_item_rep_fn,
                zeros_i(), x_blk,
            )
            y = solve_item_rep_fn(m_i, x_blk, reg_j)
        if checkpoint is not None and checkpoint.due(it + 1):
            # the shard pull is a host sync, so gate it on the interval
            # BEFORE materializing the local rows
            _write_state(it + 1)
    # oaplint: disable=stream-host-sync -- end-of-fit barrier: fence async
    jax.block_until_ready((x_blk, y))  # dispatches before timing finalize
    stats.finalize(timings, "als_iterations", elapsed())
    return x_blk, y
