"""Streamed (bounded-HBM) ALS: host-chunked grouped-edge training.

The in-memory grouped path (ops/als_ops.als_run_grouped) keeps BOTH
grouped edge layouts resident in HBM for the whole fit — ~12 bytes x
padded-nnz per side.  That is what bounded the round-3 single-chip proof
to ML-25M.  This module is the ALS leg of the framework's out-of-core
axis (survey §5; ops/stream_ops.py is the K-Means/PCA leg): the grouped
layouts live in HOST memory and each half-iteration walks them through
the device in fixed-shape group blocks, accumulating the per-destination
normal-equation moments in a device-resident flat carry.  Peak HBM is
O(chunk + factors + moments):

- chunk: one (Gc, P) slice of each grouped array (~the same
  _GROUPED_BUDGET_ELEMS bound the in-memory kernel uses for its scan
  blocks — here it bounds the UPLOAD, not just the intermediates);
- factors: (n_users + n_items) x r, resident across the fit;
- moments: (n_dst, (r+1)(r+2)) flat — flat so the carry pads to lane
  tiles once, not per (r+1, r+2) tile (als_ops grouped-path notes).

The price is re-uploading the grouped edges every iteration (the
streamed K-Means/PCA passes re-read their source per pass the same
way); the win is that nnz is bounded by host RAM, not HBM.  Host memory
is O(nnz) — the reference's executors hold their whole partition in RAM
too (OneDAL.scala:92-166); the streaming axis here is host->device.

Math parity: the per-chunk moment kernel IS the in-memory kernel
(als_ops.grouped_block_moments), and the solve consumes the summed
moments identically — streamed-vs-in-memory factors match to fp
tolerance (chunked segment-sums only reorder the additions).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu.data.prefetch import Prefetcher, PrefetchStats
from oap_mllib_tpu.ops.als_ops import (
    _GROUPED_BUDGET_ELEMS,
    _factor_gram,
    grouped_block_moments,
    regularized_solve,
    resolve_solve_kernel,
    unpack_flat_moments,
)
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.timing import tick


def groups_per_chunk(P: int, r: int) -> int:
    """Group rows per uploaded chunk, from the shared live-buffer budget
    (charging XLA's 128-lane padding and the ~3 concurrently-live
    (r+2)-deep intermediates, like als_ops._grouped_block_count)."""
    lanes = max(P, 128)
    return max(1, _GROUPED_BUDGET_ELEMS // (lanes * (r + 2) * 3))


@functools.partial(
    jax.jit, static_argnames=("n_dst", "implicit", "policy"),
    donate_argnums=(0,),
)
def _accum_moments(
    m_flat: jax.Array,  # (n_dst, (r+1)(r+2)) running moments (donated)
    src_g: jax.Array,  # (Gc, P) int32
    conf_g: jax.Array,
    valid_g: jax.Array,
    group_dst: jax.Array,  # (Gc,) int32, sorted
    factors: jax.Array,  # (n_src, r) resident
    alpha: jax.Array,
    n_dst: int,
    implicit: bool,
    policy: str = "f32",
) -> jax.Array:
    m = grouped_block_moments(
        src_g, conf_g, valid_g, factors, alpha, implicit, policy
    )
    gb = m.shape[0]
    width = m.shape[1] * m.shape[2]
    return m_flat + jax.ops.segment_sum(
        m.reshape(gb, width), group_dst, num_segments=n_dst,
        indices_are_sorted=True,
    )


@functools.partial(
    jax.jit, static_argnames=("implicit", "solve_kernel")
)
def _solve_side(
    m_flat: jax.Array, src_factors: jax.Array, reg: jax.Array,
    implicit: bool, solve_kernel: str = "xla",
) -> jax.Array:
    """Factors from the summed flat moments — identical consumption to
    als_ops.als_run_grouped's half step (the shared regularized_solve)."""
    r = src_factors.shape[1]
    a, b, n_reg = unpack_flat_moments(m_flat, r)
    eye = jnp.eye(r, dtype=src_factors.dtype)
    gram = _factor_gram(src_factors, solve_kernel) if implicit else None
    return regularized_solve(a, b, n_reg, reg, eye, gram, solve_kernel).astype(
        src_factors.dtype
    )


def _pad_group_rows(grouped, multiple: int, n_dst: int):
    """Pad a grouped layout's group count to a multiple of the chunk size
    so every uploaded slice has the same static shape (one compile).
    Padding groups carry valid=0 and dst = n_dst - 1 (keeps group_dst
    sorted for the segment-sum's indices_are_sorted contract)."""
    src_g, conf_g, valid_g, gdst = grouped
    G, P = src_g.shape
    pad = (-G) % multiple
    if pad:
        src_g = np.concatenate([src_g, np.zeros((pad, P), np.int32)])
        conf_g = np.concatenate([conf_g, np.zeros((pad, P), np.float32)])
        valid_g = np.concatenate([valid_g, np.zeros((pad, P), np.float32)])
        gdst = np.concatenate([gdst, np.full((pad,), n_dst - 1, np.int32)])
    return src_g, conf_g, valid_g, gdst


def _stage_group_chunk(grouped_host, gc: int, stats: PrefetchStats):
    """Prefetch stage for one side's grouped layout: slice the four host
    arrays at the given offset and issue their device transfers.  Runs in
    the producer thread — chunk N+1 uploads while chunk N's moment
    accumulation executes."""
    src_g, conf_g, valid_g, gdst = grouped_host

    def stage(lo):
        sl = slice(lo, lo + gc)
        with stats.transfer():
            return (
                jnp.asarray(src_g[sl]),
                jnp.asarray(conf_g[sl]),
                jnp.asarray(valid_g[sl]),
                jnp.asarray(gdst[sl]),
            )

    return stage


def _half_update_streamed(
    grouped_host, factors_dev: jax.Array, n_dst: int, gc: int, reg, alpha,
    implicit: bool, stats: Optional[PrefetchStats] = None, timings=None,
    phase: str = "als_iterations", policy: str = "f32",
    solve_kernel: str = "xla",
) -> jax.Array:
    """One side's update: walk the host-resident grouped layout (already
    padded to a multiple of ``gc`` group rows) through the device in
    chunks — prefetched, so each chunk's upload overlaps the previous
    chunk's moment kernel — then solve.  Returns the (n_dst, r)
    factors.  Chunk launches register with the program-cache registry
    (compile wall books under ``<phase>/compile``; steady-state device
    time is the prefetch ``compute`` split)."""
    r = factors_dev.shape[1]
    src_g = grouped_host[0]
    width = (r + 1) * (r + 2)
    m = jnp.zeros((n_dst, width), factors_dev.dtype)
    alpha_j = jnp.asarray(alpha, factors_dev.dtype)
    if stats is None:
        stats = PrefetchStats()
    step_key = (
        progcache.backend_fingerprint(),
        (gc, src_g.shape[1], n_dst, r), str(factors_dev.dtype), implicit,
        policy, solve_kernel,
    )
    pf = Prefetcher(
        range(0, src_g.shape[0], gc),
        stage=_stage_group_chunk(grouped_host, gc, stats),
        stats=stats,
        retire=True,
    )
    with pf:
        for src_c, conf_c, valid_c, gdst_c in pf:
            with progcache.launch(
                "als_stream.accum_moments", step_key, timings, phase,
                record_execute=False,
            ):
                m = _accum_moments(
                    m, src_c, conf_c, valid_c, gdst_c,
                    factors_dev, alpha_j, n_dst, implicit, policy,
                )
    with progcache.launch(
        "als_stream.solve_side", step_key, timings, phase,
        record_execute=False,
    ):
        return _solve_side(
            m, factors_dev, jnp.asarray(reg, factors_dev.dtype), implicit,
            solve_kernel,
        )


def als_run_streamed(
    by_user, by_item,  # host grouped layouts (src, conf, valid, dst)
    x0: np.ndarray,
    y0: np.ndarray,
    n_users: int,
    n_items: int,
    max_iter: int,
    reg: float,
    alpha: float,
    implicit: bool,
    timings=None,
    degraded: bool = False,
    policy: str = "f32",
    checkpoint=None,
    grown_fill=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full streamed ALS loop (both feedback modes), host-driven.

    ``by_user``/``by_item`` are host grouped-edge layouts
    (als_ops.build_grouped_edges outputs); factors stay device-resident
    across iterations, edges are re-uploaded per half-iteration in
    budget-bounded chunks — through the prefetch pipeline, so uploads
    overlap the moment kernels (split recorded in ``timings`` under
    ``als_iterations/``).  Same alternating math as als_run_grouped.
    Chunk padding is hoisted here, ONCE per side — padding inside the
    half-update would re-copy the whole (possibly multi-GB) host layout
    every iteration.  ``degraded`` is the resilience ladder's halved
    -chunk rung (utils/resilience.py): upload blocks shrink to half the
    budgeted group count, halving the per-step live HBM after a device
    OOM — the math is chunk-size-invariant (segment-sums only reorder
    additions).  ``checkpoint`` (utils/checkpoint.py) restores both
    factor tables + the iteration index at entry and writes them every
    ``Config.checkpoint_interval`` iterations — the iterates are exact
    state, so continuation is bit-identical (and survives a degraded
    re-chunk: chunk geometry is deliberately outside the checkpoint
    signature)."""
    from oap_mllib_tpu.utils.resilience import check_finite

    r = np.asarray(x0).shape[1]
    solve_kernel = resolve_solve_kernel(r, np.float32)
    gc_u = groups_per_chunk(by_user[0].shape[1], r)
    gc_i = groups_per_chunk(by_item[0].shape[1], r)
    if degraded:
        gc_u = max(1, gc_u // 2)
        gc_i = max(1, gc_i // 2)
    by_user = _pad_group_rows(by_user, gc_u, n_users)
    by_item = _pad_group_rows(by_item, gc_i, n_items)
    start_it = 0
    if checkpoint is not None:
        from oap_mllib_tpu.utils import checkpoint as ckpt_mod

        resume = checkpoint.restore()
        if resume.found:
            # either storage form: a block-parallel world's sharded
            # factor checkpoint restores here too (this process reads
            # every old shard — a world of one)
            x0 = ckpt_mod.factors_from_result(resume, "x", n_users)
            y0 = ckpt_mod.factors_from_result(resume, "y", n_items)
            if resume.grown and grown_fill is not None:
                # growable-axis warm start (models/als._fill_grown):
                # the grown tail of either table takes the
                # deterministic init, not the restore's zero-fill
                x0, y0 = grown_fill(resume.grown, x0, y0)
            start_it = min(int(resume.step), max_iter)
            if "x" not in resume.arrays:
                checkpoint.mark_resharded()  # sharded state -> one device
    x = jnp.asarray(np.asarray(x0, np.float32))
    y = jnp.asarray(np.asarray(y0, np.float32))
    stats = PrefetchStats()
    elapsed = tick()
    for it in range(start_it, max_iter):
        x = _half_update_streamed(
            by_user, y, n_users, gc_u, reg, alpha, implicit, stats=stats,
            timings=timings, policy=policy, solve_kernel=solve_kernel,
        )
        y = _half_update_streamed(
            by_item, x, n_items, gc_i, reg, alpha, implicit, stats=stats,
            timings=timings, policy=policy, solve_kernel=solve_kernel,
        )
        # iterate-level guardrail (Config.nonfinite_policy): a singular
        # normal-equation solve yields NaN factors that contaminate every
        # later half-iteration — detect at the iteration that produced it
        check_finite(x, f"ALS user factors (streamed iteration {it + 1})")
        check_finite(y, f"ALS item factors (streamed iteration {it + 1})")
        if checkpoint is not None:
            checkpoint.maybe_write(
                it + 1, {"x": np.asarray(x), "y": np.asarray(y)},
            )
    # oaplint: disable=stream-host-sync -- end-of-fit barrier: fence async
    jax.block_until_ready((x, y))  # dispatches before timing finalize
    stats.finalize(timings, "als_iterations", elapsed())
    return np.asarray(x), np.asarray(y)
