"""PCA compute kernels: sharded covariance + eigendecomposition.

Replaces the reference's one-shot distributed PCA
(native/PCADALImpl.cpp): there, inputs are mean-centered on the JVM via
StandardScaler (PCADALImpl.scala:101-106), each rank runs oneDAL
``pca::Distributed<step1Local, svdDense>`` (:63-69), serialized partials are
allgatherv'd (:79-113), and the root's step2Master + finalizeCompute yields
eigenvalues/eigenvectors (:122-153).

TPU-first redesign: the covariance of a row-sharded table is two global
reductions — ``sum_i x_i`` and ``X^T X`` (one (d,n)x(n,d) MXU matmul) —
which GSPMD lowers to psums over the data axis; then
``cov = (Gram - n * mu mu^T) / (n - 1)`` and a replicated d x d ``eigh``.
One jitted program, no serialization, no master rank.  The d < 65535 guard
(reference PCA.scala:103) carries over as the bound on the replicated d x d.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P
from typing import Tuple
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.parallel import collective
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.jax_compat import shard_map


def _cov_prec(precision: str):
    """Map the config tier to the Gram matmul precision.  Unknown values
    raise — a typo must not silently degrade to bf16."""
    try:
        return {
            "highest": lax.Precision.HIGHEST,
            "high": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[precision]
    except KeyError:
        raise ValueError(
            "matmul_precision must be 'highest', 'high', or 'default', "
            f"got {precision!r}"
        ) from None


@functools.partial(jax.jit, static_argnames=("precision", "policy"))
def _covariance_jit(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array,
    precision: str = "highest", policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Sample covariance (d, d) and mean (d,) of the valid rows.

    ``mask`` zeroes padded rows so they drop out of both reductions.
    Two-pass MEAN-CENTERED form at every tier: the one-pass raw-moment
    form ``(X^T X - n mu mu^T) / (n - 1)`` cancels catastrophically for
    large-mean data — measured 4.6e-3 relative at f32-HIGHEST with
    mean=50, unit-variance data (v5e, round 3), outside the 1e-4 parity
    bar — while the centered Gram has no cancellation (1.2e-5 even at
    bf16_3x on the same data).  Centering first also mirrors the
    reference, which runs StandardScaler(withMean) before its kernel
    (PCADALImpl.scala:101-106).  ``precision`` sets the Gram matmul tier
    ("highest" = full f32, the parity contract; "high" = bf16_3x ~2x
    faster within ~1e-5; "default" = bf16, ~1e-4).
    """
    xf = psn.upcast(x)  # colsum/centering reduce in f32 whatever the
    xm = xf * mask[:, None]  # input dtype (no-op for f32/f64 — bit-compat)
    total = jnp.sum(xm, axis=0)  # psum over data axis
    mean = total / n_rows
    xc = (xf - mean[None, :]) * mask[:, None]
    # policy-aware Gram (utils/precision.py): bf16 casts the centered
    # chunk — centering happened in f32 first, so the cast rounds ONCE —
    # and accumulates f32; f32 keeps the legacy tier bit-for-bit
    gram = psn.pdot(xc.T, xc, policy, precision)  # <- MXU
    cov = gram / jnp.maximum(n_rows - 1.0, 1.0)
    # numerical symmetry guard before eigh
    return 0.5 * (cov + cov.T), mean


def use_pallas_gram(kernel_cfg: str, d: int, precision: str, dtype) -> bool:
    """Single source of truth for the PCA Gram kernel dispatch (in-memory
    AND streamed entries, like kmeans_ops.use_pallas_path): the fused
    Pallas moments kernel runs only when configured/preferred AND its
    preconditions hold — TPU backend, one device, one process, f32.
    ``precision`` here is the kernel tier the policy mapped onto
    (utils/precision.kernel_tier), so the bf16 policy's "default" tier
    prices ON Pallas (the ISSUE 9 workaround retirement)."""
    if kernel_cfg not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"pca_kernel must be auto|xla|pallas, got {kernel_cfg!r}"
        )
    from oap_mllib_tpu.ops.pallas.pca_kernel import pallas_gram_preferred

    want = kernel_cfg == "pallas" or (
        kernel_cfg == "auto" and pallas_gram_preferred(d, precision)
    )
    return (
        want
        and jax.default_backend() == "tpu"
        and len(jax.devices()) == 1
        and jax.process_count() == 1
        and np.dtype(dtype) == np.float32
    )


def covariance(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array,
    precision: str = "highest",
    timings=None, phase: str = "covariance",
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Registry-tracked entry over :func:`_covariance_jit` (semantics in
    its docstring): the launch is noted with the program-cache registry
    (utils/progcache) and, when ``timings`` is given, its wall is booked
    under ``<phase>/compile`` (first program) or ``<phase>/execute``.
    ``policy`` is the compute-precision policy (utils/precision.py).

    Dispatches to the fused Pallas moments kernel
    (ops/pallas/pca_kernel.covariance_pallas — same two-pass centered
    numerics, no HBM centered temp) when :func:`use_pallas_gram` says so;
    the kernel's tier IS the mapped policy, so ``policy`` needs no
    separate plumbing there."""
    from oap_mllib_tpu.config import get_config

    if use_pallas_gram(
        get_config().pca_kernel, x.shape[1], precision, x.dtype
    ):
        from oap_mllib_tpu.ops.pallas import autotune
        from oap_mllib_tpu.ops.pallas.pca_kernel import covariance_pallas

        geo = autotune.resolve(
            "pca", autotune.shape_bucket(x.shape[1]), precision
        )
        key = (
            progcache.backend_fingerprint(),
            progcache.array_key(x, mask), precision, "pallas",
            geo["tile_rows"], geo["depth"],
        )
        with progcache.launch("pca.covariance_pallas", key, timings, phase):
            return covariance_pallas(
                x, mask, n_rows, mode=precision,
                tile_rows=geo["tile_rows"], depth=geo["depth"],
            )
    key = (
        progcache.backend_fingerprint(),
        progcache.array_key(x, mask),
        precision, policy,
    )
    with progcache.launch("pca.covariance", key, timings, phase):
        return _covariance_jit(x, mask, n_rows, precision, policy)


def _model_sharded_cov_fn(mesh, dax: str, max_: str, precision: str,
                          policy: str = "f32"):
    """Compiled model-sharded covariance program, cached in the
    process-wide program registry (utils/progcache; formerly a private
    functools.lru_cache) per mesh fingerprint — a fresh jit(shard_map)
    closure per fit would retrace/recompile every time."""
    key = (progcache.mesh_fingerprint(mesh), dax, max_, precision, policy)
    return progcache.get_or_build(
        "pca.covariance_model_sharded", key,
        lambda: _build_model_sharded_cov(mesh, dax, max_, precision,
                                         policy),
    )


def _build_model_sharded_cov(mesh, dax: str, max_: str, precision: str,
                             policy: str = "f32"):
    """Build the jitted model-sharded covariance program (cached above).
    Tier semantics match :func:`covariance`: fast tiers center on device
    before the Gram (no raw-moment cancellation amplification)."""

    def tile_program(x_blk, mask_blk, n):
        xf = psn.upcast(x_blk)
        xm = xf * mask_blk[:, None]
        col_sum = collective.psum(jnp.sum(xm, axis=0), dax)  # (d_loc,)
        mean_loc = col_sum / n
        # centered Gram at every tier (see covariance: the raw-moment
        # form cancels catastrophically for large-mean data)
        xc = (xf - mean_loc[None, :]) * mask_blk[:, None]
        xc_full = collective.all_gather(xc, max_, axis=1, tiled=True)  # (n_loc, d)
        gram_rows = collective.psum(
            psn.pdot(xc.T, xc_full, policy, precision), dax
        )  # (d_loc, d)
        cov_rows = gram_rows / jnp.maximum(n - 1.0, 1.0)
        return cov_rows, mean_loc

    sharded = shard_map(
        tile_program,
        mesh=mesh,
        in_specs=(P(dax, max_), P(dax), P()),
        out_specs=(P(max_, None), P(max_)),
        check_vma=False,
    )

    def run(x, mask, n):
        cov, mean = sharded(x, mask, n)
        # numerical symmetry guard before eigh (cross-tile roundoff)
        return 0.5 * (cov + cov.T), mean

    return jax.jit(run)


def covariance_model_sharded(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array, mesh,
    precision: str = "highest",
    timings=None, phase: str = "covariance",
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array]:
    """Covariance with the (d, d) accumulation sharded over the MODEL axis.

    Mesh-sharded linalg (survey §5): on a (data, model) mesh each device
    holds a (rows/data, d/model) tile.  Per device: all_gather the column
    tiles along the model axis (ICI), one (d_loc, n_loc) x (n_loc, d) MXU
    matmul for this device's Gram ROWS, then psum over the data axis — so
    no device ever materializes more than (d/model, d) of the Gram.  The
    reference cannot shard this dimension at all (oneDAL's step2Master
    holds the full d x d on one node, PCADALImpl.cpp:122-153).

    ``d`` must be a multiple of the model-axis size (callers pad feature
    columns with zeros and demote them with :func:`mark_padded_features`
    before eigh).  Returns (cov (d, d) sharded (model, None), mean (d,)).
    """
    from oap_mllib_tpu.config import get_config

    cfg = get_config()
    # pca_kernel validation must run on EVERY accelerated fit (the
    # covariance/use_pallas_gram invariant): a typo'd value raises here
    # too, even though the model-sharded Gram stays on the shard_map path
    use_pallas_gram(cfg.pca_kernel, x.shape[1], precision, x.dtype)
    fn = _model_sharded_cov_fn(
        mesh, cfg.data_axis, cfg.model_axis, precision, policy
    )
    key = (
        progcache.mesh_fingerprint(mesh),
        progcache.array_key(x, mask), precision, policy,
    )
    with progcache.launch(
        "pca.covariance_model_sharded.run", key, timings, phase
    ):
        return fn(x, mask, n_rows)


@functools.partial(jax.jit, static_argnums=(1,))
def mark_padded_features(cov: jax.Array, d_valid: int) -> jax.Array:
    """Set the diagonal of padded feature dims to -1 so their eigenvalues
    sort strictly BELOW any genuine (>= 0, up to roundoff) eigenvalue.

    Without this, a padded column's zero eigenvalue ties with a genuine
    null-space eigenvalue and eigh may order the padded basis vector into
    the top-k, which would slice to an all-zero component column.  cov is
    block-diagonal afterwards, so genuine eigenvectors keep exact zeros in
    the padded rows.
    """
    d_pad = cov.shape[0]
    idx = jnp.arange(d_valid, d_pad)
    return cov.at[idx, idx].set(-1.0)


@jax.jit
def _eigh_descending_jit(cov: jax.Array) -> Tuple[jax.Array, jax.Array]:
    vals, vecs = jnp.linalg.eigh(cov)  # ascending
    vals = vals[::-1]
    vecs = vecs[:, ::-1]
    return vals, vecs


def eigh_descending(cov: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Eigenvalues (descending) and matching eigenvectors (columns) of a
    symmetric matrix — the finalizeCompute analog (PCADALImpl.cpp:122-153).
    Launches register with the program-cache registry (counters only —
    eigh is the large-d wall and its reuse should show in hit rates).
    """
    progcache.note(
        "pca.eigh",
        (progcache.backend_fingerprint(), progcache.array_key(cov)),
    )
    return _eigh_descending_jit(cov)


@functools.partial(
    jax.jit, static_argnames=("k", "oversample", "iters")
)
def topk_eigh_randomized(
    cov: jax.Array, k: int, oversample: int = 16, iters: int = 8
) -> Tuple[jax.Array, jax.Array]:
    """Top-k eigenpairs of an SPD matrix by randomized subspace
    iteration (Halko/Martinsson/Tropp) — the large-d fast path behind
    ``Config.pca_solver="randomized"``.

    Round-4 kernel attribution showed eigh owns 66% of the large-d PCA
    wall (BASELINE.md row 5: 125 ms of 189 at d=2048) while k is
    typically tens; subspace iteration replaces the O(d^3)
    factorization with (2*iters + 2) MXU matmuls of (d, d) x (d, p),
    p = k + oversample, plus a (d, p) QR per iteration and one tiny
    (p, p) eigh.

    Accuracy contract (why this is NOT the default): convergence is
    gap-dependent for values AND vectors — each Ritz value approaches
    its eigenvalue like (lambda_p / lambda_i)^(2*iters), so decaying
    spectra (the practical PCA regime) match eigh to ~1e-4 at the
    defaults, while a near-flat spectrum (isotropic noise; measured on
    a d=2048 Wishart edge, v5e round 4) is biased low by ~5% at the
    defaults, ~0.3% at iters=16/oversample=64 — and its top-k
    eigenVECTORS are genuinely ill-defined, so no iteration count makes
    them match eigh's.  tests/test_pca.py pins both behaviors.

    Deterministic: the probe uses a fixed PRNG key — same cov, same
    result.  Returns (vals (k,) descending, vecs (d, k))."""
    d = cov.shape[0]
    p = min(d, k + oversample)
    probe = jax.random.normal(jax.random.PRNGKey(0), (d, p), cov.dtype)
    q, _ = jnp.linalg.qr(probe)

    def body(q, _):
        y = psn.pdot(cov, q)
        q_next, _ = jnp.linalg.qr(y)  # re-orthonormalize every step
        return q_next, None

    q, _ = lax.scan(body, q, None, length=iters)
    b = psn.pdot(q.T, psn.pdot(cov, q))
    w, v = jnp.linalg.eigh(0.5 * (b + b.T))  # ascending, (p, p)
    w = w[::-1][:k]
    v = v[:, ::-1][:, :k]
    return w, psn.pdot(q, v)


@jax.jit
def project(x: jax.Array, components: jax.Array) -> jax.Array:
    """Transform rows into the component basis: (n, d) @ (d, k).

    NOTE Spark parity: PCAModel.transform does NOT mean-center before
    projecting (mllib.feature.PCAModel), so neither do we.
    """
    return psn.pdot(x, components)
