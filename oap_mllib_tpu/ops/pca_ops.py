"""PCA compute kernels: sharded covariance + eigendecomposition.

Replaces the reference's one-shot distributed PCA
(native/PCADALImpl.cpp): there, inputs are mean-centered on the JVM via
StandardScaler (PCADALImpl.scala:101-106), each rank runs oneDAL
``pca::Distributed<step1Local, svdDense>`` (:63-69), serialized partials are
allgatherv'd (:79-113), and the root's step2Master + finalizeCompute yields
eigenvalues/eigenvectors (:122-153).

TPU-first redesign: the covariance of a row-sharded table is two global
reductions — ``sum_i x_i`` and ``X^T X`` (one (d,n)x(n,d) MXU matmul) —
which GSPMD lowers to psums over the data axis; then
``cov = (Gram - n * mu mu^T) / (n - 1)`` and a replicated d x d ``eigh``.
One jitted program, no serialization, no master rank.  The d < 65535 guard
(reference PCA.scala:103) carries over as the bound on the replicated d x d.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from typing import Tuple


@jax.jit
def covariance(x: jax.Array, mask: jax.Array, n_rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sample covariance (d, d) and mean (d,) of the valid rows.

    ``mask`` zeroes padded rows so they drop out of both reductions.
    Matches Spark's RowMatrix covariance: (X^T X - n mu mu^T) / (n - 1).
    """
    xm = x * mask[:, None]
    total = jnp.sum(xm, axis=0)  # psum over data axis
    mean = total / n_rows
    # HIGHEST precision: bf16 Gram accumulation cannot hit 1e-4 parity
    gram = jnp.matmul(xm.T, x, precision=lax.Precision.HIGHEST)  # (d, d) <- MXU
    cov = (gram - n_rows * jnp.outer(mean, mean)) / jnp.maximum(n_rows - 1.0, 1.0)
    # numerical symmetry guard before eigh
    return 0.5 * (cov + cov.T), mean


@jax.jit
def eigh_descending(cov: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Eigenvalues (descending) and matching eigenvectors (columns) of a
    symmetric matrix — the finalizeCompute analog (PCADALImpl.cpp:122-153).
    """
    vals, vecs = jnp.linalg.eigh(cov)  # ascending
    vals = vals[::-1]
    vecs = vecs[:, ::-1]
    return vals, vecs


@jax.jit
def project(x: jax.Array, components: jax.Array) -> jax.Array:
    """Transform rows into the component basis: (n, d) @ (d, k).

    NOTE Spark parity: PCAModel.transform does NOT mean-center before
    projecting (mllib.feature.PCAModel), so neither do we.
    """
    return jnp.matmul(x, components, precision=lax.Precision.HIGHEST)
