"""PCA compute kernels: sharded covariance + eigendecomposition.

Replaces the reference's one-shot distributed PCA
(native/PCADALImpl.cpp): there, inputs are mean-centered on the JVM via
StandardScaler (PCADALImpl.scala:101-106), each rank runs oneDAL
``pca::Distributed<step1Local, svdDense>`` (:63-69), serialized partials are
allgatherv'd (:79-113), and the root's step2Master + finalizeCompute yields
eigenvalues/eigenvectors (:122-153).

TPU-first redesign: the covariance of a row-sharded table is two global
reductions — ``sum_i x_i`` and ``X^T X`` (one (d,n)x(n,d) MXU matmul) —
which GSPMD lowers to psums over the data axis; then
``cov = (Gram - n * mu mu^T) / (n - 1)`` and a replicated d x d ``eigh``.
One jitted program, no serialization, no master rank.  The d < 65535 guard
(reference PCA.scala:103) carries over as the bound on the replicated d x d.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from typing import Tuple


@jax.jit
def covariance(x: jax.Array, mask: jax.Array, n_rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sample covariance (d, d) and mean (d,) of the valid rows.

    ``mask`` zeroes padded rows so they drop out of both reductions.
    Matches Spark's RowMatrix covariance: (X^T X - n mu mu^T) / (n - 1).
    """
    xm = x * mask[:, None]
    total = jnp.sum(xm, axis=0)  # psum over data axis
    mean = total / n_rows
    # HIGHEST precision: bf16 Gram accumulation cannot hit 1e-4 parity
    gram = jnp.matmul(xm.T, x, precision=lax.Precision.HIGHEST)  # (d, d) <- MXU
    cov = (gram - n_rows * jnp.outer(mean, mean)) / jnp.maximum(n_rows - 1.0, 1.0)
    # numerical symmetry guard before eigh
    return 0.5 * (cov + cov.T), mean


@functools.lru_cache(maxsize=8)
def _model_sharded_cov_fn(mesh, dax: str, max_: str):
    """Compiled model-sharded covariance program, cached per mesh (a fresh
    jit(shard_map) closure per fit would retrace/recompile every time)."""

    def tile_program(x_blk, mask_blk, n):
        xm = x_blk * mask_blk[:, None]
        col_sum = lax.psum(jnp.sum(xm, axis=0), dax)  # (d_loc,)
        mean_loc = col_sum / n
        mean_full = lax.all_gather(mean_loc, max_, tiled=True)  # (d,)
        x_full = lax.all_gather(xm, max_, axis=1, tiled=True)  # (n_loc, d)
        gram_rows = lax.psum(
            jnp.matmul(xm.T, x_full, precision=lax.Precision.HIGHEST), dax
        )  # (d_loc, d)
        cov_rows = (gram_rows - n * jnp.outer(mean_loc, mean_full)) / jnp.maximum(
            n - 1.0, 1.0
        )
        return cov_rows, mean_loc

    sharded = jax.shard_map(
        tile_program,
        mesh=mesh,
        in_specs=(P(dax, max_), P(dax), P()),
        out_specs=(P(max_, None), P(max_)),
        check_vma=False,
    )

    def run(x, mask, n):
        cov, mean = sharded(x, mask, n)
        # numerical symmetry guard before eigh (cross-tile roundoff)
        return 0.5 * (cov + cov.T), mean

    return jax.jit(run)


def covariance_model_sharded(
    x: jax.Array, mask: jax.Array, n_rows: jax.Array, mesh
) -> Tuple[jax.Array, jax.Array]:
    """Covariance with the (d, d) accumulation sharded over the MODEL axis.

    Mesh-sharded linalg (survey §5): on a (data, model) mesh each device
    holds a (rows/data, d/model) tile.  Per device: all_gather the column
    tiles along the model axis (ICI), one (d_loc, n_loc) x (n_loc, d) MXU
    matmul for this device's Gram ROWS, then psum over the data axis — so
    no device ever materializes more than (d/model, d) of the Gram.  The
    reference cannot shard this dimension at all (oneDAL's step2Master
    holds the full d x d on one node, PCADALImpl.cpp:122-153).

    ``d`` must be a multiple of the model-axis size (callers pad feature
    columns with zeros and demote them with :func:`mark_padded_features`
    before eigh).  Returns (cov (d, d) sharded (model, None), mean (d,)).
    """
    from oap_mllib_tpu.config import get_config

    cfg = get_config()
    return _model_sharded_cov_fn(mesh, cfg.data_axis, cfg.model_axis)(
        x, mask, n_rows
    )


@functools.partial(jax.jit, static_argnums=(1,))
def mark_padded_features(cov: jax.Array, d_valid: int) -> jax.Array:
    """Set the diagonal of padded feature dims to -1 so their eigenvalues
    sort strictly BELOW any genuine (>= 0, up to roundoff) eigenvalue.

    Without this, a padded column's zero eigenvalue ties with a genuine
    null-space eigenvalue and eigh may order the padded basis vector into
    the top-k, which would slice to an all-zero component column.  cov is
    block-diagonal afterwards, so genuine eigenvectors keep exact zeros in
    the padded rows.
    """
    d_pad = cov.shape[0]
    idx = jnp.arange(d_valid, d_pad)
    return cov.at[idx, idx].set(-1.0)


@jax.jit
def eigh_descending(cov: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Eigenvalues (descending) and matching eigenvectors (columns) of a
    symmetric matrix — the finalizeCompute analog (PCADALImpl.cpp:122-153).
    """
    vals, vecs = jnp.linalg.eigh(cov)  # ascending
    vals = vals[::-1]
    vecs = vecs[:, ::-1]
    return vals, vecs


@jax.jit
def project(x: jax.Array, components: jax.Array) -> jax.Array:
    """Transform rows into the component basis: (n, d) @ (d, k).

    NOTE Spark parity: PCAModel.transform does NOT mean-center before
    projecting (mllib.feature.PCAModel), so neither do we.
    """
    return jnp.matmul(x, components, precision=lax.Precision.HIGHEST)
