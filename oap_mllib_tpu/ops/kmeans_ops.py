"""K-Means compute kernels: jitted Lloyd loop + initialization.

Replaces the reference's distributed Lloyd implementation
(native/KMeansDALImpl.cpp): there, each iteration broadcasts serialized
centroids (:49-59), runs oneDAL ``kmeans::Distributed<step1Local>`` per rank
(:70-77), allgathervs partials (:97-99), merges on the root (:101-131), and
the root does a manual per-center convergence test — squared-L2 move <= tol^2
(:135-168) — then broadcasts the converged flag (:213-214).

TPU-first redesign:
- Distances via the matmul identity ``|x|^2 + |c|^2 - 2 x @ c^T`` — the
  O(n*k*d) work lands on the MXU as one (n,d)x(d,k) matmul per iteration.
- Assignment one-hots are contracted back against X with a second matmul
  to get per-cluster sums — also MXU work, no scatters.
- The whole Lloyd loop is one ``lax.while_loop`` inside one jit: convergence
  is decided on device, no host round-trips per iteration (the reference
  pays a JNI + CCL round per phase).
- Cross-device reduction (per-cluster sums/counts/cost over the row-sharded
  table) is expressed as global ``jnp.sum``/matmul; GSPMD lowers it to
  psum over the ``data`` mesh axis.  No root rank: results land replicated.
- Padded rows carry mask weight 0 so they never contribute (survey §2.6
  fixed-shape design note).

Weighted rows are supported natively (``mask`` doubles as a row-weight
vector), which the reference's DAL path cannot do (it falls back to vanilla
Spark when a weight column is set, spark-3.1.1/ml/clustering/KMeans.scala:349-351).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.parallel import collective
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.jax_compat import shard_map


def _prec(precision: str):
    """Map config's matmul_precision to a lax.Precision.

    "highest" (default) keeps full f32 on the MXU via multi-pass
    accumulation — required for the 1e-4 parity contract (survey §7.3
    determinism note).  "high" = bf16_3x sums + bf16 assignment (see
    _assign_prec) — measured within 1e-5 of highest on the parity suite;
    "default" (bf16 everywhere) measured ~1e-3 — outside the bar.
    Unknown values raise — a typo must not silently degrade to bf16."""
    try:
        return {
            "highest": lax.Precision.HIGHEST,
            "high": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[precision]
    except KeyError:
        raise ValueError(
            "matmul_precision must be 'highest', 'high', or 'default', "
            f"got {precision!r}"
        ) from None


def pallas_preferred(d: int, k: int, precision: str) -> bool:
    """Shape/tier rule for kmeans_kernel="auto" (BASELINE.md kernel table,
    measured on v5e): the fused Pallas kernel wins the profiled shapes at
    the f32-accurate tiers (its loop-mode half-score assignment + exact
    -split sums pay 1+2 bf16 passes where XLA "high" pays 3+3, "highest"
    6+6) with one known exception — small n*k at "high" (64k x 64, k=64:
    XLA 0.08 vs Pallas 0.19 ms/iter), accepted as a ~0.1 ms/iter auto-rule
    miss in BASELINE.md rather than special-cased here.

    "default" (= the bf16 compute policy via precision.kernel_tier) now
    prices ON Pallas too — the ISSUE 9 workaround retirement: the old
    rule routed it to XLA's all-bf16 single-pass pipeline, measured
    faster when the kernel's counts still ran as two f32 VPU passes over
    (bn, k); with the counts-as-bf16-matmul rework (see
    kmeans_kernel._make_kernel) the fused kernel's halved HBM traffic
    carries the tier, and dev/profile_kernels.py's fused-vs-unfused
    sweep regenerates the evidence per backend.

    Large k is excluded: the kernel holds the full (k, d) centers AND sums
    blocks in VMEM, so past ~4M padded elements apiece (2 x 16 MB f32)
    Mosaic would fail to place them — those fits stay on the chunked XLA
    path."""
    k_pad = -(-k // 128) * 128
    d_pad = -(-d // 128) * 128
    if k_pad * d_pad > (1 << 22):  # 16 MB per f32 VMEM block
        return False
    return precision in ("highest", "high", "default")


def use_pallas_path(kernel_cfg: str, d: int, k: int, precision: str, dtype) -> bool:
    """Single source of truth for the kernel dispatch (estimator AND
    bench): the fused Pallas kernel runs only when configured/preferred
    AND its preconditions hold — TPU backend, one device, one process,
    f32.  Keeping this in one place prevents the two call sites from
    silently diverging."""
    if kernel_cfg not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"kmeans_kernel must be auto|xla|pallas, got {kernel_cfg!r}"
        )
    want = kernel_cfg == "pallas" or (
        kernel_cfg == "auto" and pallas_preferred(d, k, precision)
    )
    return (
        want
        and jax.default_backend() == "tpu"
        and len(jax.devices()) == 1
        and jax.process_count() == 1
        and np.dtype(dtype) == np.float32
    )


def ring_mode_cfg(cfg=None) -> str:
    """Validated Config.ring_reduction.  Called on EVERY accelerated
    K-Means dispatch — single-device included, where the knob has no
    routing effect — so a typo raises everywhere (the als_item_layout
    contract: it must not surface only once deployed to a mesh)."""
    from oap_mllib_tpu.config import get_config

    cfg = cfg or get_config()
    mode = cfg.ring_reduction
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"ring_reduction must be auto|on|off, got {mode!r}"
        )
    return mode


def ring_enabled(mesh, data_axis: str, cfg=None) -> bool:
    """Resolve Config.ring_reduction for a mesh: the ring-fused moments
    reduction (ops/pallas/ring_reduce) runs by default ("auto"/"on")
    whenever the reduce axis actually has >= 2 devices, and falls back
    cleanly to the psum path below that — the acceptance contract."""
    return ring_mode_cfg(cfg) != "off" and mesh.shape[data_axis] >= 2


def _assign_prec(precision: str) -> str:
    """Precision for the ASSIGNMENT (distance) matmul inside the Lloyd
    loop.  The "high" tier runs it at bf16: argmin is a discrete decision
    — extra mantissa bits only matter at exact Voronoi ties, where either
    choice leaves the objective unchanged (cost is continuous across the
    boundary) — while centroid accuracy is governed by the SUMS matmul,
    which keeps bf16_3x.  Measured on TPU v5e (1M x 256, k=1000, blob
    data): bit-identical centers to dist-at-bf16_3x, 1.65x faster.
    "highest" stays full-f32 end-to-end (the strict parity tier)."""
    return "default" if precision == "high" else precision


def pairwise_sq_dists(
    x: jax.Array, centers: jax.Array, precision: str = "highest",
    policy: str = "f32",
) -> jax.Array:
    """(n, k) squared euclidean distances via the MXU-friendly identity.

    ``policy`` (utils/precision.py) governs the cross matmul: bf16 casts
    both operands (no-op when staging already delivered bf16 chunks) and
    accumulates f32; the squared norms ALWAYS reduce in f32 —
    ``psn.upcast`` is a no-op for f32/f64 inputs, so the default policy
    is bit-compatible with the pre-policy code."""
    xf = psn.upcast(x)
    cf = psn.upcast(centers)
    x_sq = jnp.sum(xf * xf, axis=1, keepdims=True)  # (n, 1)
    c_sq = jnp.sum(cf * cf, axis=1)  # (k,)
    cross = psn.pdot(x, centers.T, policy, precision)  # (n, k)  <- MXU
    d2 = x_sq + c_sq[None, :] - 2.0 * cross
    return jnp.maximum(d2, 0.0)


def assign_clusters(x: jax.Array, centers: jax.Array) -> jax.Array:
    """(n,) argmin cluster ids."""
    return jnp.argmin(pairwise_sq_dists(x, centers), axis=1)


def _accumulate(x, weights, centers, precision: str = "highest",
                need_cost: bool = True, policy: str = "f32"):
    """One assignment pass: per-cluster weighted sums, counts, and cost.

    Returns (sums (k,d), counts (k,), cost scalar).  All reductions are
    global over the row-sharded inputs — GSPMD inserts the psum.

    ``need_cost=False`` is the Lloyd-loop-body mode: cost is dead inside
    the loop (the caller recomputes it at "highest" after convergence), so
    the assignment ranks on the half-score ``|c|^2/2 - x.c`` — argmin is
    invariant to the per-row |x|^2 term — skipping the d2 assembly and the
    min reduction entirely.

    ``policy`` (utils/precision.py): bf16 runs the assignment AND
    centroid-sum matmuls on bf16 operands with f32 accumulation — the
    one-hot/weights/counts/cost side stays f32 (``weights.dtype``), so
    the f32 accumulator contract holds whatever dtype the chunk arrived
    in (streamed bf16 staging included).  The default is bit-compatible
    with the pre-policy code.
    """
    k = centers.shape[0]
    if need_cost:
        d2 = pairwise_sq_dists(
            x, centers, _assign_prec(precision), policy
        )  # (n, k)
        assign = jnp.argmin(d2, axis=1)  # (n,)
        min_d2 = jnp.min(d2, axis=1)  # (n,)
        cost = jnp.sum(min_d2 * weights)
    else:
        cf = psn.upcast(centers)
        c_sq = jnp.sum(cf * cf, axis=1)  # (k,)
        cross = psn.pdot(x, centers.T, policy, _assign_prec(precision))
        assign = jnp.argmin(0.5 * c_sq[None, :] - cross, axis=1)  # (n,)
        cost = jnp.asarray(0.0, weights.dtype)
    one_hot = (
        jax.nn.one_hot(assign, k, dtype=weights.dtype)
        * weights[:, None]
    )  # (n, k) — accum dtype: the bf16 policy must not round counts
    sums = psn.pdot(one_hot.T, x, policy, precision)  # (k, d)  <- MXU
    counts = jnp.sum(one_hot, axis=0)  # (k,)
    return sums, counts, cost


def _accumulate_chunked(x, weights, centers, row_chunks: int,
                        precision: str = "highest", need_cost: bool = True,
                        policy: str = "f32"):
    """Chunked assignment pass: bounds the live (chunk, k) distance/one-hot
    buffers so n*k never materializes in HBM (needed for bench-scale runs
    like 1M x 256 with k=1000, where (n, k) f32 alone is 4 GB).

    NOTE single-chip only for now: the reshape assumes the leading dim can
    be freely split, which conflicts with row-sharding over a mesh; the
    sharded path uses the unchunked accumulate (modest k).
    """
    n = x.shape[0]
    if n % row_chunks != 0:
        raise ValueError(f"rows {n} not divisible by row_chunks={row_chunks}")
    cs = n // row_chunks
    xc = x.reshape(row_chunks, cs, x.shape[1])
    wc = weights.reshape(row_chunks, cs)

    def step(carry, chunk):
        sums, counts, cost = carry
        xi, wi = chunk
        s, c, t = _accumulate(xi, wi, centers, precision, need_cost, policy)
        return (sums + s, counts + c, cost + t), None

    k, d = centers.shape[0], x.shape[1]
    # carries in the ACCUM dtype (weights), not x's: the bf16 policy's
    # per-chunk partials are f32 and must stay f32 across chunks (for
    # the f32/f64 paths weights.dtype == x.dtype — bit-compatible)
    zero = (
        jnp.zeros((k, d), weights.dtype),
        jnp.zeros((k,), weights.dtype),
        jnp.asarray(0.0, weights.dtype),
    )
    (sums, counts, cost), _ = lax.scan(step, zero, (xc, wc))
    return sums, counts, cost


# live-buffer element budget shared by every row-chunking site (training
# accumulate, predict/cost scoring, ALS recommend top-k): 32M f32 = 128 MB
# HBM.  One constant so a device-tier retune cannot leave the inference
# side inconsistent with training.
SCORE_BUDGET_ELEMS = 1 << 25


def rows_per_chunk(*widths: int, budget: int = SCORE_BUDGET_ELEMS) -> int:
    """Rows per scoring chunk such that the SUM of live (rows, width)
    buffers — input chunk + score/distance block — stays within budget.
    Bounding only the widest buffer would let the other grow unbounded
    (e.g. a (rows, d) input chunk at tiny k)."""
    return max(1, budget // max(1, sum(widths)))


def auto_row_chunks(n: int, k: int, budget_elems: int = SCORE_BUDGET_ELEMS) -> int:
    """Pick a chunk count so the live (chunk, k) distance buffer stays
    under ``budget_elems`` (default 32M f32 = 128 MB HBM).

    The budget is a HARD bound now: the count no longer needs to divide
    ``n`` — ``lloyd_run`` pads rows (weight 0) to the next chunk
    multiple.  (Previously an odd / non-power-of-two-divisible ``n``
    silently returned 1 chunk, letting the (n, k) buffer blow straight
    past the budget it exists to enforce.)  The bench shape (1M x 256,
    k=1000) gets 32 chunks, small fits 1 (no scan overhead).
    """
    chunks = 1
    while chunks < max(n, 1) and (-(-n // chunks)) * k > budget_elems:
        chunks *= 2
    return chunks


def _lloyd_loop(accum, moved_reduce, init_centers, max_iter, tol_sq):
    """Shared Lloyd loop skeleton (single-program AND model-sharded paths
    — one definition so convergence/empty-cluster semantics cannot drift).

    Reference semantics (KMeansDALImpl.cpp:135-168): stop when every
    center's squared L2 move <= tol^2, or at max_iter.  Empty clusters
    keep their previous center (Spark MLlib behavior).  ``accum(centers,
    prec)`` returns (sums, counts, cost) for whichever layout the caller
    closed over; ``moved_reduce`` completes the per-center move norm
    (identity, or a psum over the model axis for feature-sharded centers).
    The final cost/counts are re-computed against the returned centers at
    full precision: the fast tiers' distance error is amplified by
    cancellation when clusters are tight, and the user-facing objective
    must not carry it (centers themselves stay ~1e-6 accurate).
    """

    def cond(state):
        _, it, converged = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _ = state
        sums, counts, _ = accum(centers, None)
        safe = counts[:, None] > 0
        new_centers = jnp.where(
            safe, sums / jnp.maximum(counts[:, None], 1e-30), centers
        )
        moved_sq = moved_reduce(jnp.sum((new_centers - centers) ** 2, axis=1))
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged

    init_state = (init_centers, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    centers, n_iter, _ = lax.while_loop(cond, body, init_state)
    _, counts, cost = accum(centers, "highest")
    return centers, n_iter, cost, counts


@functools.partial(
    jax.jit,
    static_argnames=("max_iter", "row_chunks", "precision", "policy"),
)
def _lloyd_run_jit(
    x: jax.Array,
    weights: jax.Array,
    init_centers: jax.Array,
    max_iter: int,
    tol: jax.Array,
    row_chunks: int = 1,
    precision: str = "highest",
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    # rows that don't divide the chunk count pad with weight-0 rows HERE
    # — once per compiled program, outside the while_loop, so the copy
    # cannot re-run per iteration — keeping auto_row_chunks' budget a
    # hard bound for any n (bucketed tables are already divisible and
    # skip this)
    pad = (-x.shape[0]) % row_chunks
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
        weights = jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)]
        )

    def accum(centers, prec):
        # prec None = loop-body mode: no cost (recomputed at "highest" after
        # convergence), half-score assignment.  The final cost pass also
        # drops back to the f32 policy when the table itself is full
        # precision (in-memory fits): the user-facing objective should not
        # carry the fast policy's rounding when exact inputs are at hand —
        # streamed bf16-staged chunks keep the policy (x IS bf16 there).
        p = prec or precision
        need_cost = prec is not None
        pol = (
            "f32" if need_cost and x.dtype != jnp.bfloat16 else policy
        )
        if row_chunks > 1:
            return _accumulate_chunked(
                x, weights, centers, row_chunks, p, need_cost, pol
            )
        return _accumulate(x, weights, centers, p, need_cost, pol)

    return _lloyd_loop(
        accum, lambda m: m, init_centers, max_iter, tol * tol
    )


def lloyd_run(
    x: jax.Array,
    weights: jax.Array,
    init_centers: jax.Array,
    max_iter: int,
    tol: jax.Array,
    row_chunks: int = 1,
    precision: str = "highest",
    timings=None,
    phase: str = "lloyd_loop",
    policy: str = "f32",
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full Lloyd optimization: returns (centers, n_iter, cost, counts).

    Semantics in :func:`_lloyd_loop` (the reference's convergence contract,
    KMeansDALImpl.cpp:135-168).  The launch is registered with the
    program-cache registry (utils/progcache) so fits report how many
    programs they compiled vs reused; ``timings`` (when given) receives
    the ``<phase>/compile`` / ``<phase>/execute`` wall split.  ``policy``
    is the compute-precision policy (utils/precision.py) threaded into
    every matmul of the loop.
    """
    key = (
        progcache.backend_fingerprint(),
        progcache.array_key(x, weights, init_centers),
        max_iter, row_chunks, precision, policy,
    )
    with progcache.launch("kmeans.lloyd_run", key, timings, phase):
        return _lloyd_run_jit(
            x, weights, init_centers, max_iter, tol,
            row_chunks=row_chunks, precision=precision, policy=policy,
        )


def _lloyd_model_sharded_fn(mesh, dax: str, max_: str, max_iter: int,
                            precision: str, policy: str = "f32",
                            ring: bool = False, ring_segments: int = 1):
    """Compiled model-sharded Lloyd program, cached in the process-wide
    program registry (utils/progcache — this function's old private
    functools.lru_cache is the pattern the registry generalizes) per
    (mesh fingerprint, shape-free statics): a fresh jit(shard_map)
    closure per fit would recompile."""
    key = (
        progcache.mesh_fingerprint(mesh), dax, max_, max_iter, precision,
        policy, ring, ring_segments,
    )
    return progcache.get_or_build(
        "kmeans.lloyd_model_sharded", key,
        lambda: _build_lloyd_model_sharded(mesh, dax, max_, max_iter,
                                           precision, policy, ring,
                                           ring_segments),
    )


def _build_lloyd_model_sharded(mesh, dax: str, max_: str, max_iter: int,
                               precision: str, policy: str = "f32",
                               ring: bool = False, ring_segments: int = 1):
    """Build the jitted model-sharded Lloyd program (cached above).

    Mesh-sharded linalg (survey §5): on a (data, model) mesh each device
    holds a (rows/data, d/model) tile of X and a (k, d/model) tile of the
    centroids — the feature axis is split exactly like the model-sharded
    PCA Gram (pca_ops.covariance_model_sharded), so centroid blocks whose
    (k, d) outgrows one chip's HBM spread over the model axis.  Squared
    distances decompose additively over feature blocks, so the assignment
    needs ONE psum of the (n_loc, k) partial distances over the model axis;
    the centroid-sum matmul then stays entirely feature-local (each model
    shard updates its own slice) with a psum over data only.  The reference
    cannot shard this dimension at all (oneDAL centroids are single-node,
    KMeansDALImpl.cpp:101-131).

    ``ring=True`` replaces the three standalone data-axis psums of the
    accumulate (centroid sums, counts, cost) with ONE ring reduction of
    the packed (k, d_loc + 2) moments buffer
    (ops/pallas/ring_reduce.ring_allreduce — remote-DMA kernel on TPU,
    the identical-schedule ppermute program elsewhere); the model-axis
    assignment psum and the convergence-move psum are untouched.
    ``ring_segments`` > 1 splits the packed buffer into that many
    independently-fenced ring reductions (segmented-start epilogue, a
    tuned knob — see ring_allreduce's docstring).
    """
    world = mesh.shape[dax]

    def accum(x_blk, w_blk, c_blk, aprec, sprec, pol, need_cost):
        k = c_blk.shape[0]
        cf = psn.upcast(c_blk)
        c_sq = jnp.sum(cf * cf, axis=1)  # (k,)
        cross = psn.pdot(x_blk, c_blk.T, pol, aprec)  # <- MXU
        if need_cost:
            xf = psn.upcast(x_blk)
            x_sq = jnp.sum(xf * xf, axis=1, keepdims=True)  # (n_loc, 1)
            # one psum carries all three feature-block partials at once
            d2 = collective.psum(x_sq + c_sq[None, :] - 2.0 * cross, max_)
            d2 = jnp.maximum(d2, 0.0)
            assign = jnp.argmin(d2, axis=1)
            min_d2 = jnp.min(d2, axis=1)
        else:
            # loop-body mode: rank on the half-score (argmin-invariant to
            # |x|^2); still ONE psum over the model axis, no d2/min passes
            score = collective.psum(0.5 * c_sq[None, :] - cross, max_)
            assign = jnp.argmin(score, axis=1)
        one_hot = (
            jax.nn.one_hot(assign, k, dtype=w_blk.dtype) * w_blk[:, None]
        )
        sums_part = psn.pdot(one_hot.T, x_blk, pol, sprec)  # (k, d_loc)
        counts_part = jnp.sum(one_hot, axis=0)  # (k,)
        cost_part = (
            jnp.sum(min_d2 * w_blk)
            if need_cost else jnp.asarray(0.0, w_blk.dtype)
        )
        if ring:
            # ONE packed ring reduction instead of three psums: columns
            # [0:d_loc] sums, d_loc counts, d_loc+1 the cost scalar (row
            # 0; zero elsewhere so the sum is exact)
            extra = jnp.zeros((k, 2), sums_part.dtype)
            extra = extra.at[:, 0].set(counts_part)
            if need_cost:
                extra = extra.at[0, 1].set(cost_part)
            from oap_mllib_tpu.ops.pallas.ring_reduce import ring_allreduce

            d_loc = sums_part.shape[1]
            red = ring_allreduce(
                jnp.concatenate([sums_part, extra], axis=1), dax, world,
                segments=ring_segments,
            )
            sums_blk = red[:, :d_loc]
            counts = red[:, d_loc]
            cost = (
                red[0, d_loc + 1]
                if need_cost else jnp.asarray(0.0, w_blk.dtype)
            )
        else:
            sums_blk = collective.psum(sums_part, dax)  # feature-local
            counts = collective.psum(counts_part, dax)
            cost = (
                collective.psum(cost_part, dax)
                if need_cost else jnp.asarray(0.0, w_blk.dtype)
            )
        return sums_blk, counts, cost

    def rank_program(x_blk, w_blk, c0_blk, tol_sq):
        def tile_accum(c_blk, prec):
            if prec == "highest":
                # final cost/counts pass: full precision against the f32
                # table (the in-memory contract — see _lloyd_run_jit)
                return accum(
                    x_blk, w_blk, c_blk, "highest", "highest", "f32", True
                )
            return accum(
                x_blk, w_blk, c_blk, _assign_prec(precision), precision,
                policy, False,
            )

        # per-center move norms are partial over the local feature block —
        # complete them over the model axis before the convergence test
        return _lloyd_loop(
            tile_accum, lambda m: collective.psum(m, max_), c0_blk, max_iter,
            tol_sq,
        )

    from jax.sharding import PartitionSpec as P

    return jax.jit(
        shard_map(
            rank_program,
            mesh=mesh,
            in_specs=(P(dax, max_), P(dax), P(None, max_), P()),
            out_specs=(P(None, max_), P(), P(), P()),
            check_vma=False,
        )
    )


def lloyd_run_model_sharded(
    x: jax.Array,
    weights: jax.Array,
    init_centers: jax.Array,
    max_iter: int,
    tol: jax.Array,
    mesh,
    data_axis: str,
    model_axis: str,
    precision: str = "highest",
    timings=None,
    phase: str = "lloyd_loop",
    policy: str = "f32",
    ring_segments: int = 1,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Lloyd loop with centroids feature-sharded over the MODEL axis.

    Same semantics and return contract as :func:`lloyd_run`.  ``d`` must be
    a multiple of the model-axis size (the estimator zero-pads feature
    columns; zero columns contribute nothing to distances or moves, and
    their centroid entries stay exactly zero).

    The per-pass centroid moments reduce with the ring-fused path by
    default (:func:`ring_enabled`: Config.ring_reduction, >= 2 devices
    on the data axis, f32 — the ring packs/reduces in f32, so the x64
    parity lane keeps the psum path).
    """
    ring = ring_enabled(mesh, data_axis) and np.dtype(x.dtype) == np.float32
    ring_segments = max(1, int(ring_segments)) if ring else 1
    fn = _lloyd_model_sharded_fn(mesh, data_axis, model_axis, max_iter,
                                 precision, policy, ring, ring_segments)
    key = (
        progcache.mesh_fingerprint(mesh),
        progcache.array_key(x, weights),
        np.asarray(init_centers).shape, max_iter, precision, policy, ring,
        ring_segments,
    )
    with progcache.launch("kmeans.lloyd_model_sharded.run", key, timings,
                          phase):
        return fn(x, weights, jnp.asarray(init_centers), tol * tol)


@jax.jit
def total_cost(x: jax.Array, weights: jax.Array, centers: jax.Array) -> jax.Array:
    _, _, cost = _accumulate(x, weights, centers)
    return cost


@jax.jit
def min_sq_dists(x: jax.Array, centers: jax.Array) -> jax.Array:
    return jnp.min(pairwise_sq_dists(x, centers), axis=1)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------
# The reference deliberately reuses Spark's JVM-side init (random or
# k-means||) to produce initial centers before handing off to the native
# loop (spark-3.1.1/ml/clustering/KMeans.scala:388-410).  We implement both
# natively.  Parity is RNG-sensitive, so tests compare converged cost, not
# centers (survey §7.3).


def _to_host(a) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) array to host.

    An unconstrained jit output on a multi-process mesh may come back
    sharded (not fully addressable), in which case np.asarray would raise —
    re-run it through an identity jit with an explicitly replicated output
    first (every process executes the same fetch collectively).
    """
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = a.sharding.mesh
        a = progcache.get_or_build(
            "kmeans.fetch_replicated",
            (progcache.mesh_fingerprint(mesh),),
            lambda: jax.jit(
                lambda v: v,
                out_shardings=NamedSharding(mesh, PartitionSpec()),
            ),
        )(a)
    return np.asarray(a)


def _gather_rows(x, idx: np.ndarray) -> np.ndarray:
    """Fetch x[idx] to host; collective for multi-host global arrays.

    A multi-host sharded jax.Array is not fully addressable, so plain
    indexing cannot run on one host — every process executes the same
    jitted gather with a replicated output instead (all processes call
    init with the same seed, so the gathers agree).
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = x.sharding.mesh
        gathered = progcache.get_or_build(
            "kmeans.gather_rows",
            (progcache.mesh_fingerprint(mesh),),
            lambda: jax.jit(
                lambda a, i: a[i],
                out_shardings=NamedSharding(mesh, PartitionSpec()),
            ),
        )(x, jnp.asarray(idx))
        return np.asarray(gathered)
    return np.asarray(x[idx])


def init_random(
    x, n_valid: int, k: int, seed: int, index_map=None
) -> np.ndarray:
    """Sample k distinct valid rows uniformly (Spark's initRandom analog).

    ``x`` may be a (sharded) jax.Array or ndarray; only the k selected rows
    are gathered/transferred, never the full table.  ``index_map`` converts
    valid-row indices to padded-layout indices (DenseTable.valid_to_padded)
    — without it, multi-host tables would sample mid-array padding rows.
    """
    rng = np.random.default_rng(seed)
    idx = rng.choice(n_valid, size=min(k, n_valid), replace=False)
    if len(idx) < k:  # fewer points than clusters: duplicate (degenerate case)
        idx = np.resize(idx, k)
    if index_map is not None:
        idx = index_map(idx)
    return _gather_rows(x, idx)


def _slot_chunk_size(cap: int, target: int = 1024) -> int:
    """Largest divisor of ``cap`` that is <= target (slot-chunking the
    min-distance update bounds the live (n, chunk) buffer).

    Direct paired-divisor enumeration up to sqrt(cap): every divisor d
    <= sqrt(cap) pairs with cap // d, so scanning the square root covers
    them all — O(sqrt cap) where the old loop scanned all of [1, cap]."""
    if cap <= target:
        return max(cap, 1)
    best = 1
    d = 1
    while d * d <= cap:
        if cap % d == 0:
            if best < d <= target:
                best = d
            q = cap // d
            if best < q <= target:
                best = q
        d += 1
    return best


@functools.partial(jax.jit, static_argnames=("cap", "chunk"))
def _pll_round(x, w, dmin, amin, base_id, key, l, cap, chunk):
    """One k-means|| sampling round, entirely on device.

    Samples each row with probability min(l * cost / phi, 1) (Bahmani
    oversampling; padded rows have w=0 so cost=0 and are never picked),
    scatters the picked rows into a fixed ``cap``-slot buffer via their
    picked-prefix position (overflow beyond cap is dropped — cap is 2x the
    expected pick count), then folds the new slots into the running
    (min-distance, nearest-candidate) state chunk-by-chunk so no (n, cap)
    buffer ever materializes.  All reductions/scatters are global: under a
    row-sharded mesh GSPMD lowers them to psums, so the round is
    multi-host-safe with zero O(n) host transfers (round-1 pulled all n
    distances AND weights to host each round).

    Returns (slots, slot_valid, new_dmin, new_amin, phi).
    """
    cost = dmin * w
    phi = jnp.sum(cost)
    prob = jnp.minimum(l * cost / jnp.maximum(phi, 1e-30), 1.0)
    draws = jax.random.uniform(key, dmin.shape, dtype=dmin.dtype)
    picked = draws < prob
    pos = jnp.cumsum(picked.astype(jnp.int32)) - 1  # global prefix position
    slot_of = jnp.where(picked, pos, cap)  # cap = out-of-bounds -> dropped
    slots = jnp.zeros((cap, x.shape[1]), x.dtype).at[slot_of].add(
        x * picked[:, None].astype(x.dtype), mode="drop"
    )
    slot_valid = jnp.zeros((cap,), x.dtype).at[slot_of].add(
        picked.astype(x.dtype), mode="drop"
    )

    # fold new candidates into (dmin, amin) without an (n, cap) buffer
    q = cap // chunk
    slots_c = slots.reshape(q, chunk, x.shape[1])
    valid_c = slot_valid.reshape(q, chunk)
    bases = base_id + chunk * jnp.arange(q, dtype=jnp.int32)

    def fold(carry, sl):
        dm, am = carry
        s, v, b = sl
        d2 = pairwise_sq_dists(x, s)
        d2 = jnp.where(v[None, :] > 0, d2, jnp.inf)
        cm = jnp.min(d2, axis=1)
        ca = jnp.argmin(d2, axis=1).astype(jnp.int32) + b
        better = cm < dm
        return (jnp.where(better, cm, dm), jnp.where(better, ca, am)), None

    (dmin, amin), _ = lax.scan(fold, (dmin, amin), (slots_c, valid_c, bases))
    return slots, slot_valid, dmin, amin, phi


@functools.partial(jax.jit, static_argnames=("n_cand",))
def _candidate_weights(w, amin, n_cand: int):
    """Total row weight owned by each candidate (global segment-sum)."""
    return jnp.zeros((n_cand,), w.dtype).at[amin].add(w)


def init_kmeans_parallel(
    x_dev: jax.Array,
    weights_dev: jax.Array,
    n_valid: int,
    k: int,
    seed: int,
    init_steps: int = 2,
    index_map=None,
) -> np.ndarray:
    """k-means|| (Bahmani et al.) with oversampling l = 2k, Spark defaults.

    Device-side redesign (round-1 round-tripped all n distances + weights
    to host per round): the candidate set lives in a static-shape device
    buffer (1 + 4k*steps slots — 2x the expected 2k picks per round, so
    overflow-dropping is vanishingly rare), per-round sampling/prefix
    -scatter/min-fold run in one jitted program, and only the <=1+4k*steps
    candidates plus their ownership weights are fetched for the host-side
    weighted k-means++ reduction (Spark runs the same reduction on the
    driver, mllib/clustering/KMeans.scala initKMeansParallel).  Every
    device op is GSPMD-global, so the same code serves multi-host meshes.
    """
    rng = np.random.default_rng(seed)
    n, d = x_dev.shape

    # first center: uniform valid row (index_map: valid -> padded layout)
    first = np.asarray([rng.integers(n_valid)])
    if index_map is not None:
        first = np.asarray(index_map(first))
    c0 = _gather_rows(x_dev, first)  # (1, d)

    l = jnp.asarray(2.0 * k, jnp.float32)  # Spark's oversampling factor
    cap = 4 * k  # per-round slot buffer
    chunk = _slot_chunk_size(cap)
    key = jax.random.PRNGKey(seed)

    # running state: distances/assignments vs candidate 0
    d2_0 = pairwise_sq_dists(x_dev, jnp.asarray(c0))[:, 0]
    dmin = d2_0
    amin = jnp.zeros((n,), jnp.int32)

    all_slots = [np.asarray(c0)]
    all_valid = [np.ones((1,), np.float32)]
    base = 1
    for step in range(init_steps):
        slots, slot_valid, dmin, amin, phi = _pll_round(
            x_dev, weights_dev, dmin, amin,
            jnp.asarray(base, jnp.int32),
            jax.random.fold_in(key, step), l, cap, chunk,
        )
        if float(phi) <= 0.0:
            break
        # small host fetch, re-replicated if GSPMD left the output sharded
        all_slots.append(_to_host(slots))
        all_valid.append(_to_host(slot_valid))
        base += cap

    cand = np.concatenate(all_slots, axis=0)
    valid = np.concatenate(all_valid, axis=0) > 0
    cand_w = _to_host(_candidate_weights(weights_dev, amin, base))[: len(cand)]
    cand, cand_w = cand[valid], cand_w[valid]

    if cand.shape[0] <= k:
        # not enough candidates: top up with random rows
        extra = init_random(
            x_dev, n_valid, k - cand.shape[0] + 1, seed + 1, index_map
        )
        cand = np.concatenate([cand, extra], axis=0)[: max(k, 1)]
        return (
            cand[:k]
            if cand.shape[0] >= k
            else np.resize(cand, (k, cand.shape[1]))
        )

    # weight candidates by how much row weight they own, k-means++ reduce
    return _weighted_kmeans_pp(cand, cand_w, k, rng)


def _weighted_kmeans_pp(points: np.ndarray, weights: np.ndarray, k: int, rng) -> np.ndarray:
    """Host-side weighted k-means++ over the small candidate set."""
    n = points.shape[0]
    total = weights.sum()
    if total <= 0:
        weights = np.ones(n)
        total = float(n)
    centers = [points[rng.choice(n, p=weights / total)]]
    d2 = np.sum((points - centers[0]) ** 2, axis=1)
    for _ in range(1, k):
        p = d2 * weights
        s = p.sum()
        if s <= 0:
            idx = int(rng.integers(n))
        else:
            idx = int(rng.choice(n, p=p / s))
        centers.append(points[idx])
        d2 = np.minimum(d2, np.sum((points - points[idx]) ** 2, axis=1))
    return np.stack(centers)
