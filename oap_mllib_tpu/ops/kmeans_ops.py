"""K-Means compute kernels: jitted Lloyd loop + initialization.

Replaces the reference's distributed Lloyd implementation
(native/KMeansDALImpl.cpp): there, each iteration broadcasts serialized
centroids (:49-59), runs oneDAL ``kmeans::Distributed<step1Local>`` per rank
(:70-77), allgathervs partials (:97-99), merges on the root (:101-131), and
the root does a manual per-center convergence test — squared-L2 move <= tol^2
(:135-168) — then broadcasts the converged flag (:213-214).

TPU-first redesign:
- Distances via the matmul identity ``|x|^2 + |c|^2 - 2 x @ c^T`` — the
  O(n*k*d) work lands on the MXU as one (n,d)x(d,k) matmul per iteration.
- Assignment one-hots are contracted back against X with a second matmul
  to get per-cluster sums — also MXU work, no scatters.
- The whole Lloyd loop is one ``lax.while_loop`` inside one jit: convergence
  is decided on device, no host round-trips per iteration (the reference
  pays a JNI + CCL round per phase).
- Cross-device reduction (per-cluster sums/counts/cost over the row-sharded
  table) is expressed as global ``jnp.sum``/matmul; GSPMD lowers it to
  psum over the ``data`` mesh axis.  No root rank: results land replicated.
- Padded rows carry mask weight 0 so they never contribute (survey §2.6
  fixed-shape design note).

Weighted rows are supported natively (``mask`` doubles as a row-weight
vector), which the reference's DAL path cannot do (it falls back to vanilla
Spark when a weight column is set, spark-3.1.1/ml/clustering/KMeans.scala:349-351).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _prec(precision: str):
    """Map config's matmul_precision to a lax.Precision.

    "highest" (default) keeps full f32 on the MXU via multi-pass
    accumulation — required for the 1e-4 parity contract (survey §7.3
    determinism note).  "high" (bf16_3x) measured 6.6e-5 cost error on TPU
    — inside the 1e-4 bar with ~2x fewer MXU passes; "default" (bf16)
    measured 1e-3 — outside it.  Unknown values raise — a typo must not
    silently degrade to bf16."""
    try:
        return {
            "highest": lax.Precision.HIGHEST,
            "high": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[precision]
    except KeyError:
        raise ValueError(
            "matmul_precision must be 'highest', 'high', or 'default', "
            f"got {precision!r}"
        ) from None


def pairwise_sq_dists(
    x: jax.Array, centers: jax.Array, precision: str = "highest"
) -> jax.Array:
    """(n, k) squared euclidean distances via the MXU-friendly identity."""
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # (n, 1)
    c_sq = jnp.sum(centers * centers, axis=1)  # (k,)
    cross = jnp.matmul(x, centers.T, precision=_prec(precision))  # (n, k)  <- MXU
    d2 = x_sq + c_sq[None, :] - 2.0 * cross
    return jnp.maximum(d2, 0.0)


def assign_clusters(x: jax.Array, centers: jax.Array) -> jax.Array:
    """(n,) argmin cluster ids."""
    return jnp.argmin(pairwise_sq_dists(x, centers), axis=1)


def _accumulate(x, weights, centers, precision: str = "highest"):
    """One assignment pass: per-cluster weighted sums, counts, and cost.

    Returns (sums (k,d), counts (k,), cost scalar).  All reductions are
    global over the row-sharded inputs — GSPMD inserts the psum.
    """
    k = centers.shape[0]
    d2 = pairwise_sq_dists(x, centers, precision)  # (n, k)
    assign = jnp.argmin(d2, axis=1)  # (n,)
    min_d2 = jnp.min(d2, axis=1)  # (n,)
    one_hot = jax.nn.one_hot(assign, k, dtype=x.dtype) * weights[:, None]  # (n, k)
    sums = jnp.matmul(one_hot.T, x, precision=_prec(precision))  # (k, d)  <- MXU
    counts = jnp.sum(one_hot, axis=0)  # (k,)
    cost = jnp.sum(min_d2 * weights)
    return sums, counts, cost


def _accumulate_chunked(x, weights, centers, row_chunks: int, precision: str = "highest"):
    """Chunked assignment pass: bounds the live (chunk, k) distance/one-hot
    buffers so n*k never materializes in HBM (needed for bench-scale runs
    like 1M x 256 with k=1000, where (n, k) f32 alone is 4 GB).

    NOTE single-chip only for now: the reshape assumes the leading dim can
    be freely split, which conflicts with row-sharding over a mesh; the
    sharded path uses the unchunked accumulate (modest k).
    """
    n = x.shape[0]
    if n % row_chunks != 0:
        raise ValueError(f"rows {n} not divisible by row_chunks={row_chunks}")
    cs = n // row_chunks
    xc = x.reshape(row_chunks, cs, x.shape[1])
    wc = weights.reshape(row_chunks, cs)

    def step(carry, chunk):
        sums, counts, cost = carry
        xi, wi = chunk
        s, c, t = _accumulate(xi, wi, centers, precision)
        return (sums + s, counts + c, cost + t), None

    k, d = centers.shape[0], x.shape[1]
    zero = (
        jnp.zeros((k, d), x.dtype),
        jnp.zeros((k,), x.dtype),
        jnp.asarray(0.0, x.dtype),
    )
    (sums, counts, cost), _ = lax.scan(step, zero, (xc, wc))
    return sums, counts, cost


def auto_row_chunks(n: int, k: int, budget_elems: int = 1 << 25) -> int:
    """Pick a chunk count dividing ``n`` so the live (chunk, k) distance
    buffer stays under ``budget_elems`` (default 32M f32 = 128 MB HBM).

    Single-chip sizing for ``_accumulate_chunked``; the bench shape
    (1M x 256, k=1000) gets 32 chunks, small fits get 1 (no scan overhead).
    """
    chunks = 1
    while (n // chunks) * k > budget_elems and n % (chunks * 2) == 0:
        chunks *= 2
    return chunks


@functools.partial(jax.jit, static_argnames=("max_iter", "row_chunks", "precision"))
def lloyd_run(
    x: jax.Array,
    weights: jax.Array,
    init_centers: jax.Array,
    max_iter: int,
    tol: jax.Array,
    row_chunks: int = 1,
    precision: str = "highest",
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Full Lloyd optimization: returns (centers, n_iter, cost, counts).

    Convergence follows the reference semantics (KMeansDALImpl.cpp:135-168):
    stop when every center's squared L2 move <= tol^2, or at max_iter.
    Empty clusters keep their previous center (Spark MLlib behavior).
    The final cost is computed against the returned centers.
    """
    tol_sq = tol * tol

    def accum(centers, prec=precision):
        if row_chunks > 1:
            return _accumulate_chunked(x, weights, centers, row_chunks, prec)
        return _accumulate(x, weights, centers, prec)

    def cond(state):
        _, it, converged, _ = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(converged))

    def body(state):
        centers, it, _, _ = state
        sums, counts, cost = accum(centers)
        safe = counts[:, None] > 0
        new_centers = jnp.where(safe, sums / jnp.maximum(counts[:, None], 1e-30), centers)
        moved_sq = jnp.sum((new_centers - centers) ** 2, axis=1)
        converged = jnp.all(moved_sq <= tol_sq)
        return new_centers, it + 1, converged, cost

    init_state = (
        init_centers,
        jnp.asarray(0, jnp.int32),
        jnp.asarray(False),
        jnp.asarray(0.0, x.dtype),
    )
    centers, n_iter, _, _ = lax.while_loop(cond, body, init_state)
    # cost + weighted cluster sizes w.r.t. final centers (the reference
    # reports the master-step objective for the last completed iteration,
    # KMeansDALImpl.cpp:120-131; counts feed KMeansSummary.cluster_sizes).
    # Always at full precision: the fast tiers' distance error is amplified
    # by cancellation when clusters are tight, and the user-facing
    # objective must not carry it (centers themselves stay ~1e-6 accurate).
    _, counts, cost = accum(centers, "highest")
    return centers, n_iter, cost, counts


@jax.jit
def total_cost(x: jax.Array, weights: jax.Array, centers: jax.Array) -> jax.Array:
    _, _, cost = _accumulate(x, weights, centers)
    return cost


@jax.jit
def min_sq_dists(x: jax.Array, centers: jax.Array) -> jax.Array:
    return jnp.min(pairwise_sq_dists(x, centers), axis=1)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------
# The reference deliberately reuses Spark's JVM-side init (random or
# k-means||) to produce initial centers before handing off to the native
# loop (spark-3.1.1/ml/clustering/KMeans.scala:388-410).  We implement both
# natively.  Parity is RNG-sensitive, so tests compare converged cost, not
# centers (survey §7.3).


def init_random(x, n_valid: int, k: int, seed: int) -> np.ndarray:
    """Sample k distinct valid rows uniformly (Spark's initRandom analog).

    ``x`` may be a (sharded) jax.Array or ndarray; only the k selected rows
    are gathered/transferred, never the full table.
    """
    rng = np.random.default_rng(seed)
    idx = rng.choice(n_valid, size=min(k, n_valid), replace=False)
    if len(idx) < k:  # fewer points than clusters: duplicate (degenerate case)
        idx = np.resize(idx, k)
    return np.asarray(x[idx])


def init_kmeans_parallel(
    x_dev: jax.Array,
    weights_dev: jax.Array,
    n_valid: int,
    k: int,
    seed: int,
    init_steps: int = 2,
) -> np.ndarray:
    """k-means|| (Bahmani et al.) with oversampling l = 2k, Spark defaults.

    The candidate set grows dynamically, which XLA cannot express with
    static shapes — so the round structure runs on host while each round's
    O(n * |C|) distance pass is the jitted device kernel.  The final
    weighted reduction of <= 1 + 2k*steps candidates runs as host-side
    k-means++ (Spark runs the same reduction on the driver,
    mllib/clustering/KMeans.scala initKMeansParallel).
    """
    rng = np.random.default_rng(seed)
    # pick the first center uniformly among valid rows
    first = int(rng.integers(n_valid))
    centers = np.asarray(x_dev[first])[None, :]

    l = 2.0 * k  # Spark's oversampling factor

    for _ in range(init_steps):
        d2 = np.asarray(min_sq_dists(x_dev, jnp.asarray(centers)))
        w = np.asarray(weights_dev)
        d2 = d2 * w  # padded rows have weight 0 -> never sampled
        phi = float(d2.sum())
        if phi <= 0.0:
            break
        prob = np.minimum(l * d2 / phi, 1.0)
        draws = rng.random(d2.shape[0])
        picked = np.nonzero(draws < prob)[0]
        picked = picked[picked < n_valid]
        if picked.size:
            centers = np.concatenate([centers, np.asarray(x_dev[picked])], axis=0)

    if centers.shape[0] <= k:
        # not enough candidates: top up with random rows
        extra = init_random(x_dev, n_valid, k - centers.shape[0] + 1, seed + 1)
        centers = np.concatenate([centers, extra], axis=0)[: max(k, 1)]
        return centers[:k] if centers.shape[0] >= k else np.resize(centers, (k, centers.shape[1]))

    # weight candidates by how many points they own, then k-means++ reduce
    assign = np.asarray(assign_clusters(x_dev, jnp.asarray(centers)))
    w = np.asarray(weights_dev)
    cand_w = np.zeros(centers.shape[0])
    np.add.at(cand_w, assign, w)
    return _weighted_kmeans_pp(centers, cand_w, k, rng)


def _weighted_kmeans_pp(points: np.ndarray, weights: np.ndarray, k: int, rng) -> np.ndarray:
    """Host-side weighted k-means++ over the small candidate set."""
    n = points.shape[0]
    total = weights.sum()
    if total <= 0:
        weights = np.ones(n)
        total = float(n)
    centers = [points[rng.choice(n, p=weights / total)]]
    d2 = np.sum((points - centers[0]) ** 2, axis=1)
    for _ in range(1, k):
        p = d2 * weights
        s = p.sum()
        if s <= 0:
            idx = int(rng.integers(n))
        else:
            idx = int(rng.choice(n, p=p / s))
        centers.append(points[idx])
        d2 = np.minimum(d2, np.sum((points - points[idx]) ** 2, axis=1))
    return np.stack(centers)
