"""Telemetry exporters: JSONL event sink, Prometheus dump, fit reports.

Three consumers, three formats, one source of truth (the span tree +
the metrics registry):

- **JSON-lines sink** — armed by ``Config.telemetry_log`` (env
  ``OAP_MLLIB_TPU_TELEMETRY_LOG``).  Every fit finalization appends one
  record per closed span (type ``"span"``: path, duration, count,
  attrs) followed by a full registry snapshot (type ``"metrics"``), and
  the ONE registered exit hook (:func:`shutdown`, below) appends a
  final flight-recorder drain + snapshot when the process ends.
  Records are rank-tagged and multi-process worlds write per-rank files
  (``<path>.rank<r>`` when the world is larger than one process), so a
  world's files concatenate into one mergeable stream.  Records carry a
  monotonic per-process ``seq`` instead of wall-clock timestamps — the
  deterministic-accounting contract (telemetry/metrics.py).
- **Prometheus text exposition** — :func:`render_prometheus`
  (re-exported from telemetry/metrics.py) for scrapes and CI diffs.
- **Human report** — :func:`report` renders one fit's span tree with
  its per-phase walls, streamed overlap, compile split, progcache and
  resilience counters; with no summary it renders process-wide
  highlights instead (bench.py and dev/profile_kernels.py print it).

Telemetry-off is one falsy-string check per fit (`Config.telemetry_log`
empty -> no file is ever opened).

**The atexit ordering contract (ISSUE 14):** interpreter-exit work used
to race — the sink's final snapshot, the fleet metrics server teardown,
and the flight-recorder drain each hung off their own implicit
lifecycle, so which ran first depended on registration order across
modules.  :func:`shutdown` is now the ONE registered exit hook (oaplint
``atexit-outside-shutdown`` keeps it unique): it drains the flight
recorder into the sink, appends the final metrics snapshot, and stops
the fleet endpoint — in that order, so the last scrape surface outlives
the last record it could be asked about and no recorder tail is lost.
"""

from __future__ import annotations

import atexit
import itertools
import json
import threading
from typing import Any, Dict, List, Optional

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _metrics
from oap_mllib_tpu.telemetry.spans import Span
from oap_mllib_tpu.utils import locktrace

_seq = itertools.count()
# tracked (utils/locktrace.py): the sink lock serializes writers from
# fit threads and the exit hook — a seam the "locks" sanitizer watches
_lock = locktrace.TrackedLock("telemetry.sink", threading.Lock())
_shutdown_registered = False


def _rank() -> int:
    return int(get_config().process_id)


def sink_path() -> Optional[str]:
    """The armed JSONL path for THIS process, or None when telemetry
    logging is off.  Multi-process worlds get a per-rank suffix so
    concurrent writers never interleave inside one file."""
    cfg = get_config()
    path = cfg.telemetry_log
    if not path:
        return None
    if cfg.num_processes > 1:
        return f"{path}.rank{cfg.process_id}"
    return path


def _write_lines(path: str, records: List[Dict[str, Any]]) -> None:
    # the lock EXISTS to serialize appends into one sink file — the
    # file write is the critical section, not an accident of it
    # oaplint: disable=blocking-while-locked -- the sink lock's one job IS serializing this append
    with _lock, open(path, "a") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True, default=str) + "\n")


def register_shutdown() -> None:
    """Register :func:`shutdown` as the process's ONE exit hook
    (idempotent).  Called by the first sink emit and by the fleet
    endpoint arm — whichever exit-sensitive subsystem wakes first."""
    global _shutdown_registered
    if _shutdown_registered:
        return
    _shutdown_registered = True
    atexit.register(shutdown)


def shutdown() -> None:
    """The ordered interpreter-exit sequence (the atexit contract):

    1. drain the flight recorder + append the final metrics snapshot to
       the JSONL sink (one batch, so the tail and the snapshot land
       together and post-mortem tooling sees a complete stream);
    2. stop the fleet metrics endpoint LAST — a scraper can read the
       final state up to the moment the process stops answering.

    Each step is isolated: a failed sink write must not strand the
    server, and a failed teardown must not mask the exit."""
    try:
        _emit_final_snapshot()
    finally:
        from oap_mllib_tpu.telemetry import fleet as _fleet

        _fleet.stop_server()


def _emit_final_snapshot() -> None:
    path = sink_path()
    if path is None:
        return
    from oap_mllib_tpu.telemetry import flightrec

    records: List[Dict[str, Any]] = []
    events = flightrec.drain_new()
    if events:
        records.append({
            "type": "flightrec",
            "final": True,
            "rank": _rank(),
            "seq": next(_seq),
            "events": events,
        })
    records.append({
        "type": "metrics",
        "final": True,
        "rank": _rank(),
        "seq": next(_seq),
        "metrics": _metrics.snapshot(),
    })
    try:
        _write_lines(path, records)
    except OSError:
        pass  # a torn-down filesystem at exit must not mask the real exit


def emit_requests(records: List[Dict[str, Any]]) -> int:
    """Append finalized request-ledger records (serving/reqtrace.py) to
    the sink as ``type: "request"`` lines — rank- and seq-tagged like
    every other record, so dev/oaptrace.py merges them into the same
    per-rank stream.  Returns the number written (0 when the sink is
    off; an OSError is swallowed — the sink is a diagnosis channel,
    never a liveness one)."""
    path = sink_path()
    if path is None or not records:
        return 0
    register_shutdown()
    rank = _rank()
    out = [
        dict(rec, type="request", rank=rank, seq=next(_seq))
        for rec in records
    ]
    try:
        _write_lines(path, out)
    except OSError:
        return 0
    return len(out)


def emit_fit(root: Span) -> None:
    """Append one record per span in ``root``'s tree (depth-first) plus
    a registry snapshot — the per-fit JSONL batch.  No-op when the sink
    is off (one config-string check)."""
    path = sink_path()
    if path is None:
        return
    register_shutdown()
    rank = _rank()
    records: List[Dict[str, Any]] = []
    for span_path, sp in root.walk():
        rec: Dict[str, Any] = {
            "type": "span",
            "fit": root.name,
            "path": span_path,
            "name": sp.name,
            "duration_s": sp.duration_s,
            "count": sp.count,
            "rank": rank,
            "seq": next(_seq),
        }
        if sp.attrs:
            rec["attrs"] = sp.attrs
        records.append(rec)
    # flight-recorder drain (telemetry/flightrec.py): the events recorded
    # since the last fit's drain ride the sink as one batch, so
    # dev/oaptrace.py can rebuild a real per-rank timeline (span
    # open/close walls + collective fingerprints) and align ranks
    from oap_mllib_tpu.telemetry import flightrec

    events = flightrec.drain_new()
    if events:
        records.append({
            "type": "flightrec",
            "fit": root.name,
            "rank": rank,
            "seq": next(_seq),
            "events": events,
        })
    records.append({
        "type": "metrics",
        "fit": root.name,
        "rank": rank,
        "seq": next(_seq),
        "metrics": _metrics.snapshot(),
    })
    _write_lines(path, records)


# -- fit-summary attachment ---------------------------------------------------


def _summary_get(summary, key: str):
    if summary is None:
        return None
    if isinstance(summary, dict):
        return summary.get(key)
    return getattr(summary, key, None)


def finalize_fit(summary) -> None:
    """Close out one fit's telemetry: fill the root span's wall (sum of
    its top-level phases when the fit body was not itself timed), attach
    ``summary["telemetry"]`` = ``{fit, rank, spans, metrics}`` (dict
    summaries get the key, object summaries the attribute — the
    ``resilience.merge_stats`` convention), and flush the JSONL batch
    when the sink is armed.  Estimators call this once per fit at their
    outermost accelerated return."""
    timings = _summary_get(summary, "timings")
    if timings is None or summary is None:
        return
    # sanitizer fit-boundary hook (utils/sanitizers.py): attach the armed
    # set + the fit's collective fingerprint, and cross-check the
    # fingerprint across ranks — the backstop that converts a TAIL
    # divergence (extra collectives after the last common op) into a
    # diagnostic at the fit boundary.  One config-string check when off.
    from oap_mllib_tpu.utils import sanitizers as _san

    _san.finalize_fit_sanitizers(summary)
    root = timings.root
    if root.count == 0:
        root.duration_s = sum(c.duration_s for c in root.children)
    # fleet fit-boundary hook (telemetry/fleet.py): land the fleet block
    # + fleet span attrs, refresh /healthz state, and (metrics_port
    # armed) make sure the live endpoint is up.  One config check each
    # when the control plane is disarmed.
    from oap_mllib_tpu.telemetry import fleet as _fleet

    _fleet.finalize_fit(summary, root)
    # balance fit-boundary hook (parallel/balance.py, ISSUE 15): land
    # the ``balance`` block (plan origin/weights/extents + the re-plan
    # decision trail + any supervisor hint) and a ``balance`` child
    # span, then reset the controller's per-fit state.  One None-check
    # when no plan is active.
    from oap_mllib_tpu.parallel import balance as _balance

    _balance.finalize_fit(summary, root)
    _metrics.counter(
        "oap_fit_total", {"fit": root.name},
        help="Completed fits by root span name",
    ).inc()
    _metrics.histogram(
        "oap_fit_seconds", {"fit": root.name},
        help="Fit wall per root span",
    ).observe(root.duration_s)
    payload = {
        "fit": root.name,
        "rank": _rank(),
        "spans": root.as_dict(),
        "metrics": _metrics.snapshot(),
    }
    if isinstance(summary, dict):
        summary["telemetry"] = payload
    else:
        summary.telemetry = payload
    emit_fit(root)


# -- human-readable report ----------------------------------------------------


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.1f} ms" if v < 1.0 else f"{v:.3f} s"


def _span_lines(sp: Span, depth: int, out: List[str]) -> None:
    pad = "  " * depth
    extra = ""
    if sp.count > 1:
        extra += f"  x{sp.count}"
    coll = sp.attrs.get("collectives")
    if coll:
        ops = sum(c["ops"] for c in coll.values())
        byt = sum(c["bytes"] for c in coll.values())
        extra += f"  [collectives: {ops} ops, {byt} B]"
    out.append(f"{pad}{sp.name:<24s} {_fmt_s(sp.duration_s):>10s}{extra}")
    for c in sp.children:
        _span_lines(c, depth + 1, out)


def report(summary=None) -> str:
    """Render a per-fit telemetry report (span tree + the counters that
    matter), or — with no summary — a process-wide metrics digest."""
    if summary is None:
        return _process_report()
    timings = _summary_get(summary, "timings")
    lines: List[str] = []
    if timings is not None:
        root = timings.root
        if root.count == 0:
            root.duration_s = sum(c.duration_s for c in root.children)
        lines.append(f"== telemetry: {root.name} ==")
        _span_lines(root, 0, lines)
        for phase in [c.name for c in root.children]:
            eff = timings.overlap_efficiency(phase)
            if eff is not None:
                lines.append(
                    f"  {phase}: overlap efficiency "
                    f"{eff:.1%} (staging hidden behind compute)"
                )
            split = timings.compile_split(phase)
            if split is not None:
                lines.append(
                    f"  {phase}: compile {_fmt_s(split['compile'])}, "
                    f"execute {_fmt_s(split['execute'])}"
                )
    pc = _summary_get(summary, "progcache")
    if pc:
        rate = pc.get("hit_rate")
        lines.append(
            f"  progcache: {pc.get('hits', 0)} hits / "
            f"{pc.get('misses', 0)} misses"
            + (f" ({rate:.0%} hit rate)" if rate is not None else "")
        )
    rs = _summary_get(summary, "resilience")
    if rs and (rs.get("faults") or rs.get("retries")):
        lines.append(
            f"  resilience: {rs.get('faults', 0)} faults, "
            f"{rs.get('retries', 0)} retries, "
            f"{rs.get('degradations', 0)} degradations "
            f"({rs.get('backoff_s', 0.0):.2f}s backoff)"
        )
    return "\n".join(lines)


def _series_total(snap: Dict[str, Any], name: str) -> float:
    series = snap.get(name, {})
    total = 0.0
    for v in series.values():
        total += v["sum"] if isinstance(v, dict) else v
    return total


def _process_report() -> str:
    snap = _metrics.snapshot()
    lines = ["== telemetry: process metrics =="]
    rows = [
        ("fits completed", _series_total(snap, "oap_fit_total"), "d"),
        ("XLA compiles", _series_total(snap, "oap_xla_compiles_total"), "d"),
        ("XLA compile wall",
         _series_total(snap, "oap_xla_compile_seconds_total"), "s"),
        ("progcache hits", _series_total(snap, "oap_progcache_hits_total"), "d"),
        ("progcache misses",
         _series_total(snap, "oap_progcache_misses_total"), "d"),
        ("collective ops", _series_total(snap, "oap_collective_ops_total"), "d"),
        ("collective bytes",
         _series_total(snap, "oap_collective_bytes_total"), "d"),
        ("streamed chunks", _series_total(snap, "oap_prefetch_chunks_total"), "d"),
        ("streamed rows", _series_total(snap, "oap_stream_rows_total"), "d"),
        ("bytes staged", _series_total(snap, "oap_stream_bytes_staged_total"), "d"),
        ("resilience faults",
         _series_total(snap, "oap_resilience_faults_total"), "d"),
        ("serve requests", _series_total(snap, "oap_serve_requests_total"), "d"),
        ("serve batches", _series_total(snap, "oap_serve_batches_total"), "d"),
    ]
    for label, v, kind in rows:
        val = _fmt_s(v) if kind == "s" else str(int(v))
        lines.append(f"  {label:<20s} {val}")
    # the serving summary block (registry/batcher/sweep totals + p50/p99
    # latency from the factor-4 log-bucket histogram) when the plane
    # answered anything this process lifetime
    if _series_total(snap, "oap_serve_requests_total"):
        from oap_mllib_tpu.serving.registry import serving_summary

        sv = serving_summary()
        lines.append(
            f"  serving: {sv['requests']} requests / {sv['batches']} "
            f"batches, {sv['pad_rows']} pad rows, p50 "
            f"{_fmt_s(sv.get('latency_p50_s', 0.0))}, p99 "
            f"{_fmt_s(sv.get('latency_p99_s', 0.0))}"
        )
    return "\n".join(lines)
