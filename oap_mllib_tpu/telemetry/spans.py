"""Span-tree tracing: the storage layer under every fit's phase timings.

PRs 1-3 each grew a flat stats object (``Timings`` record list,
``PrefetchStats``, progcache counters, ``ResilienceStats``) with no
shared model.  This module is the shared model's skeleton: a fit is a
tree of named :class:`Span` nodes — the root is the fit itself
(``kmeans.fit``, ``pca.fit``, ``als.fit``), its children are the phases
the estimators already time (``table_convert``, ``init_centers``,
``lloyd_loop``, ...), and *their* children are the per-pass splits the
streamed pipeline records (``stage``/``transfer``/``compute``) and the
program-cache launch attribution (``compile``/``execute``).

``utils/timing.Timings`` is now a **view** over this tree — its
``as_dict``/``subphases``/``overlap_efficiency``/``compile_split``
accessors return exactly what the flat record list returned, so every
existing caller and test keeps working — and the tree itself is what the
exporters (telemetry/export.py) serialize.

Clocks are monotonic only (``time.perf_counter``): span durations and
orderings are deterministic accounting, never wall-clock timestamps.

A thread-local *active span* stack lets deeper layers attach to whatever
phase is running without threading a handle through every signature —
the collective facade (parallel/collective.py) books its per-op bytes
and dispatch wall onto ``current_span()``.  When a ``jax.profiler``
trace is active (utils/profiling.py), entering a span also emits a
``jax.profiler.TraceAnnotation`` so the same names line up in
TensorBoard/XProf; with no trace running the annotation is skipped
behind one module-level bool — the telemetry-off cheap-guard contract.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

_SEP = "/"


class Span:
    """One named node in a fit's span tree.

    ``duration_s`` accumulates across repeated entries of the same path
    (streamed passes re-enter their phase once per pass — the flat
    ``Timings.as_dict`` summed duplicate phases, the tree accumulates on
    the node, same totals).  ``count`` is the number of explicit
    recordings; implicitly-created path containers keep ``count == 0``
    and are excluded from the flat views, matching the old record list
    (which only ever held explicitly-added phases).
    """

    __slots__ = ("name", "duration_s", "count", "attrs", "children")

    def __init__(self, name: str):
        self.name = name
        self.duration_s = 0.0
        self.count = 0
        self.attrs: Dict[str, Any] = {}
        self.children: List["Span"] = []

    def child(self, name: str) -> "Span":
        """Find-or-create the child span ``name`` (first match wins, so
        repeated phases accumulate onto one node in first-seen order)."""
        for c in self.children:
            if c.name == name:
                return c
        c = Span(name)
        self.children.append(c)
        return c

    def node(self, path: str) -> "Span":
        """Find-or-create the descendant at ``a/b/c``-style ``path``."""
        n = self
        for part in path.split(_SEP):
            n = n.child(part)
        return n

    def record(self, seconds: float) -> None:
        self.duration_s += seconds
        self.count += 1

    def note_collective(self, op: str, nbytes: int, dispatch_s: float) -> None:
        """Accumulate one collective dispatch onto this span's attributes
        (parallel/collective.py calls this on ``current_span()``)."""
        per = self.attrs.setdefault("collectives", {}).setdefault(
            op, {"ops": 0, "bytes": 0, "dispatch_s": 0.0}
        )
        per["ops"] += 1
        per["bytes"] += int(nbytes)
        per["dispatch_s"] += float(dispatch_s)

    # -- flat views (the Timings compatibility surface) ----------------------

    def flat(self) -> Dict[str, float]:
        """``{path: seconds}`` over explicitly-recorded descendants, in
        first-recorded order — exactly the old ``Timings.as_dict``."""
        out: Dict[str, float] = {}
        stack = [("", c) for c in reversed(self.children)]
        while stack:
            prefix, n = stack.pop()
            path = prefix + n.name
            if n.count > 0:
                out[path] = out.get(path, 0.0) + n.duration_s
            stack.extend(
                (path + _SEP, c) for c in reversed(n.children)
            )
        return out

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready tree (exporters; ``summary["telemetry"]["spans"]``)."""
        out: Dict[str, Any] = {
            "name": self.name,
            "duration_s": self.duration_s,
            "count": self.count,
        }
        if self.attrs:
            out["attrs"] = self.attrs
        if self.children:
            out["children"] = [c.as_dict() for c in self.children]
        return out

    def walk(self, prefix: str = ""):
        """Yield ``(path, span)`` depth-first, self included."""
        path = prefix + self.name
        yield path, self
        for c in self.children:
            yield from c.walk(path + _SEP)

    def __repr__(self) -> str:
        return (
            f"Span({self.name!r}, {self.duration_s:.3f}s, "
            f"children={len(self.children)})"
        )


# -- thread-local active-span stack ------------------------------------------

_tls = threading.local()


def current_span() -> Optional[Span]:
    """The innermost span currently entered on THIS thread, or None.
    Deeper layers (collectives) attach measurements here without a
    handle threaded through the call chain."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def enter(span: Span, annotate: bool = True):
    """Time one entry of ``span``: push it as the thread's active span,
    record the monotonic wall on exit, and — only when a jax.profiler
    trace is running (one bool check) — emit a TraceAnnotation so the
    span shows up on the XProf timeline under the same name.  With the
    flight recorder armed (telemetry/flightrec.py — one config check
    when off), span open/close land in the event ring so post-mortems
    and merged timelines see which phases were in flight."""
    from oap_mllib_tpu.telemetry import flightrec

    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(span)
    ann = None
    if annotate:
        from oap_mllib_tpu.utils import profiling

        if profiling.trace_active():
            import jax

            ann = jax.profiler.TraceAnnotation(span.name)
            ann.__enter__()
    if flightrec.enabled():
        flightrec.record("span_open", span.name)
    t0 = time.perf_counter()
    try:
        yield span
    finally:
        dt = time.perf_counter() - t0
        span.record(dt)
        if flightrec.enabled():
            flightrec.record("span_close", span.name, f"{dt:.6f}s")
        if ann is not None:
            ann.__exit__(None, None, None)
        stack.pop()
