"""Flight recorder: a constant-memory per-rank ring buffer of recent
events, for post-mortems.

Crash records (utils/recovery.py) and ``CollectiveTimeoutError``
diagnoses carry only a *final* snapshot — what the process looked like
at the instant it died.  The question an operator actually asks is
"what happened in the five seconds *before* the timeout": which spans
were open, which collective dispatched last, which rank retried, when
the last checkpoint committed.  This module answers it with the black-
box pattern: a fixed-slot ring buffer (``Config.flight_recorder`` = slot
count, 0 = off) that every instrumented seam appends one tiny event to:

- ``span_open`` / ``span_close`` — telemetry/spans.enter
- ``collective`` — the eager facade (parallel/collective.py), the
  host-mediated reductions and the streamed ring reduction
  (ops/stream_ops.py)
- ``fault`` / ``retry`` / ``degrade`` — utils/resilience.py
- ``ckpt_commit`` — utils/checkpoint.py manifest flips
- ``crash`` — utils/recovery.write_crash_record
- ``serve`` — traffic-plane lifecycle instants (shed / retry / poison
  / brownout / drain / release — serving/traffic.py, serving/ha.py)
- ``request`` — one event per SAMPLED finalized request ledger
  (serving/reqtrace.py: outcome, wall, retries)
- ``ring_hop`` — per-rotation stamps of the sharded sweep's ring
  schedule (serving/sweep.py; dev/oaptrace.py draws cross-replica
  flow arrows from them)

Each event is ``(seq, t, tid, kind, name, detail)``: ``seq`` is a
process-lifetime monotonic counter (it keeps counting across ring
wrap-around, so two ranks' recorders can be merged and diffed by seq),
``t`` is the monotonic clock (``time.perf_counter`` — comparable within
a process, aligned ACROSS ranks by dev/oaptrace.py via the collective
event sequence).  Memory is constant by construction: the ring is
preallocated at arm time and old events are overwritten in place.

Off (the default) every seam pays one config check; armed, an append is
a lock + tuple store (budget-tested in tests/test_flightrec.py).  The
tail rides crash records (``flight_recorder`` field, schema v2) and the
JSONL telemetry sink (``type: "flightrec"`` records), where
dev/oaptrace.py turns it into a merged cross-rank timeline.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from oap_mllib_tpu.config import get_config

# how many trailing events ride a crash record (the post-mortem window;
# recorders smaller than this dump their whole ring)
CRASH_TAIL_EVENTS = 64

_FIELDS = ("seq", "t", "tid", "kind", "name", "detail")


class FlightRecorder:
    """Fixed-slot event ring.  ``seq`` is monotonic across wrap-around;
    slot ``seq % slots`` holds the event, so the newest ``slots`` events
    are always resident and nothing ever grows."""

    __slots__ = ("slots", "_buf", "_seq", "_lock")

    def __init__(self, slots: int):
        self.slots = int(slots)
        self._buf: List[Optional[tuple]] = [None] * self.slots
        self._seq = 0
        self._lock = threading.Lock()

    def record(self, kind: str, name: str, detail: str = "") -> int:
        t = time.perf_counter()
        tid = threading.get_ident()
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._buf[seq % self.slots] = (seq, t, tid, kind, name, detail)
        return seq

    def next_seq(self) -> int:
        with self._lock:
            return self._seq

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The newest ``n`` events (all resident events when None), in
        seq order, as JSON-ready dicts."""
        with self._lock:
            events = sorted(e for e in self._buf if e is not None)
        if n is not None:
            events = events[-n:]
        return [dict(zip(_FIELDS, e)) for e in events]


# -- module-level recorder (per-process singleton, sized by config) -----------

_lock = threading.Lock()
_rec: Optional[FlightRecorder] = None
_drained_through = 0  # JSONL sink high-water mark (drain_new)


def slots_cfg(cfg=None) -> int:
    """Validated ``Config.flight_recorder`` — negative must raise, not
    silently disarm (the kmeans_kernel/fault_spec contract)."""
    cfg = cfg or get_config()
    slots = int(cfg.flight_recorder)
    if slots < 0:
        raise ValueError(
            f"flight_recorder must be >= 0 event slots (0 = off), "
            f"got {slots}"
        )
    return slots


def enabled() -> bool:
    """One config check — the off-path cost at every recording seam."""
    return get_config().flight_recorder != 0


def _recorder() -> Optional[FlightRecorder]:
    """The armed recorder, (re)built when the configured slot count
    changes; None when off.  Seq restarts on a resize — resizing
    mid-flight is a test-only move."""
    global _rec
    slots = slots_cfg()
    if slots == 0:
        return None
    rec = _rec
    if rec is None or rec.slots != slots:
        with _lock:
            if _rec is None or _rec.slots != slots:
                _rec = FlightRecorder(slots)
            rec = _rec
    return rec


def record(kind: str, name: str, detail: str = "") -> Optional[int]:
    """Append one event; returns its seq, or None when the recorder is
    off (one config check).  Never raises on a well-formed call — the
    recorder is a diagnosis channel, not a liveness dependency."""
    rec = _recorder()
    if rec is None:
        return None
    return rec.record(kind, name, detail)


def last_seq() -> int:
    """Seq of the newest recorded event, or -1 (off / nothing yet)."""
    rec = _rec if enabled() else None
    if rec is None:
        return -1
    return rec.next_seq() - 1


def tail(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The newest ``n`` resident events ([] when off) — what crash
    records embed (``CRASH_TAIL_EVENTS`` by default)."""
    rec = _rec if enabled() else None
    if rec is None:
        return []
    return rec.tail(n)


def drain_new() -> List[Dict[str, Any]]:
    """Events recorded since the last drain (the JSONL sink's cursor):
    each fit finalization emits only its own window, so concatenated
    sink files never repeat events.  Events that wrapped out of the
    ring between drains are gone — the constant-memory contract."""
    global _drained_through
    rec = _rec if enabled() else None
    if rec is None:
        return []
    with _lock:
        mark = _drained_through
        events = [e for e in rec.tail() if e["seq"] >= mark]
        _drained_through = rec.next_seq()
    return events


def _reset_for_tests() -> None:
    global _rec, _drained_through
    with _lock:
        _rec = None
        _drained_through = 0
