"""Unified telemetry: span-tree tracing, metrics registry, exporters.

The one coherent observability layer the scattered per-PR stats objects
grew into (ISSUE 4): ``spans`` is the storage under every fit's
``Timings`` (utils/timing.py is now a view over it), ``metrics`` is the
process-wide counter/gauge/histogram registry every subsystem feeds, and
``export`` turns both into a JSONL event stream, a Prometheus dump, and
a human per-fit report.

Entry points::

    from oap_mllib_tpu import telemetry

    model = KMeans(k=8).fit(x)
    print(telemetry.report(model.summary))        # per-fit span tree
    print(telemetry.render_prometheus())          # scrapeable registry
    model.summary.telemetry["spans"]              # the raw tree
    model.summary.telemetry["metrics"]            # registry snapshot

    set_config(telemetry_log="/tmp/fits.jsonl")   # arm the JSONL sink
"""

from oap_mllib_tpu.telemetry import fleet, flightrec, metrics
from oap_mllib_tpu.telemetry.export import (
    emit_fit,
    finalize_fit,
    report,
    sink_path,
)
from oap_mllib_tpu.telemetry.metrics import (
    render_prometheus,
    snapshot,
)
from oap_mllib_tpu.telemetry.spans import Span, current_span, enter

__all__ = [
    "Span",
    "current_span",
    "emit_fit",
    "enter",
    "finalize_fit",
    "fleet",
    "flightrec",
    "metrics",
    "render_prometheus",
    "report",
    "sink_path",
    "snapshot",
]
