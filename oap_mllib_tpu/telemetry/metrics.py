"""Process-wide metrics registry: counters, gauges, log-bucket histograms.

Absorbs the ad-hoc per-subsystem counters that PRs 1-3 grew (progcache
hits/misses/evictions + XLA compile ground truth, prefetch
stage/transfer/wait splits + leaked threads, resilience
retries/degradations/faults, streamed bytes/rows, collective op
counts/bytes/dispatch wall) into one registry a dashboard, a bench
harness, and a CI gate can all read.  The legacy objects
(``ProgramCache.stats``, ``PrefetchStats``, ``ResilienceStats``) keep
their shapes — they now *also* feed this registry at the same increment
points, so nothing downstream of them moved.

Design constraints:

- **Deterministic**: no wall-clock timestamps anywhere — histograms have
  FIXED log-scale bucket bounds chosen at import time, observations use
  values measured with the monotonic clock by the caller.  Two runs of
  the same workload produce identical bucket layouts (and identical
  counters when the workload is deterministic).
- **Cheap**: an increment is a dict lookup + a float add under one
  registry lock (the lock exists because prefetch producer threads
  increment concurrently with the consumer; contention is nil next to
  what is being measured).  Telemetry "off" needs no guard here — the
  registry IS the accounting the summaries already paid for.
- **Prometheus-ready**: :func:`render_prometheus` emits the standard
  text exposition (``# TYPE``/``# HELP``, ``_bucket{le=...}``/``_sum``/
  ``_count`` for histograms) so the dump can be scraped or diffed as-is.

Naming follows Prometheus conventions: ``oap_<subsystem>_<what>_total``
for counters, ``_seconds``/``_bytes`` units spelled out.  The full
catalog is docs/observability.md.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Dict, List, Optional, Tuple

# Fixed log-scale bucket bounds (factor-4 geometric series).  Durations
# span 1 µs .. ~67 s; bytes span 256 B .. ~17 GB.  Everything past the
# last bound lands in the +Inf overflow bucket.
DURATION_BUCKETS: Tuple[float, ...] = tuple(1e-6 * 4.0 ** i for i in range(14))
BYTES_BUCKETS: Tuple[float, ...] = tuple(256.0 * 4.0 ** i for i in range(14))
COUNT_BUCKETS: Tuple[float, ...] = tuple(1.0 * 4.0 ** i for i in range(14))

LabelSet = Tuple[Tuple[str, str], ...]


def _labelset(labels: Optional[Dict[str, str]]) -> LabelSet:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with _LOCK:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        with _LOCK:
            self.value = float(v)


class Histogram:
    """Cumulative-bucket histogram over fixed log-scale bounds.

    ``counts[i]`` is the number of observations ``<= bounds[i]`` in that
    bucket (non-cumulative storage; the Prometheus renderer emits the
    cumulative form); ``counts[-1]`` is the +Inf overflow.

    ``observe(..., exemplar={...})`` pins an OpenMetrics exemplar
    (label dict + the observed value, e.g. a sampled request trace id)
    to the bucket the observation lands in — latest observation wins
    per bucket; ``render_prometheus`` emits it after the bucket line
    (`` # {trace_id="..."} 0.0042``).  Storage stays None until the
    first exemplar, so un-traced histograms pay nothing."""

    __slots__ = ("bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, bounds: Tuple[float, ...] = DURATION_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        # bucket index -> (labels dict, observed value); lazily built
        self.exemplars: Optional[Dict[int, Tuple[Dict[str, str], float]]] \
            = None

    def observe(self, v: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        v = float(v)
        with _LOCK:
            idx = bisect.bisect_left(self.bounds, v)
            self.counts[idx] += 1
            self.sum += v
            self.count += 1
            if exemplar:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[idx] = (dict(exemplar), v)


_LOCK = threading.Lock()


def histogram_quantile(h: Histogram, q: float) -> float:
    """Upper-bound estimate of the ``q``-quantile from a cumulative
    bucket read: the smallest bucket bound whose cumulative count
    reaches ``q * count`` (the +Inf overflow returns the largest finite
    bound).  With the factor-4 log buckets the estimate is within one
    bucket factor of the true quantile — the resolution the serving
    p50/p99 summary block and bench tail-latency lines report at.

    Edges: an empty histogram returns 0.0 for any ``q``; ``q=0``
    returns the lowest non-empty bucket's bound (the min estimate);
    ``q=1`` the highest non-empty finite bound; mass in the +Inf
    overflow clamps to the largest finite bound (the storage has no
    upper witness).  Out-of-range ``q`` raises."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    with _LOCK:
        total = h.count
        if total == 0:
            return 0.0
        if q == 0.0:
            for bound, c in zip(h.bounds, h.counts):
                if c > 0:
                    return float(bound)
            return float(h.bounds[-1])  # all mass in the overflow
        target = q * total
        cum = 0
        for bound, c in zip(h.bounds, h.counts):
            cum += c
            if cum >= target:
                return float(bound)
        return float(h.bounds[-1])


class Registry:
    """Name+labels -> metric instance, with per-name type/help metadata."""

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, LabelSet], Any] = {}
        self._meta: Dict[str, Tuple[str, str]] = {}  # name -> (type, help)

    def _get(self, name: str, labels, kind: str, help_: str, make):
        key = (name, _labelset(labels))
        with _LOCK:
            prev = self._meta.get(name)
            if prev is not None and prev[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {prev[0]}, "
                    f"not {kind}"
                )
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = make()
            # a family registered help-less at one call site must still
            # pick up the help a richer site supplies later — every
            # family then renders with a real # HELP line
            if prev is None or (not prev[1] and help_):
                self._meta[name] = (kind, help_)
            return m

    def family_total(self, name: str) -> float:
        """Sum of one family's values across all its label sets
        (histograms contribute their observation ``sum``); 0.0 for an
        unregistered family.  The cheap cross-label read the fleet
        rollup frames use (telemetry/fleet.local_frame)."""
        with _LOCK:
            total = 0.0
            for (n, _), m in self._metrics.items():
                if n != name:
                    continue
                total += m.sum if isinstance(m, Histogram) else m.value
            return total

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None,
                help: str = "") -> Counter:
        return self._get(name, labels, "counter", help, Counter)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None,
              help: str = "") -> Gauge:
        return self._get(name, labels, "gauge", help, Gauge)

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  bounds: Tuple[float, ...] = DURATION_BUCKETS,
                  help: str = "") -> Histogram:
        return self._get(
            name, labels, "histogram", help, lambda: Histogram(bounds)
        )

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready dump of every metric: ``{name: {labels-json:
        value-or-histogram-dict}}`` with labels rendered ``k=v,...``
        (empty string for unlabelled series).  Deterministically ordered
        (sorted names, sorted label sets)."""
        with _LOCK:
            items = sorted(
                self._metrics.items(),
                key=lambda kv: (kv[0][0], kv[0][1]),
            )
            out: Dict[str, Any] = {}
            for (name, labels), m in items:
                lab = ",".join(f"{k}={v}" for k, v in labels)
                series = out.setdefault(name, {})
                if isinstance(m, Histogram):
                    series[lab] = {
                        "buckets": dict(
                            zip([_fmt(b) for b in m.bounds] + ["+Inf"],
                                m.counts)
                        ),
                        "sum": m.sum,
                        "count": m.count,
                    }
                else:
                    series[lab] = m.value
            return out

    def render_prometheus(self) -> str:
        """Standard Prometheus text exposition of the whole registry."""
        with _LOCK:
            items = sorted(
                self._metrics.items(),
                key=lambda kv: (kv[0][0], kv[0][1]),
            )
            lines: List[str] = []
            seen_meta = set()
            for (name, labels), m in items:
                if name not in seen_meta:
                    seen_meta.add(name)
                    kind, help_ = self._meta.get(name, ("untyped", ""))
                    # the promtext spec wants one # HELP + # TYPE per
                    # family; families registered without help get a
                    # self-naming fallback so scrapers never see a bare
                    # family (tests/test_telemetry.py round-trips this)
                    lines.append(
                        f"# HELP {name} "
                        f"{_escape_help(help_ or name)}"
                    )
                    lines.append(f"# TYPE {name} {kind}")
                lab = _render_labels(labels)
                if isinstance(m, Histogram):
                    cum = 0
                    for i, (b, c) in enumerate(zip(m.bounds, m.counts)):
                        cum += c
                        lines.append(
                            f"{name}_bucket{_render_labels(labels, le=_fmt(b))}"
                            f" {cum}{_render_exemplar(m, i)}"
                        )
                    cum += m.counts[-1]
                    lines.append(
                        f'{name}_bucket{_render_labels(labels, le="+Inf")}'
                        f" {cum}{_render_exemplar(m, len(m.bounds))}"
                    )
                    lines.append(f"{name}_sum{lab} {_fmt(m.sum)}")
                    lines.append(f"{name}_count{lab} {m.count}")
                else:
                    lines.append(f"{name}{lab} {_fmt(m.value)}")
            return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric AND its metadata (tests; the per-fit delta
        consumers snapshot-and-subtract instead)."""
        with _LOCK:
            self._metrics.clear()
            self._meta.clear()


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    """Promtext HELP escaping: backslash and newline only."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(value: str) -> str:
    """Promtext label-value escaping: backslash, double-quote, newline."""
    return (
        value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _render_exemplar(m: Histogram, idx: int) -> str:
    """OpenMetrics exemplar suffix for one bucket line (`` # {k="v"}
    value``), or "" when the bucket holds none.  Label values get the
    standard promtext escaping."""
    if m.exemplars is None:
        return ""
    ex = m.exemplars.get(idx)
    if ex is None:
        return ""
    labels, v = ex
    body = ",".join(
        f'{k}="{_escape_label(str(val))}"' for k, val in sorted(labels.items())
    )
    return f" # {{{body}}} {_fmt(v)}"


def _render_labels(labels: LabelSet, le: Optional[str] = None) -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


# -- module-level singleton (the process registry) ---------------------------

_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str, labels: Optional[Dict[str, str]] = None,
            help: str = "") -> Counter:
    return _REGISTRY.counter(name, labels, help)


def gauge(name: str, labels: Optional[Dict[str, str]] = None,
          help: str = "") -> Gauge:
    return _REGISTRY.gauge(name, labels, help)


def histogram(name: str, labels: Optional[Dict[str, str]] = None,
              bounds: Tuple[float, ...] = DURATION_BUCKETS,
              help: str = "") -> Histogram:
    return _REGISTRY.histogram(name, labels, bounds, help)


def family_total(name: str) -> float:
    return _REGISTRY.family_total(name)


def snapshot() -> Dict[str, Any]:
    return _REGISTRY.snapshot()


def render_prometheus() -> str:
    return _REGISTRY.render_prometheus()


def reset() -> None:
    _REGISTRY.reset()
