"""Fleet observability control plane: live endpoints + cross-rank
rollups with straggler analytics.

The per-rank telemetry plane (spans + metrics registry + JSONL sink) is
rich but strictly *local* and mostly *post-hoc*: nothing answers the
operator's fleet-shaped questions — "which rank is slow", "how skewed is
the world", "is the imbalance getting worse" — while a fit is running.
This module closes both gaps, in the stack's own idiom (fleet rollups
are one more map-reduce over per-rank state — the DrJAX primitive
decomposition, PAPERS.md arXiv:2403.07128):

- **Live exposition** (``Config.metrics_port`` > 0): one stdlib
  ``http.server`` daemon thread per rank on port
  ``metrics_port + process_id`` serving ``GET /metrics`` (the
  Prometheus text exposition of the process registry — scrape it
  mid-fit) and ``GET /healthz`` (fit root, step, resilience ladder
  state, last-completed collective fingerprint, flight-recorder seq).

- **Fleet rollups** (``Config.fleet_stats``): at per-pass granularity,
  every streamed pass allgathers one FIXED-shape per-rank stat frame
  (:data:`FRAME_FIELDS`: pass wall, stage/transfer/compute split, bytes
  staged, retries, kernel dispatch wall) over the existing host
  collective plane — so the rollup inherits the deadline watchdog
  (utils/recovery.py) and the collective sanitizer's fingerprinting for
  free, and rank-uniformity is by construction (the decision to collect
  is a pure function of config + world size).  Rank 0 folds the frames
  into ``oap_fleet_*`` gauges/histograms (min/max/mean/p99 across ranks
  per field, a skew ratio, the slowest rank); every rank lands a
  ``fleet`` block (slowest rank, skew ratio, imbalance trend) in the
  fit summary plus a ``fleet`` child span — the measurement layer the
  ROADMAP's straggler detector (item 5) and serving SLOs (item 1)
  presuppose.

The collection seam lives in ops/stream_ops.py (it owns the pass
structure and the sanctioned ``_allgather_host``); this module is pure
fold + exposition and issues no collectives itself.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import locktrace

log = logging.getLogger("oap_mllib_tpu")

# The fixed per-rank stat frame, one float64 per field.  Walls are
# per-pass; bytes are the pass's staged payload; retries and kernel
# dispatch wall are this rank's process-cumulative totals as of the
# pass (a straggling rank shows a growing gap, which is the signal).
FRAME_FIELDS = (
    "pass_wall_s",
    "stage_s",
    "transfer_s",
    "compute_s",
    "bytes_staged",
    "retries",
    "kernel_dispatch_s",
    # ISSUE 15 (capability-weighted sharding): rows this rank actually
    # processed in the pass, and its capability weight (0 = not probed)
    # — assignment vs achievement, side by side
    "rows",
    "capability",
)

# metric family per frame field (Prometheus naming: unit suffixes)
_FIELD_METRICS = {
    "pass_wall_s": "oap_fleet_pass_seconds",
    "stage_s": "oap_fleet_stage_seconds",
    "transfer_s": "oap_fleet_transfer_seconds",
    "compute_s": "oap_fleet_compute_seconds",
    "bytes_staged": "oap_fleet_bytes_staged",
    "retries": "oap_fleet_retries",
    "kernel_dispatch_s": "oap_fleet_kernel_dispatch_seconds",
    "rows": "oap_fleet_rows",
    "capability": "oap_fleet_capability",
}

_STATS = ("min", "max", "mean", "p99")

# rollup history kept per fit for the summary block; passes beyond the
# cap fold into the running aggregates but drop their raw frames (the
# constant-memory contract, like the flight recorder)
_WINDOW_CAP = 512


def fleet_stats_cfg(cfg=None) -> str:
    """Validated ``Config.fleet_stats`` — a typo must raise, not
    silently disarm (the kmeans_kernel/fault_spec contract)."""
    cfg = cfg or get_config()
    mode = cfg.fleet_stats
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"fleet_stats must be auto|on|off, got {mode!r}"
        )
    return mode


def metrics_port_cfg(cfg=None) -> int:
    """Validated ``Config.metrics_port`` — negative must raise."""
    cfg = cfg or get_config()
    port = int(cfg.metrics_port)
    if port < 0:
        raise ValueError(
            f"metrics_port must be >= 0 (0 = no live endpoint), got {port}"
        )
    return port


def armed(world: int, cfg=None) -> bool:
    """Should this fit collect per-pass fleet rollups?  A pure function
    of (config, world size) so every rank decides identically — the
    rank-uniform-collective contract."""
    mode = fleet_stats_cfg(cfg)
    if mode == "off":
        return False
    if mode == "on":
        return True
    return world > 1


def _rank() -> int:
    import jax

    return jax.process_index()


def local_frame(stats, pass_wall_s: float) -> np.ndarray:
    """This rank's stat frame for one finished pass, from the pass's
    PrefetchStats + the process registry — shape ``(len(FRAME_FIELDS),)``
    float64, identical on every rank by construction."""
    from oap_mllib_tpu.parallel import balance

    reg = _tm.registry()
    return np.asarray(
        [
            float(pass_wall_s),
            float(stats.stage_s),
            float(stats.transfer_s),
            max(float(pass_wall_s) - float(stats.wait_s), 0.0),
            float(stats.bytes_staged),
            reg.family_total("oap_resilience_retries_total"),
            reg.family_total("oap_kernel_dispatch_seconds"),
            float(stats.rows),
            # already-gathered/pinned capability only: building a frame
            # must never trigger a probe or a collective (0 = unknown)
            balance.cached_capability(),
        ],
        np.float64,
    )


# -- per-fit rollup state ------------------------------------------------------

# tracked (utils/locktrace.py): the /healthz handler thread reads under
# this lock while fit passes write — exactly the cross-thread seam the
# "locks" sanitizer watches; disarmed it is a plain lock + one check
_state_lock = locktrace.TrackedLock("fleet.state", threading.Lock())
_window: List[Dict[str, Any]] = []  # per-pass {phase, frames(list), skew}
_passes = 0
_rank_wall_totals: Optional[np.ndarray] = None  # per-rank summed pass walls
_rank_row_totals: Optional[np.ndarray] = None  # per-rank summed rows
_rank_capability: Optional[np.ndarray] = None  # per-rank weight (last pass)
_health: Dict[str, Any] = {"fit": "", "step": 0, "ladder": "", "phase": ""}


def note_state(**kw) -> None:
    """Merge fields into the /healthz state (fit root, ladder, ...)."""
    with _state_lock:
        _health.update(kw)


def fold_pass(phase: str, frames: np.ndarray) -> Dict[str, Any]:
    """Fold one pass's gathered frames (``(world, len(FRAME_FIELDS))``)
    into the fleet metrics (rank 0) and the per-fit window (every rank —
    the data is identical everywhere, only the metric booking is
    deduplicated).  Returns the per-pass stats dict (tests + gate)."""
    frames = np.asarray(frames, np.float64)
    if frames.ndim != 2 or frames.shape[1] != len(FRAME_FIELDS):
        raise ValueError(
            f"fleet frame shape {frames.shape} != (world, "
            f"{len(FRAME_FIELDS)})"
        )
    world = frames.shape[0]
    walls = frames[:, 0]
    mean_wall = float(walls.mean())
    skew = float(walls.max() / mean_wall) if mean_wall > 0 else 1.0
    slowest = int(np.argmax(walls))
    per_field = {
        f: {
            "min": float(frames[:, i].min()),
            "max": float(frames[:, i].max()),
            "mean": float(frames[:, i].mean()),
            "p99": float(np.percentile(frames[:, i], 99)),
        }
        for i, f in enumerate(FRAME_FIELDS)
    }
    rec = {
        "phase": phase,
        "world": world,
        "skew_ratio": skew,
        "slowest_rank": slowest,
        "frames": frames.tolist(),
        "fields": per_field,
    }
    rows = frames[:, FRAME_FIELDS.index("rows")]
    caps = frames[:, FRAME_FIELDS.index("capability")]
    global _passes, _rank_wall_totals, _rank_row_totals, _rank_capability
    with _state_lock:
        _passes += 1
        if _rank_wall_totals is None or len(_rank_wall_totals) != world:
            _rank_wall_totals = np.zeros((world,), np.float64)
            _rank_row_totals = np.zeros((world,), np.float64)
        _rank_wall_totals += walls
        _rank_row_totals += rows
        _rank_capability = caps.copy()
        if len(_window) < _WINDOW_CAP:
            _window.append(rec)
        _health["step"] = _passes
        _health["phase"] = phase
    if _rank() == 0:
        for i, f in enumerate(FRAME_FIELDS):
            fam = _FIELD_METRICS[f]
            for stat in _STATS:
                _tm.gauge(
                    fam, {"stat": stat},
                    help=f"Fleet rollup of per-rank {f} (last pass, "
                         "across ranks)",
                ).set(per_field[f][stat])
        _tm.gauge(
            "oap_fleet_skew_ratio",
            help="Max/mean per-rank pass wall of the last rolled-up pass",
        ).set(skew)
        _tm.gauge(
            "oap_fleet_slowest_rank",
            help="Rank with the largest pass wall in the last rollup",
        ).set(slowest)
        _tm.counter(
            "oap_fleet_passes_total",
            help="Streamed passes folded into fleet rollups",
        ).inc()
        hist = _tm.histogram(
            "oap_fleet_pass_wall_seconds",
            help="Per-rank pass walls observed by fleet rollups",
        )
        for w in walls:
            hist.observe(float(w))
    maybe_serve()
    return rec


def _trend(skews: List[float]) -> str:
    """Imbalance trend over a fit's passes: compare the mean skew of the
    first and second halves — "rising" means the world is drifting
    apart (a cold-cache relaunch warming up reads "falling")."""
    if len(skews) < 4:
        return "flat"
    half = len(skews) // 2
    first = float(np.mean(skews[:half]))
    second = float(np.mean(skews[half:]))
    if first <= 0:
        return "flat"
    ratio = second / first
    if ratio > 1.1:
        return "rising"
    if ratio < 0.9:
        return "falling"
    return "flat"


def summary_block() -> Optional[Dict[str, Any]]:
    """The per-fit ``fleet`` block, or None when no pass was rolled up
    (disarmed, or a fit with no streamed passes)."""
    with _state_lock:
        if _passes == 0:
            return None
        window = list(_window)
        passes = _passes
        totals = (
            None if _rank_wall_totals is None
            else np.array(_rank_wall_totals)
        )
        row_totals = (
            None if _rank_row_totals is None
            else np.array(_rank_row_totals)
        )
        caps = (
            None if _rank_capability is None
            else np.array(_rank_capability)
        )
    world = window[-1]["world"] if window else 1
    skews = [w["skew_ratio"] for w in window]
    block: Dict[str, Any] = {
        "world": world,
        "passes": passes,
        "skew_ratio": skews[-1] if skews else 1.0,
        "imbalance_trend": _trend(skews),
        "window_truncated": passes > len(window),
    }
    if totals is not None and len(totals) == world:
        mean = float(totals.mean())
        block["slowest_rank"] = int(np.argmax(totals))
        block["per_rank_pass_s"] = [round(float(t), 6) for t in totals]
        block["fit_skew_ratio"] = (
            float(totals.max() / mean) if mean > 0 else 1.0
        )
    # assignment vs achievement (ISSUE 15): what each rank was handed
    # (capability weight) next to what it actually pushed through
    if row_totals is not None and len(row_totals) == world:
        block["per_rank_rows"] = [int(r) for r in row_totals]
    if caps is not None and len(caps) == world:
        block["per_rank_capability"] = [round(float(c), 4) for c in caps]
    return block


def last_window() -> List[Dict[str, Any]]:
    """The current fit's per-pass rollup records (tests + gate)."""
    with _state_lock:
        return list(_window)


def finalize_fit(summary, root) -> None:
    """Fit-boundary hook (telemetry/export.finalize_fit): land the
    ``fleet`` block in the summary + a ``fleet`` child span under the
    root carrying the straggler analytics, then reset the per-fit
    window.  One config check when the plane is disarmed."""
    cfg = get_config()
    try:
        import jax

        world = jax.process_count()
    except Exception:  # noqa: BLE001 — exposition must not kill a fit
        world = 1
    if cfg.metrics_port:
        maybe_serve(cfg)
    if not armed(world, cfg):
        return
    block = summary_block()
    _reset_fit_window()
    if summary is None:
        return
    if block is None:
        block = {"world": world, "passes": 0}
    block = dict(block, enabled=True)
    if isinstance(summary, dict):
        summary["fleet"] = block
    else:
        summary.fleet = block
    if root is not None:
        attrs = {
            k: block[k]
            for k in ("world", "passes", "skew_ratio", "slowest_rank",
                      "imbalance_trend", "fit_skew_ratio")
            if k in block
        }
        root.node("fleet").attrs.update(attrs)
    ladder = None
    res = (
        summary.get("resilience") if isinstance(summary, dict)
        else getattr(summary, "resilience", None)
    )
    if isinstance(res, dict):
        ladder = res.get("ladder")
    note_state(
        fit=getattr(root, "name", "") if root is not None else "",
        ladder=ladder or "",
    )


def _reset_fit_window() -> None:
    global _passes, _rank_wall_totals, _rank_row_totals, _rank_capability
    with _state_lock:
        _window.clear()
        _passes = 0
        _rank_wall_totals = None
        _rank_row_totals = None
        _rank_capability = None


# -- live exposition (stdlib http.server, one daemon thread per rank) ---------

_server_lock = locktrace.TrackedLock("fleet.server", threading.Lock())
_server: Optional[http.server.ThreadingHTTPServer] = None
_server_port: Optional[int] = None
_failed_ports: set = set()


def _healthz_payload() -> Dict[str, Any]:
    from oap_mllib_tpu.telemetry import flightrec
    from oap_mllib_tpu.utils import recovery

    from oap_mllib_tpu.parallel import balance

    cfg = get_config()
    rank = int(cfg.process_id)
    with _state_lock:
        health = dict(_health)
        rows_done = (
            int(_rank_row_totals[rank])
            if _rank_row_totals is not None
            and rank < len(_rank_row_totals) else 0
        )
    payload = {
        "ok": True,
        "rank": rank,
        "world": int(cfg.num_processes),
        "fit": health.get("fit", ""),
        "phase": health.get("phase", ""),
        "step": health.get("step", 0),
        "ladder": health.get("ladder", ""),
        "last_collective": recovery.last_completed(),
        "flight_recorder_seq": flightrec.last_seq(),
        "fleet_passes": health.get("step", 0),
        # assignment vs achievement (ISSUE 15): this rank's capability
        # weight next to the rows it has pushed through this fit
        "capability": balance.cached_capability(),
        "rows_processed": rows_done,
    }
    # the serving side of the replica: a scrape of a pure-serving
    # process is no longer empty of the thing it's doing
    try:
        from oap_mllib_tpu.serving import traffic

        payload["serving"] = traffic.serving_health_block()
    except Exception:  # noqa: BLE001 — health must render regardless
        payload["serving"] = {}
    return payload


def _sloz_payload() -> Dict[str, Any]:
    """``GET /sloz``: the SLO engine's full state (serving/slo.py) —
    ``{"armed": false}`` when ``serve_slo_p99_ms`` is 0."""
    from oap_mllib_tpu.serving import slo

    return slo.state()


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — stdlib handler contract
        if self.path.split("?")[0] == "/metrics":
            body = _tm.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?")[0] == "/healthz":
            body = (json.dumps(_healthz_payload(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        elif self.path.split("?")[0] == "/sloz":
            body = (json.dumps(_sloz_payload(), sort_keys=True)
                    + "\n").encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # noqa: D102 — silence stdlib logs
        pass


def maybe_serve(cfg=None) -> Optional[int]:
    """Start (once) the per-rank metrics endpoint when
    ``Config.metrics_port`` > 0; returns the bound port or None.  The
    rank offsets the port (``metrics_port + process_id``) so co-hosted
    pseudo-cluster ranks each get their own scrape surface.  A bind
    failure warns once per port and never fails the fit.

    Locking discipline (oaplint R21): the lock covers only the registry
    swap — a stale server is DETACHED under the lock and its blocking
    ``shutdown()`` runs after release, so a scraping handler thread can
    never stall fit threads queued on the lock."""
    global _server, _server_port
    cfg = cfg or get_config()
    base = metrics_port_cfg(cfg)
    if base == 0:
        return None
    port = base + int(cfg.process_id)
    stale = None
    with _server_lock:
        if _server is not None and _server_port == port:
            return port
        if port in _failed_ports:
            return None
        if _server is not None:
            stale, _server, _server_port = _server, None, None
    _stop_http(stale)
    try:
        srv = http.server.ThreadingHTTPServer(("", port), _Handler)
    except OSError as e:
        with _server_lock:
            _failed_ports.add(port)
        log.warning(
            "fleet: metrics endpoint bind failed on port %d (%s); "
            "live exposition disabled for this port", port, e,
        )
        return None
    srv.daemon_threads = True
    thread = threading.Thread(
        target=srv.serve_forever, daemon=True,
        name=f"oap-metrics-{port}",
    )
    loser = None
    with _server_lock:
        if _server is not None:
            loser = srv  # a racing arm won the registry; yield to it
        else:
            _server, _server_port = srv, port
            thread.start()
    if loser is not None:
        loser.server_close()
        return server_port()
    # interpreter-exit teardown rides the ONE ordered shutdown hook
    # (telemetry/export.shutdown — the atexit-outside-shutdown
    # contract): final JSONL snapshot first, then this server stops
    from oap_mllib_tpu.telemetry import export as _export

    _export.register_shutdown()
    log.info(
        "fleet: serving /metrics, /healthz and /sloz on port %d", port
    )
    return port


def server_port() -> Optional[int]:
    with _server_lock:
        return _server_port


def _stop_http(srv) -> None:
    """Blocking teardown of a DETACHED server — call with no lock held
    (``shutdown()`` waits for the serve loop to notice, which is
    exactly the R21 blocking-while-locked shape when under a lock)."""
    if srv is None:
        return
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass


def stop_server() -> None:
    """Tear down the live endpoint: detach under the lock, stop the
    detached server after release (tests and the ordered exit hook —
    telemetry/export.shutdown calls this last)."""
    global _server, _server_port
    with _server_lock:
        srv, _server, _server_port = _server, None, None
        _failed_ports.clear()
    _stop_http(srv)


def _reset_for_tests() -> None:
    stop_server()
    _reset_fit_window()
    with _state_lock:
        _health.update({"fit": "", "step": 0, "ladder": "", "phase": ""})
