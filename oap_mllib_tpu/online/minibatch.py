"""Mini-batch Lloyd: decayed incremental K-Means updates.

One ``partial_fit`` call = ONE assignment pass over the arriving
chunks through the batch fit's own streamed-pass machinery
(stream_ops.streamed_accumulate — same chunk programs, same prefetch
pipeline, same cross-process psum reduction), folded into the model as
the classic count-weighted mini-batch k-means rule (Sculley 2010,
web-scale k-means):

    c_new = (n_eff * c_old + batch_sum) / (n_eff + batch_count)

where ``n_eff = online_decay * n_accum`` is the decayed per-center
observation count carried across deltas (seeded from the batch fit's
cluster sizes).  ``online_decay=1`` weights every past observation
equally — the stationary-stream rule; below 1 the centers track drift
with an effective horizon of ~1/(1-decay) deltas.  No re-init, no
convergence loop: a delta is one pass, always.

Compute-then-swap: the pass accumulates into fresh buffers and the
model's centers array is REPLACED (never written in place) only after
the whole pass finished and passed the finite guard — so the
``delta.ingest`` fault site (and any mid-pass error) leaves the model
and its served pin untouched.  The replacement array is a new object,
which is exactly what the identity-keyed serving pin needs to re-stage
once on the next request (serving/registry.pin).
"""

from __future__ import annotations

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.online import delta
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils.faults import maybe_fault


def _seed_counts(model, k: int) -> np.ndarray:
    """The per-center observation counts a first delta starts from: the
    batch fit's cluster sizes when the summary carries them (the counts
    those centroids ARE the weighted mean of), zeros otherwise (a
    zero-count center adopts the first batch mean that hits it)."""
    counts = getattr(model, "_online_counts", None)
    if counts is not None:
        return np.asarray(counts, np.float64)
    sizes = getattr(model.summary, "cluster_sizes", None)
    if sizes is not None and np.asarray(sizes).shape == (k,):
        return np.asarray(sizes, np.float64)
    return np.zeros((k,), np.float64)


def partial_fit_kmeans(model, x, sample_weight=None):
    """One decayed mini-batch Lloyd delta over ``x`` (array or
    ChunkSource; optional per-row weights) folded into ``model`` —
    the ``KMeansModel.partial_fit`` implementation.  Commits through
    :func:`online.delta.commit` (telemetry + in-place serving
    re-pin).  Returns the mutated model."""
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.ops import stream_ops
    from oap_mllib_tpu.utils.resilience import check_finite
    from oap_mllib_tpu.utils.timing import x64_scope

    decay = delta.decay_cfg()  # typo'd knob raises before the fault site
    # the delta-ingestion fault site: BEFORE any accumulation or model
    # mutation, so an injected failure is indistinguishable from the
    # caller never having delivered the delta
    maybe_fault("delta.ingest")
    if model.distance_measure != "euclidean":
        raise NotImplementedError(
            "partial_fit requires distance_measure='euclidean' (the "
            "streamed assignment pass is euclidean-only)"
        )
    cfg = get_config()
    dtype = np.float64 if cfg.enable_x64 else np.float32
    centers_old = np.asarray(model.cluster_centers_, dtype)
    k, d = centers_old.shape
    if not isinstance(x, ChunkSource):
        x = ChunkSource.from_array(np.atleast_2d(np.asarray(x)))
    if x.n_features != d:
        raise ValueError(
            f"partial_fit chunk width {x.n_features} != model "
            f"dimensionality {d}"
        )
    if sample_weight is not None and not isinstance(
        sample_weight, ChunkSource
    ):
        sample_weight = ChunkSource.from_array(
            np.asarray(sample_weight).reshape(-1, 1),
            chunk_rows=x.chunk_rows,
        )
    if sample_weight is not None:
        stream_ops._checked_entry(
            lambda: stream_ops._check_weight_source(x, sample_weight)
        )
    pol = psn.resolve("kmeans")
    tier = psn.kernel_tier(pol.name, cfg.matmul_precision)
    import jax.numpy as jnp

    with x64_scope(cfg.enable_x64):
        sums, counts, _ = stream_ops.streamed_accumulate(
            x, jnp.asarray(centers_old), dtype, tier, need_cost=False,
            weights=sample_weight, phase="partial_fit", policy=pol.name,
        )
    sums = np.asarray(sums, np.float64)
    counts = np.asarray(counts, np.float64)
    # decayed count-weighted fold — host math on the psum-reduced pass
    # moments (identical on every process, so the swap is too)
    n_eff = decay * _seed_counts(model, k)
    denom = n_eff + counts
    new_centers = np.where(
        denom[:, None] > 0,
        (centers_old.astype(np.float64) * n_eff[:, None] + sums)
        / np.maximum(denom[:, None], 1e-300),
        centers_old,
    ).astype(dtype)
    check_finite(new_centers, "K-Means centroids (partial_fit delta)")
    rows = float(counts.sum())
    # compute-then-swap: everything above this line is side-effect-free
    # on the model
    model.cluster_centers_ = new_centers
    model._online_counts = denom
    _tm.counter(
        "oap_online_delta_rows_total", {"model": "kmeans"},
        help="Rows ingested by incremental-fit deltas.",
    ).inc(rows)
    delta.commit(model, "kmeans", detail=f"rows={rows:g}")
    return model
