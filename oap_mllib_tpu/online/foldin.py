"""ALS fold-in: incremental user/item rows against a frozen table.

The classic serving-time answer to "a new user rated five movies":
holding the item table Y fixed, the user's optimal factor row is the
same regularized normal-equation solve ALS runs every half-iteration,

    x_u = (Y_u^T C_u Y_u + reg * n_u * I [+ alpha Y^T Y])^{-1} Y_u^T c_u

— so a delta of new/changed rows needs ONE batched solve against the
frozen opposite table, not a full refit.  This module routes that
solve through the exact training kernels (als_ops.normal_eq_partials
for the Spark-parity weighting/ALS-WR lambda scaling,
als_ops.regularized_solve for the masked batched Cholesky — the fused
Pallas consumer on TPU f32 small-rank, XLA elsewhere, resolved by the
same resolve_solve_kernel decision point), so a folded-in row is
BIT-IDENTICAL to what a training half-iteration would have produced
for that row against the same frozen table.

Shapes bucket (edges and destination rows pad to power-of-two
buckets with valid=0) so successive deltas of different sizes reuse
the compiled program — the second commit is zero new XLA compiles,
zero autotune sweeps (the tuned geometry resolves through the
persistent cache).  ``Config.online_foldin_batch`` chunks enormous
deltas; 0 (default) is one launch per commit.

The destination axis may GROW: ids beyond the current table extend it,
the grown tail seeded with the deterministic counter-based init
(fallback/als_np.init_factors_rows — position-addressable, so an
unrated new row is bit-identical to what a from-scratch fit would
have initialized).  Growth composes with the growable-axis checkpoint
restore (utils/checkpoint.py): a later warm start admits the grown
extent.

Compute-then-swap: all solves land in a private copy of the table;
the model's host array is replaced only after every batch succeeded —
the ``delta.ingest`` (entry) and ``delta.solve`` (pre-launch) fault
sites, or any error, leave the model and its served pin untouched.
"""

from __future__ import annotations

import functools

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.online import delta
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.faults import maybe_fault
from oap_mllib_tpu.utils.timing import tick


def _bucket(n: int, floor: int) -> int:
    """Next power of two >= max(n, floor): the fold-in shape bucket.
    Geometric buckets bound the compiled-shape count at log2(max delta
    size) programs per geometry — and keep the padded edge count a
    power of two, which is what als_ops._edge_chunks needs to chunk the
    per-edge outer-product buffer."""
    b = int(floor)
    while b < n:
        b *= 2
    return b


def _foldin_solve_jit():
    """The one compiled program per (shape bucket, config) a fold-in
    commit launches: normal-equation partials + regularized solve,
    fused under a single jit so the delta costs one dispatch."""
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import als_ops

    @functools.partial(
        jax.jit,
        static_argnames=(
            "n_dst", "implicit", "policy", "solve_kernel", "solve_geo",
            "gram_geo",
        ),
    )
    def solve(dst_idx, src_idx, conf, valid, src_factors, reg, alpha,
              n_dst, implicit, policy, solve_kernel, solve_geo, gram_geo):
        a, b, n_reg = als_ops.normal_eq_partials(
            dst_idx, src_idx, conf, valid, src_factors, n_dst,
            alpha, implicit, policy,
        )
        r = src_factors.shape[1]
        eye = jnp.eye(r, dtype=src_factors.dtype)
        gram = (
            als_ops._factor_gram(src_factors, solve_kernel, gram_geo)
            if implicit else None
        )
        return (
            als_ops.regularized_solve(
                a, b, n_reg, reg, eye, gram, solve_kernel, solve_geo
            ),
            n_reg,
        )

    return solve


def _resolve_params(model, reg, alpha, implicit, seed):
    """Hyperparameter defaults from the base fit's summary["params"]
    (stamped by ALS.fit) — explicit keyword arguments win.  ``reg``
    has no safe fallback: folding in under a different lambda than the
    table was trained with silently skews every solved row."""
    params = (
        model.summary.get("params", {})
        if isinstance(model.summary, dict) else {}
    )
    if reg is None:
        reg = params.get("reg")
    if reg is None:
        raise ValueError(
            "fold_in needs reg= (the model summary carries no fit "
            "params — pass the base fit's reg_param explicitly)"
        )
    if implicit is None:
        implicit = bool(params.get("implicit", False))
    if alpha is None:
        alpha = float(params.get("alpha", 1.0))
    if seed is None:
        seed = int(params.get("seed", get_config().seed))
    return float(reg), float(alpha), bool(implicit), int(seed)


def fold_in(model, users, items, ratings, *, side: str = "user",
            reg=None, alpha=None, implicit=None, seed=None) -> dict:
    """Solve a delta of new/changed rows on ``side`` against the frozen
    opposite table and swap them into ``model`` in place — the
    ``ALSModel.fold_in_users``/``fold_in_items`` implementation.

    The triples are the touched rows' FULL current ratings (standard
    fold-in contract).  Rows whose delta carries no reg-counted rating
    (e.g. implicit with all non-positive ratings) keep their previous
    factors — new rows keep the deterministic init.  Returns
    ``{"side", "rows_solved", "grown", "repinned"}``.
    """
    import jax.numpy as jnp

    from oap_mllib_tpu.fallback import als_np
    from oap_mllib_tpu.ops import als_ops

    if side not in ("user", "item"):
        raise ValueError(f"side must be user|item, got {side!r}")
    batch_rows = delta.foldin_batch_cfg()
    # the delta-ingestion fault site: before any compute or mutation
    maybe_fault("delta.ingest")
    users = np.asarray(users).reshape(-1)
    items = np.asarray(items).reshape(-1)
    ratings = np.asarray(ratings, np.float32).reshape(-1)
    if not (len(users) == len(items) == len(ratings)):
        raise ValueError(
            f"users/items/ratings lengths differ: "
            f"{len(users)}/{len(items)}/{len(ratings)}"
        )
    if len(users) == 0:
        raise ValueError("fold_in needs at least one rating")
    reg, alpha, implicit, seed = _resolve_params(
        model, reg, alpha, implicit, seed
    )
    r = model.rank
    if side == "user":
        dst, src = users, items
        frozen = np.asarray(model.item_factors_, np.float32)
        table = model.user_factors_
        seed_side = seed  # matches init_factors(n_users, r, seed)
    else:
        dst, src = items, users
        frozen = np.asarray(model.user_factors_, np.float32)
        table = model.item_factors_
        seed_side = seed + 1  # the item-table init stream
    if dst.min() < 0:
        raise ValueError(f"{side} ids must be >= 0, got {dst.min()}")
    if src.min() < 0 or src.max() >= frozen.shape[0]:
        raise ValueError(
            f"frozen-side ids must be in [0, {frozen.shape[0]}); got "
            f"range [{src.min()}, {src.max()}] — the fold-in axis is "
            f"{side!r}, the opposite table cannot grow in the same delta"
        )
    uniq, inv = np.unique(dst, return_inverse=True)
    n_old = table.shape[0]
    n_new = max(n_old, int(uniq.max()) + 1)
    # private working copy: grown tail at the deterministic init (an
    # unrated new row is bit-identical to a from-scratch fit's init)
    new_table = np.empty((n_new, r), np.float32)
    new_table[:n_old] = table
    if n_new > n_old:
        new_table[n_old:] = als_np.init_factors_rows(
            n_old, n_new, r, seed_side
        )
    pol = psn.resolve("als")
    solve_kernel = als_ops.resolve_solve_kernel(r, np.float32)
    solve_geo, gram_geo = als_ops._tuned_geometry(
        r, solve_kernel, implicit
    )
    frozen_dev = jnp.asarray(frozen)
    reg_j = jnp.asarray(reg, np.float32)
    alpha_j = jnp.asarray(alpha, np.float32)
    solve = progcache.get_or_build(
        "online.foldin_solve_fn", (), _foldin_solve_jit
    )
    elapsed = tick()
    rows_solved = 0
    step = batch_rows or len(uniq)
    for lo in range(0, len(uniq), step):
        hi = min(lo + step, len(uniq))
        if batch_rows:
            mask = (inv >= lo) & (inv < hi)
            e_dst = (inv[mask] - lo).astype(np.int32)
            e_src = src[mask].astype(np.int32)
            e_conf = ratings[mask]
        else:
            e_dst = inv.astype(np.int32)
            e_src = src.astype(np.int32)
            e_conf = ratings
        # bucketed padding (valid=0 edges contribute zero moments):
        # successive deltas share compiled programs per bucket
        nnz_pad = _bucket(len(e_dst), 256)
        n_dst_pad = _bucket(hi - lo, 64)
        pad = nnz_pad - len(e_dst)
        dst_b = np.concatenate([e_dst, np.zeros(pad, np.int32)])
        src_b = np.concatenate([e_src, np.zeros(pad, np.int32)])
        conf_b = np.concatenate([e_conf, np.zeros(pad, np.float32)])
        valid_b = np.concatenate(
            [np.ones(len(e_dst), np.float32), np.zeros(pad, np.float32)]
        )
        step_key = (
            progcache.backend_fingerprint(),
            (nnz_pad, n_dst_pad, r), implicit, pol.name, solve_kernel,
            solve_geo, gram_geo,
        )
        # the fold-in solve fault site: immediately before the one
        # batched launch this delta (batch) costs
        maybe_fault("delta.solve")
        with progcache.launch(
            "online.foldin_solve", step_key, None, "foldin",
        ):
            solved, n_reg = solve(
                jnp.asarray(dst_b), jnp.asarray(src_b),
                jnp.asarray(conf_b), jnp.asarray(valid_b),
                frozen_dev, reg_j, alpha_j,
                n_dst_pad, implicit, pol.name, solve_kernel,
                solve_geo, gram_geo,
            )
        solved = np.asarray(solved)[: hi - lo]
        n_reg = np.asarray(n_reg)[: hi - lo]
        take = n_reg > 0  # zero-reg-count rows keep old factors / init
        new_table[uniq[lo:hi][take]] = solved[take]
        rows_solved += int(take.sum())
    wall = elapsed()
    # compute-then-swap: the model's table is replaced atomically —
    # the fresh array identity is what re-stages the serving pin
    if side == "user":
        model._user_factors = new_table
    else:
        model._item_factors = new_table
    grown = [int(n_old), int(n_new)] if n_new > n_old else None
    _tm.counter(
        "oap_online_foldin_rows_total", {"side": side},
        help="Destination rows solved by ALS fold-in deltas.",
    ).inc(rows_solved)
    _tm.histogram(
        "oap_online_foldin_seconds", {"side": side},
        help="Wall time of ALS fold-in delta commits.",
    ).observe(wall)
    out = delta.commit(
        model, "als",
        detail=f"side={side} rows={rows_solved} grown={grown}",
    )
    return {
        "side": side, "rows_solved": rows_solved, "grown": grown,
        "repinned": out["repinned"],
    }
