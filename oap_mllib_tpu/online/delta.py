"""Shared delta-commit plumbing for the incremental fit paths.

Every online path (minibatch / ipca / foldin) ends a successful delta
with :func:`commit`: book the commit counter, drop a flight-recorder
instant, and — unless ``Config.online_repin`` disables it — re-pin any
serving handle bound to the model through
:func:`serving.registry.repin_model`.  The re-pin is IN PLACE: the
handle's model version bumps, its identity-keyed device pins re-stage
the replaced host arrays exactly once, and in-flight requests keep the
handle they already hold (registry swap under the tracked lock, no
eviction, zero new XLA compiles while shapes stay in-bucket).

Config validation lives here — one place — so a typo'd knob raises at
the FIRST delta, not silently downstream (the repo-wide
validate-at-use contract, docs/configuration.md).
"""

from __future__ import annotations

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.telemetry import flightrec
from oap_mllib_tpu.telemetry import metrics as _tm


def decay_cfg() -> float:
    """Validated ``Config.online_decay``: the per-delta discount on the
    accumulated per-center counts in mini-batch Lloyd.  1.0 keeps every
    past observation at full weight (the classic mini-batch k-means
    count rule); values below 1 let the centers track drift."""
    decay = get_config().online_decay
    if not (0.0 < float(decay) <= 1.0):
        raise ValueError(
            f"online_decay must be in (0, 1], got {decay!r}"
        )
    return float(decay)


def foldin_batch_cfg() -> int:
    """Validated ``Config.online_foldin_batch``: 0 solves the whole
    delta in one batched launch (the default — one solve per commit);
    a positive value chunks huge deltas into that many destination
    rows per launch (bounds the (batch, r, r) normal-equation moments
    when a delta touches millions of rows)."""
    batch = get_config().online_foldin_batch
    if int(batch) < 0:
        raise ValueError(
            f"online_foldin_batch must be >= 0, got {batch!r}"
        )
    return int(batch)


def repin_cfg() -> str:
    """Validated ``Config.online_repin``: "auto" re-pins served handles
    on every commit; "off" leaves serving on the old device state until
    the operator re-pins explicitly (registry.repin_model)."""
    mode = get_config().online_repin
    if mode not in ("auto", "off"):
        raise ValueError(
            f"online_repin must be auto|off, got {mode!r}"
        )
    return mode


def commit(model, kind: str, detail: str = "") -> dict:
    """Commit one successful delta: telemetry + flight-recorder event +
    the in-place serving re-pin.  Called AFTER the model's host arrays
    have been swapped (compute-then-swap is each path's job — a fault
    before this point must leave the old pin serving).  Returns
    ``{"repinned": n}`` — the number of serving handles whose version
    advanced (0 when the model is not being served, or repin is
    off)."""
    _tm.counter(
        "oap_online_commits_total", {"model": kind},
        help="Committed incremental-fit deltas per model family.",
    ).inc()
    if flightrec.enabled():
        flightrec.record(
            "serve", "delta_commit",
            f"model={kind} {detail}".strip(),
        )
    repinned = 0
    if repin_cfg() == "auto":
        from oap_mllib_tpu.serving import registry

        repinned = registry.repin_model(model)
    return {"repinned": repinned}
