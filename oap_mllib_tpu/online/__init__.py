"""Live models: incremental fit paths with in-place serving re-pin.

A nightly full refit is how the reference keeps Spark-served models
fresh (train -> write -> reload -> swap).  This package is the delta
path: each estimator family gets an incremental update that reuses the
batch fit's own accumulation/solve machinery — same math, one pass over
the arriving delta, no re-initialization — and every committed delta
re-pins the model's device state IN PLACE through serving/registry.py
(version bump + identity-keyed pin refresh; in-flight requests keep
their handle, nothing is evicted, and no new XLA programs compile when
shapes stay in-bucket).

- :func:`minibatch.partial_fit_kmeans` — decayed mini-batch Lloyd over
  streamed chunks (stream_ops.streamed_accumulate; the
  ``KMeansModel.partial_fit`` entry point);
- :class:`ipca.IncrementalPCA` — rank-chunk Gram/colsum updates folded
  into the Kahan-compensated streaming accumulators, eigh re-solve
  only at commit time;
- :func:`foldin.fold_in` — ALS user/item fold-in: new or changed rows
  solved against the frozen opposite table through the batched
  normal-equation kernel (``ALSModel.fold_in_users`` /
  ``fold_in_items``), with axis growth;
- :mod:`delta` — the shared commit plumbing (config validation,
  telemetry, flight-recorder events, the registry re-pin).

Fault contract (utils/faults.py): ``delta.ingest`` fires at every
delta entry BEFORE any model mutation and ``delta.solve`` immediately
before the fold-in solve launch — every path is compute-then-swap, so
an injected failure leaves the base model and its served pin exactly
as they were (regression-tested; dev/online_gate.py kill leg).
"""

from oap_mllib_tpu.online.delta import commit  # noqa: F401
from oap_mllib_tpu.online.foldin import fold_in  # noqa: F401
from oap_mllib_tpu.online.ipca import IncrementalPCA  # noqa: F401
from oap_mllib_tpu.online.minibatch import partial_fit_kmeans  # noqa: F401
