"""Incremental PCA: streaming moment updates, commit-time eigensolve.

The batch streamed fit (stream_ops.covariance_streamed) is two passes
because it centers the Gram against the final mean.  An incremental
fit cannot see the final mean, so this class accumulates the RAW
(uncentered) second moment and the column sums instead — folded
through the SAME Kahan/Neumaier-compensated chunk accumulators the
streamed fit uses (stream_ops._gram_chunk_comp / _colsum_chunk_comp,
with mean pinned at zero), keeping the cross-delta summation error
bounded independent of how many deltas arrive.  Centering happens
algebraically at commit time:

    cov = (G_raw - colsum colsum^T / n) / max(n - 1, 1)

then symmetrized 0.5*(cov + cov^T) — the batch path's exact
normalization convention — and the spectrum re-solves through the
batch estimator's own eigensolver tail (PCA._solve_spectrum: full eigh
or the randomized top-k, per ``Config.pca_solver``).  The eigh runs
ONLY at commit time: ingesting a delta is O(chunk * d^2) accumulation,
never an O(d^3) factorization.

Compute-then-swap at both levels: ``partial_fit`` accumulates into
fresh device buffers and stores the host state back only after the
whole delta succeeded (the ``delta.ingest`` fault site fires before
any of it), and ``commit`` mutates the published :class:`PCAModel`'s
arrays only after the solve finished — so a fault anywhere leaves the
model and its served pin on the previous spectrum.  Later commits
mutate the SAME model object in place (fresh arrays, same identity),
which is what lets serving/registry re-pin the handle without
eviction.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu.online import delta
from oap_mllib_tpu.telemetry import metrics as _tm
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils.faults import maybe_fault
from oap_mllib_tpu.utils.timing import Timings


class IncrementalPCA:
    """Streaming PCA: ``partial_fit`` deltas fold into compensated raw
    moments; ``commit`` re-solves the spectrum and publishes (or
    in-place updates) a :class:`~oap_mllib_tpu.models.pca.PCAModel`."""

    def __init__(self, k: int):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self._d: Optional[int] = None
        # host-resident accumulator state (value + Kahan compensation);
        # swapped wholesale at the end of each successful delta
        self._gram = self._gcomp = None
        self._colsum = self._ccomp = None
        self._n = 0.0
        self._commits = 0
        self.model = None  # published PCAModel after the first commit

    def partial_fit(self, x) -> "IncrementalPCA":
        """Fold one delta (array or ChunkSource) into the running raw
        moments — no eigensolve, O(chunk * d^2) per chunk."""
        import jax.numpy as jnp

        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.ops import stream_ops

        # the delta-ingestion fault site: before any accumulation, so
        # an injected failure leaves the running moments untouched
        maybe_fault("delta.ingest")
        cfg = get_config()
        dtype = np.float64 if cfg.enable_x64 else np.float32
        if not isinstance(x, ChunkSource):
            x = ChunkSource.from_array(np.atleast_2d(np.asarray(x)))
        d = x.n_features
        if self._d is None:
            self._d = d
        elif d != self._d:
            raise ValueError(
                f"partial_fit chunk width {d} != accumulated "
                f"dimensionality {self._d}"
            )
        pol = psn.resolve("pca")
        tier = (
            "highest" if cfg.enable_x64
            else psn.kernel_tier(pol.name, cfg.matmul_precision)
        )
        # fresh device buffers (jnp.asarray copies the host state), so
        # the donation chain below never invalidates what we hold —
        # a mid-delta error leaves the host accumulators as they were
        if self._gram is None:
            g = jnp.zeros((d, d), dtype)
            gc = jnp.zeros((d, d), dtype)
            cs = jnp.zeros((d,), dtype)
            cc = jnp.zeros((d,), dtype)
        else:
            g = jnp.asarray(self._gram, dtype)
            gc = jnp.asarray(self._gcomp, dtype)
            cs = jnp.asarray(self._colsum, dtype)
            cc = jnp.asarray(self._ccomp, dtype)
        zero_mean = jnp.zeros((d,), dtype)
        rows = 0.0
        for chunk, nv in x:
            cj = jnp.asarray(chunk, dtype)
            wj = (jnp.arange(chunk.shape[0]) < nv).astype(dtype)
            cs, cc = stream_ops._colsum_chunk_comp(cs, cc, cj, wj)
            # RAW moment: mean pinned at zero — centering is algebraic
            # at commit time (class docstring)
            g, gc = stream_ops._gram_chunk_comp(
                g, gc, cj, wj, zero_mean, tier, pol.name
            )
            rows += float(nv)
        # compute-then-swap of the accumulator state
        self._gram = np.asarray(g)
        self._gcomp = np.asarray(gc)
        self._colsum = np.asarray(cs)
        self._ccomp = np.asarray(cc)
        self._n += rows
        _tm.counter(
            "oap_online_delta_rows_total", {"model": "pca"},
            help="Rows ingested by incremental-fit deltas.",
        ).inc(rows)
        return self

    def commit(self):
        """Re-solve the spectrum from the accumulated moments and
        publish it: the FIRST commit creates the PCAModel, later
        commits replace its component/variance arrays in place (same
        object — served handles re-pin, nothing re-registers).
        Returns the model."""
        from oap_mllib_tpu.models.pca import PCA, PCAModel

        if self._n <= 0 or self._gram is None:
            raise ValueError(
                "commit() before any partial_fit delta — nothing to solve"
            )
        d = int(self._d)
        if self.k > d:
            raise ValueError(
                f"k={self.k} exceeds data dimensionality {d}"
            )
        n = self._n
        colsum = np.asarray(self._colsum, np.float64)
        gram = np.asarray(self._gram, np.float64)
        cov = (gram - np.outer(colsum, colsum) / n) / max(n - 1.0, 1.0)
        cov = 0.5 * (cov + cov.T)  # the batch path's symmetrization
        timings = Timings("pca.commit")
        vals, vecs, total, solver = PCA(self.k)._solve_spectrum(
            np.asarray(cov, np.float32), d, timings
        )
        ratio = vals / total if total > 0 else np.zeros(self.k)
        self._commits += 1
        online = {
            "n_rows": int(n), "commits": self._commits,
            "pca_solver": solver,
        }
        if self.model is None:
            self.model = PCAModel(
                vecs, ratio,
                {"timings": timings, "accelerated": True,
                 "streamed": True, "online": online,
                 "n_rows": int(n), "pca_solver": solver},
            )
        else:
            # in-place: fresh arrays on the SAME model object — the
            # identity-keyed serving pin re-stages them on re-pin
            self.model.components_ = np.asarray(vecs)
            self.model.explained_variance_ = np.asarray(ratio)
            self.model.summary["online"] = online
        delta.commit(
            self.model, "pca",
            detail=f"rows={int(n)} commits={self._commits}",
        )
        return self.model
