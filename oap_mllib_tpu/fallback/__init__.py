"""CPU/NumPy reference implementations — the fallback path.

The reference's dispatch contract (survey §7.1 item 2): when the capability
predicate fails, training runs on vanilla Spark MLlib instead of the
accelerated native path, and user code never notices.  This package is that
vanilla path: straightforward, dependency-free NumPy implementations of each
estimator, covering the cases the accelerated path declines (e.g. cosine
distance or row weights for K-Means — spark-3.1.1/ml/clustering/
KMeans.scala:349-351; explicit-preference ALS — ALS.scala:925).

They double as in-repo correctness baselines for development; the test-suite
oracles are written independently in tests/ (survey §4 takeaway).
"""
