"""NumPy K-Means (Lloyd) — fallback path.

Supports euclidean and cosine distance and row weights, matching what
vanilla Spark MLlib handles when the reference's DAL path declines
(spark-3.1.1/ml/clustering/KMeans.scala:349-351).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _normalize(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=1, keepdims=True)
    return x / np.maximum(norms, 1e-12)


def _sq_dists(x: np.ndarray, centers: np.ndarray, measure: str) -> np.ndarray:
    if measure == "euclidean":
        x_sq = np.sum(x * x, axis=1, keepdims=True)
        c_sq = np.sum(centers * centers, axis=1)
        return np.maximum(x_sq + c_sq[None, :] - 2.0 * x @ centers.T, 0.0)
    elif measure == "cosine":
        # Spark's cosine distance: 1 - cos similarity
        return 1.0 - _normalize(x) @ _normalize(centers).T
    raise ValueError(f"unknown distance measure {measure!r}")


def lloyd_np(
    x: np.ndarray,
    init_centers: np.ndarray,
    max_iter: int,
    tol: float,
    weights: Optional[np.ndarray] = None,
    distance_measure: str = "euclidean",
) -> Tuple[np.ndarray, int, float]:
    """Returns (centers, n_iter, cost). Same convergence rule as the
    accelerated kernel: all centers' squared moves <= tol^2."""
    w = np.ones(x.shape[0]) if weights is None else np.asarray(weights, dtype=x.dtype)
    centers = np.array(init_centers, dtype=x.dtype)
    k = centers.shape[0]
    n_iter = 0
    for _ in range(max_iter):
        d2 = _sq_dists(x, centers, distance_measure)
        assign = np.argmin(d2, axis=1)
        new_centers = centers.copy()
        for j in range(k):
            sel = assign == j
            wj = w[sel]
            if wj.sum() > 0:
                pts = x[sel]
                if distance_measure == "cosine":
                    # Spark averages then re-normalizes for cosine
                    c = (pts * wj[:, None]).sum(axis=0) / wj.sum()
                    nrm = np.linalg.norm(c)
                    new_centers[j] = c / nrm if nrm > 0 else c
                else:
                    new_centers[j] = (pts * wj[:, None]).sum(axis=0) / wj.sum()
        moved_sq = np.sum((new_centers - centers) ** 2, axis=1)
        centers = new_centers
        n_iter += 1
        if np.all(moved_sq <= tol * tol):
            break
    d2 = _sq_dists(x, centers, distance_measure)
    cost = float(np.sum(np.min(d2, axis=1) * w))
    return centers, n_iter, cost


def predict_np(
    x: np.ndarray, centers: np.ndarray, distance_measure: str = "euclidean"
) -> np.ndarray:
    return np.argmin(_sq_dists(x, centers, distance_measure), axis=1)
