"""NumPy ALS — fallback path and development baseline.

Covers both explicit ALS (the case the reference's DAL path declines —
accelerated only when implicitPrefs, spark-3.1.1/ml/recommendation/
ALS.scala:925) and implicit-feedback ALS (Hu/Koren/Volinsky), the
algorithm the reference accelerates via oneDAL's 4-step distributed scheme
(native/ALSDALImpl.cpp).

Normal equations (rank r, regularization lambda, confidence c = 1 + alpha*r):
  implicit:  A_u = Y^T Y + sum_{i in R(u)} alpha*r_ui * y_i y_i^T + lambda I
             b_u = sum_{i in R(u)} (1 + alpha*r_ui) * y_i
  explicit:  A_u = sum_{i in R(u)} y_i y_i^T + lambda I
             b_u = sum_{i in R(u)} r_ui * y_i
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


_U64 = np.uint64


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 hash on uint64 arrays (wraps mod 2^64)."""
    x = (x + _U64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)
    return x ^ (x >> _U64(31))


def init_factors_rows(lo: int, hi: int, rank: int, seed: int) -> np.ndarray:
    """Rows [lo, hi) of the deterministic factor init, position-addressable.

    Counter-based (splitmix64 per element + Box-Muller), so a process can
    generate ONLY its block's rows and get bit-identical values to the
    global ``init_factors`` — the sharded multi-host ALS init never
    materializes (n_users, rank) on any host (the per-rank init the
    reference gets from per-rank seed offsets, ALSDALImpl.cpp:165-169,
    but reproducible across world sizes).  Rows are signed gaussian,
    normalized to unit L2 norm (Spark ALS.initialize style; all-positive
    init is a trap — it sits in a positive-orthant local minimum for
    signed low-rank data).
    """
    rows = np.arange(lo, hi, dtype=np.uint64)[:, None]
    cols = np.arange(rank, dtype=np.uint64)[None, :]
    idx = rows * _U64(rank) + cols
    base = _splitmix64(np.uint64(np.int64(seed)).reshape(1, 1))
    h1 = _splitmix64(idx ^ base)
    h2 = _splitmix64(h1)
    # 53-bit mantissa uniforms in (0, 1]; Box-Muller to gaussians
    u1 = ((h1 >> _U64(11)).astype(np.float64) + 1.0) * (2.0 ** -53)
    u2 = (h2 >> _U64(11)).astype(np.float64) * (2.0 ** -53)
    f = np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)
    norms = np.linalg.norm(f, axis=1, keepdims=True)
    return (f / np.maximum(norms, 1e-12)).astype(np.float32)


def init_factors(n: int, rank: int, seed: int) -> np.ndarray:
    """Deterministic factor init for rows [0, n) — see init_factors_rows."""
    return init_factors_rows(0, n, rank, seed)


def _nnls_spd(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Nonnegative solve of the SPD normal-equation system a x = b
    (min x^T a x - 2 b^T x s.t. x >= 0) — Spark's nonnegative=true NNLS
    analog.  Reduced to standard NNLS via the Cholesky factor:
    a = L L^T  =>  min ||L^T x - L^{-1} b||."""
    try:
        from scipy.optimize import nnls

        l = np.linalg.cholesky(a)
        d = np.linalg.solve(l, b)
        x, _ = nnls(l.T, d)
        return x
    except ImportError:
        # crude fallback: projected gradient on the quadratic
        x = np.maximum(np.linalg.solve(a, b), 0.0)
        step = 1.0 / np.linalg.eigvalsh(a).max()
        for _ in range(200):
            x = np.maximum(x - step * (a @ x - b), 0.0)
        return x


def _solve_side(
    dst_n: int,
    dst_idx: np.ndarray,
    src_idx: np.ndarray,
    ratings: np.ndarray,
    src_factors: np.ndarray,
    rank: int,
    reg: float,
    alpha: float,
    implicit: bool,
    nonnegative: bool = False,
) -> np.ndarray:
    out = np.zeros((dst_n, rank), dtype=np.float32)
    eye = np.eye(rank, dtype=np.float64)
    gram = src_factors.astype(np.float64).T @ src_factors.astype(np.float64) if implicit else None
    order = np.argsort(dst_idx, kind="stable")
    dst_sorted = dst_idx[order]
    bounds = np.searchsorted(dst_sorted, np.arange(dst_n + 1))
    for u in range(dst_n):
        sel = order[bounds[u] : bounds[u + 1]]
        if len(sel) == 0:
            continue
        ys = src_factors[src_idx[sel]].astype(np.float64)  # (m, r)
        rs = ratings[sel].astype(np.float64)  # (m,)
        # Spark parity (reference ALS.scala:1781-1795): implicit uses
        # c1 = alpha*|r| for A (PSD even for non-positive ratings), adds b
        # only for r > 0, and ALS-WR scales lambda by the per-row rating
        # count (numExplicits * regParam) — r > 0 count for implicit,
        # all-ratings count for explicit
        if implicit:
            c1 = alpha * np.abs(rs)
            pos = rs > 0
            n_reg = float(pos.sum())
            a = gram + ys.T @ (ys * c1[:, None]) + reg * n_reg * eye
            b = ((1.0 + c1)[:, None] * ys)[pos].sum(axis=0)
            if n_reg == 0.0:
                continue  # no positive ratings: zero factors (b == 0)
        else:
            n_reg = float(len(sel))
            a = ys.T @ ys + reg * n_reg * eye
            b = (rs[:, None] * ys).sum(axis=0)
        if nonnegative:
            out[u] = _nnls_spd(a, b).astype(np.float32)
        else:
            out[u] = np.linalg.solve(a, b).astype(np.float32)
    return out


def als_np(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    n_users: int,
    n_items: int,
    rank: int = 10,
    max_iter: int = 10,
    reg: float = 0.1,
    alpha: float = 1.0,
    implicit: bool = False,
    seed: int = 0,
    init: Tuple[np.ndarray, np.ndarray] = None,
    nonnegative: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Alternating updates; returns (user_factors, item_factors)."""
    users = np.asarray(users, dtype=np.int64)
    items = np.asarray(items, dtype=np.int64)
    ratings = np.asarray(ratings, dtype=np.float32)
    if init is not None:
        x, y = np.array(init[0], np.float32), np.array(init[1], np.float32)
    else:
        x = init_factors(n_users, rank, seed)
        y = init_factors(n_items, rank, seed + 1)
        if nonnegative:
            x, y = np.abs(x), np.abs(y)
    for _ in range(max_iter):
        x = _solve_side(n_users, users, items, ratings, y, rank, reg, alpha,
                        implicit, nonnegative)
        y = _solve_side(n_items, items, users, ratings, x, rank, reg, alpha,
                        implicit, nonnegative)
    return x, y


def predict_np(x: np.ndarray, y: np.ndarray, users: np.ndarray, items: np.ndarray) -> np.ndarray:
    return np.sum(x[users] * y[items], axis=1)


def rmse_np(x, y, users, items, ratings) -> float:
    pred = predict_np(x, y, users, items)
    return float(np.sqrt(np.mean((pred - ratings) ** 2)))
