"""NumPy PCA — fallback path (the vanilla ``mllib.feature.PCA`` analog,
reference spark-3.1.1/ml/feature/PCA.scala:110-116)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


def pca_np(x: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (components (d, k), explained_variance_ratio (k,)).

    Covariance eigendecomposition, matching Spark's
    RowMatrix.computePrincipalComponentsAndExplainedVariance semantics:
    ratios normalized by the TOTAL variance (sum over all d eigenvalues).
    """
    x = np.asarray(x, dtype=np.float64)
    n = x.shape[0]
    mean = x.mean(axis=0)
    xc = x - mean
    cov = (xc.T @ xc) / max(n - 1, 1)
    vals, vecs = np.linalg.eigh(cov)
    vals = vals[::-1]
    vecs = vecs[:, ::-1]
    total = vals.sum()
    ratio = vals[:k] / total if total > 0 else np.zeros(k)
    return vecs[:, :k], ratio
