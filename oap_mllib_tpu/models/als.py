"""ALS estimator with Spark-MLlib-compatible parameters.

API parity target: ``org.apache.spark.ml.recommendation.ALS`` as shimmed by
the reference (spark-3.1.1/ml/recommendation/ALS.scala): params rank,
maxIter, regParam, alpha, implicitPrefs, seed; model surface userFactors /
itemFactors and pairwise prediction.

Dispatch: the reference accelerates ONLY implicit-feedback ALS
(ALS.scala:925) and falls back to Spark otherwise.  Here both implicit and
explicit run accelerated (the TPU kernels cover both); the fallback NumPy
path remains for ``device=cpu`` or failed platform checks.

Ids: like the reference (ALSDALImpl.scala:62-70 computes nUsers/nItems via
RDD max), ids are dense non-negative ints; n_users/n_items default to
max+1 and may be passed explicitly.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu import telemetry
from oap_mllib_tpu.fallback import als_np
from oap_mllib_tpu.ops import als_ops
from oap_mllib_tpu.ops.pallas import autotune
from oap_mllib_tpu.utils import checkpoint as ckpt_mod
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.dispatch import should_accelerate
from oap_mllib_tpu.utils.timing import Timings, phase_timer


class ALSModel:
    """Trained ALS factors.

    User factors may be held as rank-local device shards (block-sharded
    over the mesh with per-block offsets — the ALSResult.cUserOffset
    bookkeeping of the reference, ALSDALImpl.cpp:529-575) and are only
    gathered to host on first access of ``user_factors_``.  In a
    multi-process world that gather is a COLLECTIVE: every process must
    touch ``user_factors_`` (or predict/save) together, mirroring how the
    reference reassembles factor RDDs with a cluster-wide job
    (ALSDALImpl.scala:124-164).
    """

    def __init__(self, user_factors: Optional[np.ndarray],
                 item_factors: Optional[np.ndarray],
                 summary: Optional[dict] = None, *,
                 sharded_user: Optional[tuple] = None,
                 sharded_item: Optional[tuple] = None):
        if (user_factors is None) == (sharded_user is None):
            raise ValueError("pass exactly one of user_factors / sharded_user")
        if (item_factors is None) == (sharded_item is None):
            raise ValueError("pass exactly one of item_factors / sharded_item")
        self._user_factors = (
            None if user_factors is None else np.asarray(user_factors)
        )
        self._item_factors = (
            None if item_factors is None else np.asarray(item_factors)
        )
        # each: (blocks jax.Array (world*per, r) block-sharded, offsets, per)
        self._sharded_user = sharded_user
        self._sharded_item = sharded_item
        self.summary = summary or {}
        # device-copy cache (serving/registry.pin): the top-k target
        # table pins across chunks AND across calls — one upload per
        # factor table per model lifetime, not one per recommend call
        self._dev_cache: dict = {}

    @property
    def user_factors_(self) -> np.ndarray:
        if self._user_factors is None:
            self._user_factors = self._gather_blocks(self._sharded_user)
        return self._user_factors

    @property
    def item_factors_(self) -> np.ndarray:
        """Item factors; block-sharded fits (als_item_layout="sharded")
        gather on first access — a COLLECTIVE in multi-process worlds,
        same contract as user_factors_."""
        if self._item_factors is None:
            self._item_factors = self._gather_blocks(self._sharded_item)
        return self._item_factors

    @staticmethod
    def _gather_blocks(shard: tuple) -> np.ndarray:
        """On-demand gather of block-sharded factors (collective when the
        blocks span processes).  ``shard`` = (blocks, offsets, per_block);
        block b's real rows [offsets[b], offsets[b+1]) sit at padded rows
        [b*per_block, ...) — the ALSResult cUserOffset bookkeeping of the
        reference, ALSDALImpl.cpp:529-575."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        xb, offsets, per = shard
        if not xb.is_fully_addressable:
            mesh = xb.sharding.mesh
            xb = progcache.get_or_build(
                "als.gather_replicated",
                (progcache.mesh_fingerprint(mesh),),
                lambda: jax.jit(
                    lambda a: a, out_shardings=NamedSharding(mesh, P())
                ),
            )(xb)
        xb = np.asarray(xb)
        rank = xb.shape[1]
        n = int(offsets[-1])
        x = np.zeros((n, rank), np.float32)
        for b in range(len(offsets) - 1):
            lo, hi = int(offsets[b]), int(offsets[b + 1])
            x[lo:hi] = xb[b * per : b * per + (hi - lo)]
        return x

    @property
    def rank(self) -> int:
        if self._item_factors is not None:
            return self._item_factors.shape[1]
        return self._sharded_item[0].shape[1]

    def predict(self, users: np.ndarray, items: np.ndarray) -> np.ndarray:
        """Predicted preference/rating for (user, item) pairs
        (~ ALSModel.transform's dot-product predictions)."""
        users = np.asarray(users, dtype=np.int32)
        items = np.asarray(items, dtype=np.int32)
        return np.asarray(
            als_ops.predict_pairs(
                jnp.asarray(self.user_factors_),
                jnp.asarray(self.item_factors_),
                jnp.asarray(users),
                jnp.asarray(items),
            )
        )

    def _top_k_scores(self, query: np.ndarray, targets: np.ndarray, n: int,
                      row_chunk: int = 0, with_scores: bool = True):
        """Top-n (ids, scores) per query row, chunked over query rows so
        the (n_query, n_targets) score matrix never materializes (the
        reference blocks its recommendForAll the same way —
        ALS.scala:383-401 blockify — because the full cross product is
        quadratic in memory).  ``row_chunk`` 0 sizes chunks from the
        shared live-buffer budget over the score block AND the query
        chunk (kmeans_ops.rows_per_chunk) — a fixed row count would blow
        up against a huge target side, and a score-only bound against a
        wide query side.  ``with_scores=False`` skips the host transfer
        of the float score blocks entirely (ids-only callers should not
        pay a second device->host copy); the scores slot is then None.

        Scoring routes through the serving batcher (serving/batcher.py):
        the target table PINS on-device across chunks and across calls
        (the model's device cache — one upload per table per model
        lifetime), the tail chunk rounds onto its geometric bucket, and
        the pdot policy stays the serving default (f32 = HIGHEST,
        bit-compatible: the returned scores must match predict() —
        TPU's default bf16 matmul drifts them ~1e-3 and can swap
        near-tie rankings, caught on hardware, round 5).

        ``n`` is clamped to the target count, like Spark's
        recommendForAll* which just returns fewer rows when asked for
        more than exist — without the clamp lax.top_k raises an opaque
        XLA error on an oversized request."""
        from oap_mllib_tpu.ops.kmeans_ops import rows_per_chunk
        from oap_mllib_tpu.serving import batcher
        from oap_mllib_tpu.serving.registry import pin

        if n < 0:
            raise ValueError(f"top-k count must be >= 0, got {n}")
        n = min(int(n), targets.shape[0])
        if query.shape[0] == 0:
            return (
                np.zeros((0, n), np.int32),
                np.zeros((0, n), np.float32) if with_scores else None,
            )
        rows = row_chunk or rows_per_chunk(
            targets.shape[0], query.shape[1]
        )
        if targets is self._item_factors:
            tj = pin(self._dev_cache, "targets:item", targets)
        elif targets is self._user_factors:
            tj = pin(self._dev_cache, "targets:user", targets)
        else:  # a transient target table (tests, subsets): stage once
            tj = batcher.stage(np.asarray(targets, np.float32))
        ids, scores = [], []
        for lo in range(0, query.shape[0], rows):
            q = np.asarray(query[lo : lo + rows], np.float32)
            nv = q.shape[0]
            if nv < rows:
                # tail chunk rounds onto its bucket — one extra compiled
                # shape at most, whatever the query size
                q, _ = batcher.bucket_batch(q)
            s, i = batcher.topk_pairs(batcher.stage(q), tj, n)
            ids.append(jax.device_get(i)[:nv])
            if with_scores:
                scores.append(jax.device_get(s)[:nv])
        return (
            np.concatenate(ids, axis=0),
            np.concatenate(scores, axis=0) if with_scores else None,
        )

    def recommend_for_all_users(
        self, num_items: int, with_scores: bool = False
    ):
        """Top-N item ids per user — one (n_users, r)x(r, n_items) MXU
        matmul + top_k (~ ALSModel.recommendForAllUsers).  Spark returns
        (item, rating) structs; ``with_scores=True`` returns the
        (ids, scores) pair (descending scores, the predicted
        preferences)."""
        ids, scores = self._top_k_scores(
            self.user_factors_, self.item_factors_, num_items,
            with_scores=with_scores,
        )
        return (ids, scores) if with_scores else ids

    def recommend_for_all_items(
        self, num_users: int, with_scores: bool = False
    ):
        """Top-N user ids per item (~ ALSModel.recommendForAllItems);
        ``with_scores`` as in recommend_for_all_users."""
        ids, scores = self._top_k_scores(
            self.item_factors_, self.user_factors_, num_users,
            with_scores=with_scores,
        )
        return (ids, scores) if with_scores else ids

    def _recommend_subset(self, query_ids, query_table, target_table,
                          n: int, with_scores: bool):
        """Shared subset recommender: row j of the result is the top-n
        for query_ids[j] (callers pass ids already validated/deduped)."""
        q = query_table[np.asarray(query_ids, np.int64)]
        ids, scores = self._top_k_scores(
            q, target_table, n, with_scores=with_scores
        )
        return (ids, scores) if with_scores else ids

    def recommend_for_users(self, user_ids, num_items: int,
                            with_scores: bool = False):
        """Top-N item ids for a SUBSET of users
        (~ ALSModel.recommendForUserSubset, reference
        spark-3.1.1/ml/recommendation/ALS.scala:379-403).  Row j is the
        recommendation list for ``user_ids[j]`` (ids must be in range;
        the compat layer applies Spark's distinct-and-join semantics)."""
        user_ids = np.asarray(user_ids, np.int64)
        n_u = self.user_factors_.shape[0]
        if len(user_ids) and (
            user_ids.min() < 0 or user_ids.max() >= n_u
        ):
            raise ValueError(
                f"user ids must be in [0, {n_u}); got range "
                f"[{user_ids.min()}, {user_ids.max()}]"
            )
        return self._recommend_subset(
            user_ids, self.user_factors_, self.item_factors_, num_items,
            with_scores,
        )

    def recommend_for_items(self, item_ids, num_users: int,
                            with_scores: bool = False):
        """Top-N user ids for a SUBSET of items
        (~ ALSModel.recommendForItemSubset, ALS.scala:405-429)."""
        item_ids = np.asarray(item_ids, np.int64)
        n_i = self.item_factors_.shape[0]
        if len(item_ids) and (
            item_ids.min() < 0 or item_ids.max() >= n_i
        ):
            raise ValueError(
                f"item ids must be in [0, {n_i}); got range "
                f"[{item_ids.min()}, {item_ids.max()}]"
            )
        return self._recommend_subset(
            item_ids, self.item_factors_, self.user_factors_, num_users,
            with_scores,
        )

    def fold_in_users(self, users, items, ratings, **kw) -> dict:
        """Incremental fold-in of new/changed USER rows against the
        frozen item table (online/foldin.py): one batched
        normal-equation solve per delta — the PR 9 half-update kernel,
        zero full refit — then an in-place serving re-pin.  ``users``/
        ``items``/``ratings`` are the touched users' FULL current
        rating rows (the standard fold-in contract; a partial row would
        silently solve against a truncated normal equation).  The user
        axis may GROW: ids beyond the current table extend it, with
        untouched new rows at the deterministic init.  Keyword
        arguments (reg/alpha/implicit/seed) default to the base fit's
        ``summary["params"]``.  Returns the commit record (rows solved,
        growth, new model version)."""
        from oap_mllib_tpu.online import foldin

        return foldin.fold_in(self, users, items, ratings,
                              side="user", **kw)

    def fold_in_items(self, users, items, ratings, **kw) -> dict:
        """Symmetric fold-in of new/changed ITEM rows against the
        frozen user table — see :meth:`fold_in_users`."""
        from oap_mllib_tpu.online import foldin

        return foldin.fold_in(self, users, items, ratings,
                              side="item", **kw)

    def save(self, path: str) -> None:
        """Atomic per-file writes, metadata last (data/io primitives) —
        the KMeansModel.save torn-write contract.  Sharded fits gather
        their factors first (a collective in multi-process worlds; the
        user_factors_ contract above)."""
        from oap_mllib_tpu.data import io as _io

        os.makedirs(path, exist_ok=True)
        _io.atomic_save_npy(
            os.path.join(path, "user_factors.npy"), self.user_factors_
        )
        _io.atomic_save_npy(
            os.path.join(path, "item_factors.npy"), self.item_factors_
        )
        _io.atomic_write_json(
            os.path.join(path, "metadata.json"),
            {"type": "ALSModel", "rank": int(self.rank),
             "user_shape": [int(v) for v in self.user_factors_.shape],
             "item_shape": [int(v) for v in self.item_factors_.shape],
             "version": 1},
        )

    @classmethod
    def load(cls, path: str) -> "ALSModel":
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("type") != "ALSModel":
            raise ValueError(f"not an ALSModel directory: {path}")
        uf = np.load(os.path.join(path, "user_factors.npy"))
        itf = np.load(os.path.join(path, "item_factors.npy"))
        for name, arr in (("user_factors.npy", uf), ("item_factors.npy", itf)):
            expect = meta.get(
                name.replace("_factors.npy", "_shape"),
                [None, meta["rank"]],
            )
            if arr.ndim != 2 or int(arr.shape[1]) != int(expect[1]) or (
                    expect[0] is not None
                    and int(arr.shape[0]) != int(expect[0])):
                raise ValueError(
                    f"{os.path.join(path, name)}: factors have shape "
                    f"{tuple(arr.shape)}, metadata expects {tuple(expect)} "
                    "— the model directory is torn or mixed from two saves"
                )
        return cls(uf, itf)


def _grouped_ok_single(kernel: str, users, items, n_users: int,
                       n_items: int) -> bool:
    """Grouped-vs-COO decision for the single-device layouts — ONE
    definition shared by the in-memory and streamed entries so the two
    paths can never route the same data to different kernels."""
    if kernel != "auto":
        return kernel == "grouped"
    padded_total = als_ops.grouped_padded_edges(
        users, n_users
    ) + als_ops.grouped_padded_edges(items, n_items)
    return padded_total <= als_ops.GROUPED_MAX_BLOWUP * max(len(users), 1)


def _als_kernel_cfg() -> str:
    """Validated Config.als_kernel — every dispatch site (single-device AND
    block-parallel) goes through this so a typo can never silently fall
    back to the auto heuristic."""
    from oap_mllib_tpu.config import get_config

    kernel = get_config().als_kernel
    if kernel not in ("auto", "grouped", "coo"):
        raise ValueError(
            f"als_kernel must be auto|grouped|coo, got {kernel!r}"
        )
    return kernel


class ALS:
    """ALS estimator. Param parity with Spark ML ALS defaults:
    rank=10, max_iter=10, reg_param=0.1, implicit_prefs=False, alpha=1.0."""

    def __init__(
        self,
        rank: int = 10,
        max_iter: int = 10,
        reg_param: float = 0.1,
        implicit_prefs: bool = False,
        alpha: float = 1.0,
        seed: Optional[int] = None,
        nonnegative: bool = False,
        num_user_blocks: Optional[int] = None,
        num_item_blocks: Optional[int] = None,
    ):
        if rank < 1:
            raise ValueError("rank must be >= 1")
        if max_iter < 0:
            raise ValueError("max_iter must be >= 0")
        if reg_param < 0:
            raise ValueError("reg_param must be >= 0")
        if alpha < 0:
            raise ValueError("alpha must be >= 0")
        if num_user_blocks is not None and num_user_blocks < 1:
            raise ValueError("num_user_blocks must be >= 1")
        if num_item_blocks is not None and num_item_blocks < 1:
            raise ValueError("num_item_blocks must be >= 1")
        self.rank = rank
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.implicit_prefs = implicit_prefs
        self.alpha = alpha
        # None = Config.seed (the OAP_MLLIB_TPU_SEED default for
        # estimators that do not set one — docs/configuration.md)
        from oap_mllib_tpu.config import get_config

        self.seed = get_config().seed if seed is None else seed
        self.nonnegative = nonnegative
        # Block-layout hints (Spark ALS numUserBlocks/numItemBlocks,
        # reference ALS.scala:154-169).  Here the user-block count is the
        # mesh data-axis size (one block per device); num_user_blocks CAPS
        # it in single-process worlds.  The item side follows
        # config.als_item_layout: "sharded" gives world item blocks (the
        # 2-D grid), "replicated" one; num_item_blocks is recorded in the
        # fit summary but the layout knob is the config field.
        self.num_user_blocks = num_user_blocks
        self.num_item_blocks = num_item_blocks

    def fit(
        self,
        users,
        items: Optional[np.ndarray] = None,
        ratings: Optional[np.ndarray] = None,
        n_users: Optional[int] = None,
        n_items: Optional[int] = None,
        init: Optional[tuple] = None,
    ) -> ALSModel:
        """Fit factors from (user, item, rating) triples — see
        :meth:`_fit_impl` for the full contract.  This public wrapper
        additionally stamps the fit hyperparameters into
        ``model.summary["params"]`` so the incremental paths
        (online/foldin.py) can default reg/alpha/implicit/seed to
        exactly what the base fit used instead of asking the caller to
        re-plumb them."""
        model = self._fit_impl(
            users, items, ratings, n_users, n_items, init
        )
        model.summary.setdefault("params", {
            "rank": int(self.rank),
            "reg": float(self.reg_param),
            "alpha": float(self.alpha),
            "implicit": bool(self.implicit_prefs),
            "seed": int(self.seed),
        })
        return model

    def _fit_impl(
        self,
        users,
        items: Optional[np.ndarray] = None,
        ratings: Optional[np.ndarray] = None,
        n_users: Optional[int] = None,
        n_items: Optional[int] = None,
        init: Optional[tuple] = None,
    ) -> ALSModel:
        """Fit factors from (user, item, rating) triples.

        Regularization follows Spark's ALS-WR convention (reference
        ALS.scala:1794-1795): lambda is scaled by each row's rating count
        — r>0 count for implicit (whose confidence weights also follow
        Spark: alpha*|r| in A, b only for r>0), all ratings for explicit.

        Multi-host: when ``jax.process_count() > 1`` the triples are this
        process's LOCAL shard (the per-rank partitions of the reference's
        shuffle, ALSDALImpl.scala:95-109); n_users/n_items are resolved
        globally via allgathered maxima when not passed.

        Out-of-core: ``users`` may instead be a width-3
        :class:`~oap_mllib_tpu.data.stream.ChunkSource` of (user, item,
        rating) rows (``items``/``ratings`` omitted) — the fit then keeps
        device memory bounded by O(chunk + factors + moments) instead of
        holding the full grouped edge layouts in HBM (the K-Means/PCA
        streaming axis, extended to the hardest estimator;
        ops/als_stream.py).  Ids ride f64 chunks exactly (<= 2^53).
        """
        from oap_mllib_tpu.data.stream import ChunkSource

        if isinstance(users, ChunkSource):
            if items is not None or ratings is not None:
                raise ValueError(
                    "pass EITHER a triples ChunkSource OR explicit "
                    "users/items/ratings arrays"
                )
            return self._fit_source(users, n_users, n_items, init)
        if items is None or ratings is None:
            raise TypeError("fit needs items and ratings arrays")
        users, items, ratings, n_users, n_items = self._validate_resolve(
            users, items, ratings, n_users, n_items
        )

        # nonnegative uses the NNLS fallback path (the reference likewise
        # accelerates only the unconstrained implicit solver, ALS.scala:925)
        accelerated = should_accelerate(
            "ALS", guard_ok=not self.nonnegative, reason="nonnegative=True"
        )
        if init is not None:
            x0, y0 = np.array(init[0], np.float32), np.array(init[1], np.float32)
        else:
            # deferred: the block-parallel path inits its user blocks
            # per-process (counter-based init_factors_rows) so no host
            # ever materializes (n_users, rank)
            x0 = y0 = None

        if not accelerated:
            return self._fit_fallback_np(
                users, items, ratings, n_users, n_items, x0, y0
            )

        # accelerated path (~ ALSDALImpl.train, ALSDALImpl.scala:58)
        import jax

        from oap_mllib_tpu.parallel.mesh import get_mesh

        from oap_mllib_tpu.ops.als_block import als_item_layout_cfg
        from oap_mllib_tpu.utils import resilience

        als_item_layout_cfg()  # typo'd layout raises on every path
        mesh = get_mesh()
        world = mesh.shape[mesh.axis_names[0]]
        if (
            self.num_user_blocks is not None
            and jax.process_count() == 1
            and self.num_user_blocks < world
        ):
            # honor the numUserBlocks cap: fewer user blocks = a smaller
            # DATA axis (one block per data-axis slot), so the device
            # budget is blocks x model_parallel.  Multi-process worlds keep
            # one block per global device — restricting the device set
            # there would strand processes.
            mp = mesh.shape[mesh.axis_names[1]] if len(mesh.axis_names) > 1 else 1
            mesh = get_mesh(n_devices=self.num_user_blocks * mp)
            world = mesh.shape[mesh.axis_names[0]]
        # degradation ladder (utils/resilience.py): transient faults
        # retry the fit; the single-device grouped path maps the OOM
        # rung to the streamed (bounded-HBM) kernels; the final rung is
        # the same NumPy path the static gate falls back to
        stats = resilience.ResilienceStats()

        def fallback():
            return self._fit_fallback_np(
                users, items, ratings, n_users, n_items, x0, y0
            )

        from oap_mllib_tpu.utils import membudget

        multi = world > 1 or jax.process_count() > 1
        # memory-budget route plan (utils/membudget.py): grouped edge
        # layouts whose device footprint exceeds the HBM budget run the
        # streamed (host-resident-edge) kernels instead of assuming the
        # whole layout fits
        plan = membudget.plan_als(
            len(users), n_users, n_items, self.rank,
            world=world if multi else 1,
        )
        if multi:
            # distributed 2-D block layout for BOTH modes: ratings shuffled
            # by user block, X block-sharded, Y replicated (~ the
            # reference's full cShuffleData + 4-step pipeline, survey §3.3;
            # round 1 left explicit ALS on the unsharded global program)
            def attempt(degraded):
                timings = Timings("als.fit")
                cache_before = progcache.stats()
                tune_before = autotune.mark()
                model = self._fit_block_parallel(
                    users, items, ratings, n_users, n_items, x0, y0, mesh,
                    timings,
                )
                model.summary["progcache"] = progcache.delta(cache_before)
                model.summary["tuning"] = autotune.delta(tune_before)
                return model

            model = resilience.resilient_fit(
                "ALS", attempt, fallback, stats=stats
            )
            resilience.merge_stats(model.summary, stats)
            membudget.record_plan(model.summary, plan)
            telemetry.finalize_fit(model.summary)
            return model

        def attempt(degraded):
            return self._fit_single_device(
                users, items, ratings, n_users, n_items, x0, y0, degraded,
                plan=plan,
            )

        model = resilience.resilient_fit("ALS", attempt, fallback, stats=stats)
        resilience.merge_stats(model.summary, stats)
        membudget.record_plan(model.summary, plan)
        telemetry.finalize_fit(model.summary)
        return model

    def _fit_fallback_np(self, users, items, ratings, n_users, n_items,
                         x0, y0) -> ALSModel:
        """The CPU/NumPy reference path — both the static fallback
        (failed dispatch predicate) and the resilience ladder's final
        rung reach the fit through here."""
        timings = Timings("als.fit")
        if x0 is None:
            x0 = als_np.init_factors(n_users, self.rank, self.seed)
            y0 = als_np.init_factors(n_items, self.rank, self.seed + 1)
        if self.nonnegative:
            # the nonnegative contract must hold even at max_iter=0 or
            # with a user-supplied signed init
            x0, y0 = np.abs(x0), np.abs(y0)
        with phase_timer(timings, "als_np"):
            x, y = als_np.als_np(
                users, items, ratings, n_users, n_items, self.rank,
                self.max_iter, self.reg_param, self.alpha,
                self.implicit_prefs, self.seed, init=(x0, y0),
                nonnegative=self.nonnegative,
            )
        return ALSModel(
            x, y,
            {"timings": timings, "accelerated": False,
             "item_layout": "replicated",
             **self._block_summary(1)},
        )

    # the id-space axes may GROW across restores (utils/checkpoint.py
    # growable axes): yesterday's checkpoint warm-starts today's fit
    # over a larger user/item universe — old rows restore bit-identical,
    # the grown tail initializes deterministically (_fill_grown)
    _GROWABLE = ("n_users", "n_items")

    def _ckpt_signature(self, n_users: int, n_items: int) -> dict:
        """Checkpoint identity (utils/checkpoint.py): the solver params
        and id-space shape.  World size, block layout, kernel choice,
        chunking, and precision policy are deliberately absent — every
        one of them may change across a preemption and the factor
        iterates remain valid state.  ``n_users``/``n_items`` are
        declared growable (``_GROWABLE``), so a restore accepts a
        manifest with a smaller id space (shape-prefix match) and
        records the growth in ``summary.checkpoint["grown"]``."""
        return {
            "rank": self.rank, "implicit": bool(self.implicit_prefs),
            "reg": float(self.reg_param), "alpha": float(self.alpha),
            "seed": int(self.seed), "n_users": int(n_users),
            "n_items": int(n_items),
        }

    def _fill_grown(self, grown: dict, x=None, y=None):
        """Initialize the GROWN tail of restored factor tables: rows
        [old, new) of a grown axis carry no checkpointed state (they
        restore zero-filled), so they get the deterministic init —
        ``als_np.init_factors_rows`` is position-addressable, making the
        filled rows bit-identical to what a from-scratch fit of the
        grown universe would have started those ids at."""
        if x is not None and "n_users" in grown:
            lo, hi = grown["n_users"]
            x = np.asarray(x, np.float32)
            x[lo:hi] = als_np.init_factors_rows(
                lo, hi, self.rank, self.seed
            )
        if y is not None and "n_items" in grown:
            lo, hi = grown["n_items"]
            y = np.asarray(y, np.float32)
            y[lo:hi] = als_np.init_factors_rows(
                lo, hi, self.rank, self.seed + 1
            )
        return x, y

    def _run_segmented(self, ckpt, x0, y0, run_iters, n_users, n_items):
        """Checkpoint-armed in-memory ALS: run the compiled scan in
        ``checkpoint_interval``-sized segments with a full-factor
        checkpoint between them.  The scan body is a pure function per
        iteration, so segmentation is bit-identical to the single
        compiled loop; ``run_iters(x, y, iters)`` runs one segment."""
        resume = ckpt.restore()
        done = 0
        x, y = x0, y0
        if resume.found:
            # either storage form — a block world's sharded checkpoint
            # restores onto this single-device fit too
            x = ckpt_mod.factors_from_result(resume, "x", n_users)
            y = ckpt_mod.factors_from_result(resume, "y", n_items)
            if resume.grown:
                x, y = self._fill_grown(resume.grown, x, y)
            done = min(int(resume.step), self.max_iter)
            if "x" not in resume.arrays:
                ckpt.mark_resharded()  # sharded state -> one device
        while done < self.max_iter:
            seg = min(ckpt.interval, self.max_iter - done)
            x, y = run_iters(x, y, seg)
            done += seg
            ckpt.maybe_write(
                done, {"x": np.asarray(x), "y": np.asarray(y)}, force=True,
            )
        return x, y

    def _fit_single_device(self, users, items, ratings, n_users, n_items,
                           x0, y0, degraded=0, plan=None) -> ALSModel:
        """The single-device accelerated fit (grouped or COO layouts).
        ``degraded`` is the ladder's OOM rung level: the grouped path
        re-runs through the streamed kernels (ops/als_stream.py) at
        halved upload blocks — host-resident edges, O(chunk + factors +
        moments) HBM — which is exactly the memory-shedding retry a
        device OOM calls for; the COO path has no equivalent knob and
        re-runs unchanged (a persistent OOM then falls through to the
        NumPy rung).  A ``plan`` routed "streamed" (the HBM budget
        rejected the resident grouped layouts, utils/membudget.py) runs
        the same streamed kernels from the start — the budget-driven
        twin of the OOM rung, decided BEFORE the device ever faults."""
        from oap_mllib_tpu.utils import membudget

        planned_streamed = (
            plan is not None
            and plan.route == membudget.ROUTE_STREAMED
        )
        timings = Timings("als.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        # compute-precision policy (utils/precision.py), resolved per
        # attempt so the ladder's f32-degradation scope applies on retry
        pol = psn.resolve("als")
        if x0 is None:
            x0 = als_np.init_factors(n_users, self.rank, self.seed)
            y0 = als_np.init_factors(n_items, self.rank, self.seed + 1)
        with phase_timer(timings, "table_convert"):
            # grouped-edge layout, one copy per update direction (the
            # reference's per-rank CSR + transposed CSR, ALSDALImpl.scala
            # :184-230 / .cpp:209-213, rebuilt for batched MXU matmuls —
            # see als_ops grouped-path notes); edge indices are static
            # across iterations so the sort/pad runs once per fit.  The
            # blowup guard runs on bincounts BEFORE any (G, P) layout is
            # materialized (adaptive group sizing keeps typical data under
            # 2x; extreme long-tail degree splits would pad up to 8x nnz,
            # so a "coo" decision must not pay for the build).
            nnz = len(users)
            kernel = _als_kernel_cfg()
            grouped_ok = _grouped_ok_single(
                kernel, users, items, n_users, n_items
            )
            if planned_streamed and not grouped_ok:
                # the planner routed streamed but the degree
                # distribution forces COO (streaming is grouped-only) —
                # a scale downgrade that must never be silent: strict
                # raises BudgetError here, auto warns + records
                plan.downgrade(
                    membudget.ROUTE_IN_MEMORY,
                    "grouped guard rejected the degree distribution "
                    "(COO streaming unsupported)",
                )
                planned_streamed = False
            stream_route = bool(degraded) or planned_streamed
            if grouped_ok:
                by_user = als_ops.build_grouped_edges(
                    users, items, ratings, n_users
                )
                by_item = als_ops.build_grouped_edges(
                    items, users, ratings, n_items
                )
                if not stream_route:
                    # the streamed route keeps the layouts HOST-resident
                    # for the streamed kernels instead of uploading both
                    dev = tuple(jnp.asarray(a) for a in (*by_user, *by_item))
            else:
                # COO nnz pads to a shape bucket (data/bucketing.py,
                # anchored at the 2048 edge-chunk multiple): the COO
                # programs are keyed on padded nnz, so refits of a
                # growing ratings set within one bucket reuse the
                # compiled loop; padding edges carry valid=0
                from oap_mllib_tpu.data.bucketing import bucket_rows

                pad = bucket_rows(nnz, 2048) - nnz
                u = jnp.asarray(np.pad(users, (0, pad)).astype(np.int32))
                i = jnp.asarray(np.pad(items, (0, pad)).astype(np.int32))
                c = jnp.asarray(np.pad(ratings, (0, pad)))
                valid = jnp.asarray(np.pad(np.ones(nnz, np.float32), (0, pad)))
        from oap_mllib_tpu.utils.profiling import maybe_trace

        ckpt = ckpt_mod.maybe_open(
            "als", self._ckpt_signature(n_users, n_items), timings=timings,
            growable=self._GROWABLE,
        )
        with phase_timer(timings, "als_iterations"), maybe_trace():
            if grouped_ok and stream_route:
                from oap_mllib_tpu.ops import als_stream

                x, y = als_stream.als_run_streamed(
                    by_user, by_item, x0, y0, n_users, n_items,
                    self.max_iter, self.reg_param, self.alpha,
                    self.implicit_prefs, timings=timings,
                    degraded=bool(degraded), policy=pol.name,
                    checkpoint=ckpt, grown_fill=self._fill_grown,
                )
            elif grouped_ok:
                def run_iters(xa, ya, iters):
                    return als_ops.als_run_grouped(
                        *dev, jnp.asarray(xa), jnp.asarray(ya),
                        n_users, n_items, iters, self.reg_param,
                        self.alpha, self.implicit_prefs, timings=timings,
                        policy=pol.name,
                    )

                if ckpt is None:
                    x, y = run_iters(x0, y0, self.max_iter)
                else:
                    x, y = self._run_segmented(
                        ckpt, x0, y0, run_iters, n_users, n_items
                    )
            elif self.implicit_prefs:
                def run_iters(xa, ya, iters):
                    return als_ops.als_implicit_run(
                        u, i, c, valid, jnp.asarray(xa), jnp.asarray(ya),
                        n_users, n_items, iters, self.reg_param,
                        self.alpha, timings=timings, policy=pol.name,
                    )

                if ckpt is None:
                    x, y = run_iters(x0, y0, self.max_iter)
                else:
                    x, y = self._run_segmented(
                        ckpt, x0, y0, run_iters, n_users, n_items
                    )
            else:
                def run_iters(xa, ya, iters):
                    return als_ops.als_explicit_run(
                        u, i, c, valid, jnp.asarray(xa), jnp.asarray(ya),
                        n_users, n_items, iters, self.reg_param,
                        timings=timings, policy=pol.name,
                    )

                if ckpt is None:
                    x, y = run_iters(x0, y0, self.max_iter)
                else:
                    x, y = self._run_segmented(
                        ckpt, x0, y0, run_iters, n_users, n_items
                    )
            x = np.asarray(x)
            y = np.asarray(y)
        summary = {
            "timings": timings, "accelerated": True,
            "als_kernel": "grouped" if grouped_ok else "coo",
            "item_layout": "replicated",
            "progcache": progcache.delta(cache_before),
            "tuning": autotune.delta(tune_before),
            **self._block_summary(1),
        }
        if stream_route and grouped_ok:
            # the OOM rung or the budget plan ran the streamed kernels
            summary["streamed"] = True
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        return ALSModel(x, y, summary)

    @staticmethod
    def _validate_resolve(users, items, ratings, n_users, n_items):
        """Shared triple validation + id-space resolution (array and
        streamed entries).  Multi-process: global maxima by allgather
        (the reference's RDD max jobs, ALSDALImpl.scala:62-70)."""
        users = np.asarray(users, dtype=np.int64)
        items = np.asarray(items, dtype=np.int64)
        ratings = np.asarray(ratings, dtype=np.float32)
        if not (len(users) == len(items) == len(ratings)):
            raise ValueError("users/items/ratings must have equal length")
        if len(users) == 0:
            raise ValueError("empty ratings")
        if users.min() < 0 or items.min() < 0:
            raise ValueError("ids must be non-negative")
        import jax as _jax

        if _jax.process_count() > 1:
            from jax.experimental import multihost_utils

            maxes = np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([users.max(), items.max()], np.int64)
                )
            ).reshape(-1, 2)
            if n_users is None:
                n_users = int(maxes[:, 0].max()) + 1
            if n_items is None:
                n_items = int(maxes[:, 1].max()) + 1
        if n_users is None:
            n_users = int(users.max()) + 1
        elif int(users.max()) >= n_users:
            raise ValueError(
                f"user id {int(users.max())} out of range for n_users={n_users}"
            )
        if n_items is None:
            n_items = int(items.max()) + 1
        elif int(items.max()) >= n_items:
            raise ValueError(
                f"item id {int(items.max())} out of range for n_items={n_items}"
            )
        return users, items, ratings, n_users, n_items

    def _fit_source(self, source, n_users, n_items, init) -> ALSModel:
        """Out-of-core fit from a width-3 (user, item, rating) ChunkSource
        (ops/als_stream.py).  The triples are ingested to host arrays —
        host RAM is O(nnz), like the reference's executor partitions
        (OneDAL.scala:92-166) — and the STREAMED property is device
        memory: only one budget-bounded chunk of the grouped edge layouts
        is resident per step, with factors staying on device.

        Multi-device / multi-process worlds COMPOSE streaming with the
        mesh (ops/als_block_stream.py): each rank keeps only its block's
        grouped layouts in host RAM and streams them through its device,
        with the block path's collective structure unchanged — per-device
        HBM stays O(chunk + factors + moments) while nnz scales with
        aggregate host RAM.

        Falls back to the standard in-memory fit only when the streamed
        path does not apply: fallback/nonnegative dispatch, or a
        long-tail degree distribution the grouped guard rejects (COO
        streaming would need a lane-padded (n_dst, r, r) resident
        accumulator — the flat-moment trick is grouped-only)."""
        import jax

        from oap_mllib_tpu.utils import resilience

        if source.n_features != 3:
            raise ValueError(
                "ALS source must have width 3 (user, item, rating); "
                f"got {source.n_features}"
            )
        stats = resilience.ResilienceStats()

        def ingest():
            us, its, rs = [], [], []
            for chunk, n_valid in source:
                us.append(np.asarray(chunk[:n_valid, 0], np.int64))
                its.append(np.asarray(chunk[:n_valid, 1], np.int64))
                rs.append(np.asarray(chunk[:n_valid, 2], np.float32))
            return (
                np.concatenate(us) if us else np.zeros((0,), np.int64),
                np.concatenate(its) if its else np.zeros((0,), np.int64),
                np.concatenate(rs) if rs else np.zeros((0,), np.float32),
            )

        # the ingestion pass sits BEFORE any fit ladder, so transient
        # source faults (the stream.read site) get their own retry tier
        # here; its counters merge into the same per-fit stats
        users, items, ratings = resilience.run_with_retry(
            ingest, stats=stats, site="ALS.ingest"
        )

        accelerated = should_accelerate(
            "ALS", guard_ok=not self.nonnegative, reason="nonnegative=True"
        )
        if not accelerated:
            return self.fit(
                users, items, ratings, n_users=n_users, n_items=n_items,
                init=init,
            )

        from oap_mllib_tpu.parallel.mesh import get_mesh
        from oap_mllib_tpu.ops.als_block import als_item_layout_cfg

        als_item_layout_cfg()  # typo'd layout raises on every path
        mesh = get_mesh()
        world = mesh.shape[mesh.axis_names[0]]
        if (
            self.num_user_blocks is not None
            and jax.process_count() == 1
            and self.num_user_blocks < world
        ):
            # same numUserBlocks cap as the in-memory fit (see fit)
            mp = (
                mesh.shape[mesh.axis_names[1]]
                if len(mesh.axis_names) > 1 else 1
            )
            mesh = get_mesh(n_devices=self.num_user_blocks * mp)
            world = mesh.shape[mesh.axis_names[0]]
        users, items, ratings, n_users, n_items = self._validate_resolve(
            users, items, ratings, n_users, n_items
        )
        kernel = _als_kernel_cfg()
        from oap_mllib_tpu.utils import membudget

        multi = world > 1 or jax.process_count() > 1
        # route plan for the SOURCE entry: the natural route is streamed
        # (streamed-block on a mesh) — any materialization back to
        # in-memory layouts below is a recorded, loud scale downgrade
        # (BudgetError under strict), never the silent fallback the
        # round-5 VERDICT flagged
        plan = membudget.plan_als(
            len(users), n_users, n_items, self.rank,
            world=world if multi else 1, source_backing=source.backing,
        )
        if multi:
            # out-of-core COMPOSED with the mesh: per-rank streamed
            # grouped accumulation inside the block layout
            # (ops/als_block_stream.py) — a multi-device world no longer
            # silently falls back to fully-resident device layouts.
            # Ladder: transient retries + the NumPy final rung (the
            # block chunking has no halved-chunk knob; single-process
            # worlds only — resilient_fit bypasses itself multi-process)
            model = resilience.resilient_fit(
                "ALS",
                lambda degraded: self._fit_source_block(
                    users, items, ratings, n_users, n_items, init, mesh,
                    plan=plan,
                ),
                lambda: self._fit_fallback_np(
                    users, items, ratings, n_users, n_items,
                    None if init is None else np.array(init[0], np.float32),
                    None if init is None else np.array(init[1], np.float32),
                ),
                stats=stats,
            )
            resilience.merge_stats(model.summary, stats)
            membudget.record_plan(model.summary, plan)
            telemetry.finalize_fit(model.summary)
            return model
        if not _grouped_ok_single(kernel, users, items, n_users, n_items):
            # in-memory COO fallback (the guard re-runs inside fit — an
            # O(nnz) native bincount, cheap next to the fit itself).
            # This IS a scale downgrade of a source fit: record it
            # loudly (strict raises) — the planner contract
            plan.downgrade(
                membudget.ROUTE_IN_MEMORY,
                "grouped guard rejected the degree distribution "
                "(COO streaming unsupported)",
            )
            model = self.fit(
                users, items, ratings, n_users=n_users, n_items=n_items,
                init=init,
            )
            # the source-level plan (with its downgrade trail) replaces
            # the array entry's own record on the summary
            membudget.record_plan(model.summary, plan)
            return model

        from oap_mllib_tpu.ops import als_stream

        if init is not None:
            x0 = np.array(init[0], np.float32)
            y0 = np.array(init[1], np.float32)
        else:
            x0 = als_np.init_factors(n_users, self.rank, self.seed)
            y0 = als_np.init_factors(n_items, self.rank, self.seed + 1)

        def attempt(degraded):
            timings = Timings("als.fit")
            cache_before = progcache.stats()
            tune_before = autotune.mark()
            pol = psn.resolve("als")
            with phase_timer(timings, "table_convert"):
                by_user = als_ops.build_grouped_edges(
                    users, items, ratings, n_users
                )
                by_item = als_ops.build_grouped_edges(
                    items, users, ratings, n_items
                )
            from oap_mllib_tpu.utils.profiling import maybe_trace

            ckpt = ckpt_mod.maybe_open(
                "als", self._ckpt_signature(n_users, n_items),
                timings=timings, growable=self._GROWABLE,
            )
            with phase_timer(timings, "als_iterations"), maybe_trace():
                x, y = als_stream.als_run_streamed(
                    by_user, by_item, x0, y0, n_users, n_items,
                    self.max_iter, self.reg_param, self.alpha,
                    self.implicit_prefs, timings=timings,
                    degraded=degraded, policy=pol.name, checkpoint=ckpt,
                    grown_fill=self._fill_grown,
                )
            summary = {
                "timings": timings, "accelerated": True, "streamed": True,
                "als_kernel": "grouped", "item_layout": "replicated",
                "progcache": progcache.delta(cache_before),
                "tuning": autotune.delta(tune_before),
                **self._block_summary(1),
            }
            psn.record(summary, timings, pol)
            if ckpt is not None:
                ckpt.record(summary)
            return ALSModel(x, y, summary)

        model = resilience.resilient_fit(
            "ALS", attempt,
            lambda: self._fit_fallback_np(
                users, items, ratings, n_users, n_items, x0, y0
            ),
            stats=stats,
        )
        resilience.merge_stats(model.summary, stats)
        membudget.record_plan(model.summary, plan)
        telemetry.finalize_fit(model.summary)
        return model

    def _block_dispatch(self, users, items, n_users, n_items, world):
        """(item_sharded, use_grouped, sizes) — ONE decision point for
        both block fits (in-memory and streamed), so the layout choice,
        the grouped-vs-COO guard, and the group sizes the guard priced
        can never diverge between them.  ``sizes`` is the guard's
        (p_u, p_i, nnz_global) when it ran, else None (forced kernel)."""
        from oap_mllib_tpu.ops import als_block

        item_sharded = als_block.item_layout_sharded(
            n_items, self.rank, world, n_users
        )
        kernel = _als_kernel_cfg()
        sizes = None
        if kernel == "auto":
            guard_fn = (
                als_block.block_grouped_guard_2d
                if item_sharded
                else als_block.block_grouped_guard
            )
            use_grouped, sizes = guard_fn(
                users, items, n_users, n_items, world
            )
        else:
            use_grouped = kernel == "grouped"
        return item_sharded, use_grouped, sizes

    def _place_block_factors(self, mesh, offsets, per: int,
                             init_full: Optional[np.ndarray], seed: int):
        """Block-sharded (world*per, rank) factor init where each
        device's callback builds ONLY its block's rows — from the user
        init if given, else the counter-based position-addressable
        generator (bit-identical to the global init_factors rows; the
        per-rank seeding of the reference, ALSDALImpl.cpp:165-169).  No
        host materializes the full matrix."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from oap_mllib_tpu.config import get_config

        world = len(offsets) - 1
        sharding = NamedSharding(mesh, P(get_config().data_axis, None))

        def blk(idx):
            b = (idx[0].start or 0) // per
            lo, hi = int(offsets[b]), int(offsets[b + 1])
            out = np.zeros((per, self.rank), np.float32)
            if init_full is not None:
                out[: hi - lo] = init_full[lo:hi]
            else:
                out[: hi - lo] = als_np.init_factors_rows(
                    lo, hi, self.rank, seed
                )
            return out

        return jax.make_array_from_callback(
            (world * per, self.rank), sharding, blk
        )

    def _fit_source_block(
        self, users, items, ratings, n_users, n_items, init, mesh,
        plan=None,
    ) -> ALSModel:
        """Streamed fit composed with the mesh (ops/als_block_stream.py):
        host-resident per-rank grouped layouts, chunked uploads, the
        block path's psum / all_gather structure.  COO long-tail data
        falls back to the in-memory block fit (grouped-only streaming,
        see _fit_source notes) — recorded as a loud downgrade on the
        plan (BudgetError under strict), never silent."""
        import jax

        from oap_mllib_tpu.ops import als_block_stream
        from oap_mllib_tpu.utils import membudget

        world = mesh.shape[mesh.axis_names[0]]
        item_sharded, use_grouped, sizes = self._block_dispatch(
            users, items, n_users, n_items, world
        )
        if not use_grouped:
            if plan is not None:
                plan.downgrade(
                    membudget.ROUTE_IN_MEMORY,
                    "grouped guard rejected the degree distribution "
                    "(COO streaming unsupported)",
                )
            return self.fit(
                users, items, ratings, n_users=n_users, n_items=n_items,
                init=init,
            )
        timings = Timings("als.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        pol = psn.resolve("als")
        x0 = None if init is None else np.array(init[0], np.float32)
        y0 = None if init is None else np.array(init[1], np.float32)
        # capability-weighted user blocks for the STREAMED layout too
        # (same planner + deadband as the in-memory fit below): a slow
        # rank streams and solves a smaller user block.  The 2-D
        # sharded-item layout keeps the uniform split — its identity
        # mapping requires it — and None (homogeneous worlds) keeps the
        # layout bit-identical.
        bal_offsets = None
        if not item_sharded:
            from oap_mllib_tpu.parallel import balance

            bal_offsets = balance.block_offsets(
                n_users, world,
                bytes_per_key=4 * (self.rank
                                   + (self.rank + 1) * (self.rank + 2)),
            )
        with phase_timer(timings, "table_convert"):
            lay = als_block_stream.prepare_streamed_block_layouts(
                users, items, ratings, n_users, n_items, mesh, self.rank,
                item_sharded=item_sharded, sizes=sizes,
                offsets=bal_offsets,
            )
            x0_dev = self._place_block_factors(
                mesh, lay.offsets_u, lay.upb, x0, self.seed
            )
            if item_sharded:
                y0_dev = self._place_block_factors(
                    mesh, lay.offsets_i, lay.ipb, y0, self.seed + 1
                )
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                y0_host = (
                    y0 if y0 is not None
                    else als_np.init_factors(n_items, self.rank,
                                             self.seed + 1)
                )
                y0_dev = jax.make_array_from_callback(
                    (n_items, self.rank), NamedSharding(mesh, P()),
                    lambda idx: y0_host[idx],
                )
        from oap_mllib_tpu.utils.profiling import maybe_trace

        ckpt = ckpt_mod.maybe_open(
            "als", self._ckpt_signature(n_users, n_items), timings=timings,
            growable=self._GROWABLE,
        )
        with phase_timer(timings, "als_iterations"), maybe_trace():
            x_blocks, y = als_block_stream.als_block_run_streamed(
                lay, x0_dev, y0_dev, self.max_iter, self.reg_param,
                self.alpha, mesh, implicit=self.implicit_prefs,
                timings=timings, policy=pol.name, checkpoint=ckpt,
            )
            # oaplint: disable=stream-host-sync -- end-of-fit barrier so
            jax.block_until_ready((x_blocks, y))  # phase_timer sees walls
        summary = {
            "timings": timings, "accelerated": True, "streamed": True,
            "block_parallel": True, "sharded_factors": True,
            "als_kernel": "grouped",
            "item_layout": "sharded" if item_sharded else "replicated",
            "progcache": progcache.delta(cache_before),
            "tuning": autotune.delta(tune_before),
            **self._block_summary(world),
        }
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        if item_sharded:
            return ALSModel(
                None, None, summary,
                sharded_user=(x_blocks, np.asarray(lay.offsets_u), lay.upb),
                sharded_item=(y, np.asarray(lay.offsets_i), lay.ipb),
            )
        return ALSModel(
            None, np.asarray(y), summary,
            sharded_user=(x_blocks, np.asarray(lay.offsets_u), lay.upb),
        )

    def _run_block_segmented(self, ckpt, run_iters, x0_dev, y0_dev, mesh,
                             offsets, upb, ioffsets, ipb, item_sharded):
        """Checkpoint-armed block-parallel ALS (in-memory runners): the
        compiled runners execute in ``checkpoint_interval``-sized
        segments; between segments every rank writes ITS blocks' valid
        factor rows (global ids + values), and restore re-buckets
        whatever shards the relaunched world read onto the LIVE block
        layout through the collective resharding pass
        (parallel/shuffle.reshard_factor_rows) — the full table never
        materializes on one host.  ``run_iters(x, y, iters)`` runs one
        segment on device arrays in the runner's block forms."""
        from oap_mllib_tpu.parallel.shuffle import reshard_factor_rows
        from jax.sharding import NamedSharding, PartitionSpec as P

        layout = {
            "offsets_u": [int(v) for v in offsets],
            "upb": int(upb),
            "item_sharded": bool(item_sharded),
        }
        if item_sharded:
            layout["offsets_i"] = [int(v) for v in ioffsets]
            layout["ipb"] = int(ipb)
        resume = ckpt.restore()
        done = 0
        x, y = x0_dev, y0_dev
        if resume.found:
            done = min(int(resume.step), self.max_iter)
            nproc, rank = jax.process_count(), jax.process_index()
            ids_u, vals_u = ckpt_mod.sharded_rows_from_result(
                resume, "x", nproc, rank
            )
            x = reshard_factor_rows(ids_u, vals_u, mesh, offsets, upb)
            if item_sharded:
                ids_i, vals_i = ckpt_mod.sharded_rows_from_result(
                    resume, "y", nproc, rank
                )
                y = reshard_factor_rows(ids_i, vals_i, mesh, ioffsets, ipb)
            else:
                y_host = ckpt_mod.replicated_from_result(
                    resume, "y", int(y0_dev.shape[0]),
                )
                if resume.grown:
                    # grown item tail gets the deterministic init (the
                    # grown USER tail stays zero in the sharded x — its
                    # rows re-solve from y in the next half-iteration)
                    _, y_host = self._fill_grown(resume.grown, None, y_host)
                y = jax.make_array_from_callback(
                    y_host.shape, NamedSharding(mesh, P()),
                    lambda idx: y_host[idx],
                )
            if resume.layout != layout:
                ckpt.mark_resharded()
        while done < self.max_iter:
            seg = min(ckpt.interval, self.max_iter - done)
            x, y = run_iters(x, y, seg)
            done += seg
            sharded = {"x": ckpt_mod.local_factor_rows(x, offsets, upb)}
            arrays = {}
            if item_sharded:
                sharded["y"] = ckpt_mod.local_factor_rows(y, ioffsets, ipb)
            else:
                arrays["y"] = np.asarray(y)
            ckpt.maybe_write(
                done, arrays, sharded=sharded, layout=layout, force=True,
            )
        return x, y

    def _block_summary(self, effective_user_blocks: int) -> dict:
        """Requested vs effective block layout for the fit summary."""
        out = {"num_user_blocks": effective_user_blocks}
        if self.num_user_blocks is not None:
            out["num_user_blocks_requested"] = self.num_user_blocks
        if self.num_item_blocks is not None:
            out["num_item_blocks_requested"] = self.num_item_blocks
        return out

    def _fit_block_parallel(
        self, users, items, ratings, n_users, n_items, x0, y0, mesh, timings
    ) -> ALSModel:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from oap_mllib_tpu.config import get_config
        from oap_mllib_tpu.ops import als_block

        cfg = get_config()
        axis = cfg.data_axis
        world = mesh.shape[axis]
        pol = psn.resolve("als")
        # item-factor layout (replicated-Y vs the full 2-D grid) and the
        # pre-shuffle grouped-vs-COO guard — the shared decision point
        # (_block_dispatch): a COO decision pays neither the grouped
        # build nor the device->host pull of the shuffled blocks
        item_sharded, use_grouped, sizes = self._block_dispatch(
            users, items, n_users, n_items, world
        )
        # capability-weighted user blocks (parallel/balance.py, ISSUE
        # 15): on the replicated-item layout a slow rank gets a smaller
        # user block (offsets proportional to the gathered capability
        # weights, HBM-priced) — every consumer of (offsets, upb)
        # downstream is boundary-generic.  The 2-D sharded layout keeps
        # the uniform split: its all_gather indexing is the identity
        # mapping only uniform blocks provide.  Near-equal worlds return
        # None here (deadband), keeping homogeneous fits bit-identical.
        bal_offsets = None
        if not item_sharded:
            from oap_mllib_tpu.parallel import balance

            # per-key resident bytes: one f32 factor row (r) + the
            # per-key normal-equation moment block ((r+1)(r+2) flat)
            bal_offsets = balance.block_offsets(
                n_users, world,
                bytes_per_key=4 * (self.rank
                                   + (self.rank + 1) * (self.rank + 2)),
            )
        with phase_timer(timings, "ratings_shuffle"):
            u_loc, i_glob, conf, valid, offsets, upb = als_block.prepare_block_inputs(
                users, items, ratings, mesh, n_users, offsets=bal_offsets
            )
            item_shuffle = None
            if item_sharded:
                # second shuffle, by ITEM block: the transposed per-rank
                # table of the reference (ALSDALImpl.cpp:192-214) as a
                # role-swapped run of the same exchange
                i_loc, u_glob, conf_i, valid_i, ioffsets, ipb = (
                    als_block.prepare_block_inputs(
                        items, users, ratings, mesh, n_items
                    )
                )
                item_shuffle = (i_loc, u_glob, conf_i, valid_i)
            grouped = None
            if use_grouped:
                # scatter-free grouped-edge layouts per rank (the one-time
                # device->host pull of the shuffled blocks happens only on
                # this branch; see als_ops grouped notes)
                if item_sharded:
                    grouped = als_block.prepare_grouped_inputs_2d(
                        u_loc, i_glob, conf, valid,
                        i_loc, u_glob, conf_i, valid_i,
                        mesh, upb, ipb, sizes=sizes,
                    )
                else:
                    grouped = als_block.prepare_grouped_inputs(
                        u_loc, i_glob, conf, valid, mesh, upb, n_items,
                        sizes=sizes,
                    )
        with phase_timer(timings, "table_convert"):
            # block X init stays rank-local — no host materializes
            # (n_users, r); see _place_block_factors
            x0_dev = self._place_block_factors(
                mesh, offsets, upb, x0, self.seed
            )
            if item_sharded:
                # Y block-sharded like X; real rows from the SAME
                # position-addressable generator the replicated path
                # seeds (bit-identical rows), padding zero — the zeros
                # keep the psummed block Grams exact
                y0_dev = self._place_block_factors(
                    mesh, ioffsets, ipb, y0, self.seed + 1
                )
            else:
                y0_host = (
                    y0 if y0 is not None
                    else als_np.init_factors(n_items, self.rank, self.seed + 1)
                )
                y0_dev = jax.make_array_from_callback(
                    (n_items, self.rank), NamedSharding(mesh, P()),
                    lambda idx: y0_host[idx],
                )
        from oap_mllib_tpu.utils.profiling import maybe_trace

        ckpt = ckpt_mod.maybe_open(
            "als", self._ckpt_signature(n_users, n_items), timings=timings,
            growable=self._GROWABLE,
        )
        with phase_timer(timings, "als_iterations"), maybe_trace():
            if item_sharded:
                if grouped is not None:
                    def run_iters(xa, ya, iters):
                        return als_block.als_block_run_grouped_2d(
                            grouped, xa, ya,
                            iters, self.reg_param, self.alpha, mesh,
                            implicit=self.implicit_prefs, policy=pol.name,
                        )
                else:
                    def run_iters(xa, ya, iters):
                        return als_block.als_block_run_2d(
                            u_loc, i_glob, conf, valid, *item_shuffle,
                            xa, ya,
                            iters, self.reg_param, self.alpha, mesh,
                            implicit=self.implicit_prefs, policy=pol.name,
                        )
            elif grouped is not None:
                def run_iters(xa, ya, iters):
                    return als_block.als_block_run_grouped(
                        grouped, xa, ya,
                        iters, self.reg_param, self.alpha, mesh,
                        implicit=self.implicit_prefs, policy=pol.name,
                    )
            else:
                def run_iters(xa, ya, iters):
                    return als_block.als_block_run(
                        u_loc, i_glob, conf, valid, xa, ya,
                        iters, self.reg_param, self.alpha, mesh,
                        implicit=self.implicit_prefs, policy=pol.name,
                    )

            if ckpt is None:
                x_blocks, y = run_iters(x0_dev, y0_dev, self.max_iter)
            else:
                x_blocks, y = self._run_block_segmented(
                    ckpt, run_iters, x0_dev, y0_dev, mesh,
                    offsets, upb,
                    ioffsets if item_sharded else None,
                    ipb if item_sharded else 0,
                    item_sharded,
                )
            # oaplint: disable=stream-host-sync -- end-of-fit barrier so
            jax.block_until_ready((x_blocks, y))  # phase_timer sees walls
        # X stays block-sharded on device; the model gathers on demand
        # (offset bookkeeping ~ ALSResult cUserOffset/cItemOffset,
        # ALSDALImpl.cpp:529-575).  Y mirrors that when sharded; a
        # replicated Y reads the local copy on every process.
        summary = {
            "timings": timings, "accelerated": True,
            "block_parallel": True, "sharded_factors": True,
            "als_kernel": "grouped" if grouped is not None else "coo",
            "item_layout": "sharded" if item_sharded else "replicated",
            **self._block_summary(world),
        }
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        if item_sharded:
            return ALSModel(
                None, None, summary,
                sharded_user=(x_blocks, np.asarray(offsets), upb),
                sharded_item=(y, np.asarray(ioffsets), ipb),
            )
        return ALSModel(
            None, np.asarray(y), summary,
            sharded_user=(x_blocks, np.asarray(offsets), upb),
        )
