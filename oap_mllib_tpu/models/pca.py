"""PCA estimator with Spark-MLlib-compatible parameters.

API parity target: ``org.apache.spark.ml.feature.PCA`` as shimmed by the
reference (spark-3.1.1/ml/feature/PCA.scala): param k; model surface
``pc`` (d x k principal-component matrix), ``explainedVariance`` (top-k
variance ratios), transform = projection WITHOUT mean-centering.

Dispatch mirrors the reference guard (PCA.scala:103): accelerated iff
platform compatible AND numFeatures < 65535.  Explained-variance ratios are
normalized by total variance, per Spark's
computePrincipalComponentsAndExplainedVariance (the oracle used by the
reference's own parity suite, IntelPCASuite.scala:51-54).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu import telemetry
from oap_mllib_tpu.data.table import DenseTable
from oap_mllib_tpu.fallback.pca_np import pca_np
from oap_mllib_tpu.ops import pca_ops
from oap_mllib_tpu.ops.pallas import autotune
from oap_mllib_tpu.parallel.mesh import get_mesh
from oap_mllib_tpu.utils import checkpoint as ckpt_mod
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.dispatch import MAX_PCA_FEATURES, should_accelerate
from oap_mllib_tpu.utils.timing import Timings, phase_timer


class PCAModel:
    def __init__(self, components: np.ndarray, explained_variance: np.ndarray,
                 summary: Optional[dict] = None):
        # components: (d, k), columns are principal axes (Spark's `pc`)
        self.components_ = np.asarray(components)
        self.explained_variance_ = np.asarray(explained_variance)
        self.summary = summary or {}
        # device-copy cache (serving/registry.pin): transform never
        # re-uploads the components; a refit re-stages exactly once
        self._dev_cache: dict = {}

    @property
    def k(self) -> int:
        return self.components_.shape[1]

    def transform(self, x) -> np.ndarray:
        """Project into the PC basis (no centering — Spark parity).
        Accepts a ChunkSource for out-of-core scoring (the (n, k)
        projection is the caller's host memory).  Every path routes
        through the bucketed serving program (serving/batcher.py)
        against the PINNED components — no per-call re-upload, bounded
        compiled-shape count under jittered batch sizes."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.serving import batcher
        from oap_mllib_tpu.serving.registry import pin

        if isinstance(x, ChunkSource):
            parts = [self.transform(c[:v]) for c, v in x]
            if not parts:  # empty source: same contract as an empty array
                return self.transform(np.zeros((0, x.n_features)))
            return np.concatenate(parts)
        x = np.asarray(x, dtype=self.components_.dtype)
        comp = pin(self._dev_cache, "components", self.components_)
        return batcher.project_pca(comp, x)

    def save(self, path: str) -> None:
        """Atomic per-file writes, metadata last (data/io primitives) —
        the KMeansModel.save torn-write contract."""
        from oap_mllib_tpu.data import io as _io

        os.makedirs(path, exist_ok=True)
        _io.atomic_save_npy(
            os.path.join(path, "components.npy"), self.components_
        )
        _io.atomic_save_npy(
            os.path.join(path, "explained_variance.npy"),
            self.explained_variance_,
        )
        _io.atomic_write_json(
            os.path.join(path, "metadata.json"),
            {"type": "PCAModel", "k": int(self.k),
             "shape": [int(v) for v in self.components_.shape],
             "version": 1},
        )

    @classmethod
    def load(cls, path: str) -> "PCAModel":
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("type") != "PCAModel":
            raise ValueError(f"not a PCAModel directory: {path}")
        cpath = os.path.join(path, "components.npy")
        comps = np.load(cpath)
        var = np.load(os.path.join(path, "explained_variance.npy"))
        expect = meta.get("shape", [None, meta["k"]])
        if comps.ndim != 2 or int(comps.shape[1]) != int(expect[1]) or (
                expect[0] is not None
                and int(comps.shape[0]) != int(expect[0])):
            raise ValueError(
                f"{cpath}: components have shape {tuple(comps.shape)}, "
                f"metadata expects {tuple(expect)} — the model directory "
                "is torn or mixed from two saves"
            )
        if var.shape[0] != comps.shape[1]:
            raise ValueError(
                f"{os.path.join(path, 'explained_variance.npy')}: "
                f"{var.shape[0]} variance ratios for {comps.shape[1]} "
                "components — the model directory is torn or mixed "
                "from two saves"
            )
        return cls(comps, var)


def _pca_solver_cfg() -> str:
    """Validated Config.pca_solver — a typo must raise, not silently run
    eigh (the als_kernel/als_item_layout contract).  The randomized
    tuning knobs validate here too, so a bad value fails at fit() entry
    on EVERY path (fallback included) instead of after a multi-minute
    streamed covariance pass."""
    cfg = get_config()
    solver = cfg.pca_solver
    if solver not in ("auto", "eigh", "randomized"):
        raise ValueError(
            f"pca_solver must be auto|eigh|randomized, got {solver!r}"
        )
    if solver == "randomized" and (
        cfg.pca_rand_oversample < 1 or cfg.pca_rand_iters < 1
    ):
        raise ValueError(
            "pca_rand_oversample and pca_rand_iters must be >= 1"
        )
    return solver


class PCA:
    """PCA estimator. Param parity: k (number of components)."""

    def __init__(self, k: int):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k

    def _solve_spectrum(self, cov, d: int, timings: Timings):
        """Shared eigensolver tail (in-memory and streamed paths): full
        eigh, or the randomized top-k subspace when configured.  ``cov``
        may carry padded feature dims beyond ``d`` (model-sharded path);
        the randomized path slices them off (cov is block-diagonal with
        zero padding, so the genuine spectrum is untouched) instead of
        the eigh path's -1 diagonal demotion — subspace iteration ranks
        by |eigenvalue|, and a -1 would outrank small genuine ones.
        Returns (vals_topk, vecs (d, k), total_variance, solver_used) —
        ``solver_used`` lands in the fit summary so an A/B of the knob
        can confirm which solver actually ran (the als_kernel
        convention)."""
        solver = _pca_solver_cfg()
        if solver == "randomized":
            with phase_timer(timings, "randomized_topk"):
                cfg = get_config()
                cov_valid = cov[:d, :d]
                vals, vecs = pca_ops.topk_eigh_randomized(
                    cov_valid, self.k,
                    oversample=cfg.pca_rand_oversample,
                    iters=cfg.pca_rand_iters,
                )
                # ratio denominator: trace == eigenvalue sum, no full
                # spectrum needed
                total = float(jnp.trace(cov_valid))
                return np.asarray(vals), np.asarray(vecs), total, solver
        with phase_timer(timings, "eigh"):
            if cov.shape[0] > d:
                # padded feature dims: demote their eigenvalues below any
                # genuine one so ties at zero can't surface a padded
                # basis vector in the top-k
                cov = pca_ops.mark_padded_features(cov, d)
            vals, vecs = pca_ops.eigh_descending(cov)
            vals = np.asarray(vals)[:d]  # genuine spectrum only
            vecs = np.asarray(vecs)[:d, : self.k]
        return vals[: self.k], vecs, float(vals.sum()), "eigh"

    def fit(self, x) -> PCAModel:
        from oap_mllib_tpu.data import sparse as _sparse
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.utils import membudget

        # validate up front, on EVERY path: a typo'd solver must fail
        # fast — before a (potentially multi-minute) streamed covariance
        # pass, and on the fallback path too (which runs NumPy eigh
        # regardless and must not silently accept garbage)
        _pca_solver_cfg()
        if isinstance(x, ChunkSource):
            return self._fit_source(x)
        if not _sparse.is_sparse(x):
            # SciPy inputs stay sparse: the chosen route densifies per
            # chunk/block at staging time (data/sparse.py)
            x = np.asarray(x)
        if x.ndim != 2:
            raise ValueError(f"expected 2-D data, got shape {x.shape}")
        n, d = x.shape
        if self.k > d:
            raise ValueError(f"k={self.k} exceeds n_features={d}")
        guard_ok = d < MAX_PCA_FEATURES
        if should_accelerate("PCA", guard_ok, reason=f"n_features={d}"):
            from oap_mllib_tpu.utils import resilience
            from oap_mllib_tpu.utils.profiling import maybe_trace

            # memory-budget route plan (utils/membudget.py): a table
            # whose resident footprint exceeds the HBM budget streams
            # the two-pass covariance instead of assuming it fits
            plan = membudget.plan_pca(n, d)
            if plan.route == membudget.ROUTE_STREAMED:
                src = ChunkSource.from_array(
                    x, chunk_rows=plan.chunk_rows
                )
                return self._fit_source(src, plan=plan)
            # degradation ladder: transient faults retry; the in-memory
            # covariance has no chunk knob, so the OOM rung re-runs the
            # same program once; a HOST OOM spills the table to disk and
            # re-enters the STREAMED covariance; then the CPU path
            stats = resilience.ResilienceStats()
            holder = {}

            def attempt(degraded):
                if holder.get("source") is not None:
                    # the spill rung fired: stream from disk
                    return self._stream_attempt(
                        holder["source"], degraded
                    )
                with maybe_trace():
                    return self._fit_tpu(x)

            def spill():
                return membudget.spill_array(
                    holder, x, None, plan.chunk_rows, "PCA"
                )

            model = resilience.resilient_fit(
                "PCA", attempt, lambda: self._fit_fallback(x),
                stats=stats, spill=spill,
            )
            resilience.merge_stats(model.summary, stats)
            membudget.record_plan(
                model.summary, plan, spilled=stats.spilled
            )
            telemetry.finalize_fit(model.summary)
            return model
        return self._fit_fallback(x)

    # -- streamed (out-of-core) path -----------------------------------------
    def _stream_attempt(self, source, degraded):
        """One streamed-fit attempt at halving level ``degraded``
        (geometric chunk width / 2^level, floored — the K-Means
        _stream_attempt contract)."""
        from oap_mllib_tpu.utils import resilience
        from oap_mllib_tpu.utils.profiling import maybe_trace
        from oap_mllib_tpu.utils.timing import x64_scope

        cfg = get_config()
        dtype = np.float64 if cfg.enable_x64 else np.float32
        src = source
        if degraded:
            rows = max(
                source.chunk_rows // (2 ** int(degraded)),
                min(resilience.OOM_CHUNK_FLOOR_ROWS, source.chunk_rows),
                1,
            )
            src = source.with_chunk_rows(rows)
        with maybe_trace(), x64_scope(cfg.enable_x64):
            return self._fit_stream_inner(src, dtype, cfg)

    def _fit_source(self, source, plan=None) -> PCAModel:
        """Out-of-core fit from a ChunkSource: two streamed passes (column
        sums, centered Gram — ops/stream_ops.covariance_streamed), device
        memory bounded by O(chunk + d^2).  Multi-process: every process
        passes its OWN shard; the moments reduce across processes.  The
        fallback path materializes the (local) source (CPU reference
        semantics assume host-RAM-resident data anyway)."""
        d = source.n_features
        if self.k > d:
            raise ValueError(f"k={self.k} exceeds n_features={d}")
        guard_ok = d < MAX_PCA_FEATURES
        if not should_accelerate("PCA", guard_ok, reason=f"n_features={d}"):
            import jax

            if jax.process_count() > 1:
                # each rank only holds its shard; a local-only fallback fit
                # would silently diverge across ranks
                raise NotImplementedError(
                    "the fallback path cannot run a multi-process streamed "
                    "fit (no cross-process reduction); use the accelerated "
                    "path or fit in-memory"
                )
            return self._fit_fallback(source.to_array())
        from oap_mllib_tpu.utils import membudget, resilience

        # route plan: source fits stream by construction; the decision,
        # estimates, and any budget breach are recorded (strict raises
        # when even the streamed footprint exceeds the budget)
        if plan is None:
            plan = membudget.plan_pca(
                source.n_rows, d, source_backing=source.backing,
                chunk_rows=source.chunk_rows,
            )
        # degradation ladder: transient source/staging faults retry the
        # two-pass covariance; device OOMs re-chunk the source at
        # chunk_rows/2^level geometrically down to the floor; a HOST OOM
        # on a memory-backed source spills it to disk and re-enters this
        # streamed route; then the CPU path (which materializes the
        # source) — single-process only (resilient_fit)
        stats = resilience.ResilienceStats()
        holder = {"source": source}

        def attempt(degraded):
            return self._stream_attempt(holder["source"], degraded)

        spill = None
        if source.backing not in ("disk", "spill"):
            spill = lambda: membudget.spill_source(holder, "PCA")  # noqa: E731
        model = resilience.resilient_fit(
            "PCA", attempt,
            lambda: self._fit_fallback(holder["source"].to_array()),
            stats=stats, spill=spill,
            max_halvings=resilience.halvings_available(source.chunk_rows),
        )
        resilience.merge_stats(model.summary, stats)
        membudget.record_plan(model.summary, plan, spilled=stats.spilled)
        telemetry.finalize_fit(model.summary)
        return model

    def _ckpt_signature(self, d: int, cfg, moments: str) -> dict:
        """Checkpoint identity (utils/checkpoint.py).  ``moments`` names
        the checkpointed accumulator layout — ``"colsum"`` (streamed
        pass-1 state) vs ``"cov"`` (in-memory covariance) — so the two
        paths can never consume each other's intermediate state.  ``k``
        is deliberately absent: the moments do not depend on it."""
        return {"d": int(d), "moments": moments,
                "x64": bool(cfg.enable_x64)}

    def _fit_stream_inner(self, source, dtype, cfg) -> PCAModel:
        from oap_mllib_tpu.ops import stream_ops

        # compute-precision policy, per attempt (the resilience ladder's
        # precision rung re-resolves to f32 on its retry); x64 pins f32
        pol = psn.resolve("pca")
        timings = Timings("pca.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        d = source.n_features
        ckpt = ckpt_mod.maybe_open(
            "pca", self._ckpt_signature(d, cfg, "colsum"), timings=timings
        )
        with phase_timer(timings, "covariance_streamed"):
            tier = (
                "highest" if cfg.enable_x64
                else psn.kernel_tier(pol.name, cfg.matmul_precision)
            )
            cov, _, n = stream_ops.covariance_streamed(
                source, dtype, tier, timings=timings, policy=pol.name,
                checkpoint=ckpt,
            )
        # cov is exactly (d, d) here — no model-sharding feature pad
        vals, vecs, total, solver = self._solve_spectrum(cov, d, timings)
        ratio = vals / total if total > 0 else np.zeros(self.k)
        summary = {
            "timings": timings,
            "accelerated": True,
            "streamed": True,
            "n_rows": n,
            "pca_solver": solver,
            "progcache": progcache.delta(cache_before),
            "tuning": autotune.delta(tune_before),
        }
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        return PCAModel(vecs, ratio, summary)

    # -- accelerated path (~ PCADALImpl.train, PCADALImpl.scala:35) ----------
    def _fit_tpu(self, x: np.ndarray) -> PCAModel:
        import jax

        from oap_mllib_tpu.utils.timing import x64_scope

        cfg = get_config()
        dtype = np.float64 if cfg.enable_x64 else np.float32
        with x64_scope(cfg.enable_x64):
            return self._fit_tpu_inner(x, dtype, jax)

    def _fit_tpu_inner(self, x, dtype, jax) -> PCAModel:
        timings = Timings("pca.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        cfg = get_config()
        pol = psn.resolve("pca")
        mesh = get_mesh()
        mp = mesh.shape[cfg.model_axis]
        d = x.shape[1]
        ckpt = ckpt_mod.maybe_open(
            "pca", self._ckpt_signature(d, cfg, "cov"), timings=timings
        )
        resume = ckpt.restore() if ckpt is not None else None
        restored = (
            resume is not None and resume.found
            and resume.extra.get("stage") == "cov"
        )
        if mp > 1:
            # model-sharded Gram needs d % model == 0; zero-pad feature
            # columns (they yield zero eigenvalues, which sort last) and
            # slice the component rows back after eigh
            from oap_mllib_tpu.data import sparse as _sparse

            if _sparse.is_sparse(x):
                import scipy.sparse as sp

                x = sp.csr_matrix(
                    sp.hstack(
                        [x, sp.csr_matrix(
                            (x.shape[0], (-d) % mp), dtype=x.dtype
                        )]
                    )
                )
            else:
                x = np.pad(x, ((0, 0), (0, (-d) % mp)))
        if restored:
            # the in-memory iterate state is the covariance itself
            # (stored unpadded, so it restores onto any model-parallel
            # degree): skip the table conversion AND the Gram pass, go
            # straight to the eigensolver
            cov = jnp.asarray(np.asarray(resume.arrays["cov"], dtype))
        else:
            with phase_timer(timings, "table_convert"):
                make = (
                    DenseTable.from_process_local
                    if jax.process_count() > 1
                    else DenseTable.from_numpy
                )
                table = make(x.astype(dtype), mesh)
            with phase_timer(timings, "covariance"):
                n_rows = jnp.asarray(float(table.n_rows), dtype)
                # x64 lane pins the Gram to HIGHEST regardless of tier
                # (f64 has no bf16 fast path to buy anything with); the
                # compute-precision policy maps onto the tier otherwise
                tier = (
                    "highest" if cfg.enable_x64
                    else psn.kernel_tier(pol.name, cfg.matmul_precision)
                )
                if mp > 1:
                    cov, _ = pca_ops.covariance_model_sharded(
                        table.data, table.mask, n_rows, mesh, tier,
                        timings=timings, policy=pol.name,
                    )
                else:
                    cov, _ = pca_ops.covariance(
                        table.data, table.mask, n_rows, tier,
                        timings=timings, policy=pol.name,
                    )
            if ckpt is not None:
                ckpt.maybe_write(
                    1,
                    {"cov": ckpt_mod.fetch_replicated(cov)[:d, :d]},
                    extra={"stage": "cov"}, force=True,
                )
        vals, vecs, total, solver = self._solve_spectrum(cov, d, timings)
        ratio = vals / total if total > 0 else np.zeros(self.k)
        summary = {
            "timings": timings,
            "accelerated": True,
            "mesh_shape": dict(mesh.shape),
            "pca_solver": solver,
            "progcache": progcache.delta(cache_before),
            "tuning": autotune.delta(tune_before),
        }
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        return PCAModel(vecs, ratio, summary)

    # -- fallback path (~ vanilla mllib.feature.PCA, PCA.scala:110-116) ------
    def _fit_fallback(self, x: np.ndarray) -> PCAModel:
        from oap_mllib_tpu.data import sparse as _sparse

        timings = Timings("pca.fit")
        if _sparse.is_sparse(x):
            # the NumPy reference semantics assume dense host data
            x = x.toarray()
        with phase_timer(timings, "pca_np"):
            comps, ratio = pca_np(x, self.k)
        # the fallback always factorizes fully; recording it keeps a
        # configured-but-ineffective "randomized" visible in the summary
        return PCAModel(
            comps, ratio,
            {"timings": timings, "accelerated": False, "pca_solver": "eigh"},
        )
