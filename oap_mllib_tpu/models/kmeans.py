"""K-Means estimator with Spark-MLlib-compatible parameters.

API parity target: ``org.apache.spark.ml.clustering.KMeans`` as shimmed by
the reference (spark-3.1.1/ml/clustering/KMeans.scala) — params k, maxIter,
tol, seed, initMode (random | k-means||), initSteps, distanceMeasure — and
its model surface: clusterCenters, predict, summary (trainingCost,
numIter), save/load.

Dispatch mirrors the reference's trainWithDAL guard
(KMeans.scala:349-357): accelerated iff platform compatible AND
distanceMeasure == euclidean.  Unlike the reference, row weights do NOT
force fallback — the TPU kernel supports them natively (weights fold into
the mask vector); cosine still falls back.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from oap_mllib_tpu.config import get_config
from oap_mllib_tpu import telemetry
from oap_mllib_tpu.data.table import DenseTable
from oap_mllib_tpu.fallback.kmeans_np import lloyd_np, predict_np
from oap_mllib_tpu.ops import kmeans_ops
from oap_mllib_tpu.ops.pallas import autotune
from oap_mllib_tpu.parallel.mesh import get_mesh
from oap_mllib_tpu.utils import checkpoint as ckpt_mod
from oap_mllib_tpu.utils import precision as psn
from oap_mllib_tpu.utils import progcache
from oap_mllib_tpu.utils.dispatch import should_accelerate
from oap_mllib_tpu.utils.timing import Timings, phase_timer

INIT_RANDOM = "random"
INIT_PARALLEL = "k-means||"


class KMeansSummary:
    """Training summary (~ KMeansSummary + KMeansResult,
    reference KMeansResult.java / KMeans.scala:359-368).  ``cluster_sizes``
    mirrors Spark's KMeansSummary.clusterSizes."""

    def __init__(self, training_cost: float, num_iter: int, timings: Timings,
                 accelerated: bool, cluster_sizes: Optional[np.ndarray] = None):
        self.training_cost = training_cost
        self.num_iter = num_iter
        self.timings = timings
        self.accelerated = accelerated
        self.cluster_sizes = cluster_sizes

    def __repr__(self) -> str:
        return (
            f"KMeansSummary(cost={self.training_cost:.6g}, iters={self.num_iter}, "
            f"accelerated={self.accelerated})"
        )


class KMeansModel:
    def __init__(self, cluster_centers: np.ndarray, distance_measure: str = "euclidean",
                 summary: Optional[KMeansSummary] = None):
        self.cluster_centers_ = np.asarray(cluster_centers)
        self.distance_measure = distance_measure
        self.summary = summary
        # device-copy cache (serving/registry.pin): identity-keyed on
        # the host array, so scoring calls never re-upload the centers
        # and a refit (fresh array) re-stages exactly once
        self._dev_cache: dict = {}

    @property
    def k(self) -> int:
        return self.cluster_centers_.shape[0]

    # element budget for the live buffers in predict/cost — the (chunk, k)
    # distance matrix AND the (chunk, d) input chunk (a fixed ROW count
    # would blow up at large k; bounding only k would blow up at large d);
    # the same bound the training loop gets from auto_row_chunks
    _PREDICT_BUDGET = kmeans_ops.SCORE_BUDGET_ELEMS

    def _score_chunk_rows(self) -> int:
        return kmeans_ops.rows_per_chunk(
            self.k, self.cluster_centers_.shape[1],
            budget=self._PREDICT_BUDGET,
        )

    def _centers_dev(self):
        """The pinned device copy of the centers (serving/registry.pin)
        — staged once per model lifetime, re-staged only on refit."""
        from oap_mllib_tpu.serving.registry import pin

        return pin(self._dev_cache, "centers", self.cluster_centers_)

    def _predict_euclidean(self, x: np.ndarray) -> np.ndarray:
        """Bucketed serving-program scoring (serving/batcher.py):
        fixed-width row slices against the PINNED centers, each slice
        rounded onto its geometric bucket — every full chunk shares one
        compiled shape, the tail its bucket's, and no call re-uploads
        the centers."""
        from oap_mllib_tpu.serving import batcher

        c = self._centers_dev()
        rows = self._score_chunk_rows()
        return np.concatenate([
            batcher.assign_kmeans(c, x[lo : lo + rows])
            for lo in range(0, max(len(x), 1), rows)
        ])

    def predict(self, x) -> np.ndarray:
        """Nearest-center assignment (the shim's transform/predict surface).
        Accepts a ChunkSource for out-of-core scoring (labels are O(n)
        host memory); disk-backed chunks route through the SAME bucketed
        serving program as the ndarray path, so the results are
        bit-identical and the compiled-shape count stays bounded."""
        from oap_mllib_tpu.data.stream import ChunkSource

        if isinstance(x, ChunkSource):
            if self.distance_measure == "euclidean":
                parts = [
                    self._predict_euclidean(
                        np.asarray(
                            c[:v], dtype=self.cluster_centers_.dtype
                        )
                    )
                    for c, v in x
                ]
            else:
                parts = [self.predict(c[:v]) for c, v in x]
            if not parts:  # empty source: same contract as an empty array
                return self.predict(np.zeros((0, x.n_features)))
            return np.concatenate(parts)
        x = np.asarray(x, dtype=self.cluster_centers_.dtype)
        if self.distance_measure == "euclidean" and x.shape[0] >= 1:
            return self._predict_euclidean(x)
        return predict_np(x, self.cluster_centers_, self.distance_measure)

    def transform(self, x: np.ndarray) -> np.ndarray:
        return self.predict(x)

    def partial_fit(self, x, sample_weight=None) -> "KMeansModel":
        """Mini-batch Lloyd delta (online/minibatch.py): ONE decayed,
        count-weighted assignment pass over the arriving chunks through
        the streamed-pass machinery (stream_ops.streamed_accumulate) —
        no re-init, no convergence loop.  The update is compute-then
        -swap: the centers array is replaced atomically at the end, so
        a fault mid-pass leaves the model (and its served pin) exactly
        as it was.  Commits re-pin any serving handle in place
        (serving/registry.repin_model) — in-flight requests keep their
        handle, the next batch scores the new centers.  Returns
        ``self`` (mutated)."""
        from oap_mllib_tpu.online import minibatch

        return minibatch.partial_fit_kmeans(self, x, sample_weight)

    def compute_cost(self, x) -> float:
        from oap_mllib_tpu.data.stream import ChunkSource

        if isinstance(x, ChunkSource):
            return float(sum(self.compute_cost(c[:v]) for c, v in x))
        x = np.asarray(x, dtype=self.cluster_centers_.dtype)
        if self.distance_measure != "euclidean":
            from oap_mllib_tpu.fallback.kmeans_np import _sq_dists

            d = _sq_dists(x, self.cluster_centers_, self.distance_measure)
            return float(np.sum(np.min(d, axis=1)))
        c = self._centers_dev()  # pinned — no per-call re-upload
        rows = self._score_chunk_rows()
        return float(sum(
            float(jnp.sum(jnp.min(
                kmeans_ops.pairwise_sq_dists(
                    jnp.asarray(x[lo : lo + rows]), c
                ), axis=1
            )))
            for lo in range(0, len(x), rows)
        ))

    def to_pmml(self, path: str) -> None:
        """Export as a PMML 4.3 ClusteringModel (~ Spark's
        KMeansModel.write.format("pmml"), exercised by the reference's
        IntelKMeansSuite "pmml export" test)."""
        import xml.etree.ElementTree as ET

        d = self.cluster_centers_.shape[1]
        root = ET.Element(
            "PMML",
            {"version": "4.3", "xmlns": "http://www.dmg.org/PMML-4_3"},
        )
        header = ET.SubElement(root, "Header", {"description": "k-means clustering"})
        ET.SubElement(header, "Application", {"name": "oap-mllib-tpu"})
        dd = ET.SubElement(root, "DataDictionary", {"numberOfFields": str(d)})
        for j in range(d):
            ET.SubElement(
                dd, "DataField",
                {"name": f"field_{j}", "optype": "continuous", "dataType": "double"},
            )
        cm = ET.SubElement(
            root, "ClusteringModel",
            {
                "modelName": "k-means",
                "functionName": "clustering",
                "modelClass": "centerBased",
                "numberOfClusters": str(self.k),
            },
        )
        ms = ET.SubElement(cm, "MiningSchema")
        for j in range(d):
            ET.SubElement(ms, "MiningField", {"name": f"field_{j}"})
        ET.SubElement(
            cm, "ComparisonMeasure", {"kind": "distance"}
        ).append(ET.Element("squaredEuclidean"))
        for j in range(d):
            ET.SubElement(
                cm, "ClusteringField", {"field": f"field_{j}", "compareFunction": "absDiff"}
            )
        for i, center in enumerate(self.cluster_centers_):
            cl = ET.SubElement(cm, "Cluster", {"name": f"cluster_{i}", "id": str(i)})
            arr = ET.SubElement(cl, "Array", {"n": str(d), "type": "real"})
            arr.text = " ".join(repr(float(v)) for v in center)
        ET.ElementTree(root).write(path, xml_declaration=True, encoding="utf-8")

    # -- persistence (~ Spark ML read/write, tested in IntelKMeansSuite) -----
    def save(self, path: str) -> None:
        """Atomic write (tmp+``os.replace`` per file, metadata last —
        data/io primitives): a kill mid-save leaves either the previous
        model or arrays the metadata does not reference yet, never a
        torn file the next load would misread."""
        from oap_mllib_tpu.data import io as _io

        os.makedirs(path, exist_ok=True)
        _io.atomic_save_npy(
            os.path.join(path, "centers.npy"), self.cluster_centers_
        )
        _io.atomic_write_json(
            os.path.join(path, "metadata.json"),
            {"type": "KMeansModel",
             "distance_measure": self.distance_measure,
             "k": int(self.k),
             "shape": [int(v) for v in self.cluster_centers_.shape],
             "version": 1},
        )

    @classmethod
    def load(cls, path: str) -> "KMeansModel":
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        if meta.get("type") != "KMeansModel":
            raise ValueError(f"not a KMeansModel directory: {path}")
        cpath = os.path.join(path, "centers.npy")
        centers = np.load(cpath)
        expect = meta.get("shape", [meta["k"], None])
        if centers.ndim != 2 or int(centers.shape[0]) != int(expect[0]) or (
                expect[1] is not None
                and int(centers.shape[1]) != int(expect[1])):
            raise ValueError(
                f"{cpath}: centers have shape {tuple(centers.shape)}, "
                f"metadata expects {tuple(expect)} — the model directory "
                "is torn or mixed from two saves"
            )
        return cls(centers, meta["distance_measure"])


class KMeans:
    """K-Means estimator.

    Parameters mirror Spark ML (reference shim KMeans.scala param defaults):
    k=2, max_iter=20, tol=1e-4, init_mode="k-means||", init_steps=2,
    distance_measure="euclidean", seed derived from class name there, plain
    int here.
    """

    def __init__(
        self,
        k: int = 2,
        max_iter: int = 20,
        tol: float = 1e-4,
        seed: Optional[int] = None,
        init_mode: str = INIT_PARALLEL,
        init_steps: int = 2,
        distance_measure: str = "euclidean",
    ):
        if k < 1:
            raise ValueError("k must be >= 1")
        if max_iter < 0:
            raise ValueError("max_iter must be >= 0")
        if init_mode not in (INIT_RANDOM, INIT_PARALLEL):
            raise ValueError(f"init_mode must be '{INIT_RANDOM}' or '{INIT_PARALLEL}'")
        if distance_measure not in ("euclidean", "cosine"):
            raise ValueError("distance_measure must be 'euclidean' or 'cosine'")
        if init_steps < 1:
            raise ValueError("init_steps must be >= 1")
        self.k = k
        self.max_iter = max_iter
        self.tol = tol
        # None = Config.seed (the OAP_MLLIB_TPU_SEED default for
        # estimators that do not set one — docs/configuration.md)
        self.seed = get_config().seed if seed is None else seed
        self.init_mode = init_mode
        self.init_steps = init_steps
        self.distance_measure = distance_measure

    def fit(self, x, sample_weight: Optional[np.ndarray] = None) -> KMeansModel:
        from oap_mllib_tpu.data import sparse as _sparse
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.utils import membudget

        if isinstance(x, ChunkSource):
            return self._fit_source(x, sample_weight)
        if not _sparse.is_sparse(x):
            # SciPy inputs stay sparse here: the chosen route densifies
            # per chunk/block at staging time (data/sparse.py)
            x = np.asarray(x)
        if x.ndim != 2:
            raise ValueError(f"expected 2-D data, got shape {x.shape}")
        if x.shape[0] < 1:
            raise ValueError("empty input")
        guard_ok = self.distance_measure == "euclidean"
        accelerated = should_accelerate(
            "KMeans", guard_ok, reason=f"distance_measure={self.distance_measure}"
        )
        if accelerated:
            from oap_mllib_tpu.utils import resilience
            from oap_mllib_tpu.utils.profiling import maybe_trace

            # memory-budget route plan (utils/membudget.py): an ndarray
            # whose working set exceeds the HBM budget streams through
            # the prefetch pipeline instead of silently assuming it fits
            plan = membudget.plan_kmeans(
                x.shape[0], x.shape[1], self.k,
                row_chunks_hint=kmeans_ops.auto_row_chunks(
                    x.shape[0], self.k
                ),
            )
            if plan.route == membudget.ROUTE_STREAMED:
                src = ChunkSource.from_array(
                    x, chunk_rows=plan.chunk_rows
                )
                return self._fit_source(src, sample_weight, plan=plan)
            # degradation ladder (utils/resilience.py): transient faults
            # retry the fit; device OOMs walk the geometric halved-chunk
            # rungs; a HOST OOM spills the table to disk and re-enters
            # the streamed route; the final rung is the same CPU path
            # the static gate falls back to
            stats = resilience.ResilienceStats()
            holder = {}

            def attempt(degraded):
                if holder.get("source") is not None:
                    # the spill rung fired: the table now lives on disk
                    return self._stream_attempt(
                        holder["source"], holder.get("weights"), degraded
                    )
                with maybe_trace():
                    return self._fit_tpu(x, sample_weight, degraded)

            def spill():
                return membudget.spill_array(
                    holder, x, sample_weight, plan.chunk_rows, "KMeans"
                )

            model = resilience.resilient_fit(
                "KMeans", attempt,
                lambda: self._fit_fallback(x, sample_weight),
                stats=stats, spill=spill,
            )
            resilience.merge_stats(model.summary, stats)
            membudget.record_plan(
                model.summary, plan, spilled=stats.spilled
            )
            telemetry.finalize_fit(model.summary)
            return model
        return self._fit_fallback(x, sample_weight)

    # -- streamed (out-of-core) path -----------------------------------------
    def _stream_attempt(self, source, sample_weight, degraded):
        """One streamed-fit attempt at halving level ``degraded`` (the
        resilience ladder's geometric OOM rung: chunk width / 2^level,
        floored at OOM_CHUNK_FLOOR_ROWS — never widened)."""
        from oap_mllib_tpu.config import get_config as _gc
        from oap_mllib_tpu.utils import resilience
        from oap_mllib_tpu.utils.profiling import maybe_trace
        from oap_mllib_tpu.utils.timing import x64_scope

        cfg = _gc()
        dtype = np.float64 if cfg.enable_x64 else np.float32
        src, w = source, sample_weight
        if degraded:
            rows = max(
                source.chunk_rows // (2 ** int(degraded)),
                min(resilience.OOM_CHUNK_FLOOR_ROWS, source.chunk_rows),
                1,
            )
            src = source.with_chunk_rows(rows)
            if w is not None:
                w = w.with_chunk_rows(rows)
        with maybe_trace(), x64_scope(cfg.enable_x64):
            return self._fit_stream_inner(src, w, dtype, cfg)

    def _fit_source(self, source, sample_weight, plan=None) -> KMeansModel:
        """Out-of-core fit from a ChunkSource (ops/stream_ops.py): device
        memory bounded by O(chunk), one pass per Lloyd iteration.  Multi
        -process: every process passes its OWN shard as a local source;
        sums/counts/init state reduce across processes (host-mediated, the
        DCN analog of the mesh path's ICI psums).  ``sample_weight`` may
        be a width-1 ChunkSource chunked like the data, or an in-memory
        array (wrapped automatically).  The fallback path materializes
        the (local) source — the CPU reference semantics assume
        host-RAM-resident data anyway."""
        from oap_mllib_tpu.data.stream import ChunkSource

        if sample_weight is not None and not isinstance(sample_weight, ChunkSource):
            sample_weight = ChunkSource.from_array(
                np.asarray(sample_weight).reshape(-1, 1),
                chunk_rows=source.chunk_rows,
            )
        # validate up front so BOTH branches (accelerated and fallback)
        # reject malformed weight sources with a clear error; the outcome
        # is synced across ranks so a single bad shard fails the world
        # together instead of leaving peers in process_allgather
        if sample_weight is not None:
            from oap_mllib_tpu.ops.stream_ops import (
                _check_weight_source,
                _checked_entry,
            )

            _checked_entry(
                lambda: _check_weight_source(source, sample_weight)
            )
        guard_ok = self.distance_measure == "euclidean"
        accelerated = should_accelerate(
            "KMeans", guard_ok, reason=f"distance_measure={self.distance_measure}"
        )
        if not accelerated:
            import jax

            if jax.process_count() > 1:
                # each rank only holds its shard; a local-only fallback fit
                # would silently diverge across ranks
                raise NotImplementedError(
                    "the fallback path cannot run a multi-process streamed "
                    "fit (no cross-process reduction); use the accelerated "
                    "path or fit in-memory"
                )
            w_arr = (
                sample_weight.to_array().reshape(-1)
                if sample_weight is not None else None
            )
            return self._fit_fallback(source.to_array(), w_arr)
        from oap_mllib_tpu.utils import membudget, resilience

        # route plan: source fits stream by construction; the planner
        # records the decision + estimates (and raises under strict when
        # even the streamed footprint exceeds the budget)
        if plan is None:
            plan = membudget.plan_kmeans(
                source.n_rows, source.n_features, self.k,
                source_backing=source.backing,
                chunk_rows=source.chunk_rows,
            )
        # degradation ladder: transient source/staging faults retry the
        # fit; device OOMs re-chunk the source (and its lockstep weight
        # source) at chunk_rows/2^level geometrically down to the floor;
        # a HOST OOM on a memory-backed source spills it to disk and
        # re-enters this same streamed route; then the CPU path (which
        # materializes the source) is the final rung.  Multi-process
        # worlds bypass the ladder — the fail-fast static-world contract
        # (docs/distributed.md) — resilient_fit handles that.
        stats = resilience.ResilienceStats()
        holder = {"source": source, "weights": sample_weight}

        def attempt(degraded):
            return self._stream_attempt(
                holder["source"], holder.get("weights"), degraded
            )

        def fallback():
            w = holder.get("weights")
            w_arr = w.to_array().reshape(-1) if w is not None else None
            return self._fit_fallback(holder["source"].to_array(), w_arr)

        spill = None
        if source.backing not in ("disk", "spill"):
            spill = lambda: membudget.spill_source(holder, "KMeans")  # noqa: E731
        model = resilience.resilient_fit(
            "KMeans", attempt, fallback, stats=stats, spill=spill,
            max_halvings=resilience.halvings_available(source.chunk_rows),
        )
        resilience.merge_stats(model.summary, stats)
        membudget.record_plan(model.summary, plan, spilled=stats.spilled)
        telemetry.finalize_fit(model.summary)
        return model

    def _ckpt_signature(self, d: int, cfg) -> dict:
        """Checkpoint identity (utils/checkpoint.py): the parameters that
        define WHICH optimization the iterate state belongs to.  World
        size, chunk geometry, and the precision policy are deliberately
        absent — all three may legitimately change across a preemption
        (that is the elastic-worlds point)."""
        return {
            "k": self.k, "d": int(d), "init_mode": self.init_mode,
            "init_steps": self.init_steps, "seed": int(self.seed),
            "tol": float(self.tol), "distance": self.distance_measure,
            "x64": bool(cfg.enable_x64),
        }

    def _fit_stream_inner(self, source, sample_weight, dtype, cfg) -> KMeansModel:
        from oap_mllib_tpu.ops import stream_ops

        # compute-precision policy (utils/precision.py): resolved per
        # attempt so the resilience ladder's f32-degradation scope takes
        # effect on a retry; the legacy kernel tier maps off it
        pol = psn.resolve("kmeans")
        tier = psn.kernel_tier(pol.name, cfg.matmul_precision)
        # kmeans_kernel/ring_reduction validation must run on EVERY
        # accelerated fit (the _run_lloyd invariant): a typo'd value
        # raises here too, even though the streamed path always runs the
        # chunked XLA programs (the ring engages in its multi-process
        # per-pass reductions — stream_ops._ring_mesh)
        kmeans_ops.use_pallas_path(
            cfg.kmeans_kernel, source.n_features, self.k, tier, dtype,
        )
        kmeans_ops.ring_mode_cfg(cfg)
        timings = Timings("kmeans.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        ckpt = ckpt_mod.maybe_open(
            "kmeans", self._ckpt_signature(source.n_features, cfg),
            timings=timings,
        )
        resume = ckpt.restore() if ckpt is not None else None
        with phase_timer(timings, "init_centers"):
            if resume is not None and resume.found:
                # the restored centroids ARE the iterate: the init passes
                # (reservoir / k-means||) are part of the work a resumed
                # fit does not redo
                centers0 = np.asarray(resume.arrays["centers"], dtype)
            elif self.init_mode == INIT_RANDOM:
                centers0 = stream_ops.reservoir_sample(
                    source, self.k, self.seed, timings=timings
                )
            else:
                centers0 = stream_ops.init_kmeans_parallel_streamed(
                    source, self.k, self.seed, self.init_steps, dtype,
                    weights=sample_weight, validated=True, timings=timings,
                    policy=pol.name,
                )
        with phase_timer(timings, "lloyd_loop"):
            centers, n_iter, cost, counts = stream_ops.lloyd_run_streamed(
                source, centers0, self.max_iter, self.tol, dtype,
                tier, weights=sample_weight, validated=True,
                timings=timings, policy=pol.name, checkpoint=ckpt,
                resume=resume,
            )
        summary = KMeansSummary(
            float(cost), int(n_iter), timings, accelerated=True,
            cluster_sizes=np.asarray(counts),
        )
        summary.streamed = True
        summary.progcache = progcache.delta(cache_before)
        summary.tuning = autotune.delta(tune_before)
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        return KMeansModel(np.asarray(centers), self.distance_measure, summary)

    # -- accelerated path (~ KMeansDALImpl.train, KMeansDALImpl.scala:35) ----
    def _fit_tpu(self, x: np.ndarray, sample_weight: Optional[np.ndarray],
                 degraded: bool = False) -> KMeansModel:
        from oap_mllib_tpu.utils.timing import x64_scope

        cfg = get_config()
        dtype = np.float64 if cfg.enable_x64 else np.float32
        with x64_scope(cfg.enable_x64):
            return self._fit_tpu_inner(x, sample_weight, dtype, degraded)

    def _fit_tpu_inner(self, x, sample_weight, dtype,
                       degraded: bool = False) -> KMeansModel:
        cfg = get_config()
        # compute-precision policy, resolved per attempt (the resilience
        # ladder's precision rung re-resolves to f32 on its retry)
        pol = psn.resolve("kmeans")
        timings = Timings("kmeans.fit")
        cache_before = progcache.stats()
        tune_before = autotune.mark()
        mesh = get_mesh()
        mp = mesh.shape[cfg.model_axis]
        d_orig = x.shape[1]
        if mp > 1 and cfg.kmeans_kernel != "xla" and d_orig % mp:
            # model-sharded Lloyd needs d % model == 0; zero-pad feature
            # columns (zero in data AND centroids — no distance or move
            # contribution) and slice them back off the final centers.
            # Skipped when no padding is needed or when "xla" forces the
            # GSPMD route — np.pad would copy the whole dataset.
            from oap_mllib_tpu.data import sparse as _sparse

            if _sparse.is_sparse(x):
                # zero columns add no stored entries in CSR
                import scipy.sparse as sp

                x = sp.csr_matrix(
                    sp.hstack(
                        [x, sp.csr_matrix(
                            (x.shape[0], (-d_orig) % mp), dtype=x.dtype
                        )]
                    )
                )
            else:
                x = np.pad(x, ((0, 0), (0, (-d_orig) % mp)))
        with phase_timer(timings, "table_convert"):
            # multi-process: each host contributes its local shard
            # (README multi-host flow); single-process: the full table
            make = (
                DenseTable.from_process_local
                if jax.process_count() > 1
                else DenseTable.from_numpy
            )
            table = make(x.astype(dtype), mesh)
            weights = table.mask
            if sample_weight is not None:
                # collective path: multi-host shards pad per process, so the
                # weights must be stitched with the mask's exact layout
                weights = table.align_weights(sample_weight, mesh)
        ckpt = ckpt_mod.maybe_open(
            "kmeans", self._ckpt_signature(d_orig, cfg), timings=timings
        )
        resume = ckpt.restore() if ckpt is not None else None
        with phase_timer(timings, "init_centers"):
            if resume is not None and resume.found:
                # restored centroids are stored at d_orig; re-pad the
                # feature axis to whatever the CURRENT mesh needs (the
                # model-parallel degree may have changed with the world)
                c = np.asarray(resume.arrays["centers"], dtype)
                centers0 = np.pad(
                    c, ((0, 0), (0, x.shape[1] - d_orig))
                )
            elif self.init_mode == INIT_RANDOM:
                centers0 = kmeans_ops.init_random(
                    table.data, table.n_rows, self.k, self.seed,
                    index_map=table.valid_to_padded,
                ).astype(dtype)
            else:
                centers0 = kmeans_ops.init_kmeans_parallel(
                    table.data, weights, table.n_rows, self.k, self.seed,
                    self.init_steps, index_map=table.valid_to_padded,
                ).astype(dtype)
        with phase_timer(timings, "lloyd_loop"):
            centers, n_iter, cost, counts = self._run_lloyd(
                table, weights, centers0, dtype, cfg, mesh, timings,
                degraded=degraded, pol=pol, ckpt=ckpt, resume=resume,
                d_orig=d_orig,
            )
            centers = np.asarray(centers)[:, :d_orig]
            n_iter = int(n_iter)
            cost = float(cost)
        summary = KMeansSummary(
            cost, n_iter, timings, accelerated=True,
            cluster_sizes=np.asarray(counts),
        )
        summary.progcache = progcache.delta(cache_before)
        summary.tuning = autotune.delta(tune_before)
        psn.record(summary, timings, pol)
        if ckpt is not None:
            ckpt.record(summary)
        return KMeansModel(centers, self.distance_measure, summary)

    def _run_lloyd(self, table, weights, centers0, dtype, cfg, mesh,
                   timings=None, degraded=False, pol=None, ckpt=None,
                   resume=None, d_orig=None):
        """Dispatch the hot loop to the configured kernel.

        ``auto`` picks the fastest measured path for the shape/tier
        (BASELINE.md kernel table, v5e; rule in
        kmeans_ops.pallas_preferred): the fused Pallas kernel at the
        f32-accurate tiers when (k, d) fits its VMEM blocks — its
        loop-mode assignment + exact-split cluster sums cut the
        per-iteration MXU/VPU passes — else the chunked XLA Lloyd
        (which wins the all-bf16 "default" tier).  ``xla``/``pallas`` force a path;
        ``pallas`` requires TPU + single device + f32 and falls back
        otherwise.  Chunking only applies on a single device: the scan
        reshape conflicts with GSPMD row sharding.  A mesh with a model
        axis > 1 routes to the feature-sharded shard_map Lloyd — unless
        ``xla`` is forced, which keeps the GSPMD data-parallel program
        (centroids replicated) so the two can be A/B'd on the same mesh.
        """
        # the compute-precision policy maps onto the legacy kernel tier
        # (utils/precision.kernel_tier: f32 keeps matmul_precision, tf32
        # the bf16_3x "high" tier, bf16 the single-pass "default" tier) so
        # the kernel-dispatch rules price it like the tier it runs at —
        # the bf16 policy now prices ON Pallas (kmeans_ops
        # .pallas_preferred accepts "default"; ISSUE 9 retired the
        # routes-off-Pallas workaround)
        pol = pol or psn.resolve("kmeans")
        tier = psn.kernel_tier(pol.name, cfg.matmul_precision)
        # use_pallas_path is the single kmeans_kernel validation point and
        # must run on EVERY accelerated fit — a typo'd value raises even
        # when the model-sharded route below makes its answer moot; the
        # ring_reduction knob validates under the same contract
        use_pallas = kmeans_ops.use_pallas_path(
            cfg.kmeans_kernel, table.data.shape[1], self.k, tier, dtype,
        )
        kmeans_ops.ring_mode_cfg(cfg)
        if degraded:
            # the halved-chunk rung after a device OOM: route off the
            # fused Pallas kernel (whole-table VMEM residency is exactly
            # what OOMed) onto the chunked XLA Lloyd at doubled chunk
            # count — half the live distance buffer per step
            use_pallas = False
        if ckpt is not None:
            # checkpointing segments the loop between compiled calls; the
            # fused whole-fit Pallas kernel has no segment boundary to
            # checkpoint at, so route onto the chunked XLA Lloyd
            # (docs/distributed.md "Elastic worlds")
            use_pallas = False
        if mesh.shape[cfg.model_axis] > 1 and cfg.kmeans_kernel != "xla":
            # segmented-start ring epilogue geometry: pure function of
            # (config, cache, bucket) so every rank resolves identically
            ring_segments = autotune.resolve(
                "ring",
                autotune.shape_bucket(
                    mesh.shape[cfg.data_axis], table.data.shape[1]
                ),
            )["segments"]

            def run_iters(c0, iters):
                return kmeans_ops.lloyd_run_model_sharded(
                    table.data,
                    weights,
                    c0,
                    iters,
                    jnp.asarray(self.tol, dtype),
                    mesh,
                    cfg.data_axis,
                    cfg.model_axis,
                    precision=tier,
                    timings=timings,
                    policy=pol.name,
                    ring_segments=ring_segments,
                )

            if ckpt is None:
                return run_iters(centers0, self.max_iter)
            return self._run_lloyd_segmented(
                run_iters, centers0, ckpt, resume, d_orig
            )
        single_device = len(jax.devices()) == 1 and jax.process_count() == 1
        # tuned tile geometry for the hot loop, resolved for BOTH kernel
        # routes (the XLA Lloyd derives its chunking from the same tile
        # rows, so a tuned bucket steers either program)
        geo = autotune.resolve(
            "kmeans",
            autotune.shape_bucket(self.k, table.data.shape[1]),
            tier,
        )
        if use_pallas:
            from oap_mllib_tpu.ops.pallas.kmeans_kernel import lloyd_run_pallas

            key = (
                progcache.backend_fingerprint(),
                progcache.array_key(table.data, weights),
                np.asarray(centers0).shape, self.max_iter, tier,
                geo["tile_rows"], geo["depth"],
            )
            with progcache.launch(
                "kmeans.lloyd_pallas", key, timings, "lloyd_loop"
            ):
                return lloyd_run_pallas(
                    table.data,
                    weights,
                    jnp.asarray(centers0),
                    self.max_iter,
                    self.tol,
                    mode=tier,
                    tile_rows=geo["tile_rows"],
                    depth=geo["depth"],
                )
        if single_device and geo != autotune.DEFAULTS["kmeans"]:
            # tuned bucket: chunk the scan at the tuned tile rows (the
            # default geometry keeps auto_row_chunks' occupancy rule
            # bit-for-bit, so untuned fits are unchanged)
            row_chunks = max(
                1, -(-table.n_padded // max(geo["tile_rows"], 1))
            )
        else:
            row_chunks = (
                kmeans_ops.auto_row_chunks(table.n_padded, self.k)
                if single_device
                else 1
            )
        if degraded and single_device:
            # auto_row_chunks returns a chunk COUNT — each geometric
            # rung doubles it again, halving the rows (and the live
            # (chunk, k) buffer) per scan step
            row_chunks = min(
                row_chunks * (2 ** int(degraded)), max(table.n_padded, 1)
            )

        def run_iters(c0, iters):
            return kmeans_ops.lloyd_run(
                table.data,
                weights,
                jnp.asarray(c0),
                iters,
                jnp.asarray(self.tol, dtype),
                row_chunks=row_chunks,
                precision=tier,
                timings=timings,
                policy=pol.name,
            )

        if ckpt is None:
            return run_iters(centers0, self.max_iter)
        return self._run_lloyd_segmented(
            run_iters, centers0, ckpt, resume, d_orig
        )

    def _run_lloyd_segmented(self, run_iters, centers0, ckpt, resume,
                             d_orig):
        """Checkpoint-armed in-memory Lloyd: run the compiled loop in
        ``checkpoint_interval``-sized segments and checkpoint the
        centroids + completed-iteration count between them.  The centroid
        SEQUENCE is identical to the unsegmented loop (each iteration is
        a pure function of the previous centers); the one observable
        divergence is a fit that converges exactly on a segment boundary
        running one extra (sub-tol) iteration — a resumed fit replays
        the same segment schedule, so kill-and-resume stays bit-identical
        against an uninterrupted checkpoint-armed run."""
        done = 0
        converged = False
        if resume is not None and resume.found:
            done = min(int(resume.step), self.max_iter)
            converged = bool(resume.extra.get("converged", False))
        centers = centers0
        ran_segment = False
        while done < self.max_iter and not converged:
            seg = min(ckpt.interval, self.max_iter - done)
            centers, n_it, cost, counts = run_iters(centers, seg)
            ran_segment = True
            done += int(n_it)
            converged = int(n_it) < seg
            ckpt.maybe_write(
                done,
                {"centers": ckpt_mod.fetch_replicated(centers)[:, :d_orig]},
                extra={"converged": converged}, force=True,
            )
        if not ran_segment:
            # fully restored (converged or out of budget): one
            # zero-iteration call computes cost/counts for the summary
            centers, _, cost, counts = run_iters(centers, 0)
        return centers, done, cost, counts

    # -- fallback path (~ trainWithML, KMeans.scala:355) ---------------------
    def _fit_fallback(self, x: np.ndarray, sample_weight: Optional[np.ndarray]) -> KMeansModel:
        from oap_mllib_tpu.data import sparse as _sparse

        timings = Timings("kmeans.fit")
        if _sparse.is_sparse(x):
            # the NumPy reference semantics assume dense host data
            x = x.toarray()
        x = x.astype(np.float64)
        with phase_timer(timings, "init_centers"):
            if self.init_mode == INIT_RANDOM:
                centers0 = kmeans_ops.init_random(x, x.shape[0], self.k, self.seed)
            else:
                # host k-means++ over full data as the || analog (small-data path)
                rng = np.random.default_rng(self.seed)
                w = np.ones(x.shape[0]) if sample_weight is None else np.asarray(sample_weight)
                centers0 = kmeans_ops._weighted_kmeans_pp(x, w, self.k, rng)
        with phase_timer(timings, "lloyd_loop"):
            centers, n_iter, cost = lloyd_np(
                x, centers0, self.max_iter, self.tol, sample_weight, self.distance_measure
            )
        assign = predict_np(x, centers, self.distance_measure)
        w = np.ones(len(x)) if sample_weight is None else np.asarray(sample_weight)
        sizes = np.zeros(self.k)
        np.add.at(sizes, assign, w)
        summary = KMeansSummary(
            cost, n_iter, timings, accelerated=False, cluster_sizes=sizes
        )
        return KMeansModel(centers, self.distance_measure, summary)
