"""Estimator/driver layer: fit/transform estimators with Spark-ML-compatible
parameters, transparent dispatch to accelerated or fallback paths, and model
objects with save/load.

Replaces the reference's L4 algorithm drivers + L6 Spark shims
(KMeansDALImpl.scala / PCADALImpl.scala / ALSDALImpl.scala and the vendored
per-version Spark API copies).  There is no classpath shadowing to replicate:
the Python estimator IS the public API, and dispatch happens inside ``fit``
(survey §7.2 step 4 — Python-first, PySpark-parity surface).
"""

from oap_mllib_tpu.models.kmeans import KMeans, KMeansModel
from oap_mllib_tpu.models.pca import PCA, PCAModel
from oap_mllib_tpu.models.als import ALS, ALSModel

__all__ = ["KMeans", "KMeansModel", "PCA", "PCAModel", "ALS", "ALSModel"]
