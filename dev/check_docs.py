#!/usr/bin/env python
"""Docs gate: the "docs build + samples executed by CI" contract.

- Executes every fenced ```python block in docs/*.md in its own
  subprocess (repo on PYTHONPATH, CPU backend) — samples that rot fail CI.
  A block preceded by an HTML comment containing ``no-run`` (e.g. a
  multi-host template with placeholder RANK/N) is syntax-checked only.
- Verifies intra-docs markdown links resolve.

The Config documentation/coverage/env-naming contract moved to oaplint's
static ``config-field-contract`` rule (dev/oaplint/project.py) — it needs
no runtime, so it rides the lint gate; this script keeps the checks that
genuinely execute things (samples) or touch the filesystem (links).

`mkdocs build` is run additionally by dev/ci.sh when the binary exists
(this image does not ship it).
"""

from __future__ import annotations

import ast
import re
import subprocess
import sys
import os
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
DOCS = ROOT / "docs"

_FENCE = re.compile(r"(<!--[^>]*-->\s*\n)?```python\n(.*?)```", re.S)
_LINK = re.compile(r"\]\(([^)#]+\.md)(#[^)]*)?\)")


def check_samples() -> list:
    failures = []
    env = dict(os.environ)
    env["PYTHONPATH"] = str(ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    for md in sorted(DOCS.glob("*.md")):
        for i, m in enumerate(_FENCE.finditer(md.read_text()), 1):
            marker, code = m.group(1) or "", m.group(2)
            label = f"{md.name} python block #{i}"
            try:
                ast.parse(code)
            except SyntaxError as e:
                failures.append(f"{label}: syntax error: {e}")
                continue
            if "no-run" in marker:
                print(f"  {label}: syntax-checked (no-run)")
                continue
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", code], env=env, cwd=ROOT,
                    capture_output=True, text=True, timeout=600,
                )
            except subprocess.TimeoutExpired:
                failures.append(f"{label}: timed out after 600s")
                continue
            if proc.returncode != 0:
                failures.append(f"{label}: exit {proc.returncode}\n{proc.stderr[-2000:]}")
            else:
                print(f"  {label}: OK")
    return failures


def check_links() -> list:
    failures = []
    for md in sorted(DOCS.glob("*.md")):
        for m in _LINK.finditer(md.read_text()):
            target = (md.parent / m.group(1)).resolve()
            if not target.exists():
                failures.append(f"{md.name}: broken link -> {m.group(1)}")
    return failures


def main() -> int:
    sys.path.insert(0, str(ROOT))
    print("== docs: python samples ==")
    failures = check_samples()
    print("== docs: links ==")
    failures += check_links()
    for f in failures:
        print(f"FAIL: {f}")
    print(f"docs: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
