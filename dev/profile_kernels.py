#!/usr/bin/env python
"""K-Means kernel shoot-out: chunked-XLA Lloyd vs fused Pallas, per shape
and precision tier, on the current backend.

Emits one JSON line per (shape, tier, kernel) plus a markdown table —
the evidence behind Config.kmeans_kernel="auto" picking the XLA path
(config.py cites this table in BASELINE.md; regenerate with
``python dev/profile_kernels.py`` on TPU).

Timing method: per-iteration SLOPE between a short and a long jitted
Lloyd run (the remote-device tunnel adds tens of ms of per-call dispatch
latency; the slope cancels it).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SHAPES = [
    # (n, d, k) — bench headline, smaller-k, high-d, small
    (1 << 20, 256, 1000),
    (1 << 20, 64, 128),
    (1 << 18, 1024, 256),
    (1 << 16, 64, 64),
]
TIERS = ["highest", "high", "default"]


def _iter_window(flops_per_iter: float) -> tuple:
    """(short, long) iteration counts sized so the slope window holds >= ~2s
    of assumed-30TFLOP/s work — small shapes at 4..16 iters complete in
    tens of ms and the tunnel's per-call jitter (±50 ms) swamps the slope."""
    long = int(max(16, min(1024, 2.0 * 30e12 / flops_per_iter)))
    return max(4, long // 4), long


def _time_run(fn):
    fn()  # compile + warm the exact variant
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def profile():
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import kmeans_ops
    from oap_mllib_tpu.ops.pallas.kmeans_kernel import lloyd_run_pallas

    rows = []
    for n, d, k in SHAPES:
        # UNIFORM random data + random init: Lloyd must not converge inside
        # the timed window, or the short/long runs do identical work and
        # the slope is noise (blob data converges in a handful of iters)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        w = jnp.ones((n,), jnp.float32)
        c0 = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
        tol = jnp.asarray(0.0, jnp.float32)
        chunks = kmeans_ops.auto_row_chunks(n, k)
        flops = 2 * 2 * n * k * d
        window = _iter_window(flops)

        for tier in TIERS:
            per = {}
            for kernel in ("xla", "pallas"):
                ts = {}
                win = window
                for attempt in range(3):
                    ok = True
                    for iters in win:
                        if kernel == "xla":
                            run = lambda it=iters: kmeans_ops.lloyd_run(
                                x, w, c0, it, tol, chunks, tier
                            )
                        else:
                            run = lambda it=iters: lloyd_run_pallas(
                                x, w, c0, it, tol, mode=tier
                            )
                        n_iter = int(run()[1])
                        if n_iter != iters:
                            # Lloyd hit an exact fixed point before the
                            # window closed (zero moves satisfy tol=0):
                            # shrink the window below the convergence
                            # point and retry instead of aborting
                            win = (max(2, n_iter // 8), max(8, n_iter // 2))
                            ok = False
                            break
                        fn = lambda r=run, it=iters: np.asarray(r(it)[0])
                        ts[iters] = _time_run(fn)
                    if ok:
                        break
                else:
                    print(f"# skip {n}x{d} k={k} {tier} {kernel}: converges "
                          "too fast for a stable slope", flush=True)
                    continue
                per[kernel] = (ts[win[1]] - ts[win[0]]) / (win[1] - win[0])
                if per[kernel] <= 0:
                    # long run timed faster than short: per-iteration cost
                    # is below the tunnel's jitter floor — unreportable
                    print(f"# skip {n}x{d} k={k} {tier} {kernel}: below "
                          "slope resolution", flush=True)
                    del per[kernel]
                    continue
                rows.append({
                    "shape": f"{n}x{d} k={k}", "tier": tier, "kernel": kernel,
                    "ms_per_iter": round(per[kernel] * 1e3, 2),
                    "iters_per_sec": round(1 / per[kernel], 1),
                    "tflops": round(flops / per[kernel] / 1e12, 1),
                })
                print(json.dumps(rows[-1]), flush=True)
    return rows


def markdown(rows) -> str:
    out = [
        "| shape | tier | XLA ms/iter | Pallas ms/iter | winner |",
        "|---|---|---|---|---|",
    ]
    by = {}
    for r in rows:
        by.setdefault((r["shape"], r["tier"]), {})[r["kernel"]] = r["ms_per_iter"]
    for (shape, tier), d in by.items():
        if "xla" in d and "pallas" in d:
            win = "xla" if d["xla"] <= d["pallas"] else "pallas"
            out.append(
                f"| {shape} | {tier} | {d['xla']} | {d['pallas']} | {win} |"
            )
    return "\n".join(out)


ALS_SHAPES = [
    # (n_users, n_items, nnz, rank) — MovieLens-1M scale + a small shape
    (6040, 3706, 1 << 20, 10),
    (1000, 800, 1 << 17, 10),
]


def profile_als():
    """ALS normal-equation shoot-out: grouped-edge vs COO per-iteration
    slope (implicit mode, the reference's accelerated surface) — the
    evidence behind Config.als_kernel="auto" preferring the grouped
    layout."""
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import als_ops

    rows = []
    for nu, ni, nnz, rank in ALS_SHAPES:
        rng = np.random.default_rng(0)
        u = rng.integers(0, nu, nnz).astype(np.int32)
        i = rng.integers(0, ni, nnz).astype(np.int32)
        r = (rng.random(nnz) * 4 + 1).astype(np.float32)
        x0 = jnp.asarray((rng.normal(size=(nu, rank)) * 0.1).astype(np.float32))
        y0 = jnp.asarray((rng.normal(size=(ni, rank)) * 0.1).astype(np.float32))
        pad = (-nnz) % 2048
        uj = jnp.asarray(np.pad(u, (0, pad)))
        ij = jnp.asarray(np.pad(i, (0, pad)))
        rj = jnp.asarray(np.pad(r, (0, pad)))
        vj = jnp.asarray(np.pad(np.ones(nnz, np.float32), (0, pad)))
        by_u = tuple(jnp.asarray(a) for a in als_ops.build_grouped_edges(u, i, r, nu))
        by_i = tuple(jnp.asarray(a) for a in als_ops.build_grouped_edges(i, u, r, ni))

        def run_grouped(iters):
            return als_ops.als_run_grouped(
                *by_u, *by_i, x0, y0, nu, ni, iters, 0.1, 40.0, True
            )

        def run_coo(iters):
            return als_ops.als_implicit_run(
                uj, ij, rj, vj, x0, y0, nu, ni, iters, 0.1, 40.0
            )

        for kernel, run in (("grouped", run_grouped), ("coo", run_coo)):
            # calibrate the slope window to >= ~2s of work (same rationale
            # as _iter_window: a hardcoded short window leaves fast shapes
            # at the tunnel's tens-of-ms dispatch-jitter floor).  The
            # estimate is itself a SLOPE — whole-call time divided by
            # iterations would fold the fixed per-call dispatch overhead
            # into the per-iteration cost and undershoot the window on
            # exactly the fast shapes this calibration exists for.
            fn4 = lambda r_=run: np.asarray(r_(4)[0])
            fn16 = lambda r_=run: np.asarray(r_(16)[0])
            est = max((_time_run(fn16) - _time_run(fn4)) / 12, 1e-4)
            long = int(max(16, min(2048, 2.0 / est)))
            win = (max(4, long // 4), long)
            ts = {}
            for iters in win:
                fn = lambda it=iters, r_=run: np.asarray(r_(it)[0])
                ts[iters] = _time_run(fn)
            slope = (ts[win[1]] - ts[win[0]]) / (win[1] - win[0])
            if slope <= 0:
                print(f"# skip als {nu}x{ni} nnz={nnz} {kernel}: below "
                      "slope resolution", flush=True)
                continue
            rows.append({
                "shape": f"{nu}x{ni} nnz={nnz} r={rank}",
                "kernel": kernel,
                "ms_per_iter": round(slope * 1e3, 2),
            })
            print(json.dumps(rows[-1]), flush=True)
    return rows


def markdown_als(rows) -> str:
    out = [
        "| shape | grouped ms/iter | COO ms/iter | speedup |",
        "|---|---|---|---|",
    ]
    by = {}
    for r in rows:
        by.setdefault(r["shape"], {})[r["kernel"]] = r["ms_per_iter"]
    for shape, d in by.items():
        if "grouped" in d and "coo" in d:
            # a positive slope can still round to 0.00 ms; don't let the
            # speedup column kill the table after a multi-minute bench
            ratio = (
                f"{d['coo'] / d['grouped']:.1f}×" if d["grouped"] > 0 else "—"
            )
            out.append(
                f"| {shape} | **{d['grouped']}** | {d['coo']} | {ratio} |"
            )
    return "\n".join(out)


PCA_SHAPES = [
    # (n, d) — streamed-chunk scale + the large-d wall
    (1 << 18, 256),
    (1 << 16, 1024),
]
SOLVE_SHAPES = [
    # (n_dst, rank) — ML-1M user side + a wide batch
    (6040, 10),
    (200_000, 10),
]


def profile_fused():
    """Fused-vs-unfused shoot-out for the ISSUE 9 kernels: the PCA
    covariance pass (XLA two-pass vs the fused Pallas moments kernel)
    and the ALS batched normal-equation solve (XLA unrolled batch solve
    vs the fused Pallas assembly+solve).  Off-TPU the Pallas legs run in
    interpret mode — parity-only, timings meaningless — so regenerate on
    hardware like the K-Means table."""
    import jax
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import als_ops
    from oap_mllib_tpu.ops.pallas.als_kernel import solve_normal_eq_pallas
    from oap_mllib_tpu.ops.pallas.pca_kernel import covariance_pallas
    from oap_mllib_tpu.ops.pca_ops import _covariance_jit

    interp = jax.default_backend() != "tpu"
    pca_shapes, solve_shapes = PCA_SHAPES, SOLVE_SHAPES
    if interp:
        print("# non-TPU backend: pallas legs run interpret mode on "
              "reduced shapes (parity only — timings not comparable)",
              flush=True)
        pca_shapes, solve_shapes = [(4096, 128)], [(6040, 10)]
    rows = []
    rng = np.random.default_rng(0)
    for n, d in pca_shapes:
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        m = jnp.ones((n,), jnp.float32)
        nv = jnp.asarray(float(n))
        for kernel, run in (
            ("xla", lambda: np.asarray(_covariance_jit(x, m, nv)[0])),
            ("pallas", lambda: np.asarray(
                covariance_pallas(x, m, nv, interpret=interp)[0])),
        ):
            dt = _time_run(run)
            flops = 2 * n * d * d  # centered Gram
            rows.append({
                "op": "pca_covariance", "shape": f"{n}x{d}",
                "kernel": kernel, "ms": round(dt * 1e3, 2),
                "tflops": round(flops / dt / 1e12, 2),
            })
            print(json.dumps(rows[-1]), flush=True)
    for nd, r in solve_shapes:
        mm = rng.normal(size=(nd, r, r)).astype(np.float32)
        a = jnp.asarray(np.einsum("nij,nkj->nik", mm, mm) + 0.5 * np.eye(r))
        b = jnp.asarray(rng.normal(size=(nd, r)).astype(np.float32))
        n_reg = jnp.asarray(np.ones((nd,), np.float32))
        gram = jnp.asarray(np.eye(r, dtype=np.float32))
        eye = jnp.eye(r, dtype=jnp.float32)
        solve = jax.jit(
            lambda a_, b_, n_: als_ops.regularized_solve(
                a_, b_, n_, 0.1, eye, gram
            )
        )
        for kernel, run in (
            ("xla", lambda: np.asarray(solve(a, b, n_reg))),
            ("pallas", lambda: np.asarray(solve_normal_eq_pallas(
                a, b, n_reg, 0.1, gram, interpret=interp))),
        ):
            dt = _time_run(run)
            rows.append({
                "op": "als_solve", "shape": f"{nd}xr{r}",
                "kernel": kernel, "ms": round(dt * 1e3, 2),
            })
            print(json.dumps(rows[-1]), flush=True)
    return rows


def profile_overlap():
    """Ring-overlap on/off sweep: per-iteration slope of the
    model-sharded Lloyd with the ring-fused moments reduction vs the
    psum path, on whatever mesh the backend offers (the 8-device virtual
    CPU mesh exercises the schedule; ICI overlap numbers need TPU)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops import kmeans_ops
    from oap_mllib_tpu.parallel.mesh import get_mesh

    if len(jax.devices()) < 2:
        print("# <2 devices: ring == psum fallback, nothing to sweep",
              flush=True)
        return []
    set_config(model_parallel=1)
    mesh = get_mesh()
    rng = np.random.default_rng(0)
    n, d, k = 1 << 17, 128, 128
    x = rng.normal(size=(n, d)).astype(np.float32)
    xs = jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, P("data", "model"))
    )
    ws = jax.device_put(
        jnp.ones((n,), jnp.float32), NamedSharding(mesh, P("data"))
    )
    tol = jnp.asarray(0.0, jnp.float32)
    rows = []
    for mode in ("auto", "off"):
        set_config(ring_reduction=mode)
        ts = {}
        for iters in (4, 16):
            fn = lambda it=iters: np.asarray(
                kmeans_ops.lloyd_run_model_sharded(
                    xs, ws, jnp.asarray(x[:k]), it, tol, mesh,
                    "data", "model",
                )[0]
            )
            ts[iters] = _time_run(fn)
        slope = (ts[16] - ts[4]) / 12
        rows.append({
            "op": "lloyd_model_sharded", "ring": mode,
            "shape": f"{n}x{d} k={k}",
            "ms_per_iter": round(max(slope, 0.0) * 1e3, 2),
        })
        print(json.dumps(rows[-1]), flush=True)
    set_config(ring_reduction="auto")
    return rows


SWEEP_BUCKETS = {
    # representative bucket dims per kernel family: (k, d) for kmeans,
    # (d,) for pca, (r,) for the ALS kernels — buckets are n-independent
    # (ops/pallas/autotune.shape_bucket), so one bucket per family shows
    # the whole geometry response
    "kmeans": (128, 256),
    "pca": (256,),
    "als_gram": (16,),
    "als_solve": (16,),
}


def profile_sweep():
    """Autotuner candidate-grid shoot-out (ops/pallas/autotune.py): time
    EVERY candidate geometry per kernel family at a representative shape
    bucket through the tuner's own measurement harness — the long-form
    evidence behind each cached winner.  Off-TPU the kernels run in
    interpret mode (structure-only; regenerate on hardware like the
    other tables)."""
    import jax

    from oap_mllib_tpu.ops.pallas import autotune

    interp = jax.default_backend() != "tpu"
    if interp:
        print("# non-TPU backend: candidates run interpret mode (relative "
              "timings not meaningful — regenerate on TPU)", flush=True)
    rows = []
    for kernel, dims in SWEEP_BUCKETS.items():
        bucket = autotune.shape_bucket(*dims)
        rng = np.random.default_rng(0)
        operands = autotune._bench_operands(kernel, bucket, rng)
        best = None
        for cand in autotune.CANDIDATES[kernel]:
            dt = autotune._measure(kernel, operands, cand, "highest", interp)
            row = {
                "op": "tuning_sweep", "kernel": kernel,
                "bucket": list(bucket), **cand,
                "ms": round(dt * 1e3, 3),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
            if best is None or dt < best[1]:
                best = (cand, dt)
        print(f"# winner {kernel}: {best[0]} ({best[1] * 1e3:.3f} ms)",
              flush=True)
    return rows


def profile_tuned_vs_default():
    """Tuned-vs-default contract check: resolve each kernel family's
    geometry through a fresh sweep (``tuning="on"``, throwaway cache
    dir), then time the winner against the shipped DEFAULTS on the
    tuner's own operands.  The tuned pick must never lose — the default
    is IN the candidate grid, so a loss indicts the measurement
    harness, not the search; __main__ exits nonzero on one."""
    import tempfile

    import jax

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops.pallas import autotune

    interp = jax.default_backend() != "tpu"
    if interp:
        print("# non-TPU backend: interpret-mode walls (contract still "
              "checked — both legs share the harness)", flush=True)
    rows = []
    with tempfile.TemporaryDirectory() as tmp:
        set_config(tuning="on", tuning_cache_dir=tmp)
        autotune.clear()
        try:
            for kernel, dims in SWEEP_BUCKETS.items():
                bucket = autotune.shape_bucket(*dims)
                tuned = autotune.resolve(kernel, bucket, interpret=interp)
                rng = np.random.default_rng(0)
                operands = autotune._bench_operands(kernel, bucket, rng)
                t_tuned = autotune._measure(
                    kernel, operands, tuned, "highest", interp
                )
                t_def = autotune._measure(
                    kernel, operands, autotune.DEFAULTS[kernel], "highest",
                    interp,
                )
                row = {
                    "op": "tuned_vs_default", "kernel": kernel,
                    "tuned": tuned, "default": autotune.DEFAULTS[kernel],
                    "tuned_ms": round(t_tuned * 1e3, 3),
                    "default_ms": round(t_def * 1e3, 3),
                    "speedup": round(t_def / max(t_tuned, 1e-9), 3),
                }
                rows.append(row)
                print(json.dumps(row), flush=True)
        finally:
            set_config(tuning="auto", tuning_cache_dir="")
            autotune.clear()
    return rows


def _print_progcache_stats() -> None:
    """Program-cache hit/miss report for the profiled run: the ops
    entries register every launch with utils/progcache, so after a
    shoot-out this shows how many distinct programs the sweep compiled
    and how much the repeat windows reused (the misses column is the
    compile bill a cold service would pay for these shapes)."""
    from oap_mllib_tpu.utils import progcache

    s = progcache.stats()
    print()
    print(json.dumps({"progcache": {
        k: s[k] for k in ("hits", "misses", "evictions", "hit_rate")
    }}))
    for algo, c in sorted(s["by_algo"].items()):
        print(f"# progcache {algo}: hits={c['hits']} misses={c['misses']}")
    # process-wide telemetry digest (XLA compiles, collective/stream
    # totals) — the registry view of the same sweep
    from oap_mllib_tpu import telemetry

    print()
    print(telemetry.report())


if __name__ == "__main__":
    if "--als" in sys.argv:
        rows = profile_als()
        print()
        print(markdown_als(rows))
    elif "--fused" in sys.argv:
        profile_fused()
    elif "--overlap" in sys.argv:
        profile_overlap()
    elif "--sweep" in sys.argv:
        profile_sweep()
    elif "--tuned-vs-default" in sys.argv:
        tvd = profile_tuned_vs_default()
        # re-measurement noise headroom: the sweep already took min-of-N
        # per candidate, so a real loss shows up far beyond 10%
        bad = [r for r in tvd
               if r["tuned_ms"] > r["default_ms"] * 1.10]
        if bad:
            print(f"# FAIL: tuned geometry slower than defaults: {bad}",
                  flush=True)
            _print_progcache_stats()
            sys.exit(1)
        print("# tuned geometry >= defaults on every kernel family",
              flush=True)
    else:
        rows = profile()
        print()
        print(markdown(rows))
    _print_progcache_stats()
