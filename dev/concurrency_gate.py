#!/usr/bin/env python
"""CI gate: the concurrency analysis plane (ISSUE 14) must hold its
contracts.

Legs:

1. **Analyzer required-clean** — the concurrency rules (R19
   lock-order-inversion, R20 unguarded-shared-write, R21
   blocking-while-locked, R22 unjoined-thread, atexit-outside-shutdown)
   analyze the live tree clean (every pre-existing true finding fixed
   or carrying a reasoned suppression).
2. **Seeded mutations** — one deliberately violating module per rule,
   analyzed through the lint_text overlay seam, produces EXACTLY its
   rule's finding (a refactor that weakens a rule fails here by name).
3. **Inversion drill** — a scripted two-thread lock-order inversion
   under the armed ``locks`` sanitizer raises ``LockOrderError``
   deterministically (events sequence the two orders, so the second
   thread always sees the recorded first ordering) naming both witness
   stacks, BEFORE any real deadlock can form.
4. **Hold-time watchdog** — a hold exceeding the collective deadline is
   flagged (counter + histogram populated), never killed.
5. **Disarmed seam** — tracked-lock operations with sanitizers off are
   one cached config check each; their measured cost must stay <1% of
   the 20-fit K-Means microbench wall (the sanitizer-plane overhead
   contract, dev/sanitizer_gate.py's comparison point).

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "dev"))

import numpy as np  # noqa: E402

import oaplint  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


CONCURRENCY_RULES = [
    "lock-order-inversion",
    "unguarded-shared-write",
    "blocking-while-locked",
    "unjoined-thread",
    "atexit-outside-shutdown",
]

# -- leg 1: analyzer required-clean ------------------------------------------

print("== concurrency gate: R19-R22 + atexit contract required-clean on "
      "the live tree ==")
findings, n_files = oaplint.run(Path(ROOT), rules=CONCURRENCY_RULES)
for f in findings:
    print("  " + f.render())
check(findings == [],
      f"live tree carries {len(findings)} concurrency finding(s)")
check(n_files > 80, f"only {n_files} files enumerated")

# -- leg 2: seeded mutations fire exactly their rule -------------------------

print("== concurrency gate: seeded mutation per rule ==")
OPS = "oap_mllib_tpu/ops/fake_conc.py"
SEEDED = {
    "lock-order-inversion": (
        "import threading\n\n"
        "_A = threading.Lock()\n_B = threading.Lock()\n\n\n"
        "def f():\n    with _A:\n        with _B:\n            pass\n\n\n"
        "def g():\n    with _B:\n        with _A:\n            pass\n"
    ),
    "unguarded-shared-write": (
        "import threading\n\n_STATE = {}\n\n\n"
        "def _worker():\n    _STATE['n'] = 1\n\n\n"
        "def start():\n"
        "    t = threading.Thread(target=_worker, daemon=True)\n"
        "    t.start()\n"
        "    _STATE['n'] = 2\n"
    ),
    "blocking-while-locked": (
        "import threading\nimport time\n\n_lock = threading.Lock()\n\n\n"
        "def f():\n    with _lock:\n        time.sleep(0.1)\n"
    ),
    "unjoined-thread": (
        "import threading\n\n\n"
        "def f(work):\n"
        "    t = threading.Thread(target=work)\n"
        "    t.start()\n"
    ),
    "atexit-outside-shutdown": (
        "import atexit\n\n\ndef f():\n    atexit.register(f)\n"
    ),
}
for rule_name, text in SEEDED.items():
    found = oaplint.lint_text(OPS, text, rules=[rule_name])
    got = sorted({f.rule for f in found})
    check(got == [rule_name],
          f"seeded {rule_name} mutation produced {got or 'nothing'}")
    print(f"  {rule_name}: fires")

# -- leg 3: the two-thread inversion drill -----------------------------------

print("== concurrency gate: scripted two-thread inversion raises "
      "LockOrderError under the locks sanitizer ==")
from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.utils import locktrace  # noqa: E402
from oap_mllib_tpu.utils import sanitizers as san  # noqa: E402

set_config(sanitizers="locks")
a = locktrace.TrackedLock("gate.drill.a")
b = locktrace.TrackedLock("gate.drill.b")
first_done = threading.Event()
box = {}


def leg1():
    with a:
        with b:
            pass
    first_done.set()


def leg2():
    first_done.wait(timeout=10.0)  # deterministic: order is recorded
    try:
        with b:
            with a:
                pass
        box["err"] = None
    except san.LockOrderError as e:
        box["err"] = e


t1 = threading.Thread(target=leg1, name="drill-leg1")
t2 = threading.Thread(target=leg2, name="drill-leg2")
t1.start()
t2.start()
t1.join(timeout=10.0)
t2.join(timeout=10.0)
err = box.get("err")
check(isinstance(err, san.LockOrderError),
      f"inversion drill produced {type(err).__name__} instead of "
      "LockOrderError")
if isinstance(err, san.LockOrderError):
    msg = str(err)
    check("gate.drill.a" in msg and "gate.drill.b" in msg,
          "diagnostic does not name both locks")
    check("This acquisition" in msg and "Recorded witness" in msg,
          "diagnostic does not carry both witness stacks")
    check("leg1" in msg, "recorded witness stack lost the first thread")
    print("  LockOrderError raised; both witness stacks present")

# -- leg 4: hold-time watchdog flags, never kills ----------------------------

print("== concurrency gate: hold-time watchdog flags past the deadline ==")
from oap_mllib_tpu.telemetry import metrics as _tm  # noqa: E402

san._reset_for_tests()
set_config(sanitizers="locks", collective_timeout=0.005)
hold = locktrace.TrackedLock("gate.hold")
flags0 = _tm.family_total("oap_lock_hold_flags_total")
with hold:
    time.sleep(0.02)
check(_tm.family_total("oap_lock_hold_flags_total") == flags0 + 1,
      "over-deadline hold was not flagged")
check(_tm.family_total("oap_lock_hold_seconds") > 0,
      "hold-time histogram not populated")
check(locktrace.hold_quantile(0.99) > 0.0, "hold p99 reads zero")
print(f"  flagged; hold p99 {locktrace.hold_quantile(0.99)*1e3:.2f} ms")
set_config(sanitizers="", collective_timeout=0.0)
san._reset_for_tests()

# -- leg 5: disarmed seam <1% of the 20-fit microbench -----------------------

print("== concurrency gate: disarmed tracked-lock seam on the 20-fit "
      "microbench ==")
from oap_mllib_tpu.models.kmeans import KMeans  # noqa: E402

rng = np.random.default_rng(11)
xs = rng.normal(size=(128, 8)).astype(np.float32)
KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)  # warm
t0 = time.perf_counter()
for _ in range(20):
    KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)
fit_wall = time.perf_counter() - t0

# price 20 tracked acquire/release pairs per fit — a generous
# overestimate: the tracked seams (telemetry sink, fleet state/server,
# serving registry, sanitizer seq) sit OUTSIDE the per-chunk hot loop,
# so a disarmed fit touches them ~0-2 times (sink once per finalize
# when armed, fleet/serving not at all).  Report the per-op cost too.
probe = locktrace.TrackedLock("gate.seam")
reps = 2000
per_fit = 20
t0 = time.perf_counter()
for _ in range(reps):
    for _ in range(per_fit):
        with probe:
            pass
probe_wall = time.perf_counter() - t0
seam_wall = probe_wall * (20.0 / reps)
per_op_us = probe_wall / (reps * per_fit) * 1e6
pct = 100.0 * seam_wall / fit_wall
print(f"  20-fit wall {fit_wall*1e3:.1f} ms; disarmed seam cost "
      f"{seam_wall*1e3:.3f} ms (~{pct:.2f}%, {per_op_us:.2f} us per "
      f"acquire/release pair, {per_fit} pairs/fit priced)")
check(seam_wall < max(0.01 * fit_wall, 0.002),
      f"disarmed tracked-lock seam measurable: {seam_wall:.4f}s vs "
      f"{fit_wall:.4f}s fit wall")

if failures:
    print(f"\nconcurrency gate: {len(failures)} failure(s)")
    sys.exit(1)
print("\nconcurrency gate: OK")
