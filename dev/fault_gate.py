#!/usr/bin/env python
"""CI gate: the resilience ladder must actually absorb injected faults.

Drives the retry tiers end to end on a streamed K-Means fit
(utils/resilience.py + utils/faults.py) and asserts:

- with ``stream.read:fail=2`` + ``prefetch.stage:fail=1`` injected, the
  fit COMPLETES on the accelerated path, matches the fault-free run to
  1e-6, and its summary reports EXACTLY the expected counters (3
  retries, 3 faults, 0 degradations) — injection is deterministic, so
  anything else means a retry tier regressed;
- the fault registry's own accounting agrees (2 + 1 faults fired);
- a persistent device OOM at the jitted-launch site escalates
  accelerated -> GEOMETRIC halved-chunk retries (256-row chunks have two
  halvings above the 64-row floor: /2 then /4, the divisor trail in
  ``resilience.halvings``) -> CPU fallback with NO user-visible
  exception when fallback=True (summary records every rung), and raises
  a ResilienceError carrying the fault history when fallback=False.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PARITY_TOL = 1e-6
TRANSIENT_SPEC = "stream.read:fail=2,prefetch.stage:fail=1"
EXPECT_RETRIES = 3
EXPECT_FAULTS = 3


def _fit(rng_seed: int = 123):
    import numpy as np

    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.kmeans import KMeans

    rng = np.random.default_rng(rng_seed)
    proto = rng.normal(size=(4, 8)).astype(np.float32) * 4.0
    x = (proto[rng.integers(4, size=2000)]
         + rng.normal(size=(2000, 8)).astype(np.float32) * 0.2)
    src = ChunkSource.from_array(x, chunk_rows=256)
    return KMeans(k=4, seed=7, max_iter=10).fit(src)


def main() -> int:
    import numpy as np

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.utils import faults
    from oap_mllib_tpu.utils.resilience import ResilienceError

    failures = []

    # fault-free baseline
    set_config(fault_spec="", retry_backoff=0.001)
    clean = _fit()

    # -- tier 1: transient faults absorbed, exact counters ------------------
    set_config(fault_spec=TRANSIENT_SPEC)
    faults.reset()
    faulted = _fit()
    res = faulted.summary.resilience
    reg = faults.stats()
    report = {
        "retries": res["retries"],
        "faults": res["faults"],
        "degradations": res["degradations"],
        "accelerated": bool(faulted.summary.accelerated),
        "registry": {s: st["fired"] for s, st in reg.items()},
    }
    dev = float(np.abs(
        faulted.cluster_centers_ - clean.cluster_centers_
    ).max())
    report["parity_max_dev"] = dev
    if not faulted.summary.accelerated:
        failures.append("transient faults pushed the fit off the "
                        "accelerated path")
    if res["retries"] != EXPECT_RETRIES or res["faults"] != EXPECT_FAULTS:
        failures.append(
            f"expected exactly {EXPECT_RETRIES} retries / {EXPECT_FAULTS} "
            f"faults, got {res['retries']} / {res['faults']}"
        )
    if res["degradations"] != 0:
        failures.append(
            f"transient faults must not degrade (got "
            f"{res['degradations']} degradations)"
        )
    if reg.get("stream.read", {}).get("fired") != 2 \
            or reg.get("prefetch.stage", {}).get("fired") != 1:
        failures.append(f"registry fired counts off: {report['registry']}")
    if dev > PARITY_TOL:
        failures.append(
            f"faulted vs fault-free centers deviate {dev:.2e} "
            f"(> {PARITY_TOL})"
        )

    # -- tiers 2+3: persistent OOM -> halved chunks -> CPU fallback ---------
    set_config(fault_spec="fit.execute:oom=*", fallback=True)
    faults.reset()
    try:
        oom_fit = _fit()
    except Exception as e:  # noqa: BLE001 — the gate reports, not raises
        failures.append(f"persistent OOM with fallback=True raised: {e!r}")
        oom_fit = None
    if oom_fit is not None:
        ores = oom_fit.summary.resilience
        report["oom_ladder"] = {
            "accelerated": bool(oom_fit.summary.accelerated),
            "degradations": ores["degradations"],
            "halvings": ores["halvings"],
            "history_len": len(ores["history"]),
        }
        if oom_fit.summary.accelerated:
            failures.append("persistent OOM did not land on the CPU path")
        if ores["degradations"] != 3:
            failures.append(
                "expected 3 degradations (geometric halvings /2 and /4 "
                f"+ CPU rung), got {ores['degradations']}"
            )
        if ores["halvings"] != [2, 4]:
            failures.append(
                f"expected geometric halving trail [2, 4], got "
                f"{ores['halvings']}"
            )

    set_config(fallback=False)
    faults.reset()
    try:
        _fit()
        failures.append("persistent OOM with fallback=False did NOT raise")
    except ResilienceError as e:
        if not e.history:
            failures.append("ResilienceError carried no fault history")
    except Exception as e:  # noqa: BLE001
        failures.append(
            f"expected ResilienceError, got {type(e).__name__}: {e}"
        )
    set_config(fault_spec="", fallback=True)

    print(json.dumps(report), flush=True)
    for f in failures:
        print(f"FAIL: {f}")
    print(f"fault gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
