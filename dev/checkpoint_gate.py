#!/usr/bin/env python
"""CI gate: elastic-worlds checkpoint/resume must actually work.

Drives the ISSUE 8 acceptance criteria end to end (utils/checkpoint.py):

1. **Interval writes land** — a checkpoint-armed streamed K-Means fit at
   interval=2 writes exactly its boundary passes, atomically (manifest
   names the last durable step; no ``*.tmp`` debris).
2. **Kill-and-resume parity** — a subprocess fit hard-killed
   (``os._exit(9)`` inside its own source, no cleanup) mid-pass is
   relaunched and must reproduce the uninterrupted checkpoint-armed
   run's model BIT-FOR-BIT.
3. **Resharded restore** — an ALS block checkpoint written on the
   8-block mesh restores onto a 2-block layout (decision
   ``resharded``) through the collective resharding pass and matches
   the uninterrupted fit to 1e-5.
4. **Corrupt-manifest fallback** — a torn manifest yields a fresh fit
   under ``resume="auto"`` and raises ``CheckpointError`` under
   ``resume="require"``; an injected ``ckpt.write`` fault warns + counts
   and never kills the fit.
5. **Checkpoint-off overhead ~0%** — with ``checkpoint_dir`` empty the
   20-fit K-Means microbench median must stay within noise of the
   pre-subsystem cost (one string check per fit).

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the resharded leg needs the suite's 8-device virtual mesh: on a single
# device the 8-block checkpoint and the 2-block relaunch collapse to the
# same layout and no resharding happens (the sanitizer/telemetry gates'
# setup)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

KILL_SCRIPT = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans

mode, ckdir = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(99)
x = rng.normal(size=(2000, 8)).astype(np.float32)
walks = {"n": 0}

def gen():
    walks["n"] += 1
    if mode == "victim" and walks["n"] == 4:  # mid-read of Lloyd pass 3
        os._exit(9)
    for lo in range(0, x.shape[0], 500):
        yield x[lo:lo + 500]

src = ChunkSource(gen, x.shape[1], 500, n_rows=x.shape[0])
set_config(checkpoint_dir=ckdir)
m = KMeans(k=4, seed=7, init_mode="random", max_iter=7, tol=0.0).fit(src)
ck = m.summary.checkpoint
print("RESULT", json.dumps({
    "cost": float(m.summary.training_cost),
    "centers": m.cluster_centers_.tobytes().hex(),
    "decision": ck["decision"], "step": ck["restored_step"],
}))
"""


def _run_kill(mode: str, ckdir: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-c", KILL_SCRIPT, mode, ckdir],
        capture_output=True, text=True, env=env, cwd=_REPO, timeout=300,
    )


def _parse(out: str) -> dict:
    line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
    return json.loads(line[-1][len("RESULT "):])


def main() -> int:
    import numpy as np

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data import io as data_io
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.als import ALS
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.utils import faults
    from oap_mllib_tpu.utils.checkpoint import CheckpointError

    failures = []
    root = tempfile.mkdtemp(prefix="ckpt_gate_")
    rng = np.random.default_rng(5)
    noise = rng.normal(size=(1600, 8)).astype(np.float32)

    # -- 1. interval writes land, atomically --------------------------------
    set_config(checkpoint_dir=os.path.join(root, "ivl"),
               checkpoint_interval=2)
    m = KMeans(k=3, seed=1, max_iter=5, tol=0.0).fit(
        ChunkSource.from_array(noise, chunk_rows=512)
    )
    ck = m.summary.checkpoint
    if ck["writes"] != 2 or ck["last_step"] != 4:
        failures.append(f"interval writes: expected 2 @ step 4, got {ck}")
    mdir = ck["dir"]
    man = data_io.read_json(os.path.join(mdir, "manifest.json"))
    if man["step"] != 4:
        failures.append(f"manifest names step {man['step']}, expected 4")
    debris = [f for f in os.listdir(mdir) if f.endswith(".tmp")]
    if debris:
        failures.append(f"non-atomic write debris: {debris}")
    print(f"interval writes OK: {ck['writes']} writes, "
          f"manifest step {man['step']}, {ck['bytes_written']} B")
    set_config(checkpoint_dir="", checkpoint_interval=1)

    # -- 2. kill-and-resume bit parity --------------------------------------
    full = _run_kill("full", os.path.join(root, "full"))
    if full.returncode != 0:
        failures.append(f"full run failed:\n{full.stdout}\n{full.stderr}")
    victim = _run_kill("victim", os.path.join(root, "kill"))
    if victim.returncode != 9:
        failures.append(
            f"victim exited {victim.returncode}, expected the hard kill 9:"
            f"\n{victim.stdout}\n{victim.stderr}"
        )
    resumed = _run_kill("resume", os.path.join(root, "kill"))
    if resumed.returncode != 0:
        failures.append(
            f"resume run failed:\n{resumed.stdout}\n{resumed.stderr}"
        )
    if not failures:
        rf, rr = _parse(full.stdout), _parse(resumed.stdout)
        if rr["decision"] != "found" or rr["step"] != 2:
            failures.append(f"resume did not restore at pass 2: {rr}")
        if rr["centers"] != rf["centers"] or rr["cost"] != rf["cost"]:
            failures.append(
                "kill-and-resume is not bit-identical to the "
                f"uninterrupted run (costs {rr['cost']} vs {rf['cost']})"
            )
        else:
            print(f"kill-and-resume OK: bit-identical at cost {rf['cost']}")

    # -- 3. resharded restore (8 blocks -> 2 blocks) -------------------------
    nu, ni = 50, 30
    au = rng.integers(nu, size=900).astype(np.int64)
    ai = rng.integers(ni, size=900).astype(np.int64)
    ar = (rng.random(900).astype(np.float32) * 4 + 1)
    au[0], ai[0] = nu - 1, ni - 1
    base = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3).fit(au, ai, ar)
    set_config(checkpoint_dir=os.path.join(root, "rs"))
    ALS(rank=3, max_iter=2, reg_param=0.1, seed=3).fit(au, ai, ar)
    m2 = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3,
             num_user_blocks=2).fit(au, ai, ar)
    ck = m2.summary["checkpoint"]
    if ck["decision"] != "resharded":
        failures.append(f"resharded restore decision: {ck}")
    err = float(np.abs(m2.user_factors_ - base.user_factors_).max())
    if err > 1e-5:
        failures.append(f"resharded restore parity {err:.2e} > 1e-5")
    else:
        print(f"resharded restore OK: decision={ck['decision']}, "
              f"max |Δ| {err:.2e}")
    set_config(checkpoint_dir="")

    # -- 4. corruption tiers + write-fault isolation -------------------------
    cdir = os.path.join(root, "corrupt")
    set_config(checkpoint_dir=cdir)
    src = ChunkSource.from_array(noise, chunk_rows=512)
    m = KMeans(k=3, seed=1, max_iter=3).fit(src)
    mpath = os.path.join(m.summary.checkpoint["dir"], "manifest.json")
    with open(mpath, "w") as f:
        f.write("{torn")
    m_auto = KMeans(k=3, seed=1, max_iter=3).fit(src)
    if m_auto.summary.checkpoint["decision"] != "fresh":
        failures.append(
            f"corrupt manifest under auto: {m_auto.summary.checkpoint}"
        )
    with open(mpath, "w") as f:
        f.write("{torn")  # the auto fit re-wrote a healthy manifest
    set_config(resume="require")
    try:
        KMeans(k=3, seed=1, max_iter=3).fit(src)
        failures.append("corrupt manifest under resume=require did not raise")
    except CheckpointError:
        pass
    set_config(resume="auto", fault_spec="ckpt.write:fail=*")
    faults.reset()
    m_wf = KMeans(k=3, seed=1, max_iter=3).fit(src)
    if not m_wf.summary.accelerated or m_wf.summary.checkpoint["writes"]:
        failures.append(
            "persistent ckpt.write fault should warn with 0 writes and a "
            f"healthy fit; got {m_wf.summary.checkpoint}"
        )
    if m_wf.summary.resilience["degradations"]:
        failures.append("ckpt.write fault consumed a ladder rung")
    print("corruption tiers OK: auto->fresh, require->raise, "
          "write faults isolated")
    set_config(fault_spec="", checkpoint_dir="")

    # -- 5. checkpoint-off overhead ~0% --------------------------------------
    set_config(checkpoint_dir="")
    xb = rng.normal(size=(512, 8)).astype(np.float32)

    def bench() -> float:
        walls = []
        for _ in range(20):
            t0 = time.perf_counter()
            KMeans(k=4, seed=3, max_iter=3).fit(xb)
            walls.append(time.perf_counter() - t0)
        walls.sort()
        return walls[len(walls) // 2]

    bench()  # warm compile caches
    median = bench()
    # absolute bound, like the sanitizer gate's off-path check: the off
    # path is one string compare per fit — measured medians sit far
    # below this even on loaded CI machines
    if median > 1.0:
        failures.append(
            f"checkpoint-off fit median {median * 1e3:.1f} ms "
            "is implausibly slow — the off path must be one string check"
        )
    else:
        print(f"checkpoint-off overhead OK: median fit "
              f"{median * 1e3:.1f} ms (off path is one string check)")

    if failures:
        print("\ncheckpoint gate FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("checkpoint gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
