#!/usr/bin/env python
"""CI gate: compile amortization must actually amortize.

Runs the 10-size fit sweep (bench.bench_compile_sweep — same d/k, ten
distinct row counts, shape bucketing off then on, real XLA backend
compiles counted via the jax monitoring event) and asserts:

- bucketing ON: after the per-mode warm-up fit, the remaining nine
  fits add <= 3 XLA compiles (one bucket = one program set);
- bucketing OFF restores today's behavior: every distinct size pays
  its own compiles (strictly more than the ON tail — at least one per
  remaining size);
- the two modes' per-fit centers agree to 1e-6 (padding rows are
  weight-0; bucketing must not change results).

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SIZES = 10
MAX_STEADY_ON = 3
PARITY_TOL = 1e-6


def main() -> int:
    import bench

    res = bench.bench_compile_sweep(n_sizes=N_SIZES, emit=False)
    report = {k: v for k, v in res.items() if k != "sizes"}
    print(json.dumps(report), flush=True)

    failures = []
    if res["steady_compiles_on"] > MAX_STEADY_ON:
        failures.append(
            f"bucketing on: {res['steady_compiles_on']} XLA compiles after "
            f"the warm-up fit (gate: <= {MAX_STEADY_ON})"
        )
    if res["steady_compiles_off"] < N_SIZES - 1:
        failures.append(
            f"bucketing off: {res['steady_compiles_off']} XLA compiles for "
            f"{N_SIZES - 1} fresh sizes — expected >= one per size "
            "(off no longer restores exact padding?)"
        )
    if res["parity_max_dev"] > PARITY_TOL:
        failures.append(
            f"bucketed vs unbucketed centers deviate "
            f"{res['parity_max_dev']:.2e} (> {PARITY_TOL})"
        )
    for f in failures:
        print(f"FAIL: {f}")
    print(f"compile gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
