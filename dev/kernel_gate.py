#!/usr/bin/env python
"""CI gate: the ISSUE 9 Pallas kernel plane must hold its contracts.

1. **Interpret-mode parity on every kernel** — the fused K-Means
   accumulate, the PCA moments/covariance kernel, the ALS batched
   normal-equation solve, and the factor Gram each reproduce their XLA
   reference (tight f32 bounds; bit-for-bit on exactly-representable
   data for the PCA pass), at every precision tier.
2. **bf16 prices ON Pallas** — the workaround retirement:
   ``precision.kernel_tier("bf16") == "default"`` and the kernel
   preference rules (``pallas_preferred`` / ``pallas_gram_preferred``)
   accept the "default" tier, so a bf16-policy fit on TPU dispatches the
   fused kernels instead of routing off them.
3. **Ring-reduction parity** — on the 8-device virtual mesh, the ring
   schedule (the exact segment rotation the TPU remote-DMA kernel
   drives) matches the psum reference at 1e-5, every rank identical;
   the <2-device fallback stays the psum path; and the ring-fused
   model-sharded Lloyd emits ZERO standalone centroid-moment psums
   (trace-time collective census) while matching the psum build.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the ring legs need the suite's 8-device virtual mesh
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RING_TOL = 1e-5


def _check(failures, ok, msg):
    if not ok:
        failures.append(msg)
        print(f"FAIL: {msg}", flush=True)


def kernel_parity(failures) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from oap_mllib_tpu.ops import als_ops
    from oap_mllib_tpu.ops.kmeans_ops import _accumulate
    from oap_mllib_tpu.ops.pallas.als_kernel import (
        factor_gram_pallas, solve_normal_eq_pallas,
    )
    from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
        lloyd_accumulate_pallas,
    )
    from oap_mllib_tpu.ops.pallas.pca_kernel import covariance_pallas
    from oap_mllib_tpu.ops.pca_ops import _covariance_jit
    from oap_mllib_tpu.utils import precision as psn

    rng = np.random.default_rng(0)
    out = {}

    # K-Means fused accumulate, all tiers — well-separated blobs so the
    # fast tiers' bf16 assignment cannot flip a near-tie row between the
    # two implementations (the tier contract is "argmin is decision-only
    # on non-tied rows"); each tier is compared against the XLA path AT
    # THAT TIER, which runs the same bf16 assignment
    n, d, k = 700, 24, 9
    centers_true = rng.normal(size=(k, d)).astype(np.float32) * 20.0
    assign_true = rng.integers(0, k, n)
    x = jnp.asarray(
        centers_true[assign_true]
        + rng.normal(size=(n, d)).astype(np.float32)
    )
    w = jnp.asarray((rng.random(n) + 0.5).astype(np.float32))
    c = jnp.asarray(centers_true + rng.normal(size=(k, d)).astype(np.float32))
    for mode, atol in (("highest", 1e-3), ("high", 5e-2), ("default", 2.0)):
        s_r, c_r, _ = _accumulate(x, w, c, precision=mode)
        s_p, c_p, _ = lloyd_accumulate_pallas(
            x, w, c, mode=mode, interpret=True
        )
        dev = float(np.abs(np.asarray(s_p) - np.asarray(s_r)).max())
        out[f"kmeans_{mode}_dev"] = dev
        _check(failures, dev <= atol,
               f"kmeans accumulate {mode}: sums dev {dev:.2e} > {atol}")
        _check(
            failures,
            float(np.abs(np.asarray(c_p) - np.asarray(c_r)).max()) <= 1e-3,
            f"kmeans accumulate {mode}: counts diverge (assignment flip)",
        )

    # PCA covariance: exact-data bit parity + general-data tiers
    half = rng.integers(-3, 4, size=(512, 17)).astype(np.float32)
    xe = jnp.asarray(np.concatenate([half, -half]))
    me = jnp.ones((1024,), jnp.float32)
    cov_p, mean_p = covariance_pallas(
        xe, me, jnp.asarray(1024.0), interpret=True
    )
    cov_r, mean_r = _covariance_jit(xe, me, jnp.asarray(1024.0))
    _check(
        failures,
        np.array_equal(np.asarray(cov_p), np.asarray(cov_r))
        and np.array_equal(np.asarray(mean_p), np.asarray(mean_r)),
        "pca covariance not bit-compatible at highest on exact data",
    )
    xg = jnp.asarray(rng.normal(size=(900, 33)).astype(np.float32) + 5.0)
    mg = jnp.asarray((rng.random(900) < 0.95).astype(np.float32))
    nv = jnp.asarray(float(np.asarray(mg).sum()))
    cg_r, _ = _covariance_jit(xg, mg, nv)
    for mode, atol in (("highest", 2e-6), ("high", 5e-5), ("default", 5e-3)):
        cg_p, _ = covariance_pallas(xg, mg, nv, mode=mode, interpret=True)
        dev = float(np.abs(np.asarray(cg_p) - np.asarray(cg_r)).max())
        out[f"pca_{mode}_dev"] = dev
        _check(failures, dev <= atol,
               f"pca covariance {mode}: dev {dev:.2e} > {atol}")

    # ALS batched solve + factor Gram
    r = 10
    m = rng.normal(size=(600, r, r)).astype(np.float32)
    a = jnp.asarray(np.einsum("nij,nkj->nik", m, m) + 0.5 * np.eye(r))
    b = jnp.asarray(rng.normal(size=(600, r)).astype(np.float32))
    n_reg = jnp.asarray(rng.integers(0, 40, 600).astype(np.float32))
    g = rng.normal(size=(64, r)).astype(np.float32)
    gram = jnp.asarray(g.T @ g * 0.01)
    ref = als_ops.regularized_solve(
        a, b, n_reg, 0.1, jnp.eye(r), gram
    )
    got = solve_normal_eq_pallas(a, b, n_reg, 0.1, gram, interpret=True)
    dev = float(np.abs(np.asarray(ref) - np.asarray(got)).max())
    out["als_solve_dev"] = dev
    _check(failures, dev <= 5e-5, f"als solve dev {dev:.2e} > 5e-5")
    zero = np.asarray(n_reg) == 0
    _check(failures, (np.asarray(got)[zero] == 0).all(),
           "als solve: empty rows not masked to zero")
    f = jnp.asarray(rng.normal(size=(777, r)).astype(np.float32))
    fg = factor_gram_pallas(f, interpret=True)
    fdev = float(np.abs(np.asarray(fg) - np.asarray(psn.pdot(f.T, f))).max())
    out["als_gram_dev"] = fdev
    _check(failures, fdev <= 2e-3, f"als factor gram dev {fdev:.2e}")
    return out


def bf16_routing(failures) -> dict:
    from oap_mllib_tpu.ops.kmeans_ops import pallas_preferred
    from oap_mllib_tpu.ops.pallas.als_kernel import pallas_solve_preferred
    from oap_mllib_tpu.ops.pallas.pca_kernel import pallas_gram_preferred
    from oap_mllib_tpu.utils import precision as psn

    tier = psn.kernel_tier("bf16", "highest")
    _check(failures, tier == "default",
           f"kernel_tier('bf16') -> {tier!r}, expected 'default'")
    _check(failures, pallas_preferred(256, 1000, tier),
           "bf16 tier routes OFF the K-Means Pallas kernel "
           "(workaround not retired)")
    _check(failures, pallas_gram_preferred(256, tier),
           "bf16 tier routes OFF the PCA Pallas kernel")
    _check(failures, pallas_solve_preferred(10),
           "default rank routes OFF the ALS Pallas solve")
    return {"bf16_kernel_tier": tier}


def ring_parity(failures) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops import kmeans_ops
    from oap_mllib_tpu.ops.pallas.ring_reduce import ring_allreduce
    from oap_mllib_tpu.parallel import collective
    from oap_mllib_tpu.parallel.mesh import get_mesh
    from oap_mllib_tpu.telemetry import metrics as tm
    from oap_mllib_tpu.utils.jax_compat import shard_map

    rng = np.random.default_rng(1)
    n_dev = len(jax.devices())
    _check(failures, n_dev == 8, f"gate mesh has {n_dev} devices, want 8")
    mesh = jax.make_mesh((n_dev,), ("data",))
    g = rng.normal(size=(n_dev, 64, 96)).astype(np.float32) * 10.0
    gd = jax.device_put(
        jnp.asarray(g), NamedSharding(mesh, P("data", None, None))
    )

    def prog(fn):
        return jax.jit(
            shard_map(
                lambda b: fn(b[0])[None], mesh=mesh,
                in_specs=P("data", None, None),
                out_specs=P("data", None, None), check_vma=False,
            )
        )

    ring = np.asarray(
        prog(lambda v: ring_allreduce(v, "data", n_dev))(gd)
    )
    ref = np.asarray(prog(lambda v: collective.psum(v, "data"))(gd))
    scale = float(np.abs(ref[0]).max())
    dev = float(np.abs(ring[0] - ref[0]).max()) / scale
    rank_identical = all(
        np.array_equal(ring[0], ring[i]) for i in range(n_dev)
    )
    _check(failures, dev <= RING_TOL,
           f"ring vs psum relative dev {dev:.2e} > {RING_TOL}")
    _check(failures, rank_identical, "ring results differ across ranks")

    # ring-fused model-sharded Lloyd: census + parity vs the psum build
    def fit(max_iter):
        data_rng = np.random.default_rng(7)
        x = data_rng.normal(size=(512, 16)).astype(np.float32)
        m2 = get_mesh()
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(m2, P("data", "model"))
        )
        ws = jax.device_put(
            jnp.ones((512,), jnp.float32), NamedSharding(m2, P("data"))
        )
        return kmeans_ops.lloyd_run_model_sharded(
            xs, ws, jnp.asarray(x[:5]), max_iter,
            jnp.asarray(1e-6, jnp.float32), m2, "data", "model",
        )

    set_config(model_parallel=2)
    psum_c = tm.counter("oap_collective_emitted_total", {"op": "psum"})
    p0 = psum_c.value
    c_ring = fit(31)
    ring_psums = psum_c.value - p0
    # score (loop) + d2 (final) + move — ZERO centroid-moment psums
    _check(failures, ring_psums == 3,
           f"ring Lloyd build emitted {ring_psums} psums, expected 3 "
           "(standalone centroid allreduces not eliminated)")
    set_config(ring_reduction="off")
    c_psum = fit(31)
    cdev = float(
        np.abs(np.asarray(c_ring[0]) - np.asarray(c_psum[0])).max()
    )
    _check(failures, cdev <= RING_TOL,
           f"ring vs psum Lloyd centers dev {cdev:.2e} > {RING_TOL}")
    set_config(ring_reduction="auto", model_parallel=1)
    # <2-device fallback: a 1-device mesh must resolve to the psum path
    mesh1 = get_mesh(n_devices=1)
    _check(failures, not kmeans_ops.ring_enabled(mesh1, "data"),
           "ring_enabled True on a 1-device reduce axis")
    return {
        "ring_rel_dev": dev,
        "ring_lloyd_psums": int(ring_psums),
        "ring_lloyd_centers_dev": cdev,
    }


def main() -> int:
    failures: list = []
    report = {}
    report.update(kernel_parity(failures))
    report.update(bf16_routing(failures))
    report.update(ring_parity(failures))
    print(json.dumps({k: (round(v, 8) if isinstance(v, float) else v)
                      for k, v in report.items()}), flush=True)
    print(f"kernel gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
