#!/usr/bin/env python
"""CI gate: the incremental fit paths commit cheap, exact, and safe.

Legs (ISSUE 20 acceptance):

1. **Fold-in parity + speedup** — folding a delta of brand-new users
   into a fitted ALS model matches a from-scratch refit on the same
   combined data in PREDICTION space (rel Frobenius over the folded
   rows' score vectors; the stated bound rides docs/user-guide.md —
   raw factor rows are only unique up to an invertible transform, so
   factor-space comparison would be meaningless), and costs a small
   fraction of the refit wall (>= 5x at gate scale; bench.py --online
   measures the 10k-user headline where the bound is >= 20x).
2. **Second commit is free** — a second delta in the same shape
   buckets performs ZERO new XLA compiles and ZERO autotune sweeps
   (ground truth via progcache.xla_compile_count and
   oap_tuning_sweeps_total), and a served handle answers through the
   NEW version with zero new compiles after the commit.
3. **Staleness drops across a commit** — the
   ``oap_serve_model_staleness_seconds`` gauge falls when a delta
   commits, and the handle's version bumps without eviction.
4. **Mid-commit fault leaves the old pin serving** — a fault injected
   at ``delta.solve`` on the SECOND batch of a chunked fold-in (some
   rows already solved) leaves the model table and the served answers
   bit-identical, version unchanged.
5. **Kill-mid-commit** — a REAL subprocess is SIGKILLed by the
   ``delta.solve:kill`` fault between fold-in batches: the probe
   answered before arming, the commit marker never printed (the swap
   never ran — compute-then-swap means a hard kill cannot leave a
   half-updated table behind).

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

failures = []

# the documented fold-in-vs-refit parity bound (docs/user-guide.md):
# relative Frobenius distance between the folded rows' prediction
# vectors and the refit's, over the same frozen candidate set
PARITY_BOUND = 0.15


def check(ok, msg):
    if not ok:
        failures.append(msg)
        print(f"FAIL: {msg}")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 8)

    from oap_mllib_tpu import serving
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.models.als import ALS
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.telemetry import metrics as tm
    from oap_mllib_tpu.utils import progcache
    from oap_mllib_tpu.utils.faults import FaultInjected

    rng = np.random.default_rng(20)

    # -- leg 1: fold-in parity vs refit + speedup ----------------------------
    print("== online gate: ALS fold-in parity vs from-scratch refit ==")
    nu, ni, rank = 300, 120, 6
    u = rng.integers(0, nu, size=15_000)
    i = rng.integers(0, ni, size=15_000)
    r = rng.normal(1.0, 0.5, size=15_000).astype(np.float32)
    est = dict(rank=rank, max_iter=5, reg_param=0.1, seed=3,
               num_user_blocks=1)
    base = ALS(**est).fit(u, i, r, n_users=nu, n_items=ni)
    # two deltas of brand-new users (~6 ratings each) whose padded
    # shapes land in the SAME power-of-two buckets: the first commit
    # compiles the fold-in solve, the second is the steady state the
    # gate times and compile-counts
    def _delta(lo, n):
        du = np.repeat(np.arange(lo, lo + n), 6)
        di = rng.integers(0, ni, size=du.size).astype(np.int64)
        dr = rng.normal(1.0, 0.5, size=du.size).astype(np.float32)
        return du, di, dr

    du1, di1, dr1 = _delta(nu, 700)
    du2, di2, dr2 = _delta(nu + 700, 800)
    out = base.fold_in_users(du1, di1, dr1)  # first commit: compiles
    check(out["grown"] == [nu, nu + 700],
          f"fold-in did not grow the user axis: {out['grown']}")
    compiles0 = progcache.xla_compile_count()
    sweeps0 = int(tm.family_total("oap_tuning_sweeps_total"))
    t0 = time.perf_counter()
    base.fold_in_users(du2, di2, dr2)  # steady-state commit: timed
    foldin_wall = time.perf_counter() - t0
    foldin_compiles = progcache.xla_compile_count() - compiles0
    foldin_sweeps = (
        int(tm.family_total("oap_tuning_sweeps_total")) - sweeps0
    )
    t0 = time.perf_counter()
    refit = ALS(**est).fit(
        np.concatenate([u, du1, du2]), np.concatenate([i, di1, di2]),
        np.concatenate([r, dr1, dr2]), n_users=nu + 1500, n_items=ni,
    )
    refit_wall = time.perf_counter() - t0
    pred_fold = base.user_factors_[nu:] @ base.item_factors_.T
    pred_refit = refit.user_factors_[nu:] @ refit.item_factors_.T
    rel = (np.linalg.norm(pred_fold - pred_refit)
           / np.linalg.norm(pred_refit))
    speedup = refit_wall / max(foldin_wall, 1e-9)
    print(f"  fold-in {foldin_wall * 1e3:.0f} ms vs refit "
          f"{refit_wall * 1e3:.0f} ms ({speedup:.1f}x), prediction "
          f"parity rel={rel:.3f}")
    check(rel < PARITY_BOUND,
          f"fold-in prediction parity {rel:.3f} breaches the "
          f"documented bound {PARITY_BOUND}")
    check(speedup >= 5.0,
          f"fold-in only {speedup:.1f}x faster than refit at gate "
          "scale (>= 5x required; 10k-user headline bound is 20x)")

    # -- leg 2: second delta commit is free ----------------------------------
    print("== online gate: second delta commit — zero XLA compiles, "
          "zero autotune sweeps ==")
    check(foldin_compiles == 0,
          f"second fold-in commit compiled {foldin_compiles} new XLA "
          "programs (must be 0: bucketed shapes reuse the first "
          "commit's)")
    check(foldin_sweeps == 0,
          f"second fold-in commit ran {foldin_sweeps} autotune sweeps "
          "(must be 0: tuned geometry resolves from the cache)")
    km_x = rng.normal(size=(2000, 12)).astype(np.float32)
    km = KMeans(k=5, seed=2, max_iter=4).fit(km_x)
    hk = serving.serve(km)
    probe = rng.normal(size=(64, 12)).astype(np.float32)
    hk.predict(probe)  # warm the serving bucket
    km.partial_fit(km_x[:512])  # first commit: compiles the delta pass
    compiles0 = progcache.xla_compile_count()
    sweeps0 = int(tm.family_total("oap_tuning_sweeps_total"))
    v0 = hk.model_version
    km.partial_fit(km_x[512:1024])  # same-shape delta: steady state
    served = hk.predict(probe)
    compiles = progcache.xla_compile_count() - compiles0
    sweeps = int(tm.family_total("oap_tuning_sweeps_total")) - sweeps0
    print(f"  second-commit XLA compiles: {compiles}, autotune "
          f"sweeps: {sweeps}")
    check(compiles == 0,
          f"second delta commit compiled {compiles} new XLA programs "
          "(must be 0: bucketed shapes + in-place re-pin)")
    check(sweeps == 0,
          f"second delta commit ran {sweeps} autotune sweeps "
          "(must be 0: tuned geometry resolves from the cache)")
    check(hk.model_version == v0 + 1,
          f"served handle version {hk.model_version} != {v0 + 1} "
          "after the commit")
    check(np.array_equal(served, km.predict(probe)),
          "served answers after the commit diverge from the model")

    # -- leg 3: staleness gauge drops across a commit ------------------------
    print("== online gate: staleness gauge drops across a commit ==")
    hk._committed_at -= 300.0  # age the pin five minutes
    stale_before = hk.touch_staleness()
    km.partial_fit(km_x[512:1024])
    stale_after = tm.gauge(
        "oap_serve_model_staleness_seconds", {"model": "kmeans"}
    ).value
    print(f"  staleness {stale_before:.1f}s -> {stale_after:.3f}s")
    check(stale_before > 299.0 and stale_after < 5.0,
          f"staleness did not drop across the commit "
          f"({stale_before:.1f}s -> {stale_after:.1f}s)")

    # -- leg 4: mid-commit fault leaves the old pin serving ------------------
    print("== online gate: mid-commit fault leaves the old pin "
          "serving ==")
    ha = serving.serve(base)
    ids_before = ha.recommend_for_users(np.arange(8), 5)
    table_before = np.array(base.user_factors_)
    v_before = ha.model_version
    # chunk the delta so the fault lands on the SECOND solve batch —
    # genuinely mid-commit, after rows were already solved
    set_config(fault_spec="delta.solve:err=2", online_foldin_batch=64)
    du3, di3, dr3 = _delta(50, 200)
    faulted = False
    try:
        base.fold_in_users(du3, di3, dr3)
    except FaultInjected:
        faulted = True
    set_config(fault_spec="", online_foldin_batch=0)
    check(faulted, "the armed delta.solve fault never fired")
    check(ha.model_version == v_before,
          f"version bumped across a FAILED commit "
          f"({v_before} -> {ha.model_version})")
    check(np.array_equal(base.user_factors_, table_before),
          "failed mid-commit fold-in mutated the user table")
    check(np.array_equal(ha.recommend_for_users(np.arange(8), 5),
                         ids_before),
          "served answers changed across a FAILED commit")
    print("  old pin intact: version unchanged, answers bit-identical")

    # -- leg 5: kill-mid-commit (real SIGKILL subprocess) --------------------
    print("== online gate: SIGKILL mid-commit leaves no half-updated "
          "table ==")
    _kill_mid_commit_leg()

    if failures:
        print(f"\nonline gate: {len(failures)} failure(s)")
        return 1
    print("\nonline gate: OK")
    return 0


_KILL_WORKER = r"""
import os, sys
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[1])
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.als import ALS
rng = np.random.default_rng(9)
m = ALS(rank=3, max_iter=3, reg_param=0.1, seed=4,
        num_user_blocks=1).fit(
    rng.integers(0, 40, size=1500), rng.integers(0, 30, size=1500),
    rng.normal(1.0, 0.5, size=1500).astype(np.float32),
    n_users=40, n_items=30,
)
print("PROBE_OK", m.recommend_for_users([0, 1], 3).tolist(), flush=True)
# fire the hard kill on the SECOND solve batch: mid-commit for real
set_config(fault_spec="delta.solve:kill=2", online_foldin_batch=8)
m.fold_in_users(
    np.repeat(np.arange(10, 34), 3),
    rng.integers(0, 30, size=72), np.ones(72, np.float32),
)
print("COMMIT_OK", flush=True)
"""


def _kill_mid_commit_leg():
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-c", _KILL_WORKER, repo],
        capture_output=True, text=True, timeout=240, cwd=repo, env=env,
    )
    out = p.stdout + p.stderr
    check(p.returncode == -9,
          f"worker was not SIGKILLed mid-commit (rc={p.returncode}):\n"
          f"{out[-1500:]}")
    check("PROBE_OK" in out,
          f"worker never answered the pre-kill probe:\n{out[-1500:]}")
    check("COMMIT_OK" not in out,
          "worker reached the commit marker — the kill missed the "
          "mid-commit window")
    if p.returncode == -9 and "COMMIT_OK" not in out:
        print("  worker killed between solve batches; swap never ran")


if __name__ == "__main__":
    sys.exit(main())
