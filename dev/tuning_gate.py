#!/usr/bin/env python
"""CI gate: the ISSUE 17 autotuned-kernel plane must hold its contracts.

1. **Cache round-trip determinism** — mode "on" sweeps a missing
   (backend, bucket) exactly once, persists the winner under
   ``tuning_cache_dir``, and a full in-memory wipe (the fresh-process
   stand-in) re-resolves the identical geometry with ZERO new sweeps.
2. **Fresh-process zero-sweep** — a real second interpreter sharing the
   cache dir resolves from disk: ``oap_tuning_sweeps_total`` stays 0
   and the geometry matches the first process's winner bit-for-bit.
3. **Geometry parity** — the double-buffered walks are bit-identical
   across buffering depth and dispatch route at a fixed tile partition,
   and within a scaled 1e-6 across partitions (f32 reassociation only).
4. **Segmented-ring census** — ``segments >= 2`` keeps the ring-fused
   model-sharded Lloyd at exactly 3 standalone psums and within 1e-5 of
   the psum build on the 8-device virtual mesh.
5. **Tuning-off seam cost** — the per-launch ``autotune.resolve`` seam
   in the no-sweep modes ("auto" hit/default, "off") stays microseconds
   — no measurable tax on fits that never asked to tune.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RING_TOL = 1e-5
PARITY_TOL = 1e-6
SEAM_BUDGET_S = 1e-3  # mean per-resolve wall, no-sweep modes


def _check(failures, ok, msg):
    if not ok:
        failures.append(msg)
        print(f"FAIL: {msg}", flush=True)


def _sweeps(kernel: str) -> float:
    from oap_mllib_tpu.telemetry import metrics as tm

    return tm.counter("oap_tuning_sweeps_total", {"kernel": kernel}).value


def cache_round_trip(failures, cache_dir: str) -> dict:
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops.pallas import autotune

    autotune.clear()
    set_config(tuning="on", tuning_cache_dir=cache_dir)
    before = _sweeps("kmeans")
    g1 = autotune.resolve("kmeans", (64, 64), interpret=True)
    swept = _sweeps("kmeans") - before
    _check(failures, swept == 1,
           f"first resolve ran {swept} sweeps, expected exactly 1")
    files = [f for f in os.listdir(cache_dir) if f.startswith("tune-")]
    _check(failures, len(files) == 1,
           f"cache dir holds {len(files)} entries after one sweep")

    autotune.clear()  # fresh-process stand-in: memory gone, disk stays
    before = _sweeps("kmeans")
    g2 = autotune.resolve("kmeans", (64, 64), interpret=True)
    _check(failures, _sweeps("kmeans") == before,
           "re-resolve after clear() swept again (disk entry not read)")
    _check(failures, g2 == g1,
           f"re-resolved geometry {g2} != persisted winner {g1}")
    set_config(tuning="auto", tuning_cache_dir="")
    return {"round_trip_geometry": g1}


_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.ops.pallas import autotune
from oap_mllib_tpu.telemetry import metrics as tm

set_config(tuning="on", tuning_cache_dir=sys.argv[1])
geo = autotune.resolve("kmeans", (64, 64), interpret=True)
print(json.dumps({
    "geometry": geo,
    "sweeps": tm.counter(
        "oap_tuning_sweeps_total", {"kernel": "kmeans"}
    ).value,
}))
"""


def fresh_process_zero_sweep(failures, cache_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = []
    for leg in range(2):
        p = subprocess.run(
            [sys.executable, "-c", _CHILD, cache_dir],
            capture_output=True, text=True, env=env, cwd=_REPO,
            timeout=420,
        )
        _check(failures, p.returncode == 0,
               f"subprocess leg {leg} died: {p.stderr[-1500:]}")
        if p.returncode != 0:
            return {}
        out.append(json.loads(p.stdout.strip().splitlines()[-1]))
    # the parent's round-trip leg already persisted this bucket, so BOTH
    # fresh interpreters must resolve from disk without sweeping
    _check(failures, out[0]["sweeps"] == 0 and out[1]["sweeps"] == 0,
           f"fresh processes swept ({out[0]['sweeps']}, "
           f"{out[1]['sweeps']}) times; cache not honored across exec")
    _check(failures, out[0]["geometry"] == out[1]["geometry"],
           f"fresh processes disagree: {out[0]['geometry']} vs "
           f"{out[1]['geometry']}")
    return {"fresh_process_geometry": out[0]["geometry"]}


def geometry_parity(failures) -> dict:
    import jax.numpy as jnp
    import numpy as np

    from oap_mllib_tpu.ops.pallas.kmeans_kernel import (
        _BLOCK_ROWS, lloyd_accumulate_pallas, lloyd_accumulate_walk,
    )
    from oap_mllib_tpu.ops.pallas.pca_kernel import pca_moments_pallas

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(700, 9)).astype(np.float32))
    w = jnp.ones((700,), jnp.float32)
    c = jnp.asarray(rng.normal(size=(5, 9)).astype(np.float32))

    # grid kernel vs walk at the grid's own partition: bit-identical
    ref = [np.asarray(o) for o in
           lloyd_accumulate_pallas(x, w, c, interpret=True)]
    out = [np.asarray(o) for o in lloyd_accumulate_walk(
        x, w, c, interpret=True, tile_rows=_BLOCK_ROWS, depth=2)]
    _check(failures, all(np.array_equal(a, b) for a, b in zip(out, ref)),
           "kmeans walk not bit-identical to grid kernel at _BLOCK_ROWS")

    max_dev = 0.0
    refs = {}
    for tile_rows, depth in ((256, 2), (512, 3), (1024, 2)):
        for interp in (True, False):
            got = [np.asarray(o) for o in lloyd_accumulate_walk(
                x, w, c, interpret=interp, tile_rows=tile_rows,
                depth=depth)]
            if tile_rows in refs:  # depth/route never move a bit
                _check(
                    failures,
                    all(np.array_equal(a, b)
                        for a, b in zip(got, refs[tile_rows])),
                    f"kmeans walk bits moved at fixed tile_rows="
                    f"{tile_rows} (depth={depth}, interpret={interp})",
                )
            else:
                refs[tile_rows] = got
            scale = max(1.0, float(np.abs(ref[0]).max()))
            dev = float(np.abs(got[0] - ref[0]).max()) / scale
            max_dev = max(max_dev, dev)
            _check(failures, dev <= PARITY_TOL,
                   f"kmeans walk geometry ({tile_rows},{depth},"
                   f"{interp}) dev {dev:.2e} > {PARITY_TOL}")

    xp = jnp.asarray(rng.normal(size=(900, 17)).astype(np.float32))
    mp = jnp.ones((900,), jnp.float32)
    g_ref = np.asarray(pca_moments_pallas(xp, mp, interpret=True)[0])
    scale = max(1.0, float(np.abs(g_ref).max()))
    for tile_rows, depth in ((256, 2), (1024, 3)):
        g = np.asarray(pca_moments_pallas(
            xp, mp, interpret=True, tile_rows=tile_rows, depth=depth)[0])
        dev = float(np.abs(g - g_ref).max()) / scale
        max_dev = max(max_dev, dev)
        _check(failures, dev <= PARITY_TOL,
               f"pca walk geometry ({tile_rows},{depth}) dev "
               f"{dev:.2e} > {PARITY_TOL}")
    return {"walk_parity_max_dev": max_dev}


def segmented_ring_census(failures) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops import kmeans_ops
    from oap_mllib_tpu.parallel.mesh import get_mesh
    from oap_mllib_tpu.telemetry import metrics as tm

    n_dev = len(jax.devices())
    _check(failures, n_dev == 8, f"gate mesh has {n_dev} devices, want 8")

    def fit(ring_segments):
        data_rng = np.random.default_rng(7)
        x = data_rng.normal(size=(512, 16)).astype(np.float32)
        m2 = get_mesh()
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(m2, P("data", "model"))
        )
        ws = jax.device_put(
            jnp.ones((512,), jnp.float32), NamedSharding(m2, P("data"))
        )
        return kmeans_ops.lloyd_run_model_sharded(
            xs, ws, jnp.asarray(x[:5]), 29,
            jnp.asarray(1e-6, jnp.float32), m2, "data", "model",
            ring_segments=ring_segments,
        )

    set_config(model_parallel=2)
    psum_c = tm.counter("oap_collective_emitted_total", {"op": "psum"})
    p0 = psum_c.value
    c_seg = fit(ring_segments=2)
    seg_psums = psum_c.value - p0
    _check(failures, seg_psums == 3,
           f"segmented ring Lloyd emitted {seg_psums} psums, expected 3 "
           "(segmentation broke the fused epilogue)")
    set_config(ring_reduction="off")
    c_psum = fit(ring_segments=1)
    set_config(ring_reduction="auto", model_parallel=1)
    cdev = float(
        np.abs(np.asarray(c_seg[0]) - np.asarray(c_psum[0])).max()
    )
    _check(failures, cdev <= RING_TOL,
           f"segmented ring vs psum centers dev {cdev:.2e} > {RING_TOL}")
    return {"segmented_psums": int(seg_psums),
            "segmented_centers_dev": cdev}


def seam_cost(failures, cache_dir: str) -> dict:
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.ops.pallas import autotune

    out = {}
    reps = 300
    # "auto" with a warm persisted entry (the steady-state hit path),
    # "auto" with no entry (default path), and "off"
    legs = (
        ("auto_hit", "auto", cache_dir, (64, 64)),
        ("auto_default", "auto", "", (32, 8)),
        ("off", "off", "", (64, 64)),
    )
    for name, mode, cdir, bucket in legs:
        set_config(tuning=mode, tuning_cache_dir=cdir)
        autotune.resolve("kmeans", bucket, interpret=True)  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            autotune.resolve("kmeans", bucket, interpret=True)
        per = (time.perf_counter() - t0) / reps
        out[f"seam_{name}_s"] = per
        _check(failures, per <= SEAM_BUDGET_S,
               f"no-sweep resolve ({name}) costs {per * 1e6:.0f} us "
               f"per launch > {SEAM_BUDGET_S * 1e6:.0f} us budget")
    set_config(tuning="auto", tuning_cache_dir="")
    return out


def main() -> int:
    failures: list = []
    report = {}
    with tempfile.TemporaryDirectory(prefix="oap-tuning-gate-") as tmp:
        report.update(cache_round_trip(failures, tmp))
        report.update(fresh_process_zero_sweep(failures, tmp))
        report.update(geometry_parity(failures))
        report.update(segmented_ring_census(failures))
        report.update(seam_cost(failures, tmp))
    print(json.dumps({k: (round(v, 8) if isinstance(v, float) else v)
                      for k, v in report.items()}), flush=True)
    print(f"tuning gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
