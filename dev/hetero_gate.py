#!/usr/bin/env python
"""CI gate: capability-weighted sharding (ISSUE 15) must hold its
contracts.

Legs:

1. **Planner properties** — extents sum to n at every (weights, caps)
   shape, are chunk-quantized, respect membudget caps (with the
   infeasible-cap overflow loud, never silent data loss), and a
   1-rank world degenerates to the equal plan; block offsets honor the
   deadband (near-equal worlds keep the exact uniform layout) and
   monotone non-empty boundaries.
2. **Skewed world beats equal shards, parity intact** — a 2-rank world
   SIMULATED in one process (each rank's assignment pass walks its
   planned extent through the real per-chunk program, the straggler
   paying a calibrated per-chunk sleep): the capability-weighted
   layout's wall (max over ranks, the pass barrier) must beat the
   equal layout's by a real margin, with the combined centroid moments
   within 1e-5.  The REAL 2-process legs (wall + parity + the
   summary.balance decision trail + live rebalancing) ride
   ``tests/test_pseudo_cluster.py::TestHeteroFleet`` and skip only
   where the host cannot form multiprocess worlds.
3. **Rebalance determinism** — the straggler controller, fed the same
   pinned-capability plan and the same fleet frame sequence twice,
   must produce byte-identical decisions and extents (drills are
   reproducible; a nondeterministic controller would diverge ranks).
4. **End-to-end balanced fit** — a single-process balanced streamed
   fit lands ``summary.balance`` (origin, weights, extents) + the
   ``balance`` span and is bit-identical to the plain-source fit (a
   1-rank plan is the identity extent).
5. **Disarmed seam** — capability_sharding=off costs <1% of the
   20-fit K-Means microbench (the PR 4/7/11 off-path contract).

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.models.kmeans import KMeans  # noqa: E402
from oap_mllib_tpu.parallel import balance  # noqa: E402
from oap_mllib_tpu.telemetry import fleet  # noqa: E402

# -- leg 1: planner properties -------------------------------------------------

print("== hetero gate: planner properties ==")
rng = np.random.default_rng(0)
for trial in range(200):
    world = int(rng.integers(1, 9))
    chunk = int(2 ** rng.integers(4, 10))
    n = int(rng.integers(1, 40 * chunk))
    w = rng.random(world) * 2 + 0.05
    caps = None
    if rng.random() < 0.4:
        caps = [int(c) for c in rng.integers(0, 20 * chunk, world)]
    extents, over = balance.plan_extents(n, chunk, w, caps_rows=caps)
    total = sum(r for _, r in extents)
    check(total == n, f"extents sum {total} != n {n} (trial {trial})")
    pos = 0
    for s, r in extents:
        check(s == pos, f"extent start {s} != running offset {pos}")
        pos += r
    # every boundary except the global tail is chunk-quantized
    for s, r in extents[:-1]:
        if r:
            check((s + r) % chunk == 0 or s + r == n,
                  f"unquantized boundary {s + r} (chunk {chunk})")
    if caps is not None and not over and world > 1:
        # effective cap: a participating rank floors at one chunk, and
        # the global sub-chunk tail may ride the last populated rank
        for r_i, ((_, rows), cap) in enumerate(zip(extents, caps)):
            if cap > 0:
                eff = max(1, cap // chunk) * chunk
                check(rows <= eff + chunk,
                      f"cap violated: rank {r_i} rows {rows} cap {cap} "
                      f"(chunk {chunk})")
ext1, _ = balance.plan_extents(12345, 256, [1.0])
check(ext1 == [(0, 12345)], f"world-1 plan not identity: {ext1}")
eq, _ = balance.plan_extents(4096, 256, [1.0, 1.0])
check(eq[0][1] == eq[1][1] == 2048, f"equal weights uneven: {eq}")

off = balance.plan_block_offsets(1000, [1.0, 1.02])
check(off is None, f"deadband did not keep uniform layout: {off}")
off = balance.plan_block_offsets(1000, [1.0, 0.25])
check(off is not None and list(off) == sorted(list(off))
      and off[0] == 0 and off[-1] == 1000,
      f"weighted offsets malformed: {off}")
check(off is not None and all(np.diff(off) >= 1),
      f"empty block in weighted offsets: {off}")
print(f"  200 randomized plans OK; weighted block offsets {list(off)}")

# -- leg 2: simulated skewed world beats equal shards --------------------------

print("== hetero gate: skewed 2-rank simulation (equal vs weighted) ==")
sys.path.insert(0, ROOT)
import bench  # noqa: E402

res = bench.bench_skew(rows=1 << 17, d=32, k=32, slow_factor=4.0,
                       emit=False)
check(res["hetero_speedup"] > 1.3,
      f"weighted layout speedup {res['hetero_speedup']} <= 1.3 "
      f"(equal {res['equal_wall_s']}s, weighted {res['weighted_wall_s']}s)")
check(res["parity"] <= 1e-5,
      f"cross-layout moment parity {res['parity']} > 1e-5")
print(f"  speedup {res['hetero_speedup']}x, parity {res['parity']:.2e}")

# -- leg 3: rebalance determinism under pinned capabilities --------------------

print("== hetero gate: rebalance decision determinism ==")
set_config(capability_sharding="on", rebalance_threshold=1.4,
           rebalance_patience=2, rank_capability="")
F = len(fleet.FRAME_FIELDS)
frames = np.ones((2, F))
frames[0, 0], frames[1, 0] = 1.0, 4.0


def drive():
    balance._reset_for_tests()
    cw = balance.fold_world(
        np.asarray([[1.0, 1, 0, 0], [1.0, 1, 0, 0]])
    )
    plan = balance.make_plan(30000, 512, world=2, capworld=cw)
    frames[0, 7] = plan.extents()[0][1]
    frames[1, 7] = plan.extents()[1][1]
    decs = []
    for _ in range(6):
        d = balance.observe_pass("lloyd_loop", frames)
        if d is not None:
            decs.append(d)
    return plan.extents(), decs


ext_a, dec_a = drive()
ext_b, dec_b = drive()
check(ext_a == ext_b, f"extents diverged: {ext_a} vs {ext_b}")
check(dec_a == dec_b, "re-plan decisions diverged across identical runs")
check(len(dec_a) >= 1, "no re-plan fired on a 4x-skewed frame sequence")
check(dec_a[0]["slowest_rank"] == 1, f"wrong straggler: {dec_a[0]}")
check(ext_a[1][1] < ext_a[0][1],
      f"straggler extent did not shrink: {ext_a}")
balance._reset_for_tests()
print(f"  {len(dec_a)} identical decisions; final extents {ext_a}")

# -- leg 4: end-to-end balanced fit + summary.balance --------------------------

print("== hetero gate: balanced single-process fit (identity extent, "
      "summary.balance) ==")
set_config(capability_sharding="on", fleet_stats="on",
           rebalance_threshold=1.5, rebalance_patience=3)
x = np.random.default_rng(3).normal(size=(4000, 12)).astype(np.float32)
src = balance.local_sources(x, chunk_rows=500)
m_bal = KMeans(k=4, seed=1, init_mode="random", max_iter=3, tol=0.0).fit(src)
blk = getattr(m_bal.summary, "balance", None)
check(blk is not None, "summary.balance missing on a balanced fit")
if blk is not None:
    check(blk["extents"] == [[0, 4000]],
          f"1-rank extent not identity: {blk['extents']}")
    check(blk["origin"] in ("probe", "pinned"),
          f"unexpected origin {blk['origin']}")
spans = m_bal.summary.telemetry["spans"]
check("balance" in [c["name"] for c in spans["children"]],
      "balance span missing")
flt = getattr(m_bal.summary, "fleet", None)
check(flt is not None and flt.get("per_rank_rows") is not None,
      "fleet block missing per_rank_rows")
check(flt is not None and flt.get("per_rank_capability") is not None,
      "fleet block missing per_rank_capability")

balance._reset_for_tests()
set_config(capability_sharding="off", fleet_stats="auto")
from oap_mllib_tpu.data.stream import ChunkSource  # noqa: E402

plain = ChunkSource.from_array(x, chunk_rows=500)
m_plain = KMeans(k=4, seed=1, init_mode="random", max_iter=3,
                 tol=0.0).fit(plain)
delta = float(np.max(np.abs(
    m_bal.cluster_centers_ - m_plain.cluster_centers_
)))
check(delta == 0.0,
      f"1-rank balanced fit not bit-identical to plain source: {delta}")
print(f"  summary.balance OK, bit-identical to plain source")

# -- leg 2b: REAL 2-process legs (skip where worlds cannot form) ---------------

print("== hetero gate: real 2-process skew + rebalance legs (pytest; "
      "skips where the host cannot form worlds) ==")
proc = subprocess.run(
    [sys.executable, "-m", "pytest",
     "tests/test_pseudo_cluster.py::TestHeteroFleet", "-q",
     "-p", "no:cacheprovider"],
    cwd=ROOT, capture_output=True, text=True, timeout=600,
)
print("  " + (proc.stdout.strip().splitlines()[-1]
              if proc.stdout.strip() else ""))
check(proc.returncode == 0,
      f"pseudo-cluster hetero legs failed:\n{proc.stdout[-2000:]}")

# -- leg 5: disarmed seam ------------------------------------------------------

print("== hetero gate: disarmed seam on the 20-fit microbench ==")
balance._reset_for_tests()
set_config(capability_sharding="off", fleet_stats="off")
xs = np.random.default_rng(0).normal(size=(128, 8)).astype(np.float32)
KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)  # warm
t0 = time.perf_counter()
for _ in range(20):
    KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)
fit_wall = time.perf_counter() - t0

# the disarmed path per fit: armed() config checks at pass boundaries
# plus the finalize None-check.  Price 100 seam touches per fit — an
# overestimate — 2000 times, and scale to 20 fits.
reps = 2000
t0 = time.perf_counter()
for _ in range(reps):
    for _ in range(100):
        balance.armed(1)
    balance.finalize_fit(None, None)
seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
pct = 100.0 * seam_wall / fit_wall
print(f"  20-fit wall {fit_wall*1e3:.1f} ms; disarmed seam cost "
      f"{seam_wall*1e3:.3f} ms (~{pct:.2f}%)")
check(seam_wall < max(0.01 * fit_wall, 0.005),
      f"disarmed balance seam measurable: {seam_wall:.4f}s vs "
      f"{fit_wall:.4f}s fit wall")

if failures:
    print(f"\nhetero gate: {len(failures)} failure(s)")
    sys.exit(1)
print("\nhetero gate: OK")
