#!/usr/bin/env python
"""CI gate: the fleet observability control plane (ISSUE 11) must hold
its contracts.

Legs:

1. **Live endpoint** — a streamed fit with ``metrics_port`` armed
   serves ``/metrics`` (parses as promtext, carries ``oap_fleet_*``
   families) and ``/healthz`` (parses as JSON, names the fit root and
   step) from the per-rank http thread.
2. **Rollup correctness** — on the 8-device pseudo-mesh, rank 0's
   per-pass fold equals a hand-fold of the gathered frames
   (min/max/mean/p99 recomputed with numpy), and the fit summary's
   ``fleet`` block is consistent with the recorded window.
3. **Straggler analytics** — a synthetic 2-rank frame set with one
   deliberately slowed rank folds to skew_ratio > 1.5 naming that rank;
   the REAL 2-process leg (a slow rank 1 chunk source) rides
   ``tests/test_pseudo_cluster.py::TestFleetObservability`` and skips
   only where the host cannot form multiprocess worlds.
4. **Merged timelines** — ``dev/oaptrace.py`` over the leg-1 JSONL sink
   emits a Chrome-trace file that validates against the trace-event
   schema (every event carries name/ph/ts/pid/tid, X slices carry dur).
5. **Disarmed seam** — fleet off + recorder off + no metrics port costs
   <1% of the 20-fit K-Means microbench (the PR 4/7 off-path contract).

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "dev"))

import numpy as np  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.data.stream import ChunkSource  # noqa: E402
from oap_mllib_tpu.models.kmeans import KMeans  # noqa: E402
from oap_mllib_tpu.parallel.bootstrap import free_port  # noqa: E402
from oap_mllib_tpu.telemetry import fleet, flightrec  # noqa: E402

import oaptrace  # noqa: E402


def _source(rows=2000, d=8, chunk=500, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, d)).astype(np.float32)

    def gen():
        for lo in range(0, rows, chunk):
            yield x[lo:lo + chunk]

    return ChunkSource(gen, d, chunk, n_rows=rows)


# -- leg 1 + 4 setup: one armed streamed fit -----------------------------------

print("== fleet gate: live endpoint + armed streamed fit ==")
sink = os.path.join(tempfile.mkdtemp(), "fleet.jsonl")
port = free_port("127.0.0.1", 9300)
set_config(
    fleet_stats="on", flight_recorder=256, metrics_port=port,
    telemetry_log=sink,
)
m = KMeans(k=4, seed=0, init_mode="random", max_iter=4, tol=0.0).fit(
    _source()
)
block = m.summary.fleet
check(block.get("enabled") and block.get("passes", 0) >= 4,
      f"fleet block missing or empty: {block}")

mtxt = urllib.request.urlopen(
    f"http://127.0.0.1:{port}/metrics", timeout=10
).read().decode()
fleet_lines = [ln for ln in mtxt.splitlines()
               if ln.startswith("oap_fleet_")]
check(len(fleet_lines) > 20,
      f"/metrics carries too few oap_fleet_* samples: {len(fleet_lines)}")
# promtext sanity: every non-comment line is "name{labels} value"
for ln in mtxt.splitlines():
    if not ln or ln.startswith("#"):
        continue
    parts = ln.rsplit(" ", 1)
    ok = len(parts) == 2
    if ok:
        try:
            float(parts[1].replace("+Inf", "inf"))
        except ValueError:
            ok = False
    if not ok:
        check(False, f"/metrics line does not parse: {ln!r}")
        break

hz = json.loads(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/healthz", timeout=10
).read())
check(hz.get("ok") is True and hz.get("fit") == "kmeans.fit",
      f"/healthz payload wrong: {hz}")
check(hz.get("flight_recorder_seq", -1) >= 0,
      f"/healthz missing recorder seq: {hz}")
print(f"  /metrics: {len(fleet_lines)} oap_fleet_* samples; "
      f"/healthz fit={hz.get('fit')} step={hz.get('step')}")

# -- leg 2: rollup correctness (hand-fold) -------------------------------------

print("== fleet gate: rollup fold equals a numpy hand-fold ==")
rng = np.random.default_rng(7)
frames = rng.random((8, len(fleet.FRAME_FIELDS))) + 0.1
rec = fleet.fold_pass("gate_pass", frames)
for i, f in enumerate(fleet.FRAME_FIELDS):
    col = frames[:, i]
    hand = {
        "min": float(col.min()), "max": float(col.max()),
        "mean": float(col.mean()), "p99": float(np.percentile(col, 99)),
    }
    got = rec["fields"][f]
    check(
        all(abs(hand[s] - got[s]) < 1e-12 for s in hand),
        f"fold of {f} != hand-fold: {got} vs {hand}",
    )
walls = frames[:, 0]
check(rec["slowest_rank"] == int(np.argmax(walls)),
      f"slowest_rank {rec['slowest_rank']} != argmax {np.argmax(walls)}")
check(abs(rec["skew_ratio"] - walls.max() / walls.mean()) < 1e-12,
      "skew_ratio != max/mean of pass walls")

# -- leg 3: straggler analytics ------------------------------------------------

print("== fleet gate: a delayed rank folds to skew > 1.5 naming it ==")
fleet._reset_for_tests()
slow = np.ones((2, len(fleet.FRAME_FIELDS)))
slow[1, 0] = 4.0  # rank 1's pass wall is 4x rank 0's
for _ in range(3):
    rec = fleet.fold_pass("lloyd_loop", slow)
check(rec["skew_ratio"] > 1.5 and rec["slowest_rank"] == 1,
      f"skewed fold wrong: {rec['skew_ratio']:.2f} rank "
      f"{rec['slowest_rank']}")
blk = fleet.summary_block()
check(blk["slowest_rank"] == 1 and blk["fit_skew_ratio"] > 1.5,
      f"summary block misses the straggler: {blk}")
fleet._reset_for_tests()

print("== fleet gate: 2-process pseudo-cluster legs (skip if the host "
      "cannot form multiprocess worlds) ==")
proc = subprocess.run(
    [sys.executable, "-m", "pytest",
     "tests/test_pseudo_cluster.py::TestFleetObservability", "-q",
     "-p", "no:cacheprovider"],
    cwd=ROOT, capture_output=True, text=True, timeout=900,
)
print(proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "")
check(proc.returncode == 0,
      f"pseudo-cluster fleet legs failed:\n{proc.stdout[-2000:]}")

# -- leg 4: merged timeline validates against the trace-event schema -----------

print("== fleet gate: oaptrace output validates (Chrome trace schema) ==")
trace_out = os.path.join(tempfile.mkdtemp(), "trace.json")
rc = oaptrace.main([sink, "-o", trace_out])
check(rc == 0, f"oaptrace exited {rc}")
with open(trace_out) as f:
    trace = json.load(f)
problems = oaptrace.validate_trace(trace)
check(problems == [], f"trace schema problems: {problems[:5]}")
check(trace["otherData"]["mode"] == "recorder",
      f"expected recorder-mode timeline, got {trace['otherData']}")
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
check(len(spans) > 0, "merged timeline has no span slices")
print(f"  {len(trace['traceEvents'])} events, {len(spans)} slices, "
      f"mode={trace['otherData']['mode']}")

# -- leg 5: disarmed seam ------------------------------------------------------

print("== fleet gate: disarmed seam on the 20-fit microbench ==")
fleet.stop_server()
set_config(fleet_stats="off", flight_recorder=0, metrics_port=0,
           telemetry_log="")
xs = np.random.default_rng(0).normal(size=(128, 8)).astype(np.float32)
KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)  # warm
t0 = time.perf_counter()
for _ in range(20):
    KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)
fit_wall = time.perf_counter() - t0

# the disarmed path per fit: a few armed() / flightrec.enabled() config
# checks (pass boundaries, span entries, finalize hook).  Price 100 seam
# touches per fit — an overestimate — 2000 times, and scale to 20 fits.
reps = 2000
world = 1
t0 = time.perf_counter()
for _ in range(reps):
    for _ in range(100):
        flightrec.enabled()
        fleet.armed(world)
    fleet.finalize_fit(None, None)
seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
pct = 100.0 * seam_wall / fit_wall
print(f"  20-fit wall {fit_wall*1e3:.1f} ms; disarmed seam cost "
      f"{seam_wall*1e3:.3f} ms (~{pct:.2f}%)")
check(seam_wall < max(0.01 * fit_wall, 0.005),
      f"disarmed fleet seam measurable: {seam_wall:.4f}s vs "
      f"{fit_wall:.4f}s fit wall")

if failures:
    print(f"\nfleet gate: {len(failures)} failure(s)")
    sys.exit(1)
print("\nfleet gate: OK")
