#!/usr/bin/env python
"""CI gate: the mixed-precision compute policy must hold its contracts.

Drives the policy subsystem (utils/precision.py) end to end and asserts:

- **f32 bit-compatibility** — ``compute_precision="f32"`` (the default)
  reproduces pre-policy numerics EXACTLY: op-level, the policy-threaded
  kernels called with ``policy="f32"`` match their default-argument
  (pre-policy) invocations bit-for-bit; fit-level, a default-config fit
  and an explicit-f32 fit produce identical models;
- **bf16 parity** — all three estimators fit at ``bf16`` on fixed seeds
  match their f32 fits within the registered bounds
  (``precision.PARITY_BOUNDS``): K-Means centroids/cost, PCA principal
  subspace angle + explained-variance ratios, ALS factor/prediction
  RMSE.  Streamed K-Means/PCA run the bf16-STAGED pipeline (the
  cast-at-staging path), in-memory ALS the bf16 moment kernels;
- **observability** — the chosen policy lands in the fit summary
  (``precision``), on the span-tree root (``attrs["precision"]``, the
  telemetry exporters' source), and follows the per-algorithm override;
- **degradation** — an injected non-finite iterate (``fit.execute:nan``)
  under bf16 steps the resilience ladder's precision rung: the fit
  COMPLETES at f32 (summary records the rung), accelerated.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _blobs(rng, n, d, k, spread=6.0, noise=0.2):
    import numpy as np

    proto = rng.normal(size=(k, d)).astype(np.float32) * spread
    x = (proto[rng.integers(k, size=n)]
         + rng.normal(size=(n, d)).astype(np.float32) * noise)
    return x, proto


def main() -> int:
    import numpy as np

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.als import ALS
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.models.pca import PCA
    from oap_mllib_tpu.utils import faults
    from oap_mllib_tpu.utils.precision import PARITY_BOUNDS

    failures = []
    report = {}
    rng = np.random.default_rng(11)

    # -- 1) op-level f32 bit-compat: policy="f32" == pre-policy defaults ----
    import jax.numpy as jnp

    from oap_mllib_tpu.ops import als_ops, kmeans_ops, pca_ops

    x, _ = _blobs(rng, 512, 16, 4)
    xj = jnp.asarray(x)
    w = jnp.ones((512,), jnp.float32)
    c = jnp.asarray(x[:4])
    for tier in ("highest", "high", "default"):
        a = kmeans_ops._accumulate(xj, w, c, tier, True)
        b = kmeans_ops._accumulate(xj, w, c, tier, True, "f32")
        if not all(np.array_equal(np.asarray(u), np.asarray(v))
                   for u, v in zip(a, b)):
            failures.append(f"kmeans._accumulate policy=f32 != default @ {tier}")
    cov_a = pca_ops._covariance_jit(xj, w, jnp.asarray(512.0), "highest")
    cov_b = pca_ops._covariance_jit(xj, w, jnp.asarray(512.0), "highest", "f32")
    if not np.array_equal(np.asarray(cov_a[0]), np.asarray(cov_b[0])):
        failures.append("pca._covariance_jit policy=f32 != default")
    ys = jnp.asarray(rng.normal(size=(40, 8)).astype(np.float32))
    src_g = jnp.asarray(rng.integers(40, size=(8, 16)).astype(np.int32))
    gm_a = als_ops.grouped_block_moments(
        src_g, jnp.ones((8, 16), jnp.float32), jnp.ones((8, 16), jnp.float32),
        ys, jnp.asarray(10.0), True,
    )
    gm_b = als_ops.grouped_block_moments(
        src_g, jnp.ones((8, 16), jnp.float32), jnp.ones((8, 16), jnp.float32),
        ys, jnp.asarray(10.0), True, "f32",
    )
    if not np.array_equal(np.asarray(gm_a), np.asarray(gm_b)):
        failures.append("als.grouped_block_moments policy=f32 != default")

    # -- 2) fit-level f32 bit-compat: default config == explicit f32 --------
    n, d, k = 4096, 16, 4
    x, proto = _blobs(rng, n, d, k)
    set_config(compute_precision="f32")
    km_f32 = KMeans(k=k, seed=7, max_iter=12).fit(x)
    # the true default path: a FRESH config (compute_precision never set)
    import oap_mllib_tpu.config as cfgmod

    with cfgmod._lock:
        cfgmod._config = None
    km_def = KMeans(k=k, seed=7, max_iter=12).fit(x)
    if not np.array_equal(km_f32.cluster_centers_, km_def.cluster_centers_):
        failures.append("fit under compute_precision='f32' != default-config fit")
    if km_f32.summary.precision != "f32":
        failures.append(
            f"f32 summary records {km_f32.summary.precision!r}, not 'f32'"
        )

    # -- 3) bf16 parity within the registered bounds ------------------------
    scale = float(np.abs(x).max())
    src = ChunkSource.from_array(x, chunk_rows=512)
    km_ref = KMeans(k=k, seed=7, max_iter=12).fit(src)  # streamed f32
    # k-1 components: 4 well-separated protos span a rank-3 between-
    # cluster subspace — component 4 would be an ill-defined isotropic
    # noise direction no precision reproduces
    p_ref = PCA(k=3).fit(src)
    nu, ni, nnz, rank = 800, 500, 40_000, 8
    users = rng.integers(nu, size=nnz).astype(np.int64)
    items = rng.integers(ni, size=nnz).astype(np.int64)
    ratings = (rng.random(nnz) * 4 + 1).astype(np.float32)
    als_ref = ALS(rank=rank, max_iter=5, seed=3, implicit_prefs=True,
                  alpha=10.0).fit(users, items, ratings)
    pred_ref = als_ref.predict(users[:4000], items[:4000])

    set_config(compute_precision="bf16")
    km_bf = KMeans(k=k, seed=7, max_iter=12).fit(src)
    p_bf = PCA(k=3).fit(src)
    als_bf = ALS(rank=rank, max_iter=5, seed=3, implicit_prefs=True,
                 alpha=10.0).fit(users, items, ratings)
    pred_bf = als_bf.predict(users[:4000], items[:4000])

    kb = PARITY_BOUNDS["kmeans"]
    # match centroids by nearest-reference (same seed/init, so the
    # pairing is stable on well-separated blobs)
    d2 = ((km_bf.cluster_centers_[:, None, :]
           - km_ref.cluster_centers_[None, :, :]) ** 2).sum(-1)
    cen_dev = float(np.sqrt(d2.min(axis=1)).max()) / scale
    cost_dev = abs(km_bf.summary.training_cost - km_ref.summary.training_cost)
    cost_dev /= max(km_ref.summary.training_cost, 1e-30)
    report["kmeans"] = {"centroid_rel": cen_dev, "cost_rel": cost_dev}
    if cen_dev > kb["centroid_rel"] or cost_dev > kb["cost_rel"]:
        failures.append(f"kmeans bf16 parity out of bounds: {report['kmeans']}")
    if km_bf.summary.precision != "bf16":
        failures.append("bf16 streamed kmeans summary missing precision")

    pb = PARITY_BOUNDS["pca"]
    s = np.linalg.svd(p_ref.components_.T @ p_bf.components_, compute_uv=False)
    angle = float(np.arccos(np.clip(s.min(), 0.0, 1.0)))
    ratio_dev = float(
        np.abs(p_bf.explained_variance_ - p_ref.explained_variance_).max()
    )
    report["pca"] = {"subspace_rad": angle, "ratio_abs": ratio_dev}
    if angle > pb["subspace_rad"] or ratio_dev > pb["ratio_abs"]:
        failures.append(f"pca bf16 parity out of bounds: {report['pca']}")

    ab = PARITY_BOUNDS["als"]
    f_dev = float(np.abs(als_bf.user_factors_ - als_ref.user_factors_).max())
    f_dev /= max(float(np.abs(als_ref.user_factors_).max()), 1e-30)
    rmse = float(np.sqrt(np.mean((pred_bf - pred_ref) ** 2)))
    rmse /= max(float(np.sqrt(np.mean(pred_ref ** 2))), 1e-30)
    report["als"] = {"factor_rel": f_dev, "rmse_rel": rmse}
    if f_dev > ab["factor_rel"] or rmse > ab["rmse_rel"]:
        failures.append(f"als bf16 parity out of bounds: {report['als']}")

    # -- 4) observability: summary + span attrs + per-algo override ---------
    spans = km_bf.summary.timings.root.attrs
    if spans.get("precision") != "bf16":
        failures.append(f"span-tree root attrs missing precision: {spans}")
    if p_bf.summary.get("precision") != "bf16":
        failures.append("pca summary missing precision=bf16")
    if als_bf.summary.get("precision") != "bf16":
        failures.append("als summary missing precision=bf16")
    set_config(compute_precision="bf16", kmeans_precision="f32")
    km_ov = KMeans(k=k, seed=7, max_iter=2).fit(x)
    if km_ov.summary.precision != "f32":
        failures.append(
            "kmeans_precision override ignored: "
            f"{km_ov.summary.precision!r}"
        )
    set_config(kmeans_precision="")

    # -- 5) the precision-degradation rung ----------------------------------
    set_config(compute_precision="bf16", fault_spec="fit.execute:nan=1",
               retry_backoff=0.001)
    faults.reset()
    km_rung = KMeans(k=k, seed=7, max_iter=6).fit(src)
    res = km_rung.summary.resilience
    report["rung"] = {
        "precision": km_rung.summary.precision,
        "degradations": res["degradations"],
        "accelerated": bool(km_rung.summary.accelerated),
    }
    if km_rung.summary.precision != "f32":
        failures.append(
            "precision rung did not degrade to f32: "
            f"{report['rung']}"
        )
    if res["degradations"] != 1 or not km_rung.summary.accelerated:
        failures.append(f"precision rung counters wrong: {report['rung']}")
    set_config(fault_spec="", compute_precision="f32")

    print(json.dumps(report, indent=2, sort_keys=True))
    for f in failures:
        print(f"FAIL: {f}")
    print(f"precision gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
