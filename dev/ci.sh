#!/usr/bin/env bash
# CI harness (~ the reference's dev/ci-build.sh + ci-test.sh): build the
# native library, run the full pseudo-cluster test suite (8-way SPMD on a
# virtual CPU mesh), then run every example end-to-end on the CPU fallback
# path (the pseudo-cluster example run analog).
#
# Gate tools: in CI (the CI env var GitHub always sets) every gate tool is
# REQUIRED — a missing one fails the build loudly, like the reference runs
# its style gates unconditionally (pom.xml:303).  Local dev keeps the
# self-skip so the harness stays runnable in minimal environments.
set -euo pipefail
cd "$(dirname "$0")/.."

in_ci() { [ "${CI:-}" = "true" ] || [ "${CI:-}" = "1" ]; }
have() {
  if command -v "$1" >/dev/null 2>&1; then return 0; fi
  if in_ci; then
    echo "ERROR: $1 is required in CI but not installed" >&2
    exit 1
  fi
  echo "$1 not installed - skipping (local dev only)"
  return 1
}
have_py() {
  if python -c "import $1" >/dev/null 2>&1; then return 0; fi
  if in_ci; then
    echo "ERROR: python module $1 is required in CI but not installed" >&2
    exit 1
  fi
  echo "python module $1 not installed - skipping (local dev only)"
  return 1
}

echo "== drop-in PySpark surface (REQUIRED in CI: the adapter tests and the"
echo "   verbatim-minus-import examples below then run against real Spark) =="
HAVE_PYSPARK=0
if have_py pyspark; then HAVE_PYSPARK=1; fi

echo "== oaplint (style + architecture gate — the scalastyle analog, extended"
echo "   to the PR 1-5 subsystem contracts; JSON artifact for the CI run) =="
python dev/oaplint --json /tmp/oaplint_findings.json
if have ruff; then
  ruff check .
fi
if have clang-format; then
  clang-format --dry-run -Werror oap_mllib_tpu/native/src/*.cpp
fi

echo "== docs (samples executed, config coverage, links; mkdocs strict build) =="
python dev/check_docs.py
if have mkdocs; then
  mkdocs build --strict --site-dir /tmp/oap-mllib-tpu-site
fi

echo "== build native =="
make -C oap_mllib_tpu/native -j4

echo "== test suite (8-device CPU pseudo-cluster) =="
python -m pytest tests/ -q

echo "== streamed prefetch gates: serial parity (depth=1), deep pipeline (depth=4) =="
# every streamed route must be bit-identical with the pipeline disabled
# (depth=1 = the serial loop) and healthy with a deeper-than-default
# queue; REQUIRED — the default-depth run above exercises only depth=2
OAP_MLLIB_TPU_PREFETCH_DEPTH=1 python -m pytest tests/test_prefetch.py tests/test_stream.py -q
OAP_MLLIB_TPU_PREFETCH_DEPTH=4 python -m pytest tests/test_prefetch.py tests/test_stream.py -q

echo "== compile-amortization gate: 10-size sweep, <=3 XLA compiles bucketed,"
echo "   exact padding restored with shape_bucketing=off =="
python dev/compile_gate.py

echo "== resilience gate: injected stream.read/prefetch.stage faults absorbed"
echo "   with exact retry counters + 1e-6 parity; persistent OOM escalates"
echo "   accelerated -> halved-chunk -> CPU fallback (dev/fault_gate.py) =="
python dev/fault_gate.py

echo "== oom gate: memory-budget-governed scale — deterministic route"
echo "   decisions under synthetic budgets land in summary.route, strict"
echo "   mode raises BudgetError instead of degrading scale, disk-streamed"
echo "   fits are bit-identical (K-Means) / <=1e-6 (PCA) vs in-memory, a"
echo "   seeded SIGKILL mid-spill relaunches via the supervisor and resumes"
echo "   from disk bit-identical, and the planner seam is <1% of the 20-fit"
echo "   microbench (dev/oom_gate.py) =="
python dev/oom_gate.py

echo "== precision gate: compute_precision='f32' is bit-compatible with the"
echo "   pre-policy kernels, bf16 holds the registered parity bounds on all"
echo "   three estimators, the chosen policy lands in summaries/span trees,"
echo "   and an injected non-finite iterate under bf16 degrades the fit to"
echo "   f32 via the resilience ladder's precision rung (dev/precision_gate.py) =="
python dev/precision_gate.py

echo "== telemetry gate: JSONL sink parses line-by-line, span trees match the"
echo "   expected shape per estimator, collective op counters fire on the"
echo "   pseudo-mesh ALS fit, resilience counters zero (dev/telemetry_gate.py) =="
python dev/telemetry_gate.py

echo "== checkpoint gate: elastic worlds — interval writes land atomically,"
echo "   a hard-killed fit resumes bit-identical to the uninterrupted run,"
echo "   a resharded (8->2 block) restore holds 1e-5 parity, corrupt"
echo "   manifests fall back (auto) / raise (require), ckpt.write faults"
echo "   warn + count without killing the fit, and the checkpoint-off path"
echo "   stays one string check per fit (dev/checkpoint_gate.py) =="
python dev/checkpoint_gate.py

echo "== sanitizer gate: dataflow analyzer required-clean (R16-R18 + unused-"
echo "   suppression inventory), one sanitizer-on leg per sanitizer (single-"
echo "   process + 2-process pseudo-cluster), seeded violations caught, and"
echo "   sanitizers-off overhead unmeasurable on the 20-fit K-Means"
echo "   microbench (dev/sanitizer_gate.py) =="
python dev/sanitizer_gate.py

echo "== concurrency gate: static thread/lock model (oaplint R19-R22 +"
echo "   atexit contract) required-clean on the live tree, seeded"
echo "   lock-order/shared-write/blocking/unjoined mutations each fire"
echo "   their rule, a scripted two-thread inversion raises LockOrderError"
echo "   deterministically under the 'locks' sanitizer naming both witness"
echo "   stacks, over-deadline holds are flagged (never killed), and the"
echo "   disarmed tracked-lock seam is <1% of the 20-fit microbench"
echo "   (dev/concurrency_gate.py) =="
python dev/concurrency_gate.py

echo "== chaos gate: live-world fault tolerance — seeded chaos fit at exact"
echo "   parity, deterministic + chaos-driven kill-relaunch-resume drills"
echo "   bit-identical (supervised, 1-process everywhere; 2-process + shrink"
echo "   -to-1 resharded at 1e-5 where the host can form worlds), survivors"
echo "   raise CollectiveTimeoutError within the deadline, and the disarmed"
echo "   dispatch seam is <1% of the 20-fit microbench (dev/chaos_gate.py) =="
python dev/chaos_gate.py

echo "== fleet gate: live /metrics + /healthz endpoints parse, per-pass"
echo "   rollups equal a numpy hand-fold on the 8-device pseudo-mesh, a"
echo "   deliberately delayed rank shows skew > 1.5 naming it (2-process"
echo "   legs skip where worlds cannot form), oaptrace output validates"
echo "   against the Chrome trace-event schema, and the disarmed seam is"
echo "   <1% of the 20-fit microbench (dev/fleet_gate.py) =="
python dev/fleet_gate.py

echo "== hetero gate: capability-weighted sharding — planner properties"
echo "   (extents sum to n, chunk-quantized, membudget caps honored,"
echo "   world-1 degenerates to equal), a simulated skewed 2-rank world"
echo "   beats the equal-shard layout with moment parity <= 1e-5, rebalance"
echo "   decisions deterministic under pinned capabilities, the real"
echo "   2-process skew/rebalance legs via pytest (skip where worlds cannot"
echo "   form), and the disarmed seam <1% of the 20-fit microbench"
echo "   (dev/hetero_gate.py) =="
python dev/hetero_gate.py

echo "== serve gate: serving plane — zero steady-state XLA compiles under a"
echo "   50-request jittered-size storm, served-vs-direct bit parity on all"
echo "   three estimators, a 10M-user full-sweep top-k with bounded host"
echo "   memory (no quadratic score matrix), ring-merged sharded sweep"
echo "   parity on the 8-device pseudo-mesh, p99-within-bound-of-p50 tail"
echo "   latency, and a <1% disarmed pin seam (dev/serve_gate.py) =="
python dev/serve_gate.py

echo "== slo gate: request-lifecycle tracing + the SLO/error-budget plane —"
echo "   ledger stages sum to the request wall within 5% on a traced"
echo "   jittered storm (zero-compile + p99 tail contracts hold armed),"
echo "   deterministic hash sampling across processes, multi-window burn"
echo "   rates breach under induced latency with decisions recording SLO"
echo "   state, a 2-replica traced fleet merging through dev/oaptrace.py"
echo "   (request lanes + ring-hop flow arrows), and a <1% tracing-off"
echo "   seam (dev/slo_gate.py) =="
python dev/slo_gate.py

echo "== online gate: incremental fit paths — fold-in matches a from-"
echo "   scratch refit in prediction space within the documented bound"
echo "   at >=5x the refit wall, a second delta commit performs zero XLA"
echo "   compiles and zero autotune sweeps with the served handle"
echo "   answering through the new version, the staleness gauge drops"
echo "   across a commit, and a mid-commit fault or SIGKILL leaves the"
echo "   old pin serving bit-identically (dev/online_gate.py) =="
python dev/online_gate.py

echo "== bench regression gate (soft): newest BENCH_r*.json vs the best"
echo "   prior round per headline metric+backend; >10% fails, a single"
echo "   recorded round warns only (dev/bench_regress.py) =="
python dev/bench_regress.py

echo "== kernel gate: interpret-mode parity across the Pallas kernel plane"
echo "   (K-Means accumulate, PCA moments, ALS solve, factor Gram),"
echo "   bf16-on-Pallas routing asserted, and 8-device virtual-mesh ring"
echo "   -reduction parity vs psum at 1e-5 with zero standalone centroid"
echo "   allreduces in the ring-fused Lloyd build (dev/kernel_gate.py) =="
python dev/kernel_gate.py

echo "== tuning gate: autotune cache round-trip (sweep-once, zero-sweep"
echo "   re-resolve after a memory wipe AND in a fresh interpreter),"
echo "   double-buffered walk parity (bit-identical across depth/route at"
echo "   a fixed partition, 1e-6 across partitions), segmented-ring Lloyd"
echo "   census at 3 psums with 1e-5 parity, and a microsecond-bounded"
echo "   resolve seam in the no-sweep modes (dev/tuning_gate.py) =="
python dev/tuning_gate.py

echo "== compiled-mode TPU suite (skipped unless a TPU backend is present) =="
if python -c "import jax, sys; sys.exit(0 if jax.default_backend() == 'tpu' else 1)" 2>/dev/null; then
  python -m pytest tests_tpu/ -q
else
  echo "no TPU backend - skipping tests_tpu/"
fi

echo "== mesh weak-scaling harness (8 virtual ranks, protocol check) =="
python bench.py --mesh 8

echo "== examples (CPU fallback path) =="
bash examples/run_all.sh --device cpu

echo "== examples (accelerated path on default backend) =="
bash examples/run_all.sh

if [ "$HAVE_PYSPARK" = "1" ]; then
  echo "== PySpark examples ran against REAL Spark (verbatim-minus-import,"
  echo "   ~ the reference's on-cluster example run, dev/ci-test.sh:60-62) =="
fi
echo "CI OK"
