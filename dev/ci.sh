#!/usr/bin/env bash
# CI harness (~ the reference's dev/ci-build.sh + ci-test.sh): build the
# native library, run the full pseudo-cluster test suite (8-way SPMD on a
# virtual CPU mesh), then run every example end-to-end on the CPU fallback
# path (the pseudo-cluster example run analog).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== lint (style gate — failures fail the build, like the reference's scalastyle) =="
python dev/lint.py
if command -v ruff >/dev/null 2>&1; then
  ruff check .
else
  echo "ruff not installed - stdlib gate only"
fi
if command -v clang-format >/dev/null 2>&1; then
  clang-format --dry-run -Werror oap_mllib_tpu/native/src/*.cpp
else
  echo "clang-format not installed - stdlib gate only"
fi

echo "== docs (samples executed, config coverage, links; mkdocs when present) =="
python dev/check_docs.py
if command -v mkdocs >/dev/null 2>&1; then
  mkdocs build --strict --site-dir /tmp/oap-mllib-tpu-site
fi

echo "== build native =="
make -C oap_mllib_tpu/native -j4

echo "== test suite (8-device CPU pseudo-cluster) =="
python -m pytest tests/ -q

echo "== compiled-mode TPU suite (skipped unless a TPU backend is present) =="
if python -c "import jax, sys; sys.exit(0 if jax.default_backend() == 'tpu' else 1)" 2>/dev/null; then
  python -m pytest tests_tpu/ -q
else
  echo "no TPU backend - skipping tests_tpu/"
fi

echo "== mesh weak-scaling harness (8 virtual ranks, protocol check) =="
python bench.py --mesh 8

echo "== examples (CPU fallback path) =="
bash examples/run_all.sh --device cpu

echo "== examples (accelerated path on default backend) =="
bash examples/run_all.sh

echo "CI OK"
