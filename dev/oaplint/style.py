"""Style rules (R10): the dev/lint.py checks absorbed as oaplint rules.

One entry point now runs style AND contract checks — the reference runs
scalastyle + clang-format as a single build gate (mllib-dal/pom.xml:303);
this is the analog.  The ``# noqa`` opt-out for unused imports is kept
(common-tool convention); every other opt-out uses the oaplint
suppression syntax.
"""

from __future__ import annotations

import ast

from . import rule

MAX_LEN = 100


@rule("syntax", kind="py",
      doc="File must parse (enforced by the runner before any AST rule).")
def _syntax(ctx):
    return iter(())  # the runner reports SyntaxError under this name


@rule("trailing-whitespace", kind="any",
      doc="No trailing whitespace (style gate parity with dev/lint.py).")
def _trailing(ctx):
    for i, line in enumerate(ctx.lines, 1):
        if line.rstrip("\r\n") != line.rstrip():
            yield i, line.rstrip()[-20:] or "trailing whitespace"


@rule("tab", kind="any", doc="Indent with spaces, never tabs.")
def _tab(ctx):
    for i, line in enumerate(ctx.lines, 1):
        if "\t" in line:
            yield i, "use spaces"


@rule("line-length", kind="any",
      doc=f"Lines must be <= {MAX_LEN} characters.")
def _line_length(ctx):
    for i, line in enumerate(ctx.lines, 1):
        if len(line) > MAX_LEN:
            yield i, f"{len(line)} > {MAX_LEN}"


@rule("final-newline", kind="any", doc="File must end with a newline.")
def _final_newline(ctx):
    if ctx.text and not ctx.text.endswith("\n"):
        yield len(ctx.lines), "missing"


def _names_used(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node  # leftmost name of dotted access (np.zeros -> np)
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # __all__ entries and annotations-as-strings count as uses
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


@rule("unused-import", kind="py",
      doc="Imports must be used (skipped for __init__.py re-export "
          "manifests; '# noqa' opts a line out, matching dev/lint.py).")
def _unused_import(ctx):
    if ctx.rel.endswith("__init__.py"):
        return
    used = _names_used(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            pairs = [(a.asname or a.name.split(".")[0], a.name)
                     for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            pairs = [(a.asname or a.name, f"{node.module}.{a.name}")
                     for a in node.names if a.name != "*"]
        else:
            continue
        for bound, label in pairs:
            if bound in used:
                continue
            src_line = ctx.lines[node.lineno - 1]
            if "noqa" not in src_line:
                yield node.lineno, label
