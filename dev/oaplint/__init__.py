"""oaplint: AST-based invariant checker for the subsystem contracts.

PRs 1-5 built five cross-cutting subsystems (prefetch, progcache,
resilience, telemetry, precision) whose correctness depends on every hot
path routing through them: a raw ``jax.jit`` bypasses compile
accounting, a raw ``jnp.dot`` bypasses the precision policy, a raw
``lax.psum`` bypasses collective telemetry.  Those contracts were
enforced only by convention; this package encodes them as static rules
the build fails on — the scalastyle/clang-format analog (the reference
fails its build on style violations, mllib-dal/pom.xml:303), extended
from style to *architecture*.

Layout:

- this module: rule registry, per-line suppression handling, file
  enumeration, the runner (``run``/``lint_text``);
- ``style.py``: the dev/lint.py style checks absorbed as rules (R10);
- ``contracts.py``: the per-file subsystem-contract rules (R1-R5,
  R7-R9);
- ``project.py``: the repo-wide Config documentation/coverage/env
  contract (R6);
- ``__main__.py``: the CLI (``python dev/oaplint``).

Suppression syntax (reason REQUIRED — an unexplained opt-out is itself
a finding)::

    x = jax.jit(f)(a)  # oaplint: disable=jit-outside-progcache -- why

or, as a standalone comment on the line above the finding::

    # oaplint: disable=stream-host-sync -- end-of-fit barrier
    jax.block_until_ready((x, y))

Rule catalog with rationale: docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

ROOT = Path(__file__).resolve().parent.parent.parent
PKG = "oap_mllib_tpu"
PY_DIRS = ["oap_mllib_tpu", "tests", "tests_tpu", "examples", "dev"]
PY_FILES = ["bench.py", "__graft_entry__.py"]
CPP_DIRS = ["oap_mllib_tpu/native/src"]
SKIP_PARTS = {"build", "__pycache__", ".git"}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    detail: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.detail}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Context:
    """Everything a file rule sees: the file's relative path (POSIX
    style), raw text, split lines, parsed AST (None for non-Python
    files), and the repo root (for rules that need sibling files, e.g.
    the fault-site registry)."""

    def __init__(self, rel: str, text: str, tree: Optional[ast.AST],
                 root: Path):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.root = root
        self._parents: Optional[Dict[int, ast.AST]] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        """Parent AST node (lazily built map, shared across rules)."""
        if self._parents is None:
            self._parents = {}
            for n in ast.walk(self.tree):
                for c in ast.iter_child_nodes(n):
                    self._parents[id(c)] = n
        return self._parents.get(id(node))


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    scope: Optional[str]  # regex on rel path; None = every file
    kind: str  # "py" | "any" | "project"
    doc: str
    check: Callable


RULES: "Dict[str, Rule]" = {}


def rule(name: str, *, scope: Optional[str] = None, kind: str = "py",
         doc: str = ""):
    """Register a rule.  ``check(ctx)`` yields ``(line, detail)`` pairs
    (project rules get the repo root and yield ``(rel, line, detail)``)."""

    def deco(fn):
        RULES[name] = Rule(name, scope, kind, doc or fn.__doc__ or "", fn)
        return fn

    return deco


# -- suppressions ------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*oaplint:\s*disable=([A-Za-z0-9_\-, ]+?)\s*(?:--\s*(\S.*))?$"
)


def _suppressions(lines: List[str], known: Iterable[str]):
    """Parse per-line suppression directives.

    Returns (map line -> set of rule names suppressed there, list of
    (line, detail) for malformed directives).  A directive on a
    comment-only line applies to the NEXT line; inline directives apply
    to their own line.  A missing/empty ``-- reason`` or an unknown rule
    name makes the directive invalid (and a finding)."""
    known = set(known)
    by_line: Dict[int, set] = {}
    bad: List[Tuple[int, str]] = []
    for i, line in enumerate(lines, 1):
        m = _DIRECTIVE.search(line)
        if not m:
            continue
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        reason = (m.group(2) or "").strip()
        unknown = sorted(n for n in names if n not in known)
        if not reason:
            bad.append((i, f"suppression of {sorted(names)} carries no "
                           "reason ('-- <reason>' is required)"))
            continue
        if unknown:
            bad.append((i, f"suppression names unknown rule(s): {unknown}"))
            continue
        target = i + 1 if line.lstrip().startswith("#") else i
        by_line.setdefault(target, set()).update(names)
    return by_line, bad


# -- runner ------------------------------------------------------------------


def iter_files(root: Path):
    for d in PY_DIRS:
        for p in sorted((root / d).rglob("*.py")):
            if not SKIP_PARTS & set(p.parts):
                yield p, "py"
    for f in PY_FILES:
        p = root / f
        if p.exists():
            yield p, "py"
    for d in CPP_DIRS:
        base = root / d
        for pat in ("*.cpp", "*.h"):
            for p in sorted(base.rglob(pat)):
                if not SKIP_PARTS & set(p.parts):
                    yield p, "cpp"


def _active_rules(names: Optional[Iterable[str]]):
    if names is None:
        return list(RULES.values())
    return [RULES[n] for n in names]


def lint_text(rel: str, text: str, *, root: Path = ROOT,
              rules: Optional[Iterable[str]] = None,
              kind: str = "py") -> List[Finding]:
    """Lint one file's content under a (possibly pretend) relative path.

    This is the test seam: fixtures lint snippets under paths like
    ``oap_mllib_tpu/ops/foo_stream.py`` without touching the tree."""
    findings: List[Finding] = []
    tree = None
    if kind == "py":
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            return [Finding(rel, e.lineno or 0, "syntax", e.msg or "")]
    ctx = Context(rel, text, tree, root)
    for r in _active_rules(rules):
        if r.kind == "project":
            continue
        if r.kind == "py" and kind != "py":
            continue
        if r.scope is not None and not re.match(r.scope, rel):
            continue
        for line, detail in r.check(ctx):
            findings.append(Finding(rel, line, r.name, detail))
    sup, bad = _suppressions(ctx.lines, RULES)
    findings = [
        f for f in findings if f.rule not in sup.get(f.line, ())
    ]
    findings.extend(
        Finding(rel, line, "bad-suppression", detail) for line, detail in bad
    )
    return findings


def run(root: Path = ROOT, *, rules: Optional[Iterable[str]] = None,
        paths: Optional[List[Path]] = None) -> Tuple[List[Finding], int]:
    """Lint the tree (or explicit ``paths``); returns (findings, nfiles).

    Project rules run once per invocation; file rules run per file."""
    findings: List[Finding] = []
    n_files = 0
    root = root.resolve()
    targets = (
        [(p, "cpp" if p.suffix in (".cpp", ".h") else "py") for p in paths]
        if paths is not None else list(iter_files(root))
    )
    for path, kind in targets:
        n_files += 1
        try:
            text = path.read_text()
        except OSError as e:
            findings.append(Finding(str(path), 0, "io", str(e)))
            continue
        rel = path.resolve().relative_to(root).as_posix() \
            if path.resolve().is_relative_to(root) else path.as_posix()
        findings.extend(lint_text(rel, text, root=root, rules=rules,
                                  kind=kind))
    sup_cache: Dict[str, Dict[int, set]] = {}

    def _suppressed(rel: str, line: int, name: str) -> bool:
        if rel not in sup_cache:
            try:
                text = (root / rel).read_text()
            except OSError:
                text = ""
            sup_cache[rel], _ = _suppressions(text.splitlines(), RULES)
        return name in sup_cache[rel].get(line, ())

    for r in _active_rules(rules):
        if r.kind != "project":
            continue
        for rel, line, detail in r.check(root):
            if not _suppressed(rel, line, r.name):
                findings.append(Finding(rel, line, r.name, detail))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, n_files


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2)


# importing the rule modules registers their rules
from . import style  # noqa: E402,F401  (registration side effect)
from . import contracts  # noqa: E402,F401
from . import project  # noqa: E402,F401
