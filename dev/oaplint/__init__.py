"""oaplint: AST-based invariant checker for the subsystem contracts.

PRs 1-5 built five cross-cutting subsystems (prefetch, progcache,
resilience, telemetry, precision) whose correctness depends on every hot
path routing through them: a raw ``jax.jit`` bypasses compile
accounting, a raw ``jnp.dot`` bypasses the precision policy, a raw
``lax.psum`` bypasses collective telemetry.  Those contracts were
enforced only by convention; this package encodes them as static rules
the build fails on — the scalastyle/clang-format analog (the reference
fails its build on style violations, mllib-dal/pom.xml:303), extended
from style to *architecture*.

Layout:

- this module: rule registry, per-line suppression handling, file
  enumeration, the runner (``run``/``lint_text``);
- ``style.py``: the dev/lint.py style checks absorbed as rules (R10);
- ``contracts.py``: the per-file subsystem-contract rules (R1-R5,
  R7-R9);
- ``project.py``: the repo-wide Config documentation/coverage/env
  contract (R6);
- ``__main__.py``: the CLI (``python dev/oaplint``).

Suppression syntax (reason REQUIRED — an unexplained opt-out is itself
a finding)::

    x = jax.jit(f)(a)  # oaplint: disable=jit-outside-progcache -- why

or, as a standalone comment on the line above the finding::

    # oaplint: disable=stream-host-sync -- end-of-fit barrier
    jax.block_until_ready((x, y))

Rule catalog with rationale: docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

ROOT = Path(__file__).resolve().parent.parent.parent
PKG = "oap_mllib_tpu"
PY_DIRS = ["oap_mllib_tpu", "tests", "tests_tpu", "examples", "dev"]
PY_FILES = ["bench.py", "__graft_entry__.py"]
CPP_DIRS = ["oap_mllib_tpu/native/src"]
SKIP_PARTS = {"build", "__pycache__", ".git"}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    detail: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.detail}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Context:
    """Everything a file rule sees: the file's relative path (POSIX
    style), raw text, split lines, parsed AST (None for non-Python
    files), and the repo root (for rules that need sibling files, e.g.
    the fault-site registry)."""

    def __init__(self, rel: str, text: str, tree: Optional[ast.AST],
                 root: Path):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.root = root
        self._parents: Optional[Dict[int, ast.AST]] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        """Parent AST node (lazily built map, shared across rules)."""
        if self._parents is None:
            self._parents = {}
            for n in ast.walk(self.tree):
                for c in ast.iter_child_nodes(n):
                    self._parents[id(c)] = n
        return self._parents.get(id(node))


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    scope: Optional[str]  # regex on rel path; None = every file
    kind: str  # "py" | "any" | "project" | "dataflow"
    doc: str
    check: Callable


RULES: "Dict[str, Rule]" = {}


def rule(name: str, *, scope: Optional[str] = None, kind: str = "py",
         doc: str = ""):
    """Register a rule.  ``check(ctx)`` yields ``(line, detail)`` pairs
    (project rules get the repo root and yield ``(rel, line, detail)``)."""

    def deco(fn):
        RULES[name] = Rule(name, scope, kind, doc or fn.__doc__ or "", fn)
        return fn

    return deco


# -- suppressions ------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*oaplint:\s*disable=([A-Za-z0-9_\-, ]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclasses.dataclass
class Directive:
    """One parsed suppression directive: the line it sits on, the line
    it applies to (comment-only lines apply to the NEXT line), the rule
    names it disables, and its reason."""

    line: int
    target: int
    names: Set[str]
    reason: str


def _comment_lines(text: str, lines: List[str]) -> List[Tuple[int, str]]:
    """(lineno, line) pairs that carry a REAL comment token — directives
    inside string literals (docstring examples, test fixtures) are not
    directives.  Falls back to every line when tokenization fails (the
    syntax rule owns broken files; non-Python files have no tokenizer)."""
    try:
        return sorted({
            (tok.start[0], lines[tok.start[0] - 1])
            for tok in tokenize.generate_tokens(io.StringIO(text).readline)
            if tok.type == tokenize.COMMENT
        })
    except (tokenize.TokenError, SyntaxError, IndentationError, IndexError):
        return list(enumerate(lines, 1))


def _suppressions(text: str, lines: List[str], known: Iterable[str],
                  kind: str = "py"):
    """Parse per-line suppression directives from real comments.

    Returns (directives, bad) where ``bad`` is a list of (line, detail)
    for malformed directives.  A missing/empty ``-- reason`` or an
    unknown rule name makes the directive invalid (and a finding)."""
    known = set(known)
    directives: List[Directive] = []
    bad: List[Tuple[int, str]] = []
    candidates = (
        _comment_lines(text, lines) if kind == "py"
        else list(enumerate(lines, 1))
    )
    for i, line in candidates:
        m = _DIRECTIVE.search(line)
        if not m:
            continue
        names = {n.strip() for n in m.group(1).split(",") if n.strip()}
        reason = (m.group(2) or "").strip()
        unknown = sorted(n for n in names if n not in known)
        if not reason:
            bad.append((i, f"suppression of {sorted(names)} carries no "
                           "reason ('-- <reason>' is required)"))
            continue
        if unknown:
            bad.append((i, f"suppression names unknown rule(s): {unknown}"))
            continue
        target = i + 1 if line.lstrip().startswith("#") else i
        directives.append(Directive(i, target, names, reason))
    return directives, bad


def _by_target(directives: List[Directive]) -> Dict[int, set]:
    by_line: Dict[int, set] = {}
    for d in directives:
        by_line.setdefault(d.target, set()).update(d.names)
    return by_line


# -- runner ------------------------------------------------------------------


def iter_files(root: Path):
    for d in PY_DIRS:
        for p in sorted((root / d).rglob("*.py")):
            if not SKIP_PARTS & set(p.parts):
                yield p, "py"
    for f in PY_FILES:
        p = root / f
        if p.exists():
            yield p, "py"
    for d in CPP_DIRS:
        base = root / d
        for pat in ("*.cpp", "*.h"):
            for p in sorted(base.rglob(pat)):
                if not SKIP_PARTS & set(p.parts):
                    yield p, "cpp"


def _active_rules(names: Optional[Iterable[str]]):
    if names is None:
        return list(RULES.values())
    return [RULES[n] for n in names]


def _lint_one(rel: str, text: str, *, root: Path, rules, kind: str,
              dataflow: bool):
    """Shared per-file core: returns (kept findings + bad-suppression
    findings, directives, used {(target_line, rule)} pairs).  ``dataflow``
    controls whether dataflow-kind rules run here (the lint_text seam)
    or are left to the package-wide pass (the runner)."""
    findings: List[Finding] = []
    tree = None
    if kind == "py":
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            return [Finding(rel, e.lineno or 0, "syntax", e.msg or "")], [], set()
    ctx = Context(rel, text, tree, root)
    for r in _active_rules(rules):
        if r.kind == "project":
            continue
        if r.kind in ("py", "dataflow") and kind != "py":
            continue
        if r.scope is not None and not re.match(r.scope, rel):
            continue
        if r.kind == "dataflow":
            if not dataflow:
                continue
            for frel, line, detail in r.check(root, extra=(rel, text)):
                if frel == rel:
                    findings.append(Finding(rel, line, r.name, detail))
        else:
            for line, detail in r.check(ctx):
                findings.append(Finding(rel, line, r.name, detail))
    directives, bad = _suppressions(text, ctx.lines, RULES, kind)
    sup = _by_target(directives)
    used: Set[Tuple[int, str]] = set()
    kept: List[Finding] = []
    for f in findings:
        if f.rule in sup.get(f.line, ()):
            used.add((f.line, f.rule))
        else:
            kept.append(f)
    kept.extend(
        Finding(rel, line, "bad-suppression", detail) for line, detail in bad
    )
    return kept, directives, used


def _unused_findings(rel: str, directives: List[Directive],
                     used: Set[Tuple[int, str]],
                     skip_kinds=("project",)) -> List[Finding]:
    """A directive whose rule produced no finding on its target line is
    itself a finding: stale suppressions rot the audited-opt-out
    inventory as code moves (ISSUE 7 satellite).  Project-rule names are
    skipped where per-file usage is unknowable (the lint_text seam)."""
    out = []
    for d in directives:
        for name in sorted(d.names):
            r = RULES.get(name)
            if r is not None and r.kind in skip_kinds:
                continue
            if (d.target, name) in used:
                continue
            out.append(Finding(
                rel, d.line, "unused-suppression",
                f"suppression of '{name}' matched no finding on line "
                f"{d.target}; delete the stale directive (or fix the "
                "drifted code it was auditing)",
            ))
    return out


def lint_text(rel: str, text: str, *, root: Path = ROOT,
              rules: Optional[Iterable[str]] = None,
              kind: str = "py") -> List[Finding]:
    """Lint one file's content under a (possibly pretend) relative path.

    This is the test seam: fixtures lint snippets under paths like
    ``oap_mllib_tpu/ops/foo_stream.py`` without touching the tree.
    Dataflow rules analyze the snippet against the LIVE package index
    (the snippet shadows any real file at ``rel``).  Unused-suppression
    detection runs only when every rule is active — a subset run cannot
    prove a directive dead."""
    kept, directives, used = _lint_one(
        rel, text, root=root, rules=rules, kind=kind, dataflow=True
    )
    if rules is None:
        kept.extend(_unused_findings(rel, directives, used))
    return kept


def run(root: Path = ROOT, *, rules: Optional[Iterable[str]] = None,
        paths: Optional[List[Path]] = None) -> Tuple[List[Finding], int]:
    """Lint the tree (or explicit ``paths``); returns (findings, nfiles).

    Project and dataflow rules run once per invocation (package-wide);
    file rules run per file.  With every rule active, directives whose
    rule matched nothing on their target line are reported as
    ``unused-suppression`` findings."""
    findings: List[Finding] = []
    n_files = 0
    root = root.resolve()
    targets = (
        [(p, "cpp" if p.suffix in (".cpp", ".h") else "py") for p in paths]
        if paths is not None else list(iter_files(root))
    )
    per_file: Dict[str, Tuple[List[Directive], Set[Tuple[int, str]]]] = {}
    target_rels: List[str] = []
    for path, kind in targets:
        n_files += 1
        try:
            text = path.read_text()
        except OSError as e:
            findings.append(Finding(str(path), 0, "io", str(e)))
            continue
        rel = path.resolve().relative_to(root).as_posix() \
            if path.resolve().is_relative_to(root) else path.as_posix()
        kept, directives, used = _lint_one(
            rel, text, root=root, rules=rules, kind=kind, dataflow=False
        )
        findings.extend(kept)
        per_file[rel] = (directives, used)
        target_rels.append(rel)

    def _file_state(rel: str):
        if rel not in per_file:
            try:
                text = (root / rel).read_text()
            except OSError:
                text = ""
            d, _ = _suppressions(text, text.splitlines(), RULES)
            per_file[rel] = (d, set())
        return per_file[rel]

    for r in _active_rules(rules):
        if r.kind not in ("project", "dataflow"):
            continue
        for rel, line, detail in r.check(root):
            directives, used = _file_state(rel)
            if r.name in _by_target(directives).get(line, ()):
                used.add((line, r.name))
            else:
                findings.append(Finding(rel, line, r.name, detail))
    if rules is None:
        for rel in target_rels:
            directives, used = per_file[rel]
            findings.extend(_unused_findings(rel, directives, used,
                                             skip_kinds=()))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, n_files


def suppression_inventory(root: Path = ROOT,
                          findings: Optional[List[Finding]] = None) -> List[dict]:
    """The audited-suppression inventory: one record per directive in
    the tree ({path, line, target, rules, reason, used}), for the
    ``--json`` artifact.  ``used`` is False iff the findings carry an
    ``unused-suppression`` naming it (pass the same run's findings)."""
    unused = set()
    for f in findings or ():
        if f.rule == "unused-suppression":
            m = re.search(r"suppression of '([^']+)'", f.detail)
            if m:
                unused.add((f.path, f.line, m.group(1)))
    out = []
    for path, kind in iter_files(root):
        try:
            text = path.read_text()
        except OSError:
            continue
        rel = path.resolve().relative_to(root).as_posix() \
            if path.resolve().is_relative_to(root) else path.as_posix()
        directives, _ = _suppressions(text, text.splitlines(), RULES, kind)
        for d in directives:
            names = sorted(d.names)
            out.append({
                "path": rel,
                "line": d.line,
                "target": d.target,
                "rules": names,
                "reason": d.reason,
                "used": all((rel, d.line, n) not in unused for n in names),
            })
    return out


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.as_dict() for f in findings], indent=2)


# importing the rule modules registers their rules
from . import style  # noqa: E402,F401  (registration side effect)
from . import contracts  # noqa: E402,F401
from . import project  # noqa: E402,F401
from . import dataflow  # noqa: E402,F401
from . import concurrency  # noqa: E402,F401
