"""Interprocedural concurrency rules (R19-R22 + the atexit contract).

The host thread plane grew PR by PR — prefetch producer threads (PR 1),
the per-collective watchdog dispatch threads (PR 10), the per-rank
metrics HTTP servers and the flight-recorder ring (PR 11), serving
heartbeats and request coalescing (PR 13) — guarded by 17+ ad-hoc
``threading.Lock``/``RLock`` instances across the package, none of which
the static plane modeled.  A lock-order inversion between the serving
registry and the telemetry registry, or an unguarded shared-state write
between the batcher and a heartbeat eviction, surfaces today as a
production hang that even the collective deadline watchdog cannot
diagnose (it watches collectives, not host locks).  This module makes
the thread/lock structure explicit and machine-checked — the DrJAX
argument (PAPERS.md, arXiv:2403.07128) applied to the HOST thread plane:
parallel structure should be analyzable, not implicit in runtime
behavior.  It is the same find-statically + witness-at-runtime pairing
PR 7 proved for SPMD collectives; the runtime half is the ``locks``
sanitizer (utils/locktrace.py via ``Config.sanitizers``).

Built on the PR 7 package index (dev/oaplint/dataflow.py), the model has
three layers:

- **lock identities** — module-global ``_lock = threading.Lock()``
  assignments and ``self.x = threading.Lock()`` class attributes,
  resolved at use sites through same-module bindings, the enclosing
  class, and per-module import aliases (``_tm._LOCK`` names the metrics
  registry lock) — the R17 axis-name resolution idea applied to locks;
- **a per-function may-hold lattice** — ``with lock:`` blocks and
  ``acquire()``/``release()`` pairs establish held sets, propagated
  through the call graph: a helper only ever called under a lock
  inherits that lock into its ``always_held`` context (intersection
  over call sites), and a function's transitive *acquires* and
  *may-block* facts close over the graph like R16's reachability;
- **thread roots and a shared-state map** — ``threading.Thread``
  targets, executor submissions, and ``http.server`` handler methods
  are spawn roots; module globals touched both inside a root's closure
  and outside it are *shared* and their writes must agree on a lock.

Fed rules:

- **R19 lock-order-inversion** — a cycle in the global lock-acquisition
  -order graph (lock B acquired while A is held on one path, A while B
  on another, directly or through calls).  The finding prints both
  acquisition chains; two threads interleaving the two paths deadlock.
- **R20 unguarded-shared-write** — a write to shared state (module
  global reachable from >= 2 thread roots) with no lock common to every
  write path.
- **R21 blocking-while-locked** — a blocking operation (device dispatch
  via progcache.launch/get_or_build, a host collective, ``time.sleep``,
  file I/O, subprocess, a thread ``join``/server ``shutdown``) reachable
  while a registered lock is held: every other thread needing that lock
  stalls behind the slow operation — the deadlock-by-starvation shape.
- **R22 unjoined-thread** — a ``threading.Thread`` spawn whose handle
  never reaches ``join()`` and is not declared ``daemon`` (nor
  daemonized later): process exit then blocks on the forgotten thread.
  The runtime cross-check is the ``oap_prefetch_leaked_threads_total``
  accounting (PrefetchStats.leaked_threads).
- **atexit-outside-shutdown** — ``atexit.register`` anywhere in the
  package outside ``telemetry/export.py``: interpreter-exit work must
  serialize through the one registered shutdown hook
  (telemetry/export.shutdown) or the JSONL final snapshot, the fleet
  server teardown, and the flight-recorder drain race at exit.

Known approximations (docs/static-analysis.md has the full table):
call resolution is by name (same-module preferred, import aliases
resolved, >4 ambiguous candidates dropped); callables passed as values
(``self._stage``, ``fn()`` trampolines) are opaque, so thread closures
under-approximate — the ``locks`` sanitizer witnesses those at runtime;
lambdas evaluate where they appear; per-instance locks are merged per
class attribute; ``Semaphore``/``Event`` are deliberately not locks
(not mutual exclusion); R20 covers module globals, not instance
attributes.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import PKG, rule
from .contracts import _dotted, _tail
from .dataflow import FuncInfo, PackageIndex, _collective_dispatch, build_index

EXPORT_REL = f"{PKG}/telemetry/export.py"

# constructors that create a mutual-exclusion lock worth modeling.
# Semaphore/Event/Condition are deliberately excluded: they are signaling
# primitives, and modeling them as locks would invent inversions that
# cannot deadlock.  TrackedLock/tracked_lock is the runtime sanitizer's
# registry wrapper (utils/locktrace.py) — same semantics as the inner
# lock it wraps.
_LOCK_TAILS = {"Lock", "RLock", "TrackedLock", "tracked_lock"}

# container-mutation methods that count as WRITES to a module global
_MUTATORS = {"append", "add", "update", "clear", "pop", "popitem",
             "remove", "extend", "insert", "setdefault", "discard",
             "appendleft"}

_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_HEAD"}


# ---------------------------------------------------------------------------
# model dataclasses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LockInfo:
    ident: str  # "rel::name" | "rel::Cls.attr"
    rel: str
    line: int
    simple: str  # the bare global/attr name used at call sites
    is_attr: bool


@dataclasses.dataclass
class SpawnInfo:
    fi: FuncInfo
    line: int
    target_names: List[str]  # candidate callee tails for root resolution
    daemon: bool
    assigned: List[str]  # "name" or "self.attr" forms the handle binds to


@dataclasses.dataclass
class Scan:
    """One function's concurrency-relevant behavior, held-set annotated."""

    acquires: List[Tuple[str, int, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)
    calls: List[Tuple[ast.Call, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)
    blocking: List[Tuple[str, int, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)
    gwrites: List[Tuple[str, int, FrozenSet[str]]] = \
        dataclasses.field(default_factory=list)
    greads: List[str] = dataclasses.field(default_factory=list)


class ThreadModel:
    """The whole-package thread/lock model (one per PackageIndex)."""

    def __init__(self, idx: PackageIndex):
        self.idx = idx
        self.locks: Dict[str, LockInfo] = {}
        self.global_locks: Dict[Tuple[str, str], str] = {}  # (rel, name)->id
        self.by_simple: Dict[str, List[str]] = {}  # bare name -> idents
        self.aliases: Dict[str, Dict[str, str]] = {}  # rel -> alias -> rel
        self.foreign: Dict[str, Set[str]] = {}  # rel -> non-package imports
        self.cls_of_fn: Dict[int, str] = {}  # id(fn node) -> class name
        self.module_globals: Dict[str, Set[str]] = {}
        self.scans: Dict[str, Scan] = {}  # qual -> Scan
        self.fn_by_qual: Dict[str, FuncInfo] = {}
        self.acq_trans: Dict[str, Dict[str, Tuple[int, str]]] = {}
        self.blocks: Dict[str, Tuple[str, str, int]] = {}
        self.always_held: Dict[str, Optional[FrozenSet[str]]] = {}
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.thread_roots: Dict[str, Tuple[str, int, str]] = {}
        self.spawns: List[SpawnInfo] = []
        self._closures: Dict[str, Set[str]] = {}
        self._build()

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        for rel, mod in self.idx.modules.items():
            self._index_module_statics(rel, mod.tree)
        for rel, mod in self.idx.modules.items():
            for fi in mod.functions:
                self.fn_by_qual[fi.qual] = fi
                self.scans[fi.qual] = self._scan_fn(fi)
        self._find_roots()
        self._acquires_fixpoint()
        self._blocks_fixpoint()
        self._always_held_fixpoint()
        self._build_edges()

    def _index_module_statics(self, rel: str, tree: ast.Module) -> None:
        globals_here: Set[str] = set()
        aliases: Dict[str, str] = {}
        for n in tree.body:
            if isinstance(n, ast.Assign):
                names = [t.id for t in n.targets if isinstance(t, ast.Name)]
                globals_here.update(names)
                if isinstance(n.value, ast.Call) \
                        and _tail(n.value.func) in _LOCK_TAILS:
                    for name in names:
                        self._register_lock(rel, name, n.lineno, False)
            elif isinstance(n, ast.AnnAssign) \
                    and isinstance(n.target, ast.Name):
                globals_here.add(n.target.id)
        self.module_globals[rel] = globals_here
        foreign: Set[str] = set()

        def mod_rel(dotted: str) -> Optional[str]:
            base = dotted.replace(".", "/")
            for cand in (base + ".py", base + "/__init__.py"):
                if cand in self.idx.modules:
                    return cand
            return None

        for n in ast.walk(tree):
            if isinstance(n, ast.Import):
                for a in n.names:
                    bound = a.asname or a.name.split(".")[0]
                    mrel = mod_rel(a.name)
                    if mrel is not None and a.asname:
                        aliases[a.asname] = mrel
                    elif mod_rel(bound) is None and mrel is None:
                        foreign.add(bound)  # subprocess, np, jax, ...
            elif isinstance(n, ast.ImportFrom) and n.module:
                for a in n.names:
                    bound = a.asname or a.name
                    mrel = mod_rel(f"{n.module}.{a.name}")
                    if mrel is not None:
                        aliases[bound] = mrel
                    elif mod_rel(n.module) is None:
                        foreign.add(bound)  # from jax import lax, ...
        self.aliases[rel] = aliases
        self.foreign[rel] = foreign
        # class membership + self.<attr> = threading.Lock() registration
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for n in ast.walk(cls):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.cls_of_fn.setdefault(id(n), cls.name)
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                        and _tail(n.value.func) in _LOCK_TAILS:
                    for t in n.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            self._register_lock(
                                rel, f"{cls.name}.{t.attr}", n.lineno,
                                True, simple=t.attr,
                            )

    def _register_lock(self, rel: str, name: str, line: int, is_attr: bool,
                       simple: Optional[str] = None) -> None:
        ident = f"{rel}::{name}"
        simple = simple or name
        self.locks[ident] = LockInfo(ident, rel, line, simple, is_attr)
        if not is_attr:
            self.global_locks[(rel, name)] = ident
        self.by_simple.setdefault(simple, []).append(ident)

    # -- lock resolution at a use site ---------------------------------------

    def resolve_lock(self, fi: FuncInfo, expr: ast.AST) -> Optional[str]:
        """The registered lock identity a ``with``/``acquire`` target
        names, or None (opaque).  Same-module globals win; ``self.x``
        resolves through the enclosing class then uniquely by attribute
        name package-wide; ``alias.name`` resolves through the module's
        import aliases; an ambiguous bare name resolves only if unique
        package-wide (the conservative default — a wrong identity would
        invent inversions)."""
        if isinstance(expr, ast.Name):
            ident = self.global_locks.get((fi.rel, expr.id))
            if ident is not None:
                return ident
            cands = [i for i in self.by_simple.get(expr.id, ())
                     if not self.locks[i].is_attr]
            return cands[0] if len(cands) == 1 else None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                cls = self.cls_of_fn.get(id(fi.node))
                if cls is not None:
                    ident = self.locks.get(f"{fi.rel}::{cls}.{expr.attr}")
                    if ident is not None:
                        return ident.ident if isinstance(ident, LockInfo) \
                            else ident
                cands = [i for i in self.by_simple.get(expr.attr, ())
                         if self.locks[i].is_attr]
                return cands[0] if len(cands) == 1 else None
            if isinstance(base, ast.Name):
                target_rel = self.aliases.get(fi.rel, {}).get(base.id)
                if target_rel is not None:
                    return self.global_locks.get((target_rel, expr.attr))
                cands = self.by_simple.get(expr.attr, ())
                return cands[0] if len(cands) == 1 else None
        return None

    # -- call resolution (alias-aware, ambiguity-capped) ---------------------

    def resolve_call(self, fi: FuncInfo, call: ast.Call) -> List[FuncInfo]:
        tail = _tail(call.func)
        if not tail:
            return []
        if isinstance(call.func, ast.Attribute):
            base = call.func.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                if base.id in self.foreign.get(fi.rel, ()):
                    return []  # subprocess.run is not Supervisor.run
                target_rel = self.aliases.get(fi.rel, {}).get(base.id)
                if target_rel is not None:
                    mod = self.idx.modules.get(target_rel)
                    if mod is None:
                        return []
                    return [f for f in mod.functions if f.name == tail]
        cands = self.idx.resolve(call, fi.rel)
        # a wildly ambiguous name (close, fit, run, ...) would smear one
        # function's facts over the whole package — drop it instead
        if len(cands) > 4 and not (cands and cands[0].rel == fi.rel):
            return []
        return cands

    # -- the per-function scan ----------------------------------------------

    def _scan_fn(self, fi: FuncInfo) -> Scan:
        scan = Scan()
        mod_globals = self.module_globals.get(fi.rel, set())
        declared_global: Set[str] = set()
        local_bound: Set[str] = set(fi.params)
        for n in ast.walk(fi.node):
            if self.idx.owner.get(id(n)) is not fi:
                continue
            if isinstance(n, ast.Global):
                declared_global.update(n.names)
            elif isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                ast.NamedExpr, ast.For, ast.AsyncFor)):
                from .dataflow import _assign_targets

                local_bound.update(_assign_targets(n))
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if isinstance(item.optional_vars, ast.Name):
                        local_bound.add(item.optional_vars.id)
            elif isinstance(n, ast.comprehension):
                for x in ast.walk(n.target):
                    if isinstance(x, ast.Name):
                        local_bound.add(x.id)
        local_bound -= declared_global

        def is_global(name: str) -> bool:
            return name in mod_globals and (
                name in declared_global or name not in local_bound
            )

        def expr_scan(node: ast.AST, held: Tuple[str, ...]) -> None:
            heldset = frozenset(held)
            for n in ast.walk(node):
                if self.idx.owner.get(id(n)) is not fi:
                    continue
                if isinstance(n, ast.Call):
                    scan.calls.append((n, heldset))
                    desc = _blocking_desc(n)
                    if desc is not None:
                        scan.blocking.append((desc, n.lineno, heldset))
                    # container mutation of a module global is a write
                    if isinstance(n.func, ast.Attribute) \
                            and n.func.attr in _MUTATORS \
                            and isinstance(n.func.value, ast.Name) \
                            and is_global(n.func.value.id):
                        scan.gwrites.append(
                            (n.func.value.id, n.lineno, heldset))
                elif isinstance(n, ast.Name) \
                        and isinstance(n.ctx, ast.Load) \
                        and is_global(n.id):
                    scan.greads.append(n.id)

        def note_store(target: ast.AST, line: int,
                       held: Tuple[str, ...]) -> None:
            heldset = frozenset(held)
            for t in ast.walk(target):
                if isinstance(t, ast.Name) and t.id in declared_global \
                        and t.id in mod_globals:
                    scan.gwrites.append((t.id, line, heldset))
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and is_global(t.value.id):
                    scan.gwrites.append((t.value.id, line, heldset))

        def walk(stmts, held: List[str]) -> None:
            manual: List[str] = []  # bare .acquire() state in this block
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue  # nested defs scan as their own functions
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    entered: List[str] = []
                    for item in st.items:
                        expr_scan(item.context_expr, tuple(held))
                        lid = self.resolve_lock(fi, item.context_expr)
                        if lid is None and isinstance(
                                item.context_expr, ast.Call):
                            # `with lock:` vs `with lock.acquire_ctx()`:
                            # only the bare lock form is modeled
                            pass
                        if lid is not None:
                            scan.acquires.append(
                                (lid, item.context_expr.lineno,
                                 frozenset(held)))
                            if lid not in held:
                                held.append(lid)
                                entered.append(lid)
                    walk(st.body, held)
                    for lid in entered:
                        held.remove(lid)
                    continue
                if isinstance(st, (ast.If, ast.While)):
                    expr_scan(st.test, tuple(held))
                    walk(st.body, held)
                    walk(st.orelse, held)
                    continue
                if isinstance(st, (ast.For, ast.AsyncFor)):
                    expr_scan(st.iter, tuple(held))
                    note_store(st.target, st.lineno, tuple(held))
                    walk(st.body, held)
                    walk(st.orelse, held)
                    continue
                if isinstance(st, ast.Try):
                    walk(st.body, held)
                    for h in st.handlers:
                        walk(h.body, held)
                    walk(st.orelse, held)
                    walk(st.finalbody, held)
                    continue
                # bare acquire()/release() on a resolvable lock
                acq_rel = _bare_acquire_release(st)
                if acq_rel is not None:
                    kind, expr, call = acq_rel
                    lid = self.resolve_lock(fi, expr)
                    if lid is not None:
                        if kind == "acquire":
                            scan.acquires.append(
                                (lid, call.lineno, frozenset(held)))
                            if lid not in held:
                                held.append(lid)
                                manual.append(lid)
                        elif lid in manual:
                            held.remove(lid)
                            manual.remove(lid)
                        continue
                if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    targets = st.targets if isinstance(st, ast.Assign) \
                        else [st.target]
                    for t in targets:
                        note_store(t, st.lineno, tuple(held))
                    if getattr(st, "value", None) is not None:
                        expr_scan(st.value, tuple(held))
                    continue
                expr_scan(st, tuple(held))
            for lid in manual:  # unbalanced acquire ends with the block
                if lid in held:
                    held.remove(lid)

        walk(getattr(fi.node, "body", []), [])
        return scan

    # -- thread roots + spawn inventory --------------------------------------

    def _find_roots(self) -> None:
        for rel, mod in self.idx.modules.items():
            tree = mod.tree
            for cls in ast.walk(tree):
                if isinstance(cls, ast.ClassDef):
                    for n in cls.body:
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                                and n.name in _HANDLER_METHODS:
                            for fi in mod.functions:
                                if fi.node is n:
                                    self.thread_roots[fi.qual] = (
                                        rel, n.lineno, "http handler")
        for rel, mod in self.idx.modules.items():
            for fi in mod.functions:
                for call in fi.own_calls:
                    tail = _tail(call.func)
                    d = _dotted(call.func)
                    if tail == "Thread" and (
                            d in ("threading.Thread", "Thread")):
                        self._note_spawn(fi, call)
                    elif tail == "submit" and call.args:
                        for name in _callable_tails(call.args[0]):
                            self._root_from_name(fi, name, call.lineno,
                                                 "executor submit")

    def _note_spawn(self, fi: FuncInfo, call: ast.Call) -> None:
        daemon = False
        target: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg == "target":
                target = kw.value
        names = _callable_tails(target) if target is not None else []
        assigned: List[str] = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and n.value is call:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        assigned.append(t.id)
                    elif isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        assigned.append(f"self.{t.attr}")
        self.spawns.append(
            SpawnInfo(fi, call.lineno, names, daemon, assigned))
        for name in names:
            self._root_from_name(fi, name, call.lineno, "thread target")

    def _root_from_name(self, fi: FuncInfo, name: str, line: int,
                        how: str) -> None:
        cands = self.idx.by_name.get(name, [])
        same = [c for c in cands if c.rel == fi.rel]
        for c in same or cands[:2]:
            self.thread_roots.setdefault(c.qual, (fi.rel, line, how))

    def closure(self, root_qual: str) -> Set[str]:
        hit = self._closures.get(root_qual)
        if hit is not None:
            return hit
        seen: Set[str] = set()
        stack = [root_qual]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            fi = self.fn_by_qual.get(q)
            if fi is None:
                continue
            for call, _ in self.scans[q].calls:
                for cand in self.resolve_call(fi, call):
                    if cand.qual not in seen:
                        stack.append(cand.qual)
        self._closures[root_qual] = seen
        return seen

    # -- fixpoints ------------------------------------------------------------

    def _acquires_fixpoint(self) -> None:
        for q, scan in self.scans.items():
            fi = self.fn_by_qual[q]
            self.acq_trans[q] = {
                lid: (line, f"{fi.name} acquires {_short(lid)} at "
                            f"{fi.rel}:{line}")
                for lid, line, _ in scan.acquires
            }
        changed = True
        while changed:
            changed = False
            for q, scan in self.scans.items():
                fi = self.fn_by_qual[q]
                mine = self.acq_trans[q]
                for call, _ in scan.calls:
                    for cand in self.resolve_call(fi, call):
                        if cand.qual == q:
                            continue
                        for lid, (line, chain) in self.acq_trans.get(
                                cand.qual, {}).items():
                            if lid not in mine:
                                mine[lid] = (
                                    call.lineno,
                                    f"{fi.name} -> {chain}")
                                changed = True

    def _blocks_fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for q, scan in self.scans.items():
                if q in self.blocks:
                    continue
                fi = self.fn_by_qual[q]
                for desc, line, _ in scan.blocking:
                    self.blocks[q] = ("direct", desc, line)
                    changed = True
                    break
                if q in self.blocks:
                    continue
                for call, _ in scan.calls:
                    for cand in self.resolve_call(fi, call):
                        if cand.qual in self.blocks and cand.qual != q:
                            self.blocks[q] = (
                                "via", cand.qual, call.lineno)
                            changed = True
                            break
                    if q in self.blocks:
                        break

    def block_chain(self, qual: str, limit: int = 6) -> str:
        parts: List[str] = []
        seen: Set[str] = set()
        while qual in self.blocks and qual not in seen and limit:
            seen.add(qual)
            limit -= 1
            kind, what, line = self.blocks[qual]
            name = qual.split("::", 1)[1]
            if kind == "direct":
                parts.append(f"{name} -> {what} (line {line})")
                break
            parts.append(name)
            qual = what
        return " -> ".join(parts)

    def _always_held_fixpoint(self) -> None:
        """Locks held on EVERY path into a function (intersection over
        package call sites; entry points and thread roots start empty).
        Gives ``_shutdown_locked``-style helpers their caller's lock."""
        callsites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for q, scan in self.scans.items():
            fi = self.fn_by_qual[q]
            for call, held in scan.calls:
                for cand in self.resolve_call(fi, call):
                    callsites.setdefault(cand.qual, []).append((q, held))
        for q in self.scans:
            has_sites = bool(callsites.get(q))
            self.always_held[q] = None if has_sites else frozenset()
            if q in self.thread_roots:
                self.always_held[q] = frozenset()
        for _ in range(12):
            changed = False
            for q, sites in callsites.items():
                if self.always_held.get(q) == frozenset():
                    continue
                acc: Optional[FrozenSet[str]] = None
                for caller, held in sites:
                    ch = self.always_held.get(caller)
                    if ch is None:
                        continue  # caller unresolved yet: skip this site
                    site_held = held | ch
                    acc = site_held if acc is None else (acc & site_held)
                if acc is not None and acc != self.always_held.get(q):
                    self.always_held[q] = acc
                    changed = True
            if not changed:
                break
        for q in self.scans:
            if self.always_held.get(q) is None:
                self.always_held[q] = frozenset()

    def effective_held(self, qual: str, held: FrozenSet[str]) -> FrozenSet[str]:
        return held | (self.always_held.get(qual) or frozenset())

    def _build_edges(self) -> None:
        for q, scan in self.scans.items():
            fi = self.fn_by_qual[q]
            for lid, line, held in scan.acquires:
                for h in self.effective_held(q, held):
                    if h != lid and (h, lid) not in self.edges:
                        self.edges[(h, lid)] = (
                            fi.rel, line,
                            f"{fi.name} acquires {_short(lid)} at "
                            f"{fi.rel}:{line} while holding {_short(h)}")
            for call, held in scan.calls:
                eff = self.effective_held(q, held)
                if not eff:
                    continue
                for cand in self.resolve_call(fi, call):
                    for lid, (line, chain) in self.acq_trans.get(
                            cand.qual, {}).items():
                        for h in eff:
                            if h != lid and (h, lid) not in self.edges:
                                self.edges[(h, lid)] = (
                                    fi.rel, call.lineno,
                                    f"{fi.name} (holding {_short(h)}) -> "
                                    f"{chain}")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _short(ident: str) -> str:
    rel, name = ident.split("::", 1)
    return f"{name} ({rel})"


def _callable_tails(expr: Optional[ast.AST]) -> List[str]:
    """Candidate function names a callable expression may denote:
    ``f`` -> f, ``self._produce`` -> _produce, ``mod.fn`` -> fn."""
    if expr is None:
        return []
    if isinstance(expr, ast.Name):
        return [expr.id]
    if isinstance(expr, ast.Attribute):
        return [expr.attr]
    return []


def _bare_acquire_release(st: ast.stmt):
    """('acquire'|'release', lock_expr, call) when a statement is a bare
    ``lock.acquire(...)`` / ``lock.release()`` expression or assignment
    of one; None otherwise."""
    node = None
    if isinstance(st, ast.Expr):
        node = st.value
    elif isinstance(st, ast.Assign):
        node = st.value
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("acquire", "release")):
        return None
    return node.func.attr, node.func.value, node


def _blocking_desc(call: ast.Call) -> Optional[str]:
    """Why a call is considered blocking, or None.  The set is the
    starvation-shaped operations: device dispatch/compile, host
    collectives, sleeps, file I/O, subprocess, thread joins and server
    shutdowns, event waits."""
    d = _dotted(call.func)
    t = _tail(call.func)
    if d in ("time.sleep", "sleep") and t == "sleep":
        return f"{d or 'sleep'}() sleep"
    if d.startswith("subprocess."):
        if t in ("run", "check_call", "check_output", "call", "Popen"):
            return f"{d}() subprocess"
        return None
    if t == "open" and isinstance(call.func, ast.Name):
        return "open() file I/O"
    if d in ("os.replace", "os.rename", "os.fsync", "os.makedirs"):
        return f"{d}() file I/O"
    if t in ("block_until_ready", "device_get"):
        return f"{t}() device sync"
    if t in ("launch", "get_or_build") and (
            d.startswith("progcache.") or d.startswith("_CACHE.")
            or d.endswith(".progcache." + t)):
        return f"{d}() device dispatch/compile"
    op = _collective_dispatch(call)
    if op is not None:
        return f"host collective {op}"
    if t in ("guarded_dispatch", "_allgather_host", "_psum_host",
             "_gather_with_guard", "heartbeat"):
        return f"{d or t}() host collective"
    if t == "join" and isinstance(call.func, ast.Attribute):
        numeric = (len(call.args) == 1
                   and isinstance(call.args[0], ast.Constant)
                   and isinstance(call.args[0].value, (int, float)))
        kw_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if not call.args and not call.keywords:
            return ".join() thread join"
        if numeric or kw_timeout:
            return ".join(timeout) thread join"
        return None  # str.join(iterable)
    if t == "shutdown" and isinstance(call.func, ast.Attribute) \
            and not call.args and not call.keywords:
        return ".shutdown() server/executor shutdown"
    if t == "wait" and isinstance(call.func, ast.Attribute):
        return ".wait() event/condition wait"
    return None


_MODEL_ATTR = "_concurrency_model"


def _model(idx: PackageIndex) -> ThreadModel:
    model = getattr(idx, _MODEL_ATTR, None)
    if model is None:
        model = ThreadModel(idx)
        setattr(idx, _MODEL_ATTR, model)
    return model


# ---------------------------------------------------------------------------
# R19: lock-order-inversion
# ---------------------------------------------------------------------------


@rule("lock-order-inversion", scope=rf"{PKG}/", kind="dataflow",
      doc="No cycle in the global lock-acquisition-order graph: lock B "
          "acquired while A is held on one path and A while B on "
          "another (directly or through calls, always-held caller "
          "context included) deadlocks the two paths the first time "
          "they interleave.  The finding prints both acquisition "
          "chains.  Runtime witness: the 'locks' sanitizer "
          "(Config.sanitizers) raises LockOrderError on a live "
          "inversion.")
def _r19(root, extra=None):
    idx = build_index(Path(root), extra)
    model = _model(idx)
    findings: List[Tuple[str, int, str]] = []
    reported: Set[FrozenSet[str]] = set()
    for (a, b), (rel, line, chain) in sorted(model.edges.items()):
        back = model.edges.get((b, a))
        if back is None:
            continue
        pair = frozenset((a, b))
        if pair in reported:
            continue
        reported.add(pair)
        brel, bline, bchain = back
        detail = (
            f"lock-order inversion between {_short(a)} and {_short(b)}: "
            f"[{chain}] but also [{bchain}] — two threads interleaving "
            "these paths deadlock; pick one global order (or collapse "
            "the locks)")
        findings.append((rel, line, detail))
        if (brel, bline) != (rel, line):
            findings.append((brel, bline, detail))
    # longer cycles without a 2-cycle: walk SCCs
    findings.extend(_long_cycles(model, reported))
    return findings


def _long_cycles(model: ThreadModel, reported: Set[FrozenSet[str]]):
    adj: Dict[str, List[str]] = {}
    for (a, b) in model.edges:
        adj.setdefault(a, []).append(b)
    out: List[Tuple[str, int, str]] = []
    for start in sorted(adj):
        path: List[str] = []
        on_path: Set[str] = set()

        def dfs(node: str) -> Optional[List[str]]:
            if node == start and path:
                return list(path)
            if node in on_path:
                return None
            on_path.add(node)
            path.append(node)
            for nxt in adj.get(node, ()):
                got = dfs(nxt)
                if got is not None:
                    return got
            path.pop()
            on_path.discard(node)
            return None

        cyc = dfs(start)
        if cyc and len(cyc) > 2:
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            loop = cyc + [cyc[0]]
            chains = "; ".join(
                model.edges[(loop[i], loop[i + 1])][2]
                for i in range(len(cyc)))
            rel, line, _ = model.edges[(loop[0], loop[1])]
            out.append((
                rel, line,
                f"lock-order cycle over {len(cyc)} locks "
                f"({' -> '.join(_short(c) for c in loop)}): {chains}"))
    return out


# ---------------------------------------------------------------------------
# R20: unguarded-shared-write
# ---------------------------------------------------------------------------


@rule("unguarded-shared-write", scope=rf"{PKG}/", kind="dataflow",
      doc="A module global touched both inside a spawned thread's "
          "closure and outside it is SHARED; every write to it must "
          "hold one common registered lock — a write with no common "
          "lock races whichever thread reads next.  Thread closures: "
          "threading.Thread targets, executor submissions, http "
          "handler methods, traversed through the call graph.")
def _r20(root, extra=None):
    idx = build_index(Path(root), extra)
    model = _model(idx)
    findings: List[Tuple[str, int, str]] = []
    # access table: (rel, global name) -> accessor quals + writes
    touch: Dict[Tuple[str, str], Set[str]] = {}
    writes: Dict[Tuple[str, str],
                 List[Tuple[str, int, FrozenSet[str]]]] = {}
    for q, scan in model.scans.items():
        fi = model.fn_by_qual[q]
        for name in scan.greads:
            touch.setdefault((fi.rel, name), set()).add(q)
        for name, line, held in scan.gwrites:
            touch.setdefault((fi.rel, name), set()).add(q)
            writes.setdefault((fi.rel, name), []).append(
                (q, line, model.effective_held(q, held)))
    closures = {r: model.closure(r) for r in model.thread_roots}
    for key, ws in sorted(writes.items()):
        rel, name = key
        if (rel, name) in model.global_locks:
            continue  # the locks themselves are not shared *state*
        accessors = touch[key]
        roots_touching = [r for r, cl in closures.items()
                          if accessors & cl]
        if not roots_touching:
            continue
        union = set()
        for r in roots_touching:
            union |= closures[r]
        outside = [a for a in accessors if a not in union]
        if len(roots_touching) < 2 and not outside:
            continue
        common: Optional[FrozenSet[str]] = None
        for _, _, held in ws:
            common = held if common is None else (common & held)
        if common:
            continue
        q, line, held = min(
            ws, key=lambda w: (len(w[2]), w[1]))
        sites = ", ".join(
            f"{wq.split('::', 1)[1]}:{wl}"
            + (f" holding {{{', '.join(_short(h) for h in wh)}}}"
               if wh else " holding no lock")
            for wq, wl, wh in ws)
        roots = ", ".join(
            f"{r.split('::', 1)[1]} ({model.thread_roots[r][2]})"
            for r in sorted(roots_touching))
        findings.append((
            rel, line,
            f"module global '{name}' is shared across thread roots "
            f"[{roots}] and the main flow, but its writes hold no "
            f"common lock (writes: {sites}); guard every write with "
            "one registered lock (the runtime 'locks' sanitizer "
            "witnesses the dynamic interleavings this pass cannot "
            "see)"))
    return findings


# ---------------------------------------------------------------------------
# R21: blocking-while-locked
# ---------------------------------------------------------------------------


@rule("blocking-while-locked", scope=rf"{PKG}/", kind="dataflow",
      doc="No blocking operation (device dispatch via progcache, host "
          "collectives, time.sleep, file I/O, subprocess, thread "
          "join/server shutdown, event waits) reachable while a "
          "registered lock is held — every other thread needing that "
          "lock stalls behind the slow operation, and a blocked "
          "collective under a lock is the deadlock-by-starvation "
          "shape the collective deadline watchdog cannot see.  The "
          "'locks' sanitizer's hold-time histogram + watchdog "
          "(oap_lock_hold_seconds) witnesses the residue at runtime.")
def _r21(root, extra=None):
    idx = build_index(Path(root), extra)
    model = _model(idx)
    findings: List[Tuple[str, int, str]] = []
    seen: Set[Tuple[str, int]] = set()
    for q, scan in model.scans.items():
        fi = model.fn_by_qual[q]
        for desc, line, held in scan.blocking:
            eff = model.effective_held(q, held)
            if not eff or (fi.rel, line) in seen:
                continue
            seen.add((fi.rel, line))
            findings.append((
                fi.rel, line,
                f"blocking operation ({desc}) while holding "
                f"{{{', '.join(sorted(_short(h) for h in eff))}}}; "
                "move the slow operation outside the critical section "
                "(stage under the lock, act after release)"))
        for call, held in scan.calls:
            eff = model.effective_held(q, held)
            if not eff:
                continue
            for cand in model.resolve_call(fi, call):
                if cand.qual == q or cand.qual not in model.blocks:
                    continue
                if (fi.rel, call.lineno) in seen:
                    continue
                seen.add((fi.rel, call.lineno))
                findings.append((
                    fi.rel, call.lineno,
                    f"call to '{cand.name}' blocks "
                    f"({model.block_chain(cand.qual)}) while holding "
                    f"{{{', '.join(sorted(_short(h) for h in eff))}}}; "
                    "move the blocking work outside the critical "
                    "section"))
                break
    return findings


# ---------------------------------------------------------------------------
# R22: unjoined-thread
# ---------------------------------------------------------------------------


@rule("unjoined-thread", scope=rf"{PKG}/", kind="dataflow",
      doc="Every threading.Thread spawn must either be daemon=True at "
          "construction (or daemonized via handle.daemon before start) "
          "or have its handle reach a join() somewhere in the module — "
          "a forgotten non-daemon thread blocks interpreter exit, and "
          "a forgotten daemon producer is exactly what the "
          "oap_prefetch_leaked_threads_total accounting counts at "
          "runtime.")
def _r22(root, extra=None):
    idx = build_index(Path(root), extra)
    model = _model(idx)
    findings: List[Tuple[str, int, str]] = []
    for sp in model.spawns:
        if sp.daemon:
            continue
        mod = idx.modules.get(sp.fi.rel)
        if mod is None:
            continue
        if sp.assigned and _handle_managed(mod.tree, sp.assigned):
            continue
        what = "never assigned to a handle" if not sp.assigned else (
            f"handle {sp.assigned[0]!r} never reaches join() and is "
            "never daemonized")
        findings.append((
            sp.fi.rel, sp.line,
            f"thread spawned in '{sp.fi.name}' is not daemon=True and "
            f"{what}; join it, daemonize it, or route it through a "
            "supervised lifecycle (cross-check: PrefetchStats"
            ".leaked_threads / oap_prefetch_leaked_threads_total "
            "count producers that failed to join)"))
    return findings


def _handle_managed(tree: ast.Module, assigned: List[str]) -> bool:
    """Does any ``<handle>.join(...)`` call or ``<handle>.daemon = True``
    assignment appear in the module, for any of the spawn's bound
    names (``t`` or ``self.attr`` forms)?"""
    attrs = {a.split(".", 1)[1] for a in assigned if a.startswith("self.")}
    names = {a for a in assigned if not a.startswith("self.")}

    def matches(base: ast.AST) -> bool:
        if isinstance(base, ast.Name) and base.id in names:
            return True
        return bool(
            isinstance(base, ast.Attribute) and base.attr in attrs
        )

    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "join" and matches(n.func.value):
            return True
        if isinstance(n, ast.Assign) \
                and isinstance(n.value, ast.Constant) \
                and n.value.value is True:
            for t in n.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and matches(t.value):
                    return True
    return False


# ---------------------------------------------------------------------------
# the atexit ordering contract (per-file rule; ISSUE 14 satellite)
# ---------------------------------------------------------------------------


@rule("atexit-outside-shutdown", scope=rf"{PKG}/",
      doc="atexit.register only in telemetry/export.py — interpreter-"
          "exit work (the JSONL final snapshot, the fleet metrics "
          "server teardown, the flight-recorder drain) must serialize "
          "through the ONE registered shutdown hook "
          "(telemetry/export.shutdown); independent atexit hooks run "
          "in registration order across modules and race the sink.")
def _atexit_outside_shutdown(ctx):
    if ctx.rel == EXPORT_REL:
        return
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) \
                and _dotted(n.func) == "atexit.register":
            yield (n.lineno,
                   "atexit.register outside telemetry/export.py; add "
                   "your teardown to telemetry/export.shutdown (the "
                   "one ordered exit hook) instead of racing it")
