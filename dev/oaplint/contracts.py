"""Subsystem-contract rules (R1-R5, R7-R9).

Each rule encodes one invariant the PR 1-5 subsystems depend on.  They
are heuristics over the AST — precise enough to lint the live package
clean while catching every seeded violation in tests/test_oaplint.py's
mutation fixtures.  Rationale per rule: docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import re
from functools import lru_cache

from . import PKG, rule

OPS = rf"{PKG}/ops/"
STREAM_FILES = rf"{PKG}/ops/[^/]*stream[^/]*\.py$"


def _tail(func: ast.expr) -> str:
    """Last attribute segment of a call target (a.b.c -> 'c', f -> 'f')."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _dotted(node: ast.expr) -> str:
    """Dotted name of an attribute chain ('jax.numpy.dot'); '' if any
    segment is not a plain name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _subtree_ids(*roots) -> set:
    out = set()
    for r in roots:
        if r is None:
            continue
        for n in ast.walk(r):
            out.add(id(n))
    return out


# -- R1: jit dispatch must go through the program-cache registry -------------


@rule("jit-outside-progcache", scope=rf"{PKG}/",
      doc="jax.jit/jax.pmap only in utils/progcache.py, as decorators on "
          "ops/ kernel entries (launch-tracked at dispatch), or inside a "
          "builder passed to progcache.get_or_build — anything else "
          "bypasses compile accounting and program reuse.")
def _jit_outside_progcache(ctx):
    if ctx.rel == f"{PKG}/utils/progcache.py":
        return
    tree = ctx.tree
    # builders: functions/lambdas whose product is registered via
    # progcache.get_or_build — jit inside them IS the registry path
    fn_index = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_index.setdefault(n.name, []).append(n)
    allowed = set()
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and _tail(n.func) == "get_or_build"):
            continue
        build = None
        if len(n.args) >= 3:
            build = n.args[2]
        for kw in n.keywords:
            if kw.arg == "build":
                build = kw.value
        if build is None:
            continue
        roots = []
        if isinstance(build, ast.Lambda):
            roots.append(build)
            called = {_tail(c.func) for c in ast.walk(build)
                      if isinstance(c, ast.Call)}
        elif isinstance(build, ast.Name):
            called = {build.id}
        else:
            called = set()
        for name in called:
            roots.extend(fn_index.get(name, []))
        allowed |= _subtree_ids(*roots)
    # decorators on ops/ kernel entries are the definition side of the
    # contract; their launches are progcache.note/launch-tracked
    if re.match(OPS, ctx.rel):
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                allowed |= _subtree_ids(*n.decorator_list)
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr in ("jit", "pmap") \
                and isinstance(n.value, ast.Name) and n.value.id == "jax" \
                and id(n) not in allowed:
            yield (n.lineno, f"raw jax.{n.attr} bypasses the program-cache "
                   "registry; route dispatch through utils/progcache"
                   ".get_or_build (builder) or launch/note")


# -- R2: matmuls in ops/models must go through the precision policy ----------

_MATMUL_FNS = {"dot", "matmul", "einsum", "tensordot", "vdot"}


@rule("raw-matmul", scope=rf"{PKG}/(ops|models|serving)/",
      doc="No raw jnp.dot/matmul/einsum/@ in ops/, models/, or serving/ "
          "— use precision.pdot/peinsum so the compute-precision policy "
          "(Config.compute_precision, Config.serving_precision on the "
          "request paths) governs every hot-path contraction. "
          "ops/pallas/ kernels are exempt (priced via "
          "precision.kernel_tier).")
def _raw_matmul(ctx):
    if ctx.rel.startswith(f"{PKG}/ops/pallas/"):
        return
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            # host-side numpy (np.*) contractions are exempt: the policy
            # governs device compute; the NumPy fallback plane is the
            # f64/f32 reference the policy is measured against
            if d.split(".")[-1] in _MATMUL_FNS and (
                    d.startswith("jnp.") or d.startswith("jax.numpy.")):
                yield (n.lineno, f"{d} bypasses the precision policy; use "
                       "utils/precision.pdot or peinsum (f32 defaults are "
                       "bit-compatible with Precision.HIGHEST)")
        elif isinstance(n, ast.BinOp) and isinstance(n.op, ast.MatMult):
            yield (n.lineno, "'@' matmul bypasses the precision policy; "
                   "use utils/precision.pdot")


# -- R3: collectives must go through the parallel/collective facade ----------

_COLLECTIVES = {"psum", "pmean", "all_gather", "ppermute", "all_to_all",
                "psum_scatter"}
# Pallas device-level communication primitives (inter-chip DMA +
# semaphore signaling).  EXEMPT inside ops/pallas/ kernel bodies: a
# remote-DMA ring kernel IS the collective — it books its census through
# ops/pallas/_tiers.note_emitted (oap_kernel_emitted_total) and its
# wrapper's kernel_launch telemetry, the kernel-plane analog of the
# facade seam.  Outside ops/pallas/ they are findings like any raw
# collective: ad-hoc remote DMAs would bypass every accounting seam the
# package has.  NB the exemption is primitive-scoped, not blanket — a
# raw lax.psum inside a kernel body still fires (seeded-mutation test
# in tests/test_oaplint.py).
_PALLAS_COMMS = {"make_async_remote_copy", "semaphore_signal",
                 "semaphore_wait", "get_barrier_semaphore"}


@rule("raw-collective", scope=rf"{PKG}/",
      doc="No raw lax.psum/pmean/all_gather/ppermute/all_to_all outside "
          "parallel/collective.py — the facade is the one seam that "
          "books collective telemetry (and the DrJAX-style explicit "
          "composition point).  pltpu remote-DMA/semaphore primitives "
          "(make_async_remote_copy, semaphore_signal/wait, "
          "get_barrier_semaphore) are additionally findings outside "
          "ops/pallas/ and exempt inside it — kernel bodies ARE the "
          "collective there and book the oap_kernel_* census instead; "
          "raw lax.* collectives inside kernels still fire.")
def _raw_collective(ctx):
    if ctx.rel == f"{PKG}/parallel/collective.py":
        return
    in_pallas = ctx.rel.startswith(f"{PKG}/ops/pallas/")
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Attribute) and n.attr in _COLLECTIVES:
            d = _dotted(n)
            if d.startswith("lax.") or d.startswith("jax.lax."):
                yield (n.lineno, f"raw {d} bypasses collective "
                       "accounting; use parallel/collective."
                       f"{n.attr} (in-jit) or the eager facade")
        elif (
            not in_pallas
            and isinstance(n, ast.Attribute)
            and n.attr in _PALLAS_COMMS
        ):
            d = _dotted(n)
            if d.startswith("pltpu.") or ".pallas.tpu" in d:
                yield (n.lineno, f"{d} outside ops/pallas/ bypasses the "
                       "kernel-plane communication seam; device DMA "
                       "collectives live in ops/pallas/ kernels (which "
                       "book the oap_kernel_* census)")


# -- R4: no host sync inside streamed per-chunk loops ------------------------

_SYNC_ATTRS = {"block_until_ready", "item"}
_PF_HINTS = ("Prefetcher", "staged_chunks", "prefetch")


def _pf_names(fn: ast.AST) -> set:
    """Names bound to a prefetch pipeline within a function: ``pf =
    Prefetcher(...)`` or ``with _staged_chunks(...) as pf:``."""
    names = set()

    def _is_pf_call(v):
        return isinstance(v, ast.Call) and any(
            h in _tail(v.func) for h in _PF_HINTS)

    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and _is_pf_call(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(n, ast.With):
            for item in n.items:
                if _is_pf_call(item.context_expr) and isinstance(
                        item.optional_vars, ast.Name):
                    names.add(item.optional_vars.id)
    return names


def _loop_targets(target: ast.expr) -> set:
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


@rule("stream-host-sync", scope=rf"{PKG}/(ops/[^/]*stream[^/]*\.py|models/)",
      doc="No host-sync calls (jax.device_get, .block_until_ready, "
          ".item(), np.asarray/float on chunk values) inside streamed "
          "per-chunk prefetch loops — each sync stalls the pipeline and "
          "destroys stage/compute overlap.  jax.block_until_ready "
          "anywhere in a streamed kernel or model needs an audited "
          "suppression (end-of-fit barriers).")
def _stream_host_sync(ctx):
    tree = ctx.tree
    in_stream_ops = re.match(STREAM_FILES, ctx.rel) is not None
    seen = set()

    def emit(node, detail):
        key = (node.lineno, detail)
        if key not in seen:
            seen.add(key)
            yield node.lineno, detail

    # barrier calls anywhere in scope need justification
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and _tail(n.func) == "block_until_ready":
            yield from emit(n, "device barrier; if this is a deliberate "
                            "end-of-fit sync, add a reasoned suppression")
    if not in_stream_ops:
        return
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        pf = _pf_names(fn)
        if not pf:
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, ast.For):
                continue
            it = loop.iter
            if isinstance(it, ast.Call) and _tail(it.func) == "enumerate" \
                    and it.args:
                it = it.args[0]
            if not (isinstance(it, ast.Name) and it.id in pf):
                continue
            targets = _loop_targets(loop.target)
            for n in ast.walk(loop):
                if n is loop or not isinstance(n, ast.Call):
                    continue
                d = _dotted(n.func)
                t = _tail(n.func)
                if d in ("jax.device_get",):
                    yield from emit(n, f"{d} inside the per-chunk loop "
                                    "stalls the prefetch pipeline")
                elif t == "item" and isinstance(n.func, ast.Attribute):
                    yield from emit(n, ".item() inside the per-chunk loop "
                                    "syncs the device stream")
                elif (t == "float" and isinstance(n.func, ast.Name)) or d in (
                        "np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"):
                    arg_names = set()
                    for a in n.args:
                        arg_names |= {x.id for x in ast.walk(a)
                                      if isinstance(x, ast.Name)}
                    if arg_names & targets or any(
                            isinstance(a, ast.Call) and
                            {x.id for x in ast.walk(a)
                             if isinstance(x, ast.Name)} & targets
                            for a in n.args):
                        yield from emit(
                            n, f"{d or t}() on a chunk value inside the "
                            "per-chunk loop forces a host sync; accumulate "
                            "on device (or defer the fetch past the loop)")


# -- R5: no Python control flow on traced values in jitted bodies ------------

_META_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "sharding",
               "aval", "weak_type", "at"}


def _jit_decorated(fn: ast.AST):
    """If ``fn`` is decorated with jax.jit (bare or functools.partial),
    return the set of its traced parameter names, else None."""
    for dec in fn.decorator_list:
        statics_names, statics_nums = set(), set()
        hit = False
        if _dotted(dec) in ("jax.jit", "jit"):
            hit = True
        elif isinstance(dec, ast.Call):
            if _dotted(dec.func) in ("jax.jit", "jit"):
                hit = True
            elif _tail(dec.func) == "partial" and dec.args and _dotted(
                    dec.args[0]) in ("jax.jit", "jit"):
                hit = True
            if hit:
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        for c in ast.walk(kw.value):
                            if isinstance(c, ast.Constant) and isinstance(
                                    c.value, str):
                                statics_names.add(c.value)
                    elif kw.arg == "static_argnums":
                        for c in ast.walk(kw.value):
                            if isinstance(c, ast.Constant) and isinstance(
                                    c.value, int):
                                statics_nums.add(c.value)
        if not hit:
            continue
        pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        kw_only = [a.arg for a in fn.args.kwonlyargs]
        traced = set(pos + kw_only) - statics_names
        traced -= {p for i, p in enumerate(pos) if i in statics_nums}
        traced -= {"self", "cls"}
        return traced
    return None


def _traced_use(ctx, expr: ast.expr, traced: set):
    """First traced-value use in ``expr`` that Python control flow would
    concretize, or None.  Metadata access (x.shape/...), ``x is None``
    trace-time checks, and static names are exempt."""
    for n in ast.walk(expr):
        if not (isinstance(n, ast.Name) and n.id in traced):
            continue
        parent = ctx.parent(n)
        if isinstance(parent, ast.Attribute) and parent.attr in _META_ATTRS:
            continue
        if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
            continue
        return n
    return None


@rule("traced-python-branch", scope=rf"{PKG}/",
      doc="No Python if/while/bool()/len() on traced values inside "
          "jax.jit-decorated bodies — concretization errors at trace "
          "time (or silent retraces).  static_argnames/argnums are "
          "respected; x.shape metadata and 'x is None' are exempt.")
def _traced_python_branch(ctx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        traced = _jit_decorated(fn)
        if not traced:
            continue
        for n in ast.walk(fn):
            if isinstance(n, (ast.If, ast.While, ast.IfExp)):
                bad = _traced_use(ctx, n.test, traced)
                if bad is not None:
                    kind = type(n).__name__.lower()
                    yield (n.lineno, f"Python {kind} on traced value "
                           f"'{bad.id}' in jitted '{fn.name}'; use lax."
                           "cond/select or make the argument static")
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in ("bool", "len") and n.args:
                bad = _traced_use(ctx, n.args[0], traced)
                if bad is not None and isinstance(n.args[0], ast.Name):
                    yield (n.lineno, f"{n.func.id}() on traced value "
                           f"'{bad.id}' in jitted '{fn.name}'; use "
                           ".shape metadata or lax primitives")


# -- R7: fault-injection site strings must be registered ---------------------


@lru_cache(maxsize=4)
def _registered_sites(root) -> frozenset:
    path = root / PKG / "utils" / "faults.py"
    try:
        tree = ast.parse(path.read_text())
    except (OSError, SyntaxError):
        return frozenset()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in n.targets):
            return frozenset(
                c.value for c in ast.walk(n.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str))
    return frozenset()


@rule("unregistered-fault-site", scope=rf"{PKG}/",
      doc="maybe_fault(\"<site>\") literals must come from the SITES "
          "registry in utils/faults.py — a typo'd site would silently "
          "never fire, and Config.fault_spec validation would reject it.")
def _unregistered_fault_site(ctx):
    sites = _registered_sites(ctx.root)
    if not sites:
        return
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and _tail(n.func) == "maybe_fault" \
                and n.args and isinstance(n.args[0], ast.Constant) \
                and isinstance(n.args[0].value, str):
            site = n.args[0].value
            if site not in sites:
                yield (n.lineno, f"fault site {site!r} is not in utils/"
                       f"faults.SITES {sorted(sites)}")


# -- R8: no wall-clock / RNG nondeterminism in the compute plane -------------

_LEGACY_NP_RANDOM = {"seed", "rand", "randn", "randint", "random", "choice",
                     "shuffle", "permutation", "uniform", "normal", "zipf",
                     "integers"}


@rule("nondeterminism", scope=rf"{PKG}/(ops|models|data)/",
      doc="No wall-clock reads (time.time/monotonic/perf_counter, "
          "datetime.now) or global-state RNG (random module, legacy "
          "np.random.*, unseeded default_rng) in ops/, models/, data/ — "
          "results must be a pure function of inputs + seed; duration "
          "clocks are confined to utils/timing.tick and telemetry/.")
def _nondeterminism(ctx):
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d in ("time.time", "time.monotonic", "time.perf_counter",
                     "time.process_time"):
                yield (n.lineno, f"{d}() in the compute plane; use utils/"
                       "timing.tick() for duration accounting")
            elif d in ("datetime.now", "datetime.utcnow",
                       "datetime.datetime.now", "datetime.datetime.utcnow"):
                yield n.lineno, f"wall-clock {d}() in the compute plane"
            elif d.startswith("random."):
                yield (n.lineno, f"global-state {d}() (stdlib random); "
                       "use np.random.default_rng(seed)")
            elif d.startswith("np.random.") or d.startswith("numpy.random."):
                fn = d.split(".")[-1]
                if fn == "default_rng":
                    if not n.args and not n.keywords:
                        yield (n.lineno, "unseeded np.random.default_rng()"
                               "; pass an explicit seed")
                elif fn in _LEGACY_NP_RANDOM:
                    yield (n.lineno, f"legacy global-state {d}(); use "
                           "np.random.default_rng(seed)")
        elif isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "random":
                    yield (n.lineno, "stdlib random is process-global "
                           "state; use np.random.default_rng(seed)")


# -- R9: accelerated fits must finalize telemetry ----------------------------


@rule("fit-missing-finalize", scope=rf"{PKG}/models/",
      doc="Every accelerated fit wrapper (a models/ function that calls "
          "resilience.resilient_fit) must pass its summary through "
          "telemetry.finalize_fit before returning — otherwise the fit's "
          "span tree and metrics snapshot never reach the exporters.")
def _fit_missing_finalize(ctx):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        n_fit = n_fin = 0
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                t = _tail(n.func)
                if t == "resilient_fit":
                    n_fit += 1
                elif t == "finalize_fit":
                    n_fin += 1
        if n_fit and n_fin < n_fit:
            yield (fn.lineno, f"'{fn.name}' runs {n_fit} resilient_fit "
                   f"ladder(s) but calls telemetry.finalize_fit {n_fin} "
                   "time(s); every accelerated return must finalize")
