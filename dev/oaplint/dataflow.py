"""Interprocedural SPMD dataflow rules (R16-R18).

The per-file rules in contracts.py see one AST at a time; the failure
modes that actually hang a pod are *flows*: a collective reachable only
on rank 0, an axis name no mesh binds, a bf16 value summed at bf16.
This module builds a package-wide index (module call graph + per-module
binding environments) and runs an intraprocedural abstract
interpretation over a small provenance lattice:

- **rank provenance** — a value is RANK-DERIVED if it flows (through
  assignments, comprehensions, loop targets, and calls to functions
  whose returns are rank-derived) from ``jax.process_index()`` or a
  ``.process_id``/``.process_index`` read; it is re-UNIFORMIZED by any
  world-synchronizing call (``process_allgather``, the host reduction
  helpers, the collective facade) — after a gather every rank holds the
  same value.  ``jax.process_count()`` is uniform by definition.
- **collective reachability** — a function REACHES-COLLECTIVE if its
  body dispatches one (``parallel/collective`` facade ops, raw ``lax``
  collectives, ``process_allgather``) or calls a function that does;
  the evidence chain is kept for diagnostics.
- **dtype tier** — a value is BF16-TIER once cast with
  ``.astype(bfloat16)``; the tier survives until an explicit upcast.

Fed rules (registered on import, like contracts.py):

- **R16 collective-divergence**: a collective dispatch (or a call that
  transitively reaches one) lexically under an ``if``/``while``/
  ``for``/ternary whose condition/iterable is rank-derived — the
  whole-world-hang shape.  The finding prints the full path: the
  provenance chain of the condition and the call chain to the
  collective.
- **R17 unbound-collective-axis**: a collective's axis name must
  resolve — through enclosing-scope assignments and helper-call
  argument binding, package-wide — to a mesh-bound token: a
  ``cfg.data_axis``/``cfg.model_axis`` read, a ``mesh.axis_names``
  element, or a literal that some ``Mesh``/``PartitionSpec`` context in
  the chain's modules actually binds.
- **R18 precision-flow**: bf16-tier values must accumulate in f32 —
  flags reductions (``jnp.sum``/``mean``/...) on bf16-tier operands
  without an ``upcast``/f32 cast, f32→bf16→f32 round-trips whose bf16
  value feeds no matmul (pure mantissa loss), and reduced-dtype
  accumulator allocations.  ``ops/pallas/`` is exempt (the kernel's
  hi/lo bf16 splitting is the deliberate exception, like R2) and
  ``utils/precision.py`` is the one module allowed to own these casts.

Known approximations (docs/static-analysis.md has the full table):
call resolution is by function NAME across the package (shadowing
merges conservatively); parameters are not rank-tainted from call sites
(only explicit sources and returns taint); ``raise`` under a
rank-dependent branch is NOT treated as divergence (fail-fast raises
are the sanctioned per-rank exit — the ``_PassGuard`` contract carries
them to the next reduction), while ``return``/``break``/``continue``
are; dynamic axis strings built at runtime are opaque (not findings).
The runtime sanitizer plane (``utils/sanitizers.py``,
``Config.sanitizers``) witnesses the same invariants where the static
pass cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import PKG, rule
from .contracts import _dotted, _tail

FACADE_REL = f"{PKG}/parallel/collective.py"

_LAX_COLLECTIVES = {"psum", "pmean", "all_gather", "ppermute", "all_to_all",
                    "psum_scatter"}
_FACADE_OPS = {"psum", "pmean", "all_gather", "ppermute", "all_to_all",
               "broadcast", "allgather_rows", "allreduce_sum",
               "alltoall_rows"}
# host-mediated world synchronizers: their results are identical on every
# rank by construction, so they STOP rank-taint propagation
_GATHER_TAILS = {"process_allgather"}


# ---------------------------------------------------------------------------
# package index
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuncInfo:
    rel: str
    qual: str  # rel::dotted.name
    node: ast.AST
    params: List[str]
    enclosing: List["FuncInfo"]  # innermost last
    own_calls: List[ast.Call] = dataclasses.field(default_factory=list)
    own_returns: List[ast.AST] = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        return self.node.name


class ModuleInfo:
    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.tree = tree
        self.functions: List[FuncInfo] = []
        self.bound_axis_literals: Set[str] = _bound_literals(tree)


class PackageIndex:
    """Cross-module context for the dataflow rules."""

    def __init__(self):
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.owner: Dict[int, FuncInfo] = {}  # id(node) -> owning func
        self.calls_by_tail: Dict[str, List[Tuple[FuncInfo, ast.Call]]] = {}
        self.returns_rank: Set[str] = set()
        self.returns_uniform: Set[str] = set()
        self.reaches: Dict[str, Tuple[str, str, int]] = {}
        # qual -> ("direct", op, line) | ("via", callee_qual, line)
        self._taint_cache: Dict[Tuple[str, int, int], Dict] = {}

    def resolve(self, call: ast.Call, rel: str) -> List[FuncInfo]:
        """Candidate package functions a call may target (tail-name
        resolution, same-module candidates preferred)."""
        tail = _tail(call.func)
        cands = self.by_name.get(tail, [])
        same = [f for f in cands if f.rel == rel]
        return same or cands

    def chain(self, qual: str, limit: int = 6) -> str:
        """Human-readable call chain from ``qual`` to its collective."""
        parts = []
        seen = set()
        while qual in self.reaches and qual not in seen and limit:
            seen.add(qual)
            limit -= 1
            kind, what, line = self.reaches[qual]
            name = qual.split("::", 1)[1]
            if kind == "direct":
                parts.append(f"{name} -> {what} (line {line})")
                break
            parts.append(name)
            qual = what
        return " -> ".join(parts)


def _bound_literals(tree: ast.Module) -> Set[str]:
    """Axis-name literals a module's mesh contexts bind: strings inside
    ``PartitionSpec``/``P(...)`` specs, ``Mesh``/``make_mesh`` axis
    names, and ``shard_map`` axis kwargs."""
    binders = {"P", "PartitionSpec", "Mesh", "make_mesh",
               "AbstractMesh", "shard_map"}
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and _tail(n.func) in binders:
            for c in ast.walk(n):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
    return out


def _index_module(idx: PackageIndex, rel: str, tree: ast.Module) -> None:
    mod = ModuleInfo(rel, tree)
    idx.modules[rel] = mod

    def visit(node, qual_prefix, enclosing):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{rel}::{qual_prefix}{child.name}"
                a = child.args
                params = [p.arg for p in
                          a.posonlyargs + a.args + a.kwonlyargs]
                if a.vararg:
                    params.append(a.vararg.arg)
                if a.kwarg:
                    params.append(a.kwarg.arg)
                fi = FuncInfo(rel, qual, child, params, list(enclosing))
                mod.functions.append(fi)
                idx.by_name.setdefault(child.name, []).append(fi)
                for inner in ast.walk(child):
                    idx.owner.setdefault(id(inner), fi)
                visit(child, f"{qual_prefix}{child.name}.",
                      enclosing + [fi])
            else:
                visit(child, qual_prefix, enclosing)

    visit(tree, "", [])


_INDEX_CACHE: Dict[str, PackageIndex] = {}


def _finish_index(idx: PackageIndex, only=None) -> None:
    """Precompute the per-function call/return lists (the hot inputs of
    every fixpoint sweep) once the owner map is complete.  ``only``
    restricts the precompute to freshly-indexed functions (the overlay
    path, where every other module's lists are shared with the base)."""
    funcs = only if only is not None else [
        fi for mod in idx.modules.values() for fi in mod.functions
    ]
    for fi in funcs:
        for n in ast.walk(fi.node):
            if idx.owner.get(id(n)) is not fi:
                continue
            if isinstance(n, ast.Call):
                fi.own_calls.append(n)
                idx.calls_by_tail.setdefault(
                    _tail(n.func), []).append((fi, n))
            elif isinstance(n, ast.Return) and n.value is not None:
                fi.own_returns.append(n.value)
    _fixpoints(idx)


def _base_index(root: Path) -> PackageIndex:
    key = str(root.resolve())
    idx = _INDEX_CACHE.get(key)
    if idx is not None:
        return idx
    idx = PackageIndex()
    for path in sorted((root / PKG).rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        try:
            tree = ast.parse(path.read_text(), filename=rel)
        except (OSError, SyntaxError):
            continue  # io/syntax rules own these
        _index_module(idx, rel, tree)
    _finish_index(idx)
    _INDEX_CACHE[key] = idx
    return idx


def build_index(root: Path, extra: Optional[Tuple[str, str]] = None
                ) -> PackageIndex:
    """The package index, optionally with one in-memory module shadowing
    ``extra[0]`` (the lint_text mutation-test seam).  The overlay SHARES
    the cached base index's parsed modules and per-function lists —
    only the extra module is indexed fresh, and the fixpoints restart
    from scratch over the shared structure (they only add facts, so
    convergence is quick)."""
    if extra is None:
        return _base_index(root)
    rel, text = extra
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError:
        return _base_index(root)
    base = _base_index(root)
    idx = PackageIndex()
    for mrel, mod in base.modules.items():
        if mrel == rel:
            continue
        idx.modules[mrel] = mod
        for fi in mod.functions:
            idx.by_name.setdefault(fi.name, []).append(fi)
    idx.owner = {
        k: v for k, v in base.owner.items() if v.rel != rel
    }
    for tail, sites in base.calls_by_tail.items():
        kept = [(fi, c) for fi, c in sites if fi.rel != rel]
        if kept:
            idx.calls_by_tail[tail] = kept
    # seed the fixpoints with the base facts (minus anything owned by or
    # derived via the shadowed module) — facts only grow, so re-running
    # the fixpoints on top converges in a sweep or two
    prefix = rel + "::"
    idx.returns_rank = {
        q for q in base.returns_rank if not q.startswith(prefix)
    }
    idx.returns_uniform = {
        q for q in base.returns_uniform if not q.startswith(prefix)
    }
    idx.reaches = {
        q: v for q, v in base.reaches.items()
        if not q.startswith(prefix)
        and (v[0] == "direct" or not v[1].startswith(prefix))
    }
    _index_module(idx, rel, tree)
    _finish_index(idx, only=idx.modules[rel].functions)
    return idx


# ---------------------------------------------------------------------------
# provenance predicates
# ---------------------------------------------------------------------------


def _rank_source(expr: ast.AST) -> Optional[Tuple[int, str]]:
    """(line, description) of the first explicit rank source in an
    expression: ``jax.process_index()`` or a ``.process_id`` /
    ``.process_index`` attribute read."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _tail(n.func) == "process_index":
            return n.lineno, f"{_dotted(n.func) or 'process_index'}()"
        if isinstance(n, ast.Attribute) and n.attr in (
                "process_id", "process_index"):
            return n.lineno, f".{n.attr} read"
    return None


def _collective_dispatch(call: ast.Call) -> Optional[str]:
    """The dispatched collective's name when ``call`` is a direct
    collective: a facade op, a raw lax collective, or a host
    process_allgather."""
    d = _dotted(call.func)
    tail = _tail(call.func)
    if tail in _GATHER_TAILS:
        return d or tail
    if d.startswith(("lax.", "jax.lax.")) and tail in _LAX_COLLECTIVES:
        return d
    if d.startswith("collective.") and tail in _FACADE_OPS:
        return d
    return None


def _call_names(expr: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(expr) if isinstance(n, ast.Call)]


def _flat_names(t) -> List[str]:
    """Names a store target actually REBINDS: plain names and
    tuple/list destructuring.  A subscript/attribute store
    (``summary["rank"] = r``) carries rank data without making the
    container's NAME rank-derived for control-flow purposes — flagging
    it would taint every summary dict a rank tag rides in."""
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in t.elts:
            out.extend(_flat_names(e))
        return out
    if isinstance(t, ast.Starred):
        return _flat_names(t.value)
    return []


def _assign_targets(node) -> List[str]:
    if isinstance(node, ast.Assign):
        tgts = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
        tgts = [node.target]
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        tgts = [node.target]
    elif isinstance(node, ast.withitem):
        tgts = [node.optional_vars] if node.optional_vars else []
    else:
        return []
    out: List[str] = []
    for t in tgts:
        out.extend(_flat_names(t))
    return out


def _value_of(node):
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                         ast.NamedExpr)):
        return node.value
    if isinstance(node, (ast.For, ast.AsyncFor)):
        return node.iter
    if isinstance(node, ast.withitem):
        return node.context_expr
    return None


def _uniformizing(idx: PackageIndex, expr: ast.AST, rel: str) -> bool:
    """Does the expression pass through a world synchronizer (its value
    is identical on every rank afterwards)?"""
    for call in _call_names(expr):
        if _collective_dispatch(call) is not None:
            return True
        for fi in idx.resolve(call, rel):
            if fi.qual in idx.returns_uniform:
                return True
    return False


def _fn_taints(idx: PackageIndex, fi: FuncInfo) -> Dict[str, Tuple[int, str]]:
    """Rank-tainted local names of one function: name -> (line, chain
    description).  Flow-insensitive fixpoint over the assignment-shaped
    statements (assignments, loop targets, with-as, walrus).  Cached per
    (function, fixpoint-state) — the sets only grow, so the state is the
    pair of set sizes."""
    key = (fi.qual, len(idx.returns_rank), len(idx.returns_uniform))
    cached = idx._taint_cache.get(key)
    if cached is not None:
        return cached
    tainted: Dict[str, Tuple[int, str]] = {}
    nodes = [n for n in ast.walk(fi.node)
             if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr, ast.For, ast.AsyncFor))]
    for n in list(ast.walk(fi.node)):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            nodes.extend(n.items)
    for _ in range(len(nodes) + 1):
        changed = False
        for n in nodes:
            value = _value_of(n)
            if value is None:
                continue
            targets = _assign_targets(n)
            if not targets or all(t in tainted for t in targets):
                continue
            if _uniformizing(idx, value, fi.rel):
                continue  # gathered values are world-uniform again
            src = _rank_source(value)
            if src is None:
                for c in _call_names(value):
                    for cand in idx.resolve(c, fi.rel):
                        if cand.qual in idx.returns_rank:
                            src = (c.lineno,
                                   f"{cand.name}() returns a rank-derived "
                                   "value")
                            break
                    if src:
                        break
            if src is None:
                for name_node in ast.walk(value):
                    if isinstance(name_node, ast.Name) \
                            and name_node.id in tainted:
                        line, desc = tainted[name_node.id]
                        src = (name_node.lineno,
                               f"'{name_node.id}' <- {desc}")
                        break
            if src is None:
                continue
            for t in targets:
                if t not in tainted:
                    tainted[t] = src
                    changed = True
        if not changed:
            break
    idx._taint_cache[key] = tainted
    return tainted


def _fixpoints(idx: PackageIndex) -> None:
    """Package-wide fixpoints: which functions return rank-derived
    values, which return world-uniform (gathered) values, and which
    reach a collective."""
    # reaches-collective
    changed = True
    while changed:
        changed = False
        for mod in idx.modules.values():
            for fi in mod.functions:
                if fi.qual in idx.reaches:
                    continue
                for call in fi.own_calls:
                    op = _collective_dispatch(call)
                    if op is not None:
                        idx.reaches[fi.qual] = ("direct", op, call.lineno)
                        changed = True
                        break
                    for cand in idx.resolve(call, fi.rel):
                        if cand.qual in idx.reaches and cand is not fi:
                            idx.reaches[fi.qual] = (
                                "via", cand.qual, call.lineno)
                            changed = True
                            break
                    if fi.qual in idx.reaches:
                        break
    # returns-uniform / returns-rank (interleaved: taint computation
    # consults both sets, so iterate to a joint fixpoint)
    for _ in range(8):
        changed = False
        for mod in idx.modules.values():
            for fi in mod.functions:
                rets = fi.own_returns
                if not rets:
                    continue
                if fi.qual not in idx.returns_uniform:
                    uniform_vars = set()
                    for n in ast.walk(fi.node):
                        value = _value_of(n)
                        if value is not None and _uniformizing(
                                idx, value, fi.rel):
                            uniform_vars.update(_assign_targets(n))
                    for r in rets:
                        if _uniformizing(idx, r, fi.rel) or any(
                                isinstance(x, ast.Name)
                                and x.id in uniform_vars
                                for x in ast.walk(r)):
                            idx.returns_uniform.add(fi.qual)
                            changed = True
                            break
                if fi.qual not in idx.returns_rank \
                        and fi.qual not in idx.returns_uniform:
                    tainted = _fn_taints(idx, fi)
                    for r in rets:
                        hit = _rank_source(r) is not None or any(
                            isinstance(x, ast.Name) and x.id in tainted
                            for x in ast.walk(r))
                        if not hit:
                            for c in _call_names(r):
                                if any(cand.qual in idx.returns_rank
                                       for cand in idx.resolve(c, fi.rel)):
                                    hit = True
                                    break
                        if hit:
                            idx.returns_rank.add(fi.qual)
                            changed = True
                            break
        if not changed:
            break


# ---------------------------------------------------------------------------
# R16: collective-divergence
# ---------------------------------------------------------------------------


def _cond_evidence(expr: ast.AST, tainted: Dict[str, Tuple[int, str]]
                   ) -> Optional[str]:
    """Why a condition/iterable is rank-derived, or None."""
    src = _rank_source(expr)
    if src is not None:
        return f"{src[1]} at line {src[0]}"
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in tainted:
            line, desc = tainted[n.id]
            return f"'{n.id}' ({desc}, line {line})"
    return None


def _exits(block: Sequence[ast.stmt]) -> bool:
    """Does a branch body unconditionally leave the enclosing block via
    return/break/continue?  (``raise`` is deliberately excluded: the
    fail-fast raise is the sanctioned per-rank exit — the _PassGuard
    contract carries it to the next reduction.)"""
    return bool(block) and isinstance(
        block[-1], (ast.Return, ast.Break, ast.Continue))


def _r16_function(idx: PackageIndex, fi: FuncInfo, emit) -> None:
    tainted = _fn_taints(idx, fi)

    def describe_call(call: ast.Call) -> Optional[str]:
        op = _collective_dispatch(call)
        if op is not None:
            return f"collective {op}"
        for cand in idx.resolve(call, fi.rel):
            if cand.qual in idx.reaches:
                return (f"call to '{cand.name}' which reaches a "
                        f"collective ({idx.chain(cand.qual)})")
        return None

    def scan_calls(node: ast.AST, ctx: List[str]) -> None:
        for call in _call_names(node):
            what = describe_call(call)
            if what is not None:
                emit(call.lineno,
                     f"{what} is reachable only under rank-divergent "
                     "control flow: " + "; ".join(ctx) + " — every rank "
                     "must issue the same collective sequence "
                     "(static-world contract); hoist the collective out "
                     "of the branch or make the condition world-uniform "
                     "(gather/psum it first)")

    def walk(stmts: Sequence[ast.stmt], ctx: List[str]) -> None:
        diverged: Optional[str] = None
        for st in stmts:
            here = list(ctx)
            if diverged is not None:
                here.append(diverged)
            if isinstance(st, (ast.If, ast.While)):
                ev = _cond_evidence(st.test, tainted)
                if ev is not None:
                    kind = "if" if isinstance(st, ast.If) else "while"
                    cond_ctx = here + [
                        f"{kind} at line {st.lineno} branches on {ev}"]
                    walk(st.body, cond_ctx)
                    walk(st.orelse, cond_ctx)
                    if diverged is None and (
                            _exits(st.body) or _exits(st.orelse)):
                        diverged = (
                            f"code after line {st.lineno} (a rank-"
                            f"dependent {kind} on {ev} exits early, so "
                            "ranks diverge from here on)")
                else:
                    walk(st.body, here)
                    walk(st.orelse, here)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                ev = _cond_evidence(st.iter, tainted)
                if ev is not None:
                    loop_ctx = here + [
                        f"for at line {st.lineno} iterates over "
                        f"rank-derived {ev}"]
                    walk(st.body, loop_ctx)
                else:
                    walk(st.body, here)
                walk(st.orelse, here)
                # the loop header itself may dispatch when divergent ctx
                if here:
                    scan_calls(st.iter, here)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs are scanned as their own functions
            if isinstance(st, (ast.With, ast.AsyncWith, ast.Try)):
                for item in getattr(st, "items", []):
                    if here:
                        scan_calls(item.context_expr, here)
                for blk in (getattr(st, "body", []),
                            getattr(st, "orelse", []),
                            getattr(st, "finalbody", [])):
                    walk(blk, here)
                for h in getattr(st, "handlers", []):
                    walk(h.body, here)
                continue
            # plain statement: ternaries inside count as branches
            for n in ast.walk(st):
                if isinstance(n, ast.IfExp):
                    ev = _cond_evidence(n.test, tainted)
                    if ev is not None:
                        scan_calls(n.body, here + [
                            f"ternary at line {n.lineno} branches on "
                            f"{ev}"])
                        scan_calls(n.orelse, here + [
                            f"ternary at line {n.lineno} branches on "
                            f"{ev}"])
            if here:
                scan_calls(st, here)

    body = getattr(fi.node, "body", [])
    walk(body, [])


@rule("collective-divergence", scope=rf"{PKG}/", kind="dataflow",
      doc="No collective (facade op, lax collective, process_allgather) "
          "reachable under control flow derived from jax.process_index()"
          " / Config.process_id — a rank-divergent collective does not "
          "error, it hangs the whole world.  Interprocedural: calls that"
          " transitively reach a collective count, and helper returns "
          "propagate rank provenance; gathers re-uniformize.")
def _r16(root, extra=None):
    idx = build_index(Path(root), extra)
    findings: List[Tuple[str, int, str]] = []
    for rel, mod in idx.modules.items():
        if rel == FACADE_REL:
            continue
        for fi in mod.functions:
            _r16_function(
                idx, fi,
                lambda line, detail, _rel=rel: findings.append(
                    (_rel, line, detail)),
            )
    return findings


# ---------------------------------------------------------------------------
# R17: unbound-collective-axis
# ---------------------------------------------------------------------------

_AXIS_ARG_OPS = {"psum": 1, "pmean": 1, "all_gather": 1, "ppermute": 1,
                 "all_to_all": 1, "psum_scatter": 1}


def _axis_expr(call: ast.Call) -> Optional[ast.AST]:
    tail = _tail(call.func)
    pos = _AXIS_ARG_OPS.get(tail)
    if pos is None:
        return None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _local_binding(fi: FuncInfo, name: str) -> Optional[ast.AST]:
    """The assignment value bound to ``name`` in ``fi`` or its enclosing
    (closure) functions, innermost first; None if it is a parameter or
    free."""
    for scope in [fi] + list(reversed(fi.enclosing)):
        own = [n for n in ast.walk(scope.node)
               if isinstance(n, ast.Assign)]
        for n in own:
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in n.targets):
                return n.value
    return None


def _param_scope(fi: FuncInfo, name: str) -> Optional[FuncInfo]:
    for scope in [fi] + list(reversed(fi.enclosing)):
        if name in scope.params:
            return scope
    return None


def _resolve_axis(idx: PackageIndex, fi: FuncInfo, expr: ast.AST,
                  depth: int, seen: Set[str]) -> List[Tuple[str, ...]]:
    """Possible resolutions of an axis expression: ('config', field) |
    ('mesh',) | ('literal', value, rel, line) | ('opaque',)."""
    if depth <= 0:
        return [("opaque",)]
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [("literal", expr.value, fi.rel, expr.lineno)]
    if isinstance(expr, ast.Attribute):
        if expr.attr in ("data_axis", "model_axis"):
            return [("config", expr.attr)]
        return [("opaque",)]
    if isinstance(expr, ast.Subscript):
        # mesh.axis_names[i] — bound by the mesh that carries it
        if isinstance(expr.value, ast.Attribute) \
                and expr.value.attr == "axis_names":
            return [("mesh",)]
        return [("opaque",)]
    if isinstance(expr, ast.Name):
        bound = _local_binding(fi, expr.id)
        if bound is not None:
            return _resolve_axis(idx, fi, bound, depth - 1, seen)
        pscope = _param_scope(fi, expr.id)
        if pscope is not None:
            return _resolve_param(idx, pscope, expr.id, depth - 1, seen)
        return [("opaque",)]
    return [("opaque",)]


def _resolve_param(idx: PackageIndex, fi: FuncInfo, param: str,
                   depth: int, seen: Set[str]) -> List[Tuple[str, ...]]:
    """Resolve a parameter through every package call site of its
    function (the 'resolved through helper calls' half of R17)."""
    key = f"{fi.qual}#{param}"
    if key in seen:
        return [("opaque",)]
    seen = seen | {key}
    a = fi.node.args
    pos_names = [p.arg for p in a.posonlyargs + a.args]
    try:
        pos_idx = pos_names.index(param)
    except ValueError:
        pos_idx = None
    default = None
    defaults = list(a.defaults)
    if pos_idx is not None and defaults:
        first_default = len(pos_names) - len(defaults)
        if pos_idx >= first_default:
            default = defaults[pos_idx - first_default]
    out: List[Tuple[str, ...]] = []
    for caller, call in idx.calls_by_tail.get(fi.name, []):
        if fi not in idx.resolve(call, caller.rel):
            continue
        arg = None
        for kw in call.keywords:
            if kw.arg == param:
                arg = kw.value
        if arg is None and pos_idx is not None \
                and len(call.args) > pos_idx:
            arg = call.args[pos_idx]
        if arg is None:
            arg = default
        if arg is None:
            continue
        out.extend(_resolve_axis(idx, caller, arg, depth, seen))
    if not out and default is not None:
        out.extend(_resolve_axis(idx, fi, default, depth, seen))
    return out or [("opaque",)]


@rule("unbound-collective-axis", scope=rf"{PKG}/", kind="dataflow",
      doc="A collective's axis name must resolve — through enclosing "
          "scopes and helper-call arguments, package-wide — to a mesh-"
          "bound token: cfg.data_axis/model_axis, a mesh.axis_names "
          "element, or a literal some Mesh/PartitionSpec context binds. "
          "An unbound axis name fails only at trace time on the one "
          "mesh shape that reaches it — or silently reduces over the "
          "wrong axis.")
def _r17(root, extra=None):
    idx = build_index(Path(root), extra)
    findings: List[Tuple[str, int, str]] = []
    for rel, mod in idx.modules.items():
        if rel == FACADE_REL:
            continue
        for fi in mod.functions:
            for call in fi.own_calls:
                d = _dotted(call.func)
                if not (d.startswith(("collective.", "lax.", "jax.lax."))
                        and _tail(call.func) in _AXIS_ARG_OPS):
                    continue
                axis = _axis_expr(call)
                if axis is None:
                    continue
                for res in _resolve_axis(idx, fi, axis, 6, set()):
                    if res[0] != "literal":
                        continue
                    value, src_rel, src_line = res[1], res[2], res[3]
                    bound = set()
                    for m in (idx.modules.get(src_rel),
                              idx.modules.get(rel)):
                        if m is not None:
                            bound |= m.bound_axis_literals
                    if value not in bound:
                        findings.append((
                            rel, call.lineno,
                            f"collective axis name {value!r} (bound at "
                            f"{src_rel}:{src_line}) is not bound by any "
                            "enclosing shard_map/mesh context in the "
                            "resolution chain's modules; use "
                            "cfg.data_axis/cfg.model_axis (or a "
                            "mesh.axis_names element) so the axis and "
                            "the mesh cannot drift apart"))
    return findings


# ---------------------------------------------------------------------------
# R18: precision-flow
# ---------------------------------------------------------------------------

_BF16_TOKENS = {"jnp.bfloat16", "jax.numpy.bfloat16", "np.bfloat16",
                "ml_dtypes.bfloat16"}
_F32_TOKENS = {"jnp.float32", "jax.numpy.float32", "np.float32",
               "numpy.float32"}
_REDUCTIONS = {"sum", "mean", "prod", "nansum", "nanmean", "cumsum",
               "average", "var", "std"}
_MATMULISH = {"pdot", "peinsum", "matmul", "dot", "einsum", "tensordot"}
_ALLOC = {"zeros", "ones", "full", "empty"}


def _dtype_token(expr: ast.AST) -> str:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return _dotted(expr)


def _is_bf16_dtype(expr: ast.AST) -> bool:
    return _dtype_token(expr) in _BF16_TOKENS | {"bfloat16"}


def _is_f32_dtype(expr: ast.AST) -> bool:
    return _dtype_token(expr) in _F32_TOKENS | {"float32"}


def _astype_to(call: ast.Call, pred) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype" and call.args
            and pred(call.args[0]))


@rule("precision-flow", scope=rf"{PKG}/(ops|models|data)/",
      doc="bf16-tier values must accumulate into f32: no reductions "
          "(jnp.sum/mean/...) on bf16-cast values without an upcast, no "
          "f32->bf16->f32 round-trips whose bf16 value feeds no matmul "
          "(pure mantissa loss), no reduced-dtype accumulator "
          "allocations.  utils/precision.pdot/peinsum own the bf16 "
          "matmul path (f32 accumulation via preferred_element_type); "
          "ops/pallas/ hi/lo-split kernels are exempt.")
def _r18(ctx):
    if ctx.rel.startswith(f"{PKG}/ops/pallas/"):
        return
    seen: Set[Tuple[int, str]] = set()
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Module)):
            continue
        for finding in _r18_scope(fn):
            if finding not in seen:
                seen.add(finding)
                yield finding


def _r18_scope(fn):
        # bf16-tier names assigned in this scope
        bf16: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _astype_to(n.value, _is_bf16_dtype):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        bf16.add(t.id)
        consumed_by_matmul: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and _tail(n.func) in _MATMULISH:
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    for x in ast.walk(a):
                        if isinstance(x, ast.Name):
                            consumed_by_matmul.add(x.id)
            elif isinstance(n, ast.BinOp) and isinstance(n.op, ast.MatMult):
                for x in ast.walk(n):
                    if isinstance(x, ast.Name):
                        consumed_by_matmul.add(x.id)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            tail = _tail(n.func)
            # (1) reduced-dtype accumulator allocation
            if tail in _ALLOC:
                dt = None
                for kw in n.keywords:
                    if kw.arg == "dtype":
                        dt = kw.value
                if dt is None and len(n.args) >= 2:
                    dt = n.args[-1]
                d = _dotted(n.func)
                if dt is not None and _is_bf16_dtype(dt) and (
                        d.startswith("jnp.") or d.startswith("jax.numpy.")):
                    yield (n.lineno, f"{d} allocates a bfloat16 "
                           "accumulator; accumulators must stay f32 "
                           "(the precision-policy contract: bf16 "
                           "operands, f32 accumulation)")
                continue
            # (2) f32->bf16->f32 round-trips
            if _astype_to(n, _is_f32_dtype):
                inner = n.func.value
                if isinstance(inner, ast.Call) \
                        and _astype_to(inner, _is_bf16_dtype):
                    yield (n.lineno, "f32->bf16->f32 round-trip: the "
                           "cast chain discards 16 mantissa bits and "
                           "buys nothing (no matmul consumes the bf16 "
                           "value); drop both casts or feed the bf16 "
                           "value to precision.pdot/peinsum")
                elif isinstance(inner, ast.Name) and inner.id in bf16 \
                        and inner.id not in consumed_by_matmul:
                    yield (n.lineno, f"'{inner.id}' is cast f32->bf16->"
                           "f32 without feeding any matmul — a pure "
                           "precision loss; remove the bf16 cast or "
                           "route the contraction through "
                           "precision.pdot/peinsum")
            # (3) reductions on bf16-tier operands
            d = _dotted(n.func)
            if tail in _REDUCTIONS and (
                    d.startswith("jnp.") or d.startswith("jax.numpy.")):
                for a in n.args[:1]:
                    sanitized = any(
                        isinstance(c, ast.Call) and (
                            _tail(c.func) == "upcast"
                            or _astype_to(c, _is_f32_dtype))
                        for c in ast.walk(a))
                    if sanitized:
                        continue
                    hit = None
                    for x in ast.walk(a):
                        if isinstance(x, ast.Name) and x.id in bf16:
                            hit = x.id
                            break
                        if isinstance(x, ast.Call) \
                                and _astype_to(x, _is_bf16_dtype):
                            hit = "<bf16 cast>"
                            break
                    if hit is not None:
                        yield (n.lineno, f"{d} reduces bf16-tier value "
                               f"{hit!r} at reduced dtype — summing at "
                               "bf16 loses whole rows at realistic "
                               "sizes; wrap the operand in precision."
                               "upcast (f32 accumulation) like the "
                               "streamed kernels do")
