"""CLI: ``python dev/oaplint [paths...] [--json FILE] [--list-rules]``.

Exit 1 on any finding; prints ``file:line: rule: detail`` per finding
(the dev/lint.py output contract, so editors/CI parse it unchanged).
``--json`` additionally writes the findings as a JSON artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import oaplint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="oaplint")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files to lint (default: the whole tree)")
    ap.add_argument("--json", metavar="FILE",
                    help="also write a JSON artifact to FILE ('-' for "
                         "stdout): {findings: [...], suppressions: "
                         "[the audited-directive inventory]}")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, r in sorted(oaplint.RULES.items()):
            doc = " ".join(r.doc.split())
            print(f"{name} [{r.kind}]: {doc}")
        return 0

    findings, n_files = oaplint.run(paths=args.paths or None)
    for f in findings:
        print(f.render())
    if args.json:
        # the artifact pairs the findings with the audited-suppression
        # inventory (ISSUE 7 satellite): every directive in the tree,
        # its rules/reason, and whether it still suppresses anything
        payload = json.dumps({
            "findings": [f.as_dict() for f in findings],
            "suppressions": oaplint.suppression_inventory(
                findings=findings
            ),
        }, indent=2)
        if args.json == "-":
            print(payload)
        else:
            Path(args.json).write_text(payload + "\n")
    if findings:
        print(f"oaplint: {len(findings)} finding(s) in {n_files} files "
              f"({len(oaplint.RULES)} rules)")
        return 1
    print(f"oaplint: OK ({n_files} files, {len(oaplint.RULES)} rules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
