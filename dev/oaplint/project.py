"""Project-wide rule (R6): the Config field contract.

Every ``Config`` dataclass field must be (a) documented in
docs/configuration.md, (b) covered in tests/test_config_coverage.py, and
(c) reachable via the automatic ``OAP_MLLIB_TPU_<UPPER>`` env override —
so any hardcoded ``OAP_MLLIB_TPU_*`` string literal in the package must
match a real field's env name.  This promotes dev/check_docs.py's
runtime config-coverage check to a static pass (check_docs keeps the
sample-execution and link checks, which need a runtime).
"""

from __future__ import annotations

import ast
import re

from . import PKG, rule

ENV_PREFIX = "OAP_MLLIB_TPU_"


def _config_fields(root):
    """(name, lineno) per Config dataclass field, from the AST (no
    import: the linter must run without jax/numpy present)."""
    path = root / PKG / "config.py"
    tree = ast.parse(path.read_text())
    for n in ast.walk(tree):
        if isinstance(n, ast.ClassDef) and n.name == "Config":
            return [
                (s.target.id, s.lineno)
                for s in n.body
                if isinstance(s, ast.AnnAssign)
                and isinstance(s.target, ast.Name)
            ]
    return []


@rule("config-field-contract", kind="project",
      doc="Every Config field must be documented in docs/configuration.md,"
          " covered in tests/test_config_coverage.py, and any hardcoded "
          "OAP_MLLIB_TPU_* env literal in the package must match a field's"
          " derived env name (OAP_MLLIB_TPU_<FIELD_UPPER>).")
def _config_field_contract(root):
    fields = _config_fields(root)
    names = [f for f, _ in fields]
    cfg_rel = f"{PKG}/config.py"

    docs = root / "docs" / "configuration.md"
    doc_text = docs.read_text() if docs.exists() else ""
    tests = root / "tests" / "test_config_coverage.py"
    test_text = tests.read_text() if tests.exists() else ""
    # the coverage test sweeps dataclasses.fields(Config) generically
    # (read-somewhere, documented, env-override legs) — that sweep covers
    # every field structurally; a field is uncovered only if BOTH the
    # sweep and a by-name mention are absent
    generic = "dataclasses.fields(Config)" in test_text

    for name, lineno in fields:
        if f"`{name}`" not in doc_text:
            yield (cfg_rel, lineno,
                   f"Config.{name} is not documented in "
                   "docs/configuration.md")
        if not generic and not re.search(rf"\b{re.escape(name)}\b",
                                         test_text):
            yield (cfg_rel, lineno,
                   f"Config.{name} is not covered in "
                   "tests/test_config_coverage.py")

    valid_env = {ENV_PREFIX + f.upper() for f in names} | {ENV_PREFIX}
    for path in sorted((root / PKG).rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue  # the syntax rule owns this
        rel = path.relative_to(root).as_posix()
        for n in ast.walk(tree):
            if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and n.value.startswith(ENV_PREFIX) \
                    and n.value not in valid_env:
                yield (rel, n.lineno,
                       f"env literal {n.value!r} does not match any "
                       "Config field's derived override name "
                       f"({ENV_PREFIX}<FIELD_UPPER>)")
