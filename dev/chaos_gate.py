#!/usr/bin/env python
"""CI gate: the live-world fault-tolerance loop (ISSUE 10) must hold its
contracts — detect → abort → relaunch → resume, drilled deterministically.

Legs:

1. **Chaos parity** — a streamed K-Means fit with the seeded chaos
   schedule armed (transient kinds) completes and matches the
   undisturbed fit bit-for-bit: every injected fault was absorbed by
   the resilience ladder, at least one actually fired, and the fit
   summary shows the retries.
2. **Kill-relaunch-resume, deterministic** — a supervised 1-process
   world is SIGKILLed mid-pass 3; the supervisor relaunches, the resumed
   fit restores step 2 and the final centers are BIT-IDENTICAL to the
   undisturbed supervised run.  Runs on every host (no multiprocess
   collectives involved).
3. **Chaos kill drill** — the same loop driven by the chaos plane
   (`seed:rate:kill:1`, supervisor re-seeding per attempt): attempt 0
   dies by schedule, the relaunch resumes and lands bit-identical.
4. **2-process drills** — the supervised 2-process kill-relaunch leg and
   the shrink-to-1 resharded leg (≤1e-5 parity), plus the
   pseudo-cluster collective-timeout suite
   (tests/test_pseudo_cluster.py::TestLiveWorldRecovery: every survivor
   raises CollectiveTimeoutError within the deadline, no hang).  Hosts
   whose jax build cannot form multiprocess CPU worlds skip these, like
   every pseudo-cluster suite.
5. **Disarmed overhead** — `collective_timeout=0` keeps the dispatch
   seam at one config check: its measured cost must be <1% of the
   20-fit K-Means microbench wall.

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


# mirror tests/test_pseudo_cluster.py: a host whose jax build cannot
# form multiprocess CPU worlds skips the world legs, not fails them
_ENV_FAILURE_MARKERS = (
    "Multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
    "Unable to initialize backend",
    "failed to join world",
    "DEADLINE_EXCEEDED",
    "Failed to connect to coordinator",
)


def _env_incapable(sup) -> bool:
    for att in sup.attempts:
        for e in att.exits:
            if any(m in (e.output or "") for m in _ENV_FAILURE_MARKERS):
                return True
    return False


def _results(summary):
    out = {}
    for o in summary["outputs"]:
        for line in o.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                out[r["rank"]] = r
    return out


# -- leg 1: chaos parity ------------------------------------------------------

print("== chaos gate: seeded chaos fit completes at parity "
      "(transient kinds absorbed by the ladder) ==")
from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.data.stream import ChunkSource  # noqa: E402
from oap_mllib_tpu.models.kmeans import KMeans  # noqa: E402
from oap_mllib_tpu.utils import faults  # noqa: E402

rng = np.random.default_rng(11)
x = rng.normal(size=(1024, 8)).astype(np.float32)


def _streamed_fit():
    return KMeans(k=4, seed=3, init_mode="random", max_iter=3).fit(
        ChunkSource.from_array(x, chunk_rows=256)
    )


baseline = _streamed_fit()
# seed pinned so the schedule fires on this exact call sequence; the
# decision is a pure hash of (seed, rank, site, call), so this is stable
set_config(chaos="17:0.03:fail")
chaotic = _streamed_fit()
st = faults.stats().get("chaos", {})
set_config(chaos="")
check(st.get("fired", 0) >= 1,
      f"chaos schedule fired nothing (stats: {st}) — the leg proved "
      "nothing; re-pin the seed")
check(chaotic.summary.training_cost == baseline.summary.training_cost,
      f"chaos fit diverged: {chaotic.summary.training_cost} vs "
      f"{baseline.summary.training_cost}")
check(np.array_equal(np.asarray(chaotic.cluster_centers_),
                     np.asarray(baseline.cluster_centers_)),
      "chaos fit centers are not bit-identical to the undisturbed fit")
check(chaotic.summary.resilience["retries"] >= 1,
      f"chaos faults fired but no retries recorded: "
      f"{chaotic.summary.resilience}")
print(f"  chaos fired {st.get('fired')} fault(s) over "
      f"{sum(st.get('calls', {}).values())} site calls; "
      f"{chaotic.summary.resilience['retries']} retries, parity exact")

# -- legs 2+3: supervised kill-relaunch-resume (single-process world) --------

from dev.supervise import supervise  # noqa: E402

print("== chaos gate: deterministic kill-relaunch-resume, supervised "
      "(1-process world — runs on every host) ==")
tmp = tempfile.mkdtemp(prefix="chaos_gate_")


def _run_supervised(tag, **kw):
    return supervise(
        kw.pop("procs", 1),
        os.path.join(tmp, tag, "ck"), os.path.join(tmp, tag, "crash"),
        backoff=0.1, collective_timeout=10.0, **kw,
    )


undisturbed, _ = _run_supervised("full", budget=0)
check(undisturbed["ok"], f"undisturbed supervised run failed: {undisturbed}")
base_res = _results(undisturbed)[0]
check(base_res["decision"] == "fresh", f"unexpected restore: {base_res}")

killed, _ = _run_supervised("kill", budget=3, kill_rank=0, kill_walk=4)
check(killed["ok"], f"kill drill did not recover: {killed}")
check(killed["relaunches"] == 1,
      f"expected exactly 1 relaunch, got {killed['relaunches']}")
check(killed["attempts"][0]["exits"][0]["classification"] == "killed",
      f"kill not classified: {killed['attempts'][0]}")
kill_res = _results(killed)[0]
check(kill_res["decision"] == "found" and kill_res["restored_step"] == 2,
      f"resume did not restore the durable step: {kill_res}")
check(kill_res["centers_hex"] == base_res["centers_hex"],
      "kill-relaunch-resume is not bit-identical to the undisturbed run")
print(f"  killed at pass 3, resumed at step {kill_res['restored_step']}, "
      "centers bit-identical")

print("== chaos gate: chaos-driven kill drill (seeded schedule, "
      "re-seeded per relaunch) ==")
# seed 5 @ rate .004, kill budget 1: attempt 0 dies mid-fit, the
# re-seeded attempt 1 completes — pinned like every chaos seed here
chaos_killed, sup_ck = _run_supervised("chaos", budget=3,
                                       chaos="5:0.004:kill:1")
check(chaos_killed["ok"], f"chaos kill drill did not recover: {chaos_killed}")
check(chaos_killed["relaunches"] >= 1,
      "chaos schedule killed nothing — re-pin the seed")
ck_res = _results(chaos_killed)[0]
check(ck_res["centers_hex"] == base_res["centers_hex"],
      "chaos-killed supervised run is not bit-identical to undisturbed")
print(f"  chaos killed attempt 0, {chaos_killed['relaunches']} relaunch(es), "
      f"resume decision {ck_res['decision']}, centers bit-identical")

# -- leg 4: 2-process drills (skip when the host cannot form worlds) ---------

print("== chaos gate: 2-process supervised drills (skip if the host "
      "cannot form multiprocess jax worlds) ==")
# capability probe doubles as the shrink leg's undisturbed oracle —
# budget 0, so an incapable host fails it in ONE attempt and skips
full2, supf2 = _run_supervised("full2", procs=2, budget=0)
if _env_incapable(supf2):
    print("  SKIP: multiprocess jax worlds unavailable on this host")
else:
    check(full2["ok"], f"undisturbed 2-process run failed: {full2}")
    base2 = _results(full2)[0]

    two_proc, sup2 = _run_supervised("kill2", procs=2, budget=3,
                                     kill_rank=1, kill_walk=4)
    check(two_proc["ok"], f"2-process kill drill did not recover: {two_proc}")
    res2 = _results(two_proc)
    check(res2[0]["centers_hex"] == res2[1]["centers_hex"],
          "ranks disagree after resume")
    check(res2[0]["centers_hex"] == base2["centers_hex"],
          "2-process kill-relaunch-resume not bit-identical to undisturbed")
    check(res2[0]["ladder"] == "supervised",
          f"multi-process ladder not stamped supervised: {res2[0]}")
    # the survivor must have converted the hang into a timeout record
    att0 = two_proc["attempts"][0]
    classes = {e["rank"]: e["classification"] for e in att0["exits"]}
    check(classes[1] == "killed", f"culprit misclassified: {att0}")
    check(att0["culprit"] == 1, f"culprit misattributed: {att0}")

    print("== chaos gate: shrink-to-1 resharded resume (rank 1 bad on "
          "every multi-process attempt) ==")
    shrunk, sups = _run_supervised(
        "shrink", procs=2, budget=3, shrink_after=1, kill_rank=1,
        kill_walk=4, kill_scope="multi",
    )
    check(shrunk["ok"], f"shrink drill did not recover: {shrunk}")
    check(shrunk["final_world"] == 1 and shrunk["shrinks"] == 1,
          f"world did not shrink: {shrunk}")
    sh_res = _results(shrunk)[0]
    check(sh_res["decision"] == "resharded",
          f"shrunken world did not reshard: {sh_res}")
    rel = abs(sh_res["cost"] - base2["cost"]) / abs(base2["cost"])
    check(rel <= 1e-5,
          f"resharded resume parity {rel:.2e} > 1e-5 "
          f"({sh_res['cost']} vs {base2['cost']})")
    print(f"  shrunk 2->1, resharded resume parity {rel:.2e}")

print("== chaos gate: pseudo-cluster collective-timeout legs ==")
proc = subprocess.run(
    [sys.executable, "-m", "pytest",
     "tests/test_pseudo_cluster.py::TestLiveWorldRecovery", "-q",
     "-p", "no:cacheprovider"],
    cwd=ROOT, capture_output=True, text=True, timeout=600,
)
print("  " + (proc.stdout.strip().splitlines()[-1]
              if proc.stdout.strip() else ""))
check(proc.returncode == 0,
      f"pseudo-cluster recovery legs failed:\n{proc.stdout[-2000:]}")

# -- leg: serving-chaos determinism (ISSUE 18) --------------------------------

print("== chaos gate: seeded serving chaos is deterministic "
      "(identical per-request outcome vectors) ==")
from oap_mllib_tpu import serving  # noqa: E402
from oap_mllib_tpu.utils import faults  # noqa: E402


def _serving_storm():
    """One seeded storm through the traffic plane under armed chaos;
    returns the per-request outcome tags.  ``start=False`` + a manual
    pump loop keeps the chaos schedule's (site, call-index) sequence
    identical across runs — a live dispatcher's wakeup timing would
    not be."""
    q = serving.TrafficQueue(_SERVE_HANDLE, start=False)
    r = np.random.default_rng(9)
    tags = []
    for s in r.integers(2, 24, size=24):
        f = q.submit(r.normal(size=(int(s), 8)).astype(np.float32),
                     deadline_ms=120_000)
        for _ in range(20):
            if f.done():
                break
            try:
                q.pump()
            except Exception:
                pass  # crash cycles already landed their futures
        exc = f.exception() if f.done() else RuntimeError("unresolved")
        if exc is None:
            tags.append("ok")
        elif isinstance(exc, serving.ServeError):
            tags.append(f"serve:{exc.reason}")
        else:
            tags.append(type(exc).__name__)
    q.close()
    return tags


_km_serve = KMeans(k=3, seed=4, init_mode="random", max_iter=3).fit(
    rng.normal(size=(256, 8)).astype(np.float32)
)
_SERVE_HANDLE = serving.serve(_km_serve)
_SERVE_HANDLE.warmup(32)
set_config(serve_retry_limit=2, serve_retry_backoff=0.0,
           chaos="1234:0.15:fail+nan")
run1 = _serving_storm()
faults.reset()  # restart the schedule's call counters
run2 = _serving_storm()
set_config(chaos="", serve_retry_backoff=0.01)
check(run1 == run2,
      f"serving chaos outcome vectors diverged:\n  {run1}\n  {run2}")
check(any(t != "ok" for t in run1),
      "serving chaos never fired (schedule dead at the serve.* sites)")
check(any(t == "ok" for t in run1),
      "serving chaos drowned every request (schedule should leave "
      "survivors at this rate)")
n_faulted = sum(1 for t in run1 if t != "ok")
print(f"  24-request storm x2: identical outcomes, "
      f"{n_faulted}/24 chaos-faulted ({sorted(set(run1))})")

# -- leg 5: disarmed overhead -------------------------------------------------

print("== chaos gate: collective_timeout=0 (disarmed) overhead on the "
      "20-fit microbench ==")
from oap_mllib_tpu.utils import recovery  # noqa: E402

set_config(collective_timeout=0.0, crash_dir="", chaos="")
xs = rng.normal(size=(128, 8)).astype(np.float32)
KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)  # warm
t0 = time.perf_counter()
for _ in range(20):
    KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)
fit_wall = time.perf_counter() - t0

# the disarmed seam: one config check + the inline fn call.  Price 100
# dispatch seams per fit — an overestimate — 2000 times, and scale.
reps = 2000
noop = (lambda: None)
t0 = time.perf_counter()
for _ in range(reps):
    for _ in range(100):
        recovery.guarded_dispatch("psum", "data", noop)
seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
pct = 100.0 * seam_wall / fit_wall
print(f"  20-fit wall {fit_wall*1e3:.1f} ms; disarmed seam cost "
      f"{seam_wall*1e3:.3f} ms (~{pct:.2f}%)")
check(seam_wall < max(0.01 * fit_wall, 0.005),
      f"disarmed collective-deadline seam measurable: {seam_wall:.4f}s "
      f"vs {fit_wall:.4f}s fit wall (>{pct:.1f}%)")

if failures:
    print(f"\nchaos gate: {len(failures)} failure(s)")
    sys.exit(1)
print("\nchaos gate: OK")
