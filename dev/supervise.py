#!/usr/bin/env python
"""Supervised-relaunch driver: the live-world recovery loop end to end.

One file, two roles:

- **Supervisor** (default): builds a world of ``--procs`` worker
  processes (each a ``--worker`` invocation of this same file), arms the
  recovery plane (crash-record sideband + collective deadlines +
  ``resume=auto`` checkpointing) and supervises them under the restart
  budget — classify, relaunch, shrink — via
  ``utils/supervisor.Supervisor``.  Prints ``SUPERVISOR <json>`` (the
  machine-readable run summary) and each final worker ``RESULT`` line;
  exits nonzero when the budget ran out.

- **Worker** (``--worker RANK WORLD COORD``): one rank of the world —
  joins the jax.distributed rendezvous (world > 1), streams its shard of
  a deterministic K-Means dataset with checkpointing armed, and prints
  ``RESULT <json>`` (cost, bit-exact centers, checkpoint decision,
  resilience ladder).  Drill hooks via env:

  - ``SUPERVISE_KILL_RANK`` / ``SUPERVISE_KILL_WALK`` — that rank
    SIGKILLs itself mid-read of the given source walk (a preemption);
    by default only on attempt 0 (``SUPERVISE_KILL_SCOPE=first``), or on
    every multi-process attempt (``=multi`` — forces the supervisor to
    shrink past it).
  - ``OAP_MLLIB_TPU_CHAOS`` — the seeded chaos schedule (the supervisor
    re-seeds it per attempt).

Examples::

    # 2-process world, kill rank 1 mid-fit once, watch it resume
    python dev/supervise.py --procs 2 --checkpoint-dir /tmp/ck \\
        --crash-dir /tmp/crash --kill-rank 1

    # chaos drill: seeded random kills, supervised to completion
    python dev/supervise.py --procs 2 --checkpoint-dir /tmp/ck \\
        --crash-dir /tmp/crash --chaos 7:0.01:kill:1

CI drives both through dev/chaos_gate.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ROWS, D, K, MAX_ITER, CHUNK = 3000, 8, 4, 6, 500
DATA_SEED = 321  # matches the elastic-worlds drill dataset


def _worker(rank: int, world: int, coord: str) -> int:
    """One rank: streamed K-Means over this rank's shard, checkpoint
    armed, recovery plane live.  Exit codes: 0 = RESULT printed, 17 =
    recovery-plane abort (crash record written), 3 = unexpected error."""
    local_dev = 1
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", local_dev)

    import numpy as np

    if world > 1:
        from oap_mllib_tpu.parallel import bootstrap

        if not bootstrap.initialize_distributed(coord, world, rank):
            print("failed to join world", flush=True)
            return 3

    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.utils import recovery

    # deterministic GLOBAL dataset; each rank streams a contiguous shard
    # (world-independent data, so a shrunken world resumes over the same
    # global rows — the resharded-restore parity contract)
    rng = np.random.default_rng(int(os.environ.get(
        "SUPERVISE_DATA_SEED", str(DATA_SEED))))
    x = rng.normal(size=(ROWS, D)).astype(np.float32)
    per = ROWS // world
    shard = x[rank * per: ROWS if rank == world - 1 else (rank + 1) * per]

    kill_rank = int(os.environ.get("SUPERVISE_KILL_RANK", "-1"))
    kill_walk = int(os.environ.get("SUPERVISE_KILL_WALK", "4"))
    kill_scope = os.environ.get("SUPERVISE_KILL_SCOPE", "first")
    attempt = int(os.environ.get("SUPERVISE_ATTEMPT", "0"))
    arm_kill = rank == kill_rank and (
        attempt == 0 if kill_scope == "first" else world > 1
    )
    walks = {"n": 0}

    def gen():
        walks["n"] += 1
        # walk 1 = the random-init reservoir pass; Lloyd passes are
        # walks 2+.  The victim dies mid-read of the kill walk — earlier
        # passes are durable on every rank, peers are left inside the
        # pass collective for the deadline plane to convert.
        if arm_kill and walks["n"] == kill_walk:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        for lo in range(0, shard.shape[0], CHUNK):
            yield shard[lo: lo + CHUNK]

    src = ChunkSource(gen, D, CHUNK, n_rows=shard.shape[0])
    try:
        m = KMeans(k=K, seed=7, init_mode="random", max_iter=MAX_ITER,
                   tol=0.0).fit(src)
    except recovery.RecoveryError as e:
        # crash record already written by the plane; exit promptly so
        # the supervisor can classify and relaunch
        print(f"RECOVERY_ABORT rank={rank} {type(e).__name__}: {e}",
              flush=True)
        os._exit(17)
    except Exception as e:  # noqa: BLE001 — worker boundary
        print(f"WORKER_ERROR rank={rank} {type(e).__name__}: {e}",
              flush=True)
        os._exit(3)
    ck = getattr(m.summary, "checkpoint", {}) or {}
    print("RESULT " + json.dumps({
        "rank": rank,
        "world": world,
        "cost": float(m.summary.training_cost),
        "centers_hex": np.ascontiguousarray(
            m.cluster_centers_).tobytes().hex(),
        "decision": ck.get("decision"),
        "restored_step": ck.get("restored_step"),
        "ladder": m.summary.resilience["ladder"],
    }), flush=True)
    return 0


def supervise(procs: int, checkpoint_dir: str, crash_dir: str, *,
              chaos: str = "", budget: int = 3, backoff: float = 0.2,
              shrink_after: int = 2, collective_timeout: float = 15.0,
              kill_rank: int = -1, kill_walk: int = 4,
              kill_scope: str = "first", attempt_timeout: float = 300.0):
    """Supervise one K-Means world to completion; returns
    ``(summary, Supervisor)`` — the CLI prints the summary, and
    dev/chaos_gate.py inspects the Supervisor's per-attempt exits (env-
    incapability markers ride each rank's captured output)."""
    from oap_mllib_tpu.utils.supervisor import Supervisor

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["OAP_MLLIB_TPU_CHECKPOINT_DIR"] = checkpoint_dir
    if collective_timeout:
        env["OAP_MLLIB_TPU_COLLECTIVE_TIMEOUT"] = str(collective_timeout)
    if kill_rank >= 0:
        env["SUPERVISE_KILL_RANK"] = str(kill_rank)
        env["SUPERVISE_KILL_WALK"] = str(kill_walk)
        env["SUPERVISE_KILL_SCOPE"] = kill_scope

    def build_argv(rank, world, coord, attempt):
        return [sys.executable, os.path.abspath(__file__),
                "--worker", str(rank), str(world), coord]

    sup = Supervisor(
        build_argv, procs, crash_dir, env=env, chaos=chaos,
        restart_budget=budget, restart_backoff=backoff,
        shrink_after=shrink_after, attempt_timeout=attempt_timeout,
        grace_s=max(10.0, 2 * collective_timeout),
    )
    return sup.run(), sup


def _supervise(args) -> int:
    summary, _ = supervise(
        args.procs, args.checkpoint_dir, args.crash_dir, chaos=args.chaos,
        budget=args.budget, backoff=args.backoff,
        shrink_after=args.shrink_after,
        collective_timeout=args.collective_timeout,
        kill_rank=args.kill_rank, kill_walk=args.kill_walk,
        kill_scope=args.kill_scope, attempt_timeout=args.attempt_timeout,
    )
    for out in summary["outputs"]:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                print(line, flush=True)
    print("SUPERVISOR " + json.dumps(
        {k: v for k, v in summary.items() if k != "outputs"}), flush=True)
    return 0 if summary["ok"] else 1


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        rank, world, coord = (int(sys.argv[2]), int(sys.argv[3]),
                              sys.argv[4])
        return _worker(rank, world, coord)
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--crash-dir", required=True)
    ap.add_argument("--chaos", default="",
                    help="base chaos spec (seed re-seeded +attempt)")
    ap.add_argument("--budget", type=int, default=3)
    ap.add_argument("--backoff", type=float, default=0.2)
    ap.add_argument("--shrink-after", type=int, default=2)
    ap.add_argument("--collective-timeout", type=float, default=15.0)
    ap.add_argument("--kill-rank", type=int, default=-1)
    ap.add_argument("--kill-walk", type=int, default=4)
    ap.add_argument("--kill-scope", choices=("first", "multi"),
                    default="first")
    ap.add_argument("--attempt-timeout", type=float, default=300.0)
    return _supervise(ap.parse_args())


if __name__ == "__main__":
    sys.exit(main())
