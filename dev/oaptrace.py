#!/usr/bin/env python
"""oaptrace: merge per-rank JSONL telemetry sinks into ONE Chrome
trace-event file (Perfetto-loadable) — the fleet's timeline view.

A multi-process world writes per-rank JSONL files
(``<path>.rank<r>``, telemetry/export.py).  Each carries span records
(durations only — the deterministic-accounting contract keeps wall
clocks out of the span tree) and, with the flight recorder armed
(``Config.flight_recorder``), ``flightrec`` batches whose events DO
carry a per-process monotonic clock.  This tool merges them:

- **One track per rank** (trace ``pid`` = rank; threads map to ``tid``).
- **Recorder mode** (flightrec events present): span open/close pairs
  become real "X" slices at their recorded monotonic times; chunk /
  fault / retry / checkpoint-commit events become instants.  Per-rank
  clocks are aligned via the **collective event sequence**: every rank
  issues the same host-collective sequence (the sanitizer-witnessed
  invariant), so the i-th collective event on rank r and on rank 0 are
  the same synchronization point — the median pairwise delta is the
  rank's clock offset.  Cross-rank **flow arrows** connect each
  collective's per-rank instants, so a skewed pass reads as staircased
  spans with arrows pulling the stragglers' collectives late.
- **Synthesized mode** (no recorder events): span trees are laid out
  cumulatively (children sequential inside their parent, fits
  sequential per rank, every rank's fit aligned at t=0) — shape-true,
  not clock-true; the tool says so in ``otherData``.
- **Request flows** (``type: "request"`` ledger records,
  serving/reqtrace.py): each sampled request renders as a lane of
  sequential stage slices (admission / queue_wait / batch_form /
  bucket_pad / compile / execute / dispatch) on its rank's track, with
  instants for its lifecycle events (shed / retry / poison / brownout
  / drain).  Ledger ``t0`` and recorder times share the monotonic
  clock family, so request lanes land on the recorder timeline
  clock-true; recorder-off worlds get a per-rank aligned layout.
  ``ring_hop`` recorder events (serving/sweep.py) additionally become
  cross-replica **flow arrows** per rotated item block — the sharded
  sweep's ring schedule made visible.

Usage::

    python dev/oaptrace.py /tmp/fits.jsonl -o /tmp/trace.json
    # expands /tmp/fits.jsonl.rank* siblings automatically; load the
    # output at https://ui.perfetto.dev (or chrome://tracing)
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from typing import Any, Dict, List, Tuple

US = 1e6  # trace-event timestamps are microseconds

# the fixed ledger stage order (serving/reqtrace.STAGES — kept literal
# here so the tool stays standalone); unknown stages render after these
REQUEST_STAGES = (
    "admission",
    "queue_wait",
    "batch_form",
    "bucket_pad",
    "compile",
    "execute",
    "dispatch",
)

# request lanes share a rank's pid but live on high tids so they group
# below the real threads; 16 lanes round-robined by admission seq
_REQUEST_LANE_BASE = 900_000
_REQUEST_LANES = 16


def expand_paths(paths: List[str]) -> List[str]:
    """Each argument expands to itself (if it exists) plus any
    ``<path>.rank*`` per-rank siblings — pass the base sink path and
    get the whole world."""
    out: List[str] = []
    for p in paths:
        import os

        if os.path.exists(p):
            out.append(p)
        out.extend(sorted(glob.glob(p + ".rank*")))
    seen = set()
    uniq = [p for p in out if not (p in seen or seen.add(p))]
    if not uniq:
        raise FileNotFoundError(f"no JSONL sink files match {paths}")
    return uniq


def load_records(paths: List[str]) -> List[Dict[str, Any]]:
    records = []
    for path in paths:
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise ValueError(f"{path}:{i}: unparsable JSONL: {e}")
    return records


def _rank_events(records) -> Dict[int, List[Dict[str, Any]]]:
    """rank -> flightrec events in seq order (merged across batches)."""
    per: Dict[int, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("type") != "flightrec":
            continue
        per.setdefault(int(rec.get("rank", 0)), []).extend(
            rec.get("events", [])
        )
    for ev in per.values():
        ev.sort(key=lambda e: e["seq"])
    return per


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    if n == 0:
        return 0.0
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def _clock_offsets(per_rank) -> Dict[int, float]:
    """Per-rank clock offset vs the lowest rank, from the collective
    event sequence: collective i on rank r == collective i on the
    reference rank (same dispatch — the rank-uniform-sequence
    invariant), so the median of their time deltas is the offset."""
    ranks = sorted(per_rank)
    if not ranks:
        return {}
    ref = ranks[0]
    ref_coll = [e for e in per_rank[ref] if e["kind"] == "collective"]
    offsets = {ref: 0.0}
    for r in ranks[1:]:
        coll = [e for e in per_rank[r] if e["kind"] == "collective"]
        n = min(len(coll), len(ref_coll))
        if n == 0:
            offsets[r] = 0.0
            continue
        offsets[r] = _median(
            [coll[i]["t"] - ref_coll[i]["t"] for i in range(n)]
        )
    return offsets


def _parse_kv(detail: str) -> Dict[str, str]:
    """``"rank=0 hop=1 block=1"`` -> dict (ring_hop detail format)."""
    out: Dict[str, str] = {}
    for part in detail.split():
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = v
    return out


def _recorder_trace(per_rank, offsets=None, t0=None) -> List[Dict[str, Any]]:
    """Trace events from real recorder events (clock-true mode)."""
    if offsets is None:
        offsets = _clock_offsets(per_rank)
    if t0 is None:
        t0 = min(
            e["t"] - offsets[r]
            for r, evs in per_rank.items() for e in evs
        )
    out: List[Dict[str, Any]] = []

    def ts(r, t):
        return round((t - offsets[r] - t0) * US, 1)

    flow_id = 0
    coll_index: Dict[int, int] = {}  # rank -> collectives seen so far
    flows: Dict[int, List[Tuple[int, int, float, str]]] = {}
    # ring-hop flow members: (sweep occurrence, item block) ->
    # [(hop, rank, tid, t)] — the deterministic ring schedule means
    # block b sits on rank (b - t) mod world at hop t, so chaining a
    # block's members in hop order draws its rotation across replicas
    ring: Dict[Tuple[int, int], List[Tuple[int, int, int, float]]] = {}
    rank_sweeps: Dict[int, int] = {}  # rank -> hop-0 events seen
    for r, events in sorted(per_rank.items()):
        # span open/close pairing per (thread) — unmatched events (ring
        # wrap-around ate the partner) are dropped, slices must nest
        stacks: Dict[int, List[Tuple[str, float]]] = {}
        for e in events:
            tid = int(e.get("tid", 0)) % 1_000_000
            kind = e["kind"]
            if kind == "span_open":
                stacks.setdefault(tid, []).append((e["name"], e["t"]))
            elif kind == "span_close":
                stack = stacks.get(tid, [])
                while stack:
                    name, t_open = stack.pop()
                    if name == e["name"]:
                        out.append({
                            "name": name, "ph": "X", "cat": "span",
                            "ts": ts(r, t_open),
                            "dur": round((e["t"] - t_open) * US, 1),
                            "pid": r, "tid": tid,
                        })
                        break
            elif kind == "collective":
                i = coll_index.get(r, 0)
                coll_index[r] = i + 1
                flows.setdefault(i, []).append(
                    (r, int(e.get("tid", 0)) % 1_000_000, e["t"],
                     e["name"])
                )
                out.append({
                    "name": f"collective:{e['name']}", "ph": "i",
                    "s": "p", "cat": "collective", "ts": ts(r, e["t"]),
                    "pid": r, "tid": tid,
                    "args": {"detail": e.get("detail", ""), "seq": e["seq"]},
                })
            else:  # chunk / fault / retry / serve / request / ring_hop / ...
                if kind == "ring_hop":
                    kv = _parse_kv(e.get("detail", ""))
                    hop = int(kv.get("hop", 0))
                    block = int(kv.get("block", 0))
                    if hop == 0:
                        rank_sweeps[r] = rank_sweeps.get(r, 0) + 1
                    occ = max(0, rank_sweeps.get(r, 1) - 1)
                    ring.setdefault((occ, block), []).append(
                        (hop, r, tid, e["t"])
                    )
                out.append({
                    "name": f"{kind}:{e['name']}", "ph": "i", "s": "t",
                    "cat": kind, "ts": ts(r, e["t"]),
                    "pid": r, "tid": tid,
                    "args": {"detail": e.get("detail", ""), "seq": e["seq"]},
                })
    # cross-rank flow arrows: one flow per collective index touching
    # >= 2 ranks — start on the earliest rank, finish on the others
    for i, members in sorted(flows.items()):
        if len(members) < 2:
            continue
        members = sorted(members, key=lambda m: m[2] - offsets[m[0]])
        r0, tid0, t_start, op = members[0]
        out.append({
            "name": f"collective:{op}", "ph": "s", "cat": "collective",
            "id": flow_id, "ts": ts(r0, t_start), "pid": r0, "tid": tid0,
        })
        for r, tid, t, _ in members[1:]:
            out.append({
                "name": f"collective:{op}", "ph": "f", "bp": "e",
                "cat": "collective", "id": flow_id, "ts": ts(r, t),
                "pid": r, "tid": tid,
            })
        flow_id += 1
    # ring-hop flow arrows: one chain per rotated item block, hop
    # order — start where the block begins, step ("t") through the
    # intermediate replicas, finish on its last holder
    for (occ, block), members in sorted(ring.items()):
        if len(members) < 2:
            continue
        members.sort(key=lambda m: m[0])
        name = f"ring:block{block}"
        _, r0, tid0, t_first = members[0]
        out.append({
            "name": name, "ph": "s", "cat": "ring_hop", "id": flow_id,
            "ts": ts(r0, t_first), "pid": r0, "tid": tid0,
        })
        for _, r, tid, t in members[1:-1]:
            out.append({
                "name": name, "ph": "t", "cat": "ring_hop",
                "id": flow_id, "ts": ts(r, t), "pid": r, "tid": tid,
            })
        _, rn, tidn, t_last = members[-1]
        out.append({
            "name": name, "ph": "f", "bp": "e", "cat": "ring_hop",
            "id": flow_id, "ts": ts(rn, t_last), "pid": rn, "tid": tidn,
        })
        flow_id += 1
    return out


def _request_records(records) -> Dict[int, List[Dict[str, Any]]]:
    """rank -> finalized request-ledger records, admission order."""
    per: Dict[int, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("type") != "request":
            continue
        per.setdefault(int(rec.get("rank", 0)), []).append(rec)
    for recs in per.values():
        recs.sort(key=lambda rec: rec.get("t0", 0.0))
    return per


def _request_trace(per_rank_reqs, offsets,
                   t0: float) -> List[Dict[str, Any]]:
    """Request lanes: each ledger renders as sequential stage slices
    from its ``t0`` (the stages sum to the wall by construction, so
    the lane IS the request's deadline budget), plus instants for its
    lifecycle events.  Lanes are high tids on the owning rank's track
    (16 lanes, round-robined by admission seq)."""
    out: List[Dict[str, Any]] = []
    for r, recs in sorted(per_rank_reqs.items()):
        off = offsets.get(r, 0.0)
        for rec in recs:
            lane = _REQUEST_LANE_BASE + int(
                rec.get("seq", 0)
            ) % _REQUEST_LANES
            stages = rec.get("stages", {}) or {}
            order = [s for s in REQUEST_STAGES if s in stages]
            order += [s for s in stages if s not in REQUEST_STAGES]
            cursor = float(rec.get("t0", 0.0))
            args = {
                "trace_id": rec.get("trace_id", ""),
                "outcome": rec.get("outcome", ""),
                "model": rec.get("model", ""),
                "retries": rec.get("retries", 0),
            }
            for s in order:
                dur = float(stages.get(s, 0.0))
                if dur <= 0.0:
                    continue
                out.append({
                    "name": s, "ph": "X", "cat": "request",
                    "ts": round((cursor - off - t0) * US, 1),
                    "dur": round(dur * US, 1),
                    "pid": r, "tid": lane, "args": args,
                })
                cursor += dur
            for ev in rec.get("events", []) or []:
                out.append({
                    "name": f"request:{ev.get('kind', 'event')}",
                    "ph": "i", "s": "t", "cat": "request",
                    "ts": round(
                        (float(ev.get("t", cursor)) - off - t0) * US, 1
                    ),
                    "pid": r, "tid": lane,
                    "args": {
                        "detail": ev.get("detail", ""),
                        "trace_id": rec.get("trace_id", ""),
                    },
                })
    return out


def _synthesized_trace(records) -> List[Dict[str, Any]]:
    """Shape-true layout from span records alone (recorder off): one
    fit batch at a time per rank, children sequential inside parents."""
    # batches: consecutive span records per rank, flushed at each
    # "metrics" record (export.emit_fit writes one batch per fit)
    batches: Dict[int, List[List[Dict[str, Any]]]] = {}
    open_batch: Dict[int, List[Dict[str, Any]]] = {}
    for rec in records:
        r = int(rec.get("rank", 0))
        if rec.get("type") == "span":
            open_batch.setdefault(r, []).append(rec)
        elif rec.get("type") == "metrics" and open_batch.get(r):
            batches.setdefault(r, []).append(open_batch.pop(r))
    for r, batch in open_batch.items():
        if batch:
            batches.setdefault(r, []).append(batch)
    out: List[Dict[str, Any]] = []
    for r, fit_batches in sorted(batches.items()):
        cursor = 0.0  # rank-local layout clock, seconds
        for batch in fit_batches:
            starts: Dict[str, float] = {}
            child_cursor: Dict[str, float] = {}
            for rec in batch:  # depth-first order (export walks the tree)
                path = rec["path"]
                parent = path.rsplit("/", 1)[0] if "/" in path else None
                if parent is None:
                    start = cursor
                else:
                    base = starts.get(parent, cursor)
                    start = child_cursor.get(parent, base)
                    child_cursor[parent] = start + rec["duration_s"]
                starts[path] = start
                child_cursor.setdefault(path, start)
                out.append({
                    "name": rec["name"], "ph": "X", "cat": "span",
                    "ts": round(start * US, 1),
                    "dur": round(rec["duration_s"] * US, 1),
                    "pid": r, "tid": 0,
                    "args": {"path": path, "count": rec.get("count", 0)},
                })
            roots = [rec for rec in batch if "/" not in rec["path"]]
            cursor += (roots[0]["duration_s"] if roots else 0.0) + 1e-3
    return out


def merge_trace(paths: List[str]) -> Dict[str, Any]:
    """The merged Chrome trace object for a set of JSONL sink files."""
    records = load_records(paths)
    per_rank = _rank_events(records)
    reqs = _request_records(records)
    mode = "recorder" if per_rank else "synthesized"
    if per_rank:
        offsets = _clock_offsets(per_rank)
        t0 = min(
            e["t"] - offsets[r]
            for r, evs in per_rank.items() for e in evs
        )
        # request ledgers share the recorder's monotonic clock family —
        # widen the origin so an early admission never goes negative
        req_t0s = [
            rec["t0"] - offsets.get(r, 0.0)
            for r, recs in reqs.items() for rec in recs
            if isinstance(rec.get("t0"), (int, float))
        ]
        if req_t0s:
            t0 = min(t0, min(req_t0s))
        events = _recorder_trace(per_rank, offsets, t0)
        events += _request_trace(reqs, offsets, t0)
    else:
        events = _synthesized_trace(records)
        if reqs:
            # no recorder clock to align against: lay each rank's
            # request lanes out from its own earliest admission
            for r, recs in reqs.items():
                r_t0 = min(
                    (rec.get("t0", 0.0) for rec in recs), default=0.0
                )
                events += _request_trace({r: recs}, {r: 0.0}, r_t0)
    ranks = sorted(
        {int(r.get("rank", 0)) for r in records}
        | set(per_rank) | set(reqs)
    )
    meta = [
        {
            "name": "process_name", "ph": "M", "pid": r, "tid": 0,
            "args": {"name": f"rank {r}"},
        }
        for r in ranks
    ]
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "oaptrace",
            "mode": mode,
            "ranks": ranks,
            "requests": sum(len(v) for v in reqs.values()),
            "clock": (
                "per-rank monotonic clocks aligned via the collective "
                "event sequence" if mode == "recorder"
                else "synthesized layout (durations only — arm "
                     "Config.flight_recorder for clock-true timelines)"
            ),
            "sources": list(paths),
        },
    }


def validate_trace(trace: Dict[str, Any]) -> List[str]:
    """Chrome trace-event schema check (the fleet gate's contract):
    returns problems, [] when loadable."""
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    known_ph = {"X", "B", "E", "i", "I", "s", "t", "f", "M", "C"}
    for i, e in enumerate(events):
        for key in ("name", "ph", "pid", "tid"):
            if key not in e:
                problems.append(f"event #{i} missing {key!r}: {e}")
                break
        else:
            if e["ph"] not in known_ph:
                problems.append(f"event #{i} unknown ph {e['ph']!r}")
            if e["ph"] != "M" and not isinstance(
                    e.get("ts"), (int, float)):
                problems.append(f"event #{i} non-numeric ts")
            if e["ph"] == "X" and not isinstance(
                    e.get("dur"), (int, float)):
                problems.append(f"event #{i} X without dur")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sinks", nargs="+",
                    help="JSONL sink path(s); <path>.rank* siblings are "
                         "merged in automatically")
    ap.add_argument("-o", "--out", default="oaptrace.json",
                    help="output Chrome trace file (default %(default)s)")
    args = ap.parse_args(argv)
    paths = expand_paths(args.sinks)
    trace = merge_trace(paths)
    problems = validate_trace(trace)
    if problems:
        for p in problems[:20]:
            print(f"oaptrace: INVALID: {p}", file=sys.stderr)
        return 1
    with open(args.out, "w") as f:
        json.dump(trace, f)
    n = len(trace["traceEvents"])
    print(
        f"oaptrace: wrote {args.out} ({n} events, "
        f"{len(trace['otherData']['ranks'])} rank track(s), "
        f"{trace['otherData']['mode']} mode) — load at "
        "https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
