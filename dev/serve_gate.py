#!/usr/bin/env python
"""CI gate: the serving plane serves fast, exact, and compile-free.

Legs (ISSUE 13 acceptance):

1. **Parity** — registry-served results are bit-identical to direct
   model calls (K-Means/ALS ids + score bits) and <= 1e-6 (PCA) —
   served scoring must never drift from the model surface.
2. **Zero steady-state compiles** — after a bucket-family warmup, a
   50-request jittered-size storm compiles ZERO new XLA programs
   (ground truth via ``progcache.xla_compile_count``), with every
   answer matching the NumPy oracle.
3. **Full-sweep scale** — ``recommend_for_all_users`` over a 10M-user
   synthetic factor table completes with host memory bounded by
   output + O(chunk) (peak-RSS bound far under the quadratic score
   matrix), with exact parity on sampled rows.
4. **Sharded sweep** — the ring-merged factor-sharded sweep on the
   8-device pseudo-mesh exactly matches the single-device reference.
5. **Tail latency** — the request-storm microbench's p99 stays within
   bound of its p50 (no compile or upload spikes hiding in the tail).
6. **Disarmed seam** — the serving plane's only hook in the non-serving
   path (the identity-keyed device-pin check in model scoring) prices
   at <1% of the 20-predict microbench.
7. **Storm under eviction** (ISSUE 16) — a REAL 2-replica fleet runs a
   jittered storm through the async TrafficQueue while rank 1 is
   SIGKILLed mid-storm: the survivor must evict the fleet, keep the
   zero-steady-compile and p99-vs-p50 contracts in local-only mode,
   and shed loudly (one shed of each reason).  Hosts that cannot form
   a multiprocess jax world at all (the tests' _ENV_FAILURE_MARKERS
   signatures) WARN and skip the leg instead of failing the gate.
8. **Poison bisection** (ISSUE 18) — a NaN-payload request coalesced
   with innocents is isolated by log2 bisection: exactly one
   quarantine (``oap_serve_poison_total``), every innocent answered
   bit-identically, and ZERO new XLA compiles (the halves re-coalesce
   on the warmed bucket family).
9. **Graceful drain** (ISSUE 18) — ``TrafficQueue.drain`` answers
   every pending future, books ``oap_serve_drains_total`` exactly
   once, and the drained queue sheds new admissions with
   ``reason="draining"``.
10. **Brownout ladder** (ISSUE 18) — sustained 2x over-budget pressure
    walks the auto ladder exactly topk -> bf16 -> stale (3 steps
    booked), absorbing breaches at active rungs; the bf16 rung flips
    the serving precision policy only where a parity bound exists, and
    a pinned rung halves top-k depth.
11. **Request-lifecycle chaos drill** (ISSUE 18) — a REAL 2-replica
    fleet under a 220-request storm with armed ``serve.dispatch``
    transients, an injected ``serve.batch`` poison, real NaN-payload
    requests, and rank 1 SIGKILLed mid-storm: the survivor resolves
    EVERY accepted future (answered bit-identically or classified),
    quarantines exactly the poison payloads, retries the transients,
    compiles nothing in steady state, then re-forms the sharded sweep
    on its local layout with bit-identical answers.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import os
import resource
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

failures = []


def check(ok, msg):
    if not ok:
        failures.append(msg)
        print(f"FAIL: {msg}")


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 8)

    from oap_mllib_tpu import serving
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.fallback.kmeans_np import predict_np
    from oap_mllib_tpu.models.als import ALS, ALSModel
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.models.pca import PCA
    from oap_mllib_tpu.serving import sweep as sweep_mod
    from oap_mllib_tpu.utils import progcache

    rng = np.random.default_rng(11)

    # -- leg 1: served vs direct parity --------------------------------------
    print("== serve gate: served-vs-direct parity (3 estimators) ==")
    x = rng.normal(size=(500, 16)).astype(np.float32)
    km = KMeans(k=6, seed=3, max_iter=4).fit(x)
    hk = serving.serve(km)
    check(np.array_equal(hk.predict(x[:123]), km.predict(x[:123])),
          "served K-Means ids != direct predict")

    pca = PCA(k=4).fit(x)
    hp = serving.serve(pca)
    dev = np.abs(hp.transform(x[:77]) - pca.transform(x[:77])).max()
    check(dev <= 1e-6, f"served PCA projection deviates {dev:.2e}")

    u = rng.integers(0, 80, size=4000)
    i = rng.integers(0, 64, size=4000)
    r = rng.normal(size=4000).astype(np.float32)
    als = ALS(rank=5, max_iter=2, seed=1).fit(u, i, r, n_users=80,
                                              n_items=64)
    ha = serving.serve(als)
    ids_m, s_m = als.recommend_for_all_users(7, with_scores=True)
    ids_h, s_h = ha.recommend_for_all_users(7, with_scores=True)
    check(np.array_equal(ids_m, ids_h), "served ALS sweep ids != model")
    check(np.array_equal(s_m, s_h), "served ALS sweep scores != model bits")

    # -- leg 2: zero steady-state compiles under a jittered storm ------------
    print("== serve gate: 50-request jittered-size storm, zero XLA "
          "compiles after warmup ==")
    storm_x = rng.normal(size=(1024, 16)).astype(np.float32)
    hk.warmup(1024)
    oracle_centers = km.cluster_centers_.astype(np.float64)
    before = progcache.xla_compile_count()
    for s in rng.integers(1, 1024, size=50):
        s = int(s)
        ids = hk.predict(storm_x[:s])
        expect = predict_np(
            storm_x[:s].astype(np.float64), oracle_centers, "euclidean"
        )
        if not np.array_equal(ids, expect):
            check(False, f"storm answer diverged at size {s}")
            break
    storm_compiles = progcache.xla_compile_count() - before
    print(f"  storm XLA compiles: {storm_compiles}")
    check(storm_compiles == 0,
          f"jittered storm compiled {storm_compiles} new XLA programs "
          "(steady state must be 0)")

    # -- leg 3: 10M-user full sweep, bounded host memory ---------------------
    big = int(os.environ.get("SERVE_GATE_SWEEP_USERS", 10_000_000))
    print(f"== serve gate: {big:,}-user full-sweep top-k "
          "(streamed + prefetched, no quadratic score matrix) ==")
    nu, ni, rk, topk = big, 64, 4, 2
    uf = rng.normal(size=(nu, rk)).astype(np.float32)
    itf = rng.normal(size=(ni, rk)).astype(np.float32)
    big_model = ALSModel(uf, itf)
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    t0 = time.perf_counter()
    ids = sweep_mod.recommend_for_all_users(big_model, topk)
    wall = time.perf_counter() - t0
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    grew_mb = max(0, rss1 - rss0) / 1024.0
    print(f"  {nu:,} users in {wall:.1f}s "
          f"({nu / wall / 1e6:.2f}M users/sec), peak-RSS growth "
          f"{grew_mb:.0f} MB")
    check(ids.shape == (nu, topk), f"sweep shape {ids.shape}")
    # quadratic scores would be nu x ni x 4 B (2.4 GB at 10M x 64);
    # the streamed sweep's growth is output + chunks — bound well under
    quad_mb = nu * ni * 4 / 1024 / 1024
    bound_mb = 0.5 * quad_mb
    check(grew_mb < bound_mb,
          f"sweep grew RSS {grew_mb:.0f} MB (>= {bound_mb:.0f} MB — "
          "the quadratic score matrix may be materializing)")
    sample = rng.integers(0, nu, size=32)
    expect = np.argsort(-(uf[sample] @ itf.T), axis=1,
                        kind="stable")[:, :topk]
    check(np.array_equal(ids[sample], expect),
          "10M sweep sampled rows diverge from the direct top-k")
    del uf, itf, big_model, ids

    # -- leg 4: factor-sharded ring sweep on the 8-device pseudo-mesh --------
    print("== serve gate: ring-merged sharded sweep parity "
          "(8-device pseudo-mesh) ==")
    set_config(als_item_layout="sharded")
    m_sh = ALS(rank=6, max_iter=2, seed=2).fit(
        rng.integers(0, 200, size=6000), rng.integers(0, 96, size=6000),
        rng.normal(size=6000).astype(np.float32),
        n_users=200, n_items=96,
    )
    set_config(als_item_layout="auto")
    check(m_sh._sharded_user is not None and m_sh._sharded_item is not None,
          "sharded fixture did not produce a block-sharded model")
    ids_sh, s_sh = sweep_mod.recommend_for_all_users(
        m_sh, 7, with_scores=True
    )
    ref = ALSModel(np.array(m_sh.user_factors_),
                   np.array(m_sh.item_factors_))
    ids_ref, s_ref = ref._top_k_scores(ref.user_factors_,
                                       ref.item_factors_, 7)
    check(np.array_equal(ids_sh, ids_ref),
          "sharded ring sweep ids != single-device reference")
    check(np.array_equal(s_sh, s_ref),
          "sharded ring sweep score bits != single-device reference")

    # -- leg 5: tail latency bound on the request-storm microbench -----------
    print("== serve gate: p99-vs-p50 tail bound on the storm microbench ==")
    import bench

    res = bench.bench_serving(requests=100, sweep_users=100_000,
                              emit=False)
    p50, p99 = res["p50_s"], res["p99_s"]
    print(f"  p50 {p50 * 1e3:.2f} ms, p99 {p99 * 1e3:.2f} ms, "
          f"qps {res['qps']:.0f}")
    check(res["steady_compiles"] == 0,
          f"microbench storm compiled {res['steady_compiles']} programs")
    # generous CI-noise bound: a compile or re-upload hiding in the
    # tail costs 100x+, scheduler jitter does not
    check(p99 <= max(50.0 * p50, 0.25),
          f"p99 {p99 * 1e3:.1f} ms breaches the tail bound "
          f"(p50 {p50 * 1e3:.1f} ms)")

    # -- leg 6: disarmed seam — the pin check prices at ~0 -------------------
    print("== serve gate: device-pin seam cost vs the 20-predict "
          "microbench ==")
    from oap_mllib_tpu.serving.registry import pin

    xs = rng.normal(size=(256, 16)).astype(np.float32)
    km.predict(xs)  # warm
    t0 = time.perf_counter()
    for _ in range(20):
        km.predict(xs)
    predict_wall = time.perf_counter() - t0
    cache = km._dev_cache
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        for _ in range(100):  # 100 seam touches per predict: a large
            pin(cache, "centers", km.cluster_centers_)  # overestimate
    seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
    pct = 100.0 * seam_wall / predict_wall
    print(f"  20-predict wall {predict_wall * 1e3:.1f} ms; seam cost "
          f"{seam_wall * 1e3:.3f} ms (~{pct:.2f}%)")
    check(seam_wall < max(0.01 * predict_wall, 0.005),
          f"pin seam cost measurable: {seam_wall:.4f}s vs "
          f"{predict_wall:.4f}s predict wall")

    # -- leg 7: storm under eviction on a REAL 2-replica fleet ---------------
    print("== serve gate: traffic-plane storm under replica eviction "
          "(2-process fleet) ==")
    _traffic_eviction_leg()

    # -- leg 8: poison-batch bisection, zero compiles ------------------------
    print("== serve gate: poison-batch bisection (quarantine + "
          "innocents + zero compiles) ==")
    from oap_mllib_tpu.serving import traffic as traffic_mod
    from oap_mllib_tpu.telemetry import metrics as tm

    traffic_mod._reset_for_tests()
    poison0 = int(tm.family_total("oap_serve_poison_total"))
    bisect0 = int(tm.family_total("oap_serve_bisect_total"))
    compiles0 = progcache.xla_compile_count()
    q8 = serving.TrafficQueue(hk, start=False)
    innocents = [storm_x[:5], storm_x[5:17], storm_x[17:47]]
    bad = np.full((7, 16), np.nan, np.float32)
    futs8 = [q8.submit(b) for b in innocents]
    fp8 = q8.submit(bad)
    q8.pump()
    q8.close()
    check(progcache.xla_compile_count() - compiles0 == 0,
          "bisection halves compiled new programs (bucket family "
          "must stay warm)")
    poison_n = int(tm.family_total("oap_serve_poison_total")) - poison0
    check(poison_n == 1, f"expected exactly 1 quarantine, got {poison_n}")
    check(int(tm.family_total("oap_serve_bisect_total")) - bisect0 >= 1,
          "poison batch was never bisected")
    exc8 = fp8.exception()
    check(isinstance(exc8, serving.ServeError)
          and exc8.reason == "poison",
          f"poison request not quarantined: {exc8!r}")
    for b, f in zip(innocents, futs8):
        if not np.array_equal(f.result(), hk.predict(b)):
            check(False, "innocent sharing the poisoned flush diverged")
            break
    print(f"  quarantined 1 of {len(innocents) + 1} coalesced requests, "
          f"0 compiles")

    # -- leg 9: graceful drain -----------------------------------------------
    print("== serve gate: graceful drain flushes every future, then "
          "sheds admissions ==")
    drains0 = int(tm.family_total("oap_serve_drains_total"))
    q9 = serving.TrafficQueue(hk, start=False)
    futs9 = [q9.submit(storm_x[:9]) for _ in range(5)]
    stats9 = q9.drain(timeout_s=5.0)
    check(stats9["drained"] and stats9["failed"] == 0,
          f"drain left failures: {stats9}")
    check(stats9["answered"] == 5,
          f"drain answered {stats9['answered']}/5 pending futures")
    check(all(f.exception() is None for f in futs9),
          "drained futures did not all answer")
    check(int(tm.family_total("oap_serve_drains_total")) - drains0 == 1,
          "oap_serve_drains_total not booked exactly once")
    try:
        q9.submit(storm_x[:3])
        check(False, "drained queue admitted a new request")
    except serving.ShedError as e:
        check(e.reason == "draining",
              f"post-drain shed reason {e.reason!r} != 'draining'")
    q9.close()
    print(f"  drained {stats9['answered']} futures, admissions shed")

    # -- leg 10: brownout ladder ---------------------------------------------
    print("== serve gate: brownout ladder steps topk -> bf16 -> stale "
          "under sustained pressure ==")
    from oap_mllib_tpu.serving import batcher as batcher_mod

    steps0 = int(tm.family_total("oap_serve_brownout_steps_total"))
    absorbed0 = int(tm.family_total("oap_serve_brownout_absorbed_total"))
    b10 = serving.BrownoutController("auto")
    for _ in range(12):
        b10.observe(200, 100)  # sustained 2x over-budget
    check(b10.rung == 3,
          f"ladder stopped at rung {b10.rung} (expected 3/stale)")
    check([s["to"] for s in b10.steps] == ["topk", "bf16", "stale"],
          f"ladder walked {[s['to'] for s in b10.steps]}")
    check(int(tm.family_total("oap_serve_brownout_steps_total"))
          - steps0 == 3, "expected exactly 3 brownout steps booked")
    check(int(tm.family_total("oap_serve_brownout_absorbed_total"))
          - absorbed0 >= 1, "no breach was absorbed at an active rung")
    set_config(serve_brownout="pin:bf16")
    traffic_mod._reset_for_tests()
    pol10 = batcher_mod.resolve_policy("kmeans").name
    check(pol10 == "bf16",
          f"bf16 rung did not flip serving precision (got {pol10!r})")
    set_config(serve_brownout="pin:topk")
    traffic_mod._reset_for_tests()
    check(serving.brownout_topk(8) == 4,
          "topk rung did not halve the sweep depth")
    set_config(serve_brownout="auto")
    traffic_mod._reset_for_tests()
    print("  ladder: topk -> bf16 -> stale, precision + depth rungs "
          "verified")

    # -- leg 11: request-lifecycle chaos drill (2-process fleet) -------------
    print("== serve gate: request-lifecycle chaos drill (retries + "
          "poison + SIGKILL on a 2-process fleet) ==")
    _traffic_drill_leg()

    if failures:
        print(f"\nserve gate: {len(failures)} failure(s)")
        return 1
    print("\nserve gate: OK")
    return 0


# environment-incapability signatures (mirrors the pseudo-cluster
# suite): a worker that died on one of these means this HOST cannot
# form a multiprocess jax world — warn + skip, not a gate failure
_ENV_FAILURE_MARKERS = (
    "Multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
    "Unable to initialize backend",
    "failed to join world",
    "DEADLINE_EXCEEDED",
    "Failed to connect to coordinator",
)


def _spawn_traffic_world(mode, nproc, crash_dir, timeout=180,
                         env_extra=None):
    """Spawn an nproc traffic-worker world and return (procs, outs),
    or None when the host cannot form a multiprocess jax world (the
    WARN-skip path).  Workers pick their own device count, so the
    gate's 8-device forcing is stripped from their environment."""
    import subprocess

    from oap_mllib_tpu.parallel.bootstrap import free_port

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "pseudo_cluster_worker_traffic.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["TRAFFIC_WORKER_MODE"] = mode
    env["TRAFFIC_CRASH_DIR"] = crash_dir
    env.update(env_extra or {})
    coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(nproc), coord, "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for out in outs:
        if any(m in out for m in _ENV_FAILURE_MARKERS):
            print("  WARN: this host cannot form a multiprocess jax "
                  "world; skipping the leg (not a gate failure)")
            return None
    return procs, outs


def _traffic_fields(out, tag):
    line = [ln for ln in out.splitlines() if ln.startswith(tag + " ")]
    if not line:
        return None
    return dict(p.split("=", 1) for p in line[-1].split()[1:])


def _traffic_eviction_leg():
    import tempfile

    with tempfile.TemporaryDirectory() as crash_dir:
        spawned = _spawn_traffic_world("evict", 2, crash_dir)
        if spawned is None:
            return
        procs, outs = spawned
        # rank 1 genuinely preempted mid-storm; rank 0 survived
        check(procs[1].returncode == -9,
              f"victim replica was not SIGKILLed:\n{outs[1][-1500:]}")
        check(procs[0].returncode == 0,
              f"survivor replica failed:\n{outs[0][-1500:]}")
        check("EVICTED rank=0" in outs[0],
              "survivor never evicted the dead replica")
        storm = _traffic_fields(outs[0], "STORM_OK rank=0")
        check(storm is not None, "survivor never finished the storm")
        if storm is not None:
            print(f"  survivor storm: p50 {storm['p50_ms']} ms, "
                  f"p99 {storm['p99_ms']} ms, "
                  f"compiles {storm['compiles']}")
            check(storm["compiles"] == "0",
                  f"storm under eviction compiled {storm['compiles']} "
                  "programs (steady state must be 0)")
            check(storm["local_only"] == "True",
                  "survivor did not flip to local-only mode")
            p50, p99 = float(storm["p50_ms"]), float(storm["p99_ms"])
            # same bound as leg 5, in ms
            check(p99 <= max(50.0 * p50, 250.0),
                  f"eviction-storm p99 {p99:.1f} ms breaches the tail "
                  f"bound (p50 {p50:.1f} ms)")
        check("SHED_OK rank=0 sheds=3" in outs[0],
              "survivor's shed legs incomplete (expected one shed of "
              "each reason: queue_full, budget, deadline)")


def _traffic_drill_leg():
    import tempfile

    with tempfile.TemporaryDirectory() as crash_dir:
        spawned = _spawn_traffic_world("drill", 2, crash_dir, timeout=300)
        if spawned is None:
            return
        procs, outs = spawned
        check(procs[1].returncode == -9,
              f"victim replica was not SIGKILLed:\n{outs[1][-1500:]}")
        check(procs[0].returncode == 0,
              f"survivor replica failed the drill:\n{outs[0][-1500:]}")
        check("EVICTED rank=0" in outs[0],
              "survivor never evicted the dead replica")
        drill = _traffic_fields(outs[0], "DRILL_OK rank=0")
        check(drill is not None,
              f"survivor never finished the drill:\n{outs[0][-1500:]}")
        if drill is not None:
            print(f"  drill: submitted {drill['submitted']}, answered "
                  f"{drill['answered']}, poison {drill['poison']}, "
                  f"retried {drill['retried']}, bisects "
                  f"{drill['bisects']}, compiles {drill['compiles']}")
            check(int(drill["submitted"]) >= 200,
                  f"drill storm too small: {drill['submitted']} < 200")
            check(drill["unresolved"] == "0",
                  f"{drill['unresolved']} accepted futures never "
                  "resolved (silent loss)")
            check(drill["poison"] == "3",
                  f"expected exactly 3 quarantines, got {drill['poison']}")
            check(int(drill["retried"]) >= 1,
                  "dispatcher transients were never retried")
            check(int(drill["bisects"]) >= 1,
                  "poison batches were never bisected")
            check(drill["compiles"] == "0",
                  f"drill compiled {drill['compiles']} programs in "
                  "steady state (must be 0)")
        reform = _traffic_fields(outs[0], "REFORM_OK rank=0")
        check(reform is not None,
              "survivor never re-formed the sharded sweep on its "
              "local layout")
        if reform is not None:
            check(int(reform["reforms"]) >= 1,
                  "oap_serve_sweep_reforms_total was never booked")
            print(f"  re-formed sweep: {reform['reforms']} reform(s), "
                  f"digest {reform['digest']}")


if __name__ == "__main__":
    sys.exit(main())
