"""A/B the ALS gather levers on the real chip.

Results recorded in BASELINE.md "Round-5 lever A/B" — both levers
rejected with data (the gather bound is per-index, not per-byte).
Re-run to reproduce; protocol follows the kernel-table slope method.

Levers, measured at the ML-1M attribution shape (6040x3706, nnz=1M,
r=10, P=256 grouped layout, user side):
  A. bf16 factor table for the gather (halves gathered BYTES; tests
     whether the measured gather bound is byte-bandwidth or per-index).
  B. hi/lo split bf16 gather (two bf16 gathers, f32-accurate sum; same
     bytes as f32 — only wins if per-GATHER overhead dominates, loses
     if per-index cost dominates).
  C. degree/src-sorted edge ordering ((dst, src)-lexsorted input ->
     ascending src ids within each group -> gather locality).

Protocol: ONE process, interleaved variants, in-jit repeat slopes with
runtime trip counts (verify-skill gotchas 3-5); standalone gather slope
AND full-iteration slope for each lever; parity of final factors vs the
f32 fit for lever A.
"""

import sys

import os

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from oap_mllib_tpu.ops import als_ops

NU, NI, NNZ, R = 6040, 3706, 1 << 20, 10
REG, ALPHA = 0.1, 40.0


def best_of(fn, reps=3):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def slope(run, r1, r2, reps=3):
    run(r1)  # compile+warm
    t1 = best_of(lambda: run(r1), reps)
    t2 = best_of(lambda: run(r2), reps)
    return (t2 - t1) / (r2 - r1)


def main():
    rng = np.random.default_rng(0)
    u = rng.integers(0, NU, NNZ).astype(np.int64)
    i = rng.integers(0, NI, NNZ).astype(np.int64)
    r = (rng.random(NNZ) * 4 + 1).astype(np.float32)

    # unsorted (input-order) grouped layout, user side
    by_u = als_ops.build_grouped_edges(u, i, r, NU)
    # (dst, src)-lexsorted input -> ascending src within groups
    order = np.lexsort((i, u))
    by_u_sorted = als_ops.build_grouped_edges(
        u[order], i[order], r[order], NU
    )
    src_g = jnp.asarray(by_u[0])
    src_g_sorted = jnp.asarray(by_u_sorted[0])
    G, P = by_u[0].shape
    print(f"grouped layout: G={G} P={P} padded={G*P} "
          f"({G*P/NNZ:.2f}x nnz)", flush=True)

    table = jnp.asarray((rng.normal(size=(NI, R)) * 0.1).astype(np.float32))

    # ---- standalone gather slopes -------------------------------------
    @jax.jit
    def g_f32(idx, reps):
        def body(k, acc):
            t2 = table * (1.0 + acc[0] * 0.0)
            ys = t2.T[:, idx]
            return acc + ys.sum(axis=(1, 2))
        return lax.fori_loop(0, reps, body, jnp.zeros((R,), jnp.float32))

    table_bf = table.astype(jnp.bfloat16)

    @jax.jit
    def g_bf16(idx, reps):
        def body(k, acc):
            t2 = table_bf * (1.0 + acc[0] * 0.0).astype(jnp.bfloat16)
            ys = t2.T[:, idx].astype(jnp.float32)
            return acc + ys.sum(axis=(1, 2))
        return lax.fori_loop(0, reps, body, jnp.zeros((R,), jnp.float32))

    hi = table.astype(jnp.bfloat16)
    lo = (table - hi.astype(jnp.float32)).astype(jnp.bfloat16)

    @jax.jit
    def g_hilo(idx, reps):
        def body(k, acc):
            s = (1.0 + acc[0] * 0.0).astype(jnp.bfloat16)
            ys = (hi * s).T[:, idx].astype(jnp.float32) + \
                 (lo * s).T[:, idx].astype(jnp.float32)
            return acc + ys.sum(axis=(1, 2))
        return lax.fori_loop(0, reps, body, jnp.zeros((R,), jnp.float32))

    r1, r2 = 8, 128
    res = {}
    # interleaved rounds
    for name, fn, idx in [
        ("f32", g_f32, src_g), ("bf16", g_bf16, src_g),
        ("hilo", g_hilo, src_g), ("f32_sorted", g_f32, src_g_sorted),
        ("bf16_sorted", g_bf16, src_g_sorted),
    ]:
        s = slope(lambda reps, f=fn, ix=idx: np.asarray(f(ix, reps)), r1, r2)
        res[name] = s * 1e3
        print(f"standalone gather {name}: {s*1e3:.2f} ms", flush=True)

    # ---- full-iteration slopes ----------------------------------------
    by_i = als_ops.build_grouped_edges(i, u, r, NI)
    by_i_sorted_o = np.lexsort((u, i))
    by_i_sorted = als_ops.build_grouped_edges(
        i[by_i_sorted_o], u[by_i_sorted_o], r[by_i_sorted_o], NI
    )
    dev_u = tuple(jnp.asarray(a) for a in by_u)
    dev_i = tuple(jnp.asarray(a) for a in by_i)
    dev_us = tuple(jnp.asarray(a) for a in by_u_sorted)
    dev_is = tuple(jnp.asarray(a) for a in by_i_sorted)
    x0 = jnp.asarray((rng.normal(size=(NU, R)) * 0.1).astype(np.float32))
    y0 = jnp.asarray((rng.normal(size=(NI, R)) * 0.1).astype(np.float32))

    def run_f32(iters, du=dev_u, di=dev_i):
        return als_ops.als_run_grouped(
            *du, *di, x0, y0, NU, NI, iters, REG, ALPHA, True
        )

    # bf16-gather variant of the full loop (local copy of the kernel
    # with the table cast around the gather only — moments/solve f32)
    from functools import partial

    def moments_bf16(src_b, conf_b, valid_b, fac, alpha):
        ys = fac.astype(jnp.bfloat16).T[:, src_b].astype(jnp.float32)
        a_w = alpha * jnp.abs(conf_b) * valid_b
        pos = (conf_b > 0).astype(conf_b.dtype) * valid_b
        b_w = (1.0 + alpha * jnp.abs(conf_b)) * pos
        n_w = pos
        lhs = jnp.concatenate([ys, jnp.ones_like(conf_b)[None]], axis=0)
        rhs = jnp.concatenate([ys * a_w[None], b_w[None], n_w[None]], axis=0)
        return jnp.einsum("agp,bgp->gab", lhs, rhs,
                          precision=lax.Precision.HIGHEST)

    from oap_mllib_tpu.ops.als_ops import regularized_solve

    @partial(jax.jit, static_argnames=("iters",))
    def run_bf16(iters, du=dev_u, di=dev_i):
        eye = jnp.eye(R, dtype=jnp.float32)

        def half(grp, fac, n_dst):
            sg, cg, vg, gd = grp
            m = jax.ops.segment_sum(
                moments_bf16(sg, cg, vg, fac, ALPHA), gd,
                num_segments=n_dst, indices_are_sorted=True,
            )
            a, b, n_reg = m[:, :R, :R], m[:, :R, R], m[:, R, R + 1]
            gram = jnp.matmul(fac.T, fac, precision=lax.Precision.HIGHEST)
            return regularized_solve(a, b, n_reg, REG, eye, gram).astype(
                jnp.float32
            )

        def body(carry, _):
            x, y = carry
            x = half(du, y, NU)
            y = half(di, x, NI)
            return (x, y), None

        (x, y), _ = lax.scan(body, (x0, y0), None, length=iters)
        return x, y

    runs = {
        "iter_f32": lambda it: np.asarray(run_f32(it)[0]),
        "iter_bf16gather": lambda it: np.asarray(run_bf16(it)[0]),
        "iter_f32_srcsorted": lambda it: np.asarray(
            run_f32(it, dev_us, dev_is)[0]
        ),
    }
    # NOTE: run_f32 with static iters compiles per window; warm both
    for name, fn in runs.items():
        s = slope(fn, 4, 64)
        res[name] = s * 1e3
        print(f"full iteration {name}: {s*1e3:.2f} ms/iter", flush=True)

    # ---- parity of the bf16-gather fit --------------------------------
    xf, yf = (np.asarray(a) for a in run_f32(10))
    xb, yb = (np.asarray(a) for a in run_bf16(10))
    rel = np.abs(xb - xf) / np.maximum(np.abs(xf), 1e-6)
    print(f"bf16-gather factor parity after 10 iters: "
          f"max_rel={rel.max():.3e} p99_rel={np.percentile(rel, 99):.3e}",
          flush=True)
    # held-out-style score impact: RMS prediction delta over the edges
    pf = (xf[u] * yf[i]).sum(1)
    pb = (xb[u] * yb[i]).sum(1)
    print(f"prediction RMS delta: "
          f"{np.sqrt(np.mean((pb-pf)**2)) / np.sqrt(np.mean(pf**2)):.3e}",
          flush=True)
    print({k: round(v, 3) for k, v in res.items()})


if __name__ == "__main__":
    main()
