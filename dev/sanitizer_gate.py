#!/usr/bin/env python
"""CI gate: the SPMD dataflow analyzer + runtime sanitizer plane
(ISSUE 7) must hold their contracts.

Legs:

1. **Analyzer required-clean** — ``python dev/oaplint --json`` exits 0,
   the artifact carries zero findings, and every suppression in the
   inventory is still *used* (a stale directive is a finding by
   construction, so this doubles as a schema check on the artifact).
2. **Sanitizer legs, single-process** — for each sanitizer, a streamed
   K-Means fit on the 8-device pseudo-cluster runs clean with it armed
   (no false positives), AND the sanitizer demonstrably catches its
   seeded violation (an implicit transfer in a guarded loop, a
   mid-steady-state retrace, a divergence diagnostic with the gather
   stubbed) — positive and negative evidence per sanitizer.
3. **Sanitizer legs, pseudo-cluster** — the 2-process suite
   (tests/test_pseudo_cluster.py::TestSanitizerPlane): rank-divergent
   collective -> diagnostic instead of hang, per-shard byte booking,
   world-checked fingerprints.  Hosts that cannot form multiprocess
   jax worlds skip these (the suite's environment-incapability
   contract); everywhere else they are asserted.
4. **Sanitizers-off overhead** — the off path is one cached config
   check per seam: its measured cost over 20 fits must be unmeasurable
   next to the 20-fit K-Means microbench wall (reported next to the
   telemetry finalize cost, the PR 4 comparison point).

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


# -- leg 1: analyzer required-clean ------------------------------------------

print("== sanitizer gate: analyzer (oaplint + dataflow rules) required-clean ==")
artifact = os.path.join(tempfile.mkdtemp(), "oaplint.json")
proc = subprocess.run(
    [sys.executable, os.path.join(ROOT, "dev", "oaplint"),
     "--json", artifact],
    cwd=ROOT, capture_output=True, text=True,
)
check(proc.returncode == 0,
      f"oaplint found violations:\n{proc.stdout[-2000:]}")
with open(artifact) as f:
    payload = json.load(f)
check(payload["findings"] == [], f"artifact findings: {payload['findings']}")
check(len(payload["suppressions"]) > 0,
      "suppression inventory missing from --json artifact")
stale = [s for s in payload["suppressions"] if not s["used"]]
check(stale == [], f"stale suppressions shipped: {stale}")
reasonless = [s for s in payload["suppressions"] if not s["reason"]]
check(reasonless == [], f"reasonless suppressions: {reasonless}")

# -- leg 2: per-sanitizer single-process legs --------------------------------

from oap_mllib_tpu.config import set_config  # noqa: E402
from oap_mllib_tpu.data.stream import ChunkSource  # noqa: E402
from oap_mllib_tpu.models.kmeans import KMeans  # noqa: E402
from oap_mllib_tpu.utils import sanitizers as san  # noqa: E402

rng = np.random.default_rng(11)
x = rng.normal(size=(1024, 8)).astype(np.float32)


def _streamed_fit():
    return KMeans(k=4, seed=3, max_iter=3).fit(
        ChunkSource.from_array(x, chunk_rows=256)
    )


baseline_cost = _streamed_fit().summary.training_cost

for name in san.VALID:
    print(f"== sanitizer gate: '{name}' leg (streamed fit must run clean) ==")
    set_config(sanitizers=name)
    m = _streamed_fit()
    check(m.summary.training_cost == baseline_cost,
          f"{name}: sanitized fit diverged from baseline cost")
    check(m.summary.sanitizers["enabled"] == [name],
          f"{name}: summary does not record the armed set")
set_config(sanitizers="")

print("== sanitizer gate: seeded violations are caught ==")
# transfer: implicit host->device in a guarded chunk loop
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from oap_mllib_tpu.data.prefetch import Prefetcher  # noqa: E402

set_config(sanitizers="transfer")
caught = False
try:
    with Prefetcher([jnp.ones((4, 4))] * 2) as pf:
        for c in pf:
            _ = c + np.ones((4, 4), np.float32)
except Exception:
    caught = True
check(caught, "transfer sanitizer missed an implicit in-loop transfer")

# retrace: a steady-state scope that compiles
set_config(sanitizers="retrace")
f = jax.jit(lambda a: a * 2)
f(jnp.ones((3,)))
caught = False
try:
    with san.steady_state("gate"):
        f(jnp.ones((5,)))
except san.RetraceError:
    caught = True
check(caught, "retrace sanitizer missed a steady-state compile")

# collective: divergence diagnostic names both ops (gather stubbed here;
# the real 2-process pairing is leg 3)
set_config(sanitizers="collective")
orig_world, orig_gather = san._world, san._gather_frames
san._world = lambda: 2
san._gather_frames = lambda frame: [
    frame.rstrip(b"\x00"), b"op:allgather_rows|data|(4, 4)|float32:full",
]
caught = ""
try:
    san.note_collective("allreduce_sum", "data", (4, 4), "float32")
except san.CollectiveDivergenceError as e:
    caught = str(e)
finally:
    san._world, san._gather_frames = orig_world, orig_gather
    san._reset_for_tests()
check("allreduce_sum" in caught and "allgather_rows" in caught,
      f"collective divergence diagnostic incomplete: {caught[:200]}")
set_config(sanitizers="")

# -- leg 3: pseudo-cluster sanitizer legs ------------------------------------

print("== sanitizer gate: 2-process pseudo-cluster legs (skip if the host "
      "cannot form multiprocess worlds) ==")
proc = subprocess.run(
    [sys.executable, "-m", "pytest",
     "tests/test_pseudo_cluster.py::TestSanitizerPlane", "-q",
     "-p", "no:cacheprovider"],
    cwd=ROOT, capture_output=True, text=True, timeout=600,
)
print(proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "")
check(proc.returncode == 0,
      f"pseudo-cluster sanitizer legs failed:\n{proc.stdout[-2000:]}")

# -- leg 4: sanitizers-off overhead ------------------------------------------

print("== sanitizer gate: sanitizers-off overhead on the 20-fit microbench ==")
xs = rng.normal(size=(128, 8)).astype(np.float32)
KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)  # warm
t0 = time.perf_counter()
for _ in range(20):
    KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(xs)
fit_wall = time.perf_counter() - t0

# the off path per fit: a handful of enabled() checks (prefetch passes,
# facade dispatches) + one finalize hook.  Price 100 seam touches per
# fit — an overestimate of the real count — 2000 times, and scale.
reps = 2000
t0 = time.perf_counter()
for _ in range(reps):
    for _ in range(100):
        san.enabled("transfer")
    san.finalize_fit_sanitizers(None)
seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
pct = 100.0 * seam_wall / fit_wall
print(f"  20-fit wall {fit_wall*1e3:.1f} ms; off-path seam cost "
      f"{seam_wall*1e3:.3f} ms (~{pct:.2f}% — the telemetry-off "
      "finalize cost for comparison is ~100 us/fit, docs/observability.md)")
check(seam_wall < max(0.01 * fit_wall, 0.005),
      f"sanitizers-off seam cost measurable: {seam_wall:.4f}s vs "
      f"{fit_wall:.4f}s fit wall")

if failures:
    print(f"\nsanitizer gate: {len(failures)} failure(s)")
    sys.exit(1)
print("\nsanitizer gate: OK")
