#!/usr/bin/env python
"""Back-compat shim: the style gate moved into dev/oaplint (PR 6).

The stdlib style checks that lived here (syntax, unused imports, tabs,
trailing whitespace, final newline, line length) are oaplint rules now,
running alongside the subsystem-contract rules — one entry point, one
output format, one CI gate (`python dev/oaplint`).  This shim keeps
`python dev/lint.py` working for muscle memory and old scripts.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from oaplint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    print("dev/lint.py is now dev/oaplint (style + contract rules); "
          "forwarding.", file=sys.stderr)
    sys.exit(main())
