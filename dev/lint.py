#!/usr/bin/env python
"""Style gate (the scalastyle/clang-format analog — the reference FAILS the
build on style violations, mllib-dal/pom.xml:303).

This image ships no ruff/flake8/clang-format and installs are forbidden, so
the always-on gate is this stdlib linter; dev/ci.sh additionally runs ruff
and clang-format (configs live in pyproject.toml / native/.clang-format)
whenever those binaries exist.

Checks — Python (.py): syntax (ast parse), unused imports (skipped for
__init__.py re-export manifests and names in __all__), tabs, trailing
whitespace, missing final newline, lines > MAX_LEN.  C++ (.cpp/.h): tabs,
trailing whitespace, missing final newline, lines > MAX_LEN.

Exit code 1 on any finding; prints file:line: rule: detail.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LEN = 100
ROOT = Path(__file__).resolve().parent.parent
PY_DIRS = ["oap_mllib_tpu", "tests", "tests_tpu", "examples", "dev"]
PY_FILES = ["bench.py", "__graft_entry__.py"]
CPP_DIRS = ["oap_mllib_tpu/native/src"]
SKIP_PARTS = {"build", "__pycache__", ".git"}


def _iter_files():
    for d in PY_DIRS:
        for p in sorted((ROOT / d).rglob("*.py")):
            if not SKIP_PARTS & set(p.parts):
                yield p, "py"
    for f in PY_FILES:
        yield ROOT / f, "py"
    for d in CPP_DIRS:
        base = ROOT / d
        for pat in ("*.cpp", "*.h"):
            for p in sorted(base.rglob(pat)):
                if not SKIP_PARTS & set(p.parts):
                    yield p, "cpp"


def _names_used(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # leftmost name of dotted access (np.zeros -> np)
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
    # __all__ entries and annotations-as-strings count as uses
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)
    return used


def _unused_imports(tree: ast.AST):
    used = _names_used(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                if bound not in used:
                    yield node.lineno, a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":  # future statement, not a binding
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                if bound not in used:
                    yield node.lineno, f"{node.module}.{a.name}"


def lint_file(path: Path, kind: str):
    findings = []
    try:
        text = path.read_text()
    except OSError as e:
        return [(path, 0, "io", str(e))]
    rel = path.relative_to(ROOT)
    if text and not text.endswith("\n"):
        findings.append((rel, len(text.splitlines()), "final-newline", "missing"))
    for i, line in enumerate(text.splitlines(), 1):
        if line.rstrip("\r\n") != line.rstrip():
            findings.append((rel, i, "trailing-whitespace", line.rstrip()[-20:]))
        if "\t" in line:
            findings.append((rel, i, "tab", "use spaces"))
        if len(line) > MAX_LEN:
            findings.append((rel, i, "line-length", f"{len(line)} > {MAX_LEN}"))
    if kind == "py":
        try:
            tree = ast.parse(text, filename=str(path))
        except SyntaxError as e:
            findings.append((rel, e.lineno or 0, "syntax", e.msg))
            return findings
        if path.name != "__init__.py":
            for lineno, name in _unused_imports(tree):
                # "# noqa" opt-out, matching the common-tool convention
                src_line = text.splitlines()[lineno - 1]
                if "noqa" not in src_line:
                    findings.append((rel, lineno, "unused-import", name))
    return findings


def main() -> int:
    all_findings = []
    n_files = 0
    for path, kind in _iter_files():
        n_files += 1
        all_findings.extend(lint_file(path, kind))
    for rel, line, rule, detail in all_findings:
        print(f"{rel}:{line}: {rule}: {detail}")
    if all_findings:
        print(f"lint: {len(all_findings)} finding(s) in {n_files} files")
        return 1
    print(f"lint: OK ({n_files} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
