#!/usr/bin/env python
"""Perf-trajectory regression gate: the newest BENCH_r<NN>.json vs the
best prior round, per headline metric.

Every driver-captured bench round lands as ``BENCH_r<NN>.json``
({"n": round, "tail": last stdout lines, "parsed": last JSON metric
line}).  The trajectory only helps if someone reads it — this gate does:
for every metric in the NEWEST round it finds the best value any PRIOR
round recorded for the same metric name and fails (exit 1) on a >10%
regression, naming the metric and the diff.  Direction comes from the
unit: ``*/sec`` rates are higher-is-better, ``sec*`` walls are
lower-is-better.

Soft-gate semantics: with only one recorded round (or a metric with no
prior — e.g. a renamed headline or a new backend's proxy metric) there
is nothing to regress against, so it WARNS and exits 0.  Metrics are
compared strictly by name, so CPU-proxy headlines
(``*_cpuproxy``, bench.py on accelerator-less hosts) never get diffed
against accelerator rounds.

Usage: python dev/bench_regress.py [--dir REPO_ROOT] [--threshold 0.10]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def find_rounds(root: str) -> List[Tuple[int, str]]:
    """[(round number, path)] sorted ascending."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def metrics_of(path: str) -> Dict[str, Dict]:
    """metric name -> line dict, from ``parsed`` (dict or list) plus any
    JSON metric lines embedded in ``tail`` — rounds whose driver only
    parsed the last line still contribute every line they captured."""
    with open(path) as f:
        rec = json.load(f)
    lines: List[Dict] = []
    parsed = rec.get("parsed")
    if isinstance(parsed, dict):
        lines.append(parsed)
    elif isinstance(parsed, list):
        lines.extend(p for p in parsed if isinstance(p, dict))
    for raw in str(rec.get("tail", "")).splitlines():
        raw = raw.strip()
        if raw.startswith("{"):
            try:
                lines.append(json.loads(raw))
            except json.JSONDecodeError:
                pass
    out: Dict[Tuple[str, str], Dict] = {}
    for line in lines:
        name = line.get("metric")
        if name and isinstance(line.get("value"), (int, float)):
            # keyed by (metric, backend): rounds captured on different
            # backends are different trajectories — never diffed.
            # Legacy rounds without a backend field land in "unknown"
            # and only ever compare with each other.
            out[(str(name), str(line.get("backend", "unknown")))] = line
    return out


def higher_is_better(unit: str) -> bool:
    """Rates (iters/sec, rows/sec, QPS) improve upward; walls (sec,
    sec/iter, sec/pass) improve downward."""
    unit = (unit or "").lower()
    if "/sec" in unit or unit.endswith("ps"):
        return True
    return not unit.startswith("sec")


def compare(root: str, threshold: float):
    """(failures, warnings, report lines) for the newest round."""
    rounds = find_rounds(root)
    if not rounds:
        return [], ["no BENCH_r*.json rounds recorded yet"], []
    newest_n, newest_path = rounds[-1]
    newest = metrics_of(newest_path)
    # (metric, backend) -> (best value, round, unit)
    prior: Dict[Tuple[str, str], Tuple[float, int, str]] = {}
    for n, path in rounds[:-1]:
        for key, line in metrics_of(path).items():
            v, unit = float(line["value"]), str(line.get("unit", ""))
            best = prior.get(key)
            if best is None:
                prior[key] = (v, n, unit)
            else:
                better = (
                    v > best[0] if higher_is_better(unit) else v < best[0]
                )
                if better:
                    prior[key] = (v, n, unit)
    failures, warnings, report = [], [], []
    if len(rounds) < 2:
        warnings.append(
            f"only one bench round recorded (r{newest_n:02d}) — nothing "
            "to regress against; gate is warn-only"
        )
    for key, line in sorted(newest.items()):
        name = f"{key[0]}[{key[1]}]"
        v, unit = float(line["value"]), str(line.get("unit", ""))
        if key not in prior:
            warnings.append(
                f"{name}: no prior round records this metric on this "
                "backend (new headline or new backend) — skipped"
            )
            continue
        best, best_n, _ = prior[key]
        hib = higher_is_better(unit)
        if best == 0:
            continue
        change = (v - best) / abs(best)
        regress = -change if hib else change
        arrow = f"{v:.4g} vs best r{best_n:02d}={best:.4g} {unit}"
        if regress > threshold:
            failures.append(
                f"{name}: REGRESSION {regress:+.1%} beyond the "
                f"{threshold:.0%} gate ({arrow})"
            )
        else:
            report.append(f"{name}: ok ({change:+.1%}; {arrow})")
    return failures, warnings, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="allowed fractional regression (default 10%%)")
    args = ap.parse_args(argv)
    failures, warnings, report = compare(args.dir, args.threshold)
    for line in report:
        print(f"  {line}")
    for w in warnings:
        print(f"  WARN: {w}")
    if failures:
        for fline in failures:
            print(f"  FAIL: {fline}")
        print(f"bench regression gate: {len(failures)} regression(s)")
        return 1
    print("bench regression gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
