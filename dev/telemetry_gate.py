#!/usr/bin/env python
"""CI gate: the telemetry layer must tell the truth.

Runs one fit per estimator surface on the 8-device CPU pseudo-cluster —
K-Means in-memory, K-Means streamed, PCA in-memory, ALS (block-parallel
on the pseudo-mesh) — with the JSONL sink armed and fallback disabled
(the accelerated path must actually run), then asserts:

- every JSONL line parses, and each fit's span records reproduce
  exactly the span tree attached to that fit's summary (paths AND
  durations);
- span trees have the expected shape per estimator (kmeans.fit ->
  table_convert/init_centers/lloyd_loop, streamed lloyd_loop ->
  stage/transfer/compute/stream_wall, pca.fit -> covariance + a solver
  phase, als.fit -> table_convert + als_iterations);
- required metrics are present and consistent: XLA compiles were
  counted (the monitoring-event ground truth), the streamed fit moved
  its rows through the prefetch counters, the pseudo-mesh ALS fit drove
  the collective facade (nonzero op count), and the resilience counters
  are zero on this fault-free run — in the registry AND in each fit's
  summary.

Exit 1 with the offending evidence on any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

failures = []


def check(ok: bool, what: str) -> None:
    if not ok:
        failures.append(what)
        print(f"FAIL: {what}")


def span_index(tree: dict, prefix: str = "") -> dict:
    """{path: node} over a summary's span tree."""
    path = prefix + tree["name"]
    out = {path: tree}
    for c in tree.get("children", []):
        out.update(span_index(c, path + "/"))
    return out


def read_new_lines(path: str, offset: int):
    with open(path) as f:
        text = f.read()
    lines = [ln for ln in text.splitlines()[offset:] if ln]
    records = []
    for i, ln in enumerate(lines):
        try:
            records.append(json.loads(ln))
        except json.JSONDecodeError as e:
            check(False, f"JSONL line {offset + i} does not parse: {e}")
    return records, offset + len(lines)


def get_summary_field(summary, key):
    return summary.get(key) if isinstance(summary, dict) else getattr(
        summary, key, None
    )


def verify_fit(name, summary, records, expect_children, expect_sub=()):
    tele = get_summary_field(summary, "telemetry")
    check(tele is not None, f"{name}: summary exposes no telemetry")
    if tele is None:
        return
    tree = tele["spans"]
    check(tree["name"] == name, f"{name}: root span is {tree['name']!r}")
    idx = span_index(tree)
    for child in expect_children:
        check(
            f"{name}/{child}" in idx,
            f"{name}: missing expected phase span {child!r} "
            f"(has {sorted(idx)})",
        )
    for sub in expect_sub:
        check(
            f"{name}/{sub}" in idx,
            f"{name}: missing expected streamed sub-span {sub!r}",
        )
    # the JSONL batch for this fit must reproduce the summary tree
    span_recs = {
        r["path"]: r for r in records
        if r["type"] == "span" and r["fit"] == name
    }
    check(
        set(span_recs) == set(idx),
        f"{name}: JSONL span paths != summary span paths "
        f"(jsonl-only: {sorted(set(span_recs) - set(idx))}, "
        f"summary-only: {sorted(set(idx) - set(span_recs))})",
    )
    for path, rec in span_recs.items():
        if path in idx:
            check(
                abs(rec["duration_s"] - idx[path]["duration_s"]) < 1e-9,
                f"{name}: {path} duration differs between JSONL and summary",
            )
    metrics_recs = [
        r for r in records if r["type"] == "metrics" and r.get("fit") == name
    ]
    check(
        len(metrics_recs) == 1,
        f"{name}: expected exactly one metrics record in the fit batch, "
        f"got {len(metrics_recs)}",
    )
    # fault-free run: resilience counters must be zero in the summary
    res = get_summary_field(summary, "resilience")
    if res is not None:
        check(
            res["faults"] == 0 and res["retries"] == 0
            and res["degradations"] == 0,
            f"{name}: nonzero resilience counters on a fault-free run: {res}",
        )
    return metrics_recs[0]["metrics"] if metrics_recs else None


def series_total(snap, metric):
    return sum(
        (v["sum"] if isinstance(v, dict) else v)
        for v in snap.get(metric, {}).values()
    )


def main() -> int:
    from oap_mllib_tpu import ALS, KMeans, PCA, set_config, telemetry
    from oap_mllib_tpu.data.stream import ChunkSource

    sink = os.path.join(
        tempfile.mkdtemp(prefix="oap-telemetry-gate-"), "telemetry.jsonl"
    )
    set_config(fallback=False, telemetry_log=sink)
    rng = np.random.default_rng(0)
    offset = 0

    # -- K-Means in-memory ---------------------------------------------------
    x = rng.normal(size=(512, 8)).astype(np.float32)
    m = KMeans(k=4, max_iter=4, seed=0).fit(x)
    records, offset = read_new_lines(sink, offset)
    verify_fit(
        "kmeans.fit", m.summary, records,
        ("table_convert", "init_centers", "lloyd_loop"),
    )

    # -- K-Means streamed ----------------------------------------------------
    src = ChunkSource.from_array(x, chunk_rows=128)
    ms = KMeans(k=4, max_iter=4, seed=0).fit(src)
    records, offset = read_new_lines(sink, offset)
    snap = verify_fit(
        "kmeans.fit", ms.summary, records,
        ("init_centers", "lloyd_loop"),
        expect_sub=(
            "lloyd_loop/stage", "lloyd_loop/transfer",
            "lloyd_loop/compute", "lloyd_loop/stream_wall",
        ),
    )
    if snap is not None:
        check(
            series_total(snap, "oap_prefetch_chunks_total") > 0,
            "streamed fit recorded no prefetch chunks",
        )
        check(
            series_total(snap, "oap_stream_rows_total") > 0,
            "streamed fit recorded no staged rows",
        )

    # -- PCA -----------------------------------------------------------------
    p = PCA(k=3).fit(x)
    records, offset = read_new_lines(sink, offset)
    verify_fit(
        "pca.fit", p.summary, records, ("table_convert", "covariance")
    )
    solver = p.summary.get("pca_solver")
    tele = p.summary["telemetry"]
    check(
        any(
            path.endswith("eigh") or path.endswith("randomized_topk")
            for path in span_index(tele["spans"])
        ),
        f"pca.fit: no solver span for solver={solver!r}",
    )

    # -- ALS on the pseudo-mesh (collective facade must fire) ----------------
    before_coll = series_total(
        telemetry.snapshot(), "oap_collective_ops_total"
    )
    nnz = 4000
    u = rng.integers(0, 64, nnz)
    i = rng.integers(0, 48, nnz)
    r = (rng.random(nnz) * 4 + 1).astype(np.float32)
    a = ALS(rank=4, max_iter=2, seed=0).fit(u, i, r)
    records, offset = read_new_lines(sink, offset)
    snap = verify_fit(
        "als.fit", a.summary, records, ("table_convert", "als_iterations")
    )
    check(
        bool(a.summary.get("block_parallel")),
        "als fit did not take the block-parallel (pseudo-mesh) path",
    )
    if snap is not None:
        after_coll = series_total(snap, "oap_collective_ops_total")
        check(
            after_coll > before_coll,
            "pseudo-mesh ALS fit drove no collective facade ops "
            f"(before={before_coll}, after={after_coll})",
        )
        check(
            series_total(snap, "oap_collective_bytes_total") > 0,
            "collective facade counted no payload bytes",
        )

    # -- process-wide registry invariants ------------------------------------
    snap = telemetry.snapshot()
    check(
        series_total(snap, "oap_xla_compiles_total") > 0,
        "no XLA backend compiles counted across four accelerated fits",
    )
    check(
        series_total(snap, "oap_resilience_faults_total") == 0,
        "resilience fault counter nonzero on a fault-free gate run",
    )
    check(
        series_total(snap, "oap_fit_total") == 4,
        f"expected 4 finalized fits, registry says "
        f"{series_total(snap, 'oap_fit_total')}",
    )
    # the Prometheus dump must render and carry the headline families
    prom = telemetry.render_prometheus()
    for family in (
        "oap_fit_seconds_bucket", "oap_progcache_", "oap_collective_ops_total",
    ):
        check(family in prom, f"prometheus rendering lacks {family}")

    print(f"telemetry gate: {'FAIL' if failures else 'OK'} "
          f"({offset} JSONL records, sink={sink})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
