#!/usr/bin/env python
"""CI gate: memory-budget-governed scale (ISSUE 12).

Legs, all deterministic on any host:

1. **Route decisions under synthetic budgets** — the planner picks
   in-memory on an unlimited budget and streams the SAME fit under a
   tiny HBM budget, recording the decision, every candidate's estimate,
   and the rejection reasons in ``summary.route``.
2. **Strict mode** — ``scale_policy=strict`` raises ``BudgetError`` at
   fit entry instead of degrading scale.
3. **Disk-streamed parity** — a fit from a disk-backed ``.npy``
   ChunkSource is BIT-identical to the same streamed fit from memory
   (K-Means) and within 1e-6 of the in-memory route (PCA).
4. **Kill-mid-spill relaunch-resume drill** — a worker whose source
   raises a host OOM mid-fit spills to disk; a seeded SIGKILL lands on
   the 3rd spill chunk; the supervisor relaunches, the relaunched
   attempt spills cleanly, resumes from the durable checkpoint, and
   finishes BIT-identical to an uninterrupted reference run (the PR 8
   same-world continuation contract composed with the spill rung).
5. **Planner seam** — 20 plan+record cycles cost <1% of the 20-fit
   K-Means microbench wall (route planning is arithmetic, not passes).

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ROWS, D, K, MAX_ITER, CHUNK = 3000, 8, 4, 6, 500
DATA_SEED = 777
KILL_SPILL_CHUNK = 3  # SIGKILL mid-spill: the 3rd of 6 spill chunks


def _single_device_env() -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=1"
    ).strip()


def _worker(rank: int, world: int, coord: str) -> int:
    """One drill worker: streamed K-Means whose source raises a host
    OOM at walk 2 (once per process) — the spill rung fires; checkpoint
    + spill dirs from env; attempt 0 arms a SIGKILL on spill chunk 3."""
    _single_device_env()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.kmeans import KMeans

    attempt = int(os.environ.get("SUPERVISE_ATTEMPT", "0"))
    spec = ""
    if attempt == 0 and os.environ.get("OOMGATE_KILL") == "1":
        spec = f"spill.write:kill={KILL_SPILL_CHUNK}"
    set_config(
        checkpoint_dir=os.environ["OOMGATE_CKPT"],
        spill_dir=os.environ["OOMGATE_SPILL"],
        fault_spec=spec,
        retry_backoff=0.001,
    )

    rng = np.random.default_rng(DATA_SEED)
    x = rng.normal(size=(ROWS, D)).astype(np.float32)
    oomed = {"fired": False}
    walks = {"n": 0}

    def gen():
        walks["n"] += 1
        # walk 1 = the reservoir init pass, walk 2 = Lloyd pass 1
        # (checkpointed when it completes); the host OOM lands at the
        # START of walk 3, once per process, so the spill (and the
        # attempt-0 kill mid-spill) happen with a durable checkpoint
        # behind them — the relaunch must resume AND re-spill.  The
        # message deliberately avoids the device-OOM markers: a bare
        # MemoryError is the HOST class (the spill rung).
        if walks["n"] == 3 and not oomed["fired"]:
            oomed["fired"] = True
            raise MemoryError("synthetic host memory exhaustion")
        for lo in range(0, ROWS, CHUNK):
            yield x[lo: lo + CHUNK]

    src = ChunkSource(gen, D, CHUNK, n_rows=ROWS)
    try:
        m = KMeans(k=K, seed=7, init_mode="random", max_iter=MAX_ITER,
                   tol=0.0).fit(src)
    except Exception as e:  # noqa: BLE001 — the gate reads the record
        print(f"worker failed: {e!r}", flush=True)
        return 3
    centers = np.ascontiguousarray(m.cluster_centers_, np.float32)
    print("RESULT " + json.dumps({
        "sha": hashlib.sha256(centers.tobytes()).hexdigest(),
        "cost": float(m.summary.training_cost),
        "route": m.summary.route["route"],
        "spilled": bool(m.summary.route.get("spilled", False)),
        "ckpt_decision": m.summary.checkpoint.get("decision", "fresh"),
    }), flush=True)
    return 0


def _reference_run(tmp: str) -> dict:
    """The uninterrupted run: same worker, no kill, its own dirs."""
    import subprocess

    env = dict(os.environ)
    env["OOMGATE_CKPT"] = os.path.join(tmp, "ckpt-ref")
    env["OOMGATE_SPILL"] = os.path.join(tmp, "spill-ref")
    env["SUPERVISE_ATTEMPT"] = "1"  # never arms the kill
    env.pop("OOMGATE_KILL", None)
    os.makedirs(env["OOMGATE_SPILL"], exist_ok=True)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", "0", "1",
         ""],
        env=env, capture_output=True, text=True, timeout=300,
    )
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError(
        f"reference run printed no RESULT: {out.stdout}\n{out.stderr}"
    )


def main() -> int:
    import time

    import numpy as np

    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.data.stream import ChunkSource
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.models.pca import PCA
    from oap_mllib_tpu.utils import membudget as mb

    failures = []
    report = {}

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    rng = np.random.default_rng(DATA_SEED)
    # well-separated blobs: the streamed and in-memory init RNG streams
    # legitimately differ, but both converge to the same optimum
    proto = rng.normal(size=(3, 8)).astype(np.float32) * 4.0
    x = (proto[rng.integers(3, size=1200)]
         + rng.normal(size=(1200, 8)).astype(np.float32) * 0.2)
    xs = (rng.normal(size=(1200, 8))
          @ np.diag([5, 4, 3, 2, 1, .5, .3, .1])).astype(np.float32)

    # -- leg 1: deterministic route decisions under synthetic budgets --------
    set_config(memory_budget_hbm="unlimited",
               memory_budget_host="unlimited", scale_policy="auto")
    m_big = KMeans(k=3, seed=1, max_iter=20).fit(x)
    set_config(memory_budget_hbm="3M")
    m_small = KMeans(k=3, seed=1, max_iter=20).fit(x)
    report["routes"] = {
        "unlimited": m_big.summary.route["route"],
        "3M": m_small.summary.route["route"],
    }
    check(m_big.summary.route["route"] == "in-memory",
          f"unlimited budget routed {m_big.summary.route['route']}, "
          "expected in-memory")
    check(m_small.summary.route["route"] == "streamed",
          f"3M budget routed {m_small.summary.route['route']}, "
          "expected streamed")
    check(m_small.summary.route.get("degraded_scale") is True,
          "budget-forced reroute not flagged degraded_scale")
    rejected = [e for e in m_small.summary.route["estimates"]
                if e.get("reject")]
    check(len(rejected) >= 1, "no rejection reasons recorded")
    np.testing.assert_allclose(
        m_small.summary.training_cost, m_big.summary.training_cost,
        rtol=1e-4,
    )

    # -- leg 2: strict raises instead of degrading ---------------------------
    set_config(scale_policy="strict")
    try:
        KMeans(k=3, seed=1, max_iter=2).fit(x)
        check(False, "strict mode did NOT raise on an over-budget fit")
    except mb.BudgetError:
        pass
    set_config(memory_budget_hbm="unlimited", scale_policy="auto")

    # -- leg 3: disk-streamed parity ----------------------------------------
    tmp = tempfile.mkdtemp(prefix="oom-gate.")
    npy = os.path.join(tmp, "x.npy")
    np.save(npy, x)
    m_mem = KMeans(k=3, seed=5, max_iter=5).fit(
        ChunkSource.from_array(x, chunk_rows=256)
    )
    m_disk = KMeans(k=3, seed=5, max_iter=5).fit(
        ChunkSource.from_npy(npy, chunk_rows=256)
    )
    bit_dev = float(np.abs(
        m_disk.cluster_centers_ - m_mem.cluster_centers_
    ).max())
    report["disk_bit_dev"] = bit_dev
    check(bit_dev == 0.0,
          f"disk-streamed K-Means deviates {bit_dev} from "
          "memory-streamed (must be bit-identical)")
    np.save(os.path.join(tmp, "xs.npy"), xs)
    p_mem = PCA(k=3).fit(xs)
    p_disk = PCA(k=3).fit(
        ChunkSource.from_npy(os.path.join(tmp, "xs.npy"), chunk_rows=256)
    )
    pca_dev = float(max(
        np.abs(np.abs(p_disk.components_) - np.abs(p_mem.components_)
               ).max(),
        np.abs(p_disk.explained_variance_ - p_mem.explained_variance_
               ).max(),
    ))
    report["pca_disk_vs_inmem_dev"] = pca_dev
    check(pca_dev <= 1e-6,
          f"disk-streamed PCA deviates {pca_dev:.2e} from the in-memory "
          "route (> 1e-6)")

    # -- leg 4: seeded kill-mid-spill relaunch-resume drill ------------------
    from oap_mllib_tpu.utils.supervisor import Supervisor

    ref = _reference_run(tmp)
    report["reference"] = ref
    check(ref["spilled"] and ref["route"] == "streamed",
          f"reference run did not spill+stream: {ref}")
    check(ref["ckpt_decision"] == "found",
          "reference run's post-spill attempt did not resume from its "
          f"own checkpoint: {ref['ckpt_decision']}")
    drill_env = dict(os.environ)
    drill_env["OOMGATE_CKPT"] = os.path.join(tmp, "ckpt-drill")
    drill_env["OOMGATE_SPILL"] = os.path.join(tmp, "spill-drill")
    drill_env["OOMGATE_KILL"] = "1"
    os.makedirs(drill_env["OOMGATE_SPILL"], exist_ok=True)
    sup = Supervisor(
        lambda rank, world, coord, attempt: [
            sys.executable, os.path.abspath(__file__), "--worker",
            str(rank), str(world), coord,
        ],
        1, os.path.join(tmp, "crash"), env=drill_env,
        restart_budget=3, restart_backoff=0.1, attempt_timeout=300.0,
    )
    summary = sup.run()
    report["drill"] = {
        "ok": summary["ok"], "attempts": len(summary["attempts"]),
        "first_attempt": summary["attempts"][0] if summary["attempts"]
        else None,
    }
    check(summary["ok"], f"supervised drill did not complete: {summary}")
    check(len(summary["attempts"]) == 2,
          f"expected exactly 2 attempts (kill + relaunch), got "
          f"{len(summary['attempts'])}")
    if summary["attempts"]:
        first = summary["attempts"][0]
        kinds = [e.get("classification") for e in first.get("exits", [])]
        check("killed" in kinds,
              f"first attempt not classified killed: {first}")
    drill = None
    for out in summary.get("outputs", []):
        for ln in str(out).splitlines():
            if ln.startswith("RESULT "):
                drill = json.loads(ln[len("RESULT "):])
    report["drill_result"] = drill
    check(drill is not None, "drill printed no RESULT line")
    if drill is not None:
        check(drill["sha"] == ref["sha"],
              f"kill-mid-spill resume NOT bit-identical: drill sha "
              f"{drill['sha'][:12]} vs reference {ref['sha'][:12]}")
        check(drill["spilled"], "relaunched attempt did not spill")
        check(drill["ckpt_decision"] == "found",
              f"relaunched attempt did not resume from the checkpoint: "
              f"{drill['ckpt_decision']}")

    # -- leg 5: planner seam <1% of the 20-fit microbench --------------------
    set_config(memory_budget_hbm="", memory_budget_host="")
    xb = rng.normal(size=(512, 16)).astype(np.float32)
    KMeans(k=4, seed=1, max_iter=3).fit(xb)  # warm the caches
    t0 = time.perf_counter()
    for _ in range(20):
        KMeans(k=4, seed=1, max_iter=3).fit(xb)
    fit_wall = time.perf_counter() - t0
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        plan = mb.plan_kmeans(512, 16, 4, row_chunks_hint=1)
        mb.record_plan({"timings": None}, plan)
    seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
    pct = 100.0 * seam_wall / fit_wall
    report["seam"] = {"fit_wall_s": round(fit_wall, 4),
                      "seam_wall_s": round(seam_wall, 6),
                      "pct": round(pct, 3)}
    check(seam_wall < max(0.01 * fit_wall, 0.005),
          f"planner seam measurable: {seam_wall:.4f}s vs 20-fit wall "
          f"{fit_wall:.3f}s (~{pct:.2f}%)")

    print(json.dumps(report), flush=True)
    for f in failures:
        print(f"FAIL: {f}")
    print(f"oom gate: {'FAIL' if failures else 'OK'}")
    return 1 if failures else 0


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        sys.exit(_worker(int(sys.argv[2]), int(sys.argv[3]),
                         sys.argv[4] if len(sys.argv) > 4 else ""))
    sys.exit(main())
