#!/usr/bin/env python
"""CI gate: request tracing accounts for every wall and the SLO plane
witnesses it — without taxing the untraced path.

Legs (ISSUE 19 acceptance):

1. **Attribution sums to wall** — a jittered storm through the async
   TrafficQueue with ``serve_trace_sample=1.0``: every answered future
   carries a finalized ledger whose stages sum to the request wall
   within 5%, the zero-steady-compile and p99-vs-p50 contracts hold
   WITH tracing armed, and ``serving_summary()`` gains attribution +
   slo blocks.
2. **Deterministic sampling** — the sampled-id set at
   ``serve_trace_sample=0.37`` is a pure hash of the trace id: a fresh
   subprocess recomputes the identical decisions (no RNG anywhere).
3. **Burn under breach** — a fake-clock SLOEngine fed an induced
   latency breach moves both burn-rate windows above 1.0, flips the
   multi-window breach flag, drains the error budget, and the live
   brownout/scale decisions RECORD the SLO state that witnessed them.
4. **oaptrace merges a 2-replica trace world** — a REAL 2-process
   fleet (leg-1 sharded sweep + traced storm, flight recorder + JSONL
   sinks armed) merges through dev/oaptrace.py into a validated
   recorder-mode timeline with request lanes AND ring-hop flow arrows
   spanning both replica tracks.  Hosts that cannot form a
   multiprocess jax world WARN and skip (the serve-gate convention).
5. **Disarmed seam** — with ``serve_trace_sample=0``, the tracing
   hooks (begin / note_flush / note_event / exemplar / finalize / SLO
   observe) price at <1% of the 20-predict serving microbench.

Exit 1 with the offending numbers on any violation.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

from serve_gate import (  # noqa: E402
    _spawn_traffic_world,
    _traffic_fields,
    check,
    failures,
)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_num_cpu_devices"):
        jax.config.update("jax_num_cpu_devices", 8)

    from oap_mllib_tpu import serving
    from oap_mllib_tpu.config import set_config
    from oap_mllib_tpu.models.kmeans import KMeans
    from oap_mllib_tpu.serving import reqtrace
    from oap_mllib_tpu.serving import slo as slo_mod
    from oap_mllib_tpu.serving import traffic as traffic_mod
    from oap_mllib_tpu.telemetry import metrics as tm
    from oap_mllib_tpu.utils import progcache

    rng = np.random.default_rng(19)
    x = rng.normal(size=(1024, 16)).astype(np.float32)
    km = KMeans(k=6, seed=3, max_iter=4).fit(x[:500])
    hk = serving.serve(km)
    hk.warmup(1024)

    # -- leg 1: stages sum to wall on a jittered storm, contracts armed --
    print("== slo gate: attribution sums to wall on a traced jittered "
          "storm (sample=1.0) ==")
    set_config(serve_trace_sample=1.0, serve_slo_p99_ms=250.0)
    try:
        with serving.TrafficQueue(hk) as qw:
            for s in rng.integers(5, 512, size=12):  # warm wave
                qw.submit(x[: int(s)], deadline_ms=120_000).result(
                    timeout=60
                )
        compiles0 = progcache.xla_compile_count()
        with serving.TrafficQueue(hk) as q:
            subs = [
                (time.perf_counter(),
                 q.submit(x[: int(s)], deadline_ms=120_000))
                for s in rng.integers(5, 512, size=80)
            ]
            walls = []
            for ts, f in subs:
                f.result(timeout=120)
                walls.append(time.perf_counter() - ts)
        steady = progcache.xla_compile_count() - compiles0
        check(steady == 0,
              f"traced storm compiled {steady} programs (tracing must "
              "not perturb the zero-steady-compile contract)")
        walls.sort()
        p50, p99 = walls[len(walls) // 2], walls[-1]
        check(p99 <= max(50.0 * p50, 0.25),
              f"traced-storm p99 {p99 * 1e3:.1f} ms breaches the tail "
              f"bound (p50 {p50 * 1e3:.1f} ms)")
        ledgers = [reqtrace.ledger_of(f) for _, f in subs]
        missing = sum(
            1 for lg in ledgers if lg is None or lg.outcome != "answered"
        )
        check(missing == 0,
              f"{missing}/80 answered futures lack a finalized ledger")
        bad_cov = [
            (lg.ctx.trace_id, lg.stage_sum(), lg.wall_s)
            for lg in ledgers
            if lg is not None and lg.wall_s > 1e-6
            and abs(lg.stage_sum() - lg.wall_s) > 0.05 * lg.wall_s
        ]
        check(not bad_cov,
              f"{len(bad_cov)} ledgers miss the 5% sum-to-wall bound "
              f"(first: {bad_cov[:3]})")
        summ = serving.serving_summary()
        attr = summ.get("attribution", {})
        check(attr.get("traced", 0) >= 80,
              f"summary attribution traced={attr.get('traced')} < 80")
        check(0.95 <= attr.get("coverage", 0.0) <= 1.05,
              f"aggregate stage coverage {attr.get('coverage')} outside "
              "[0.95, 1.05]")
        check("slo" in summ and summ["slo"].get("armed") is True,
              "serving_summary() lacks an armed slo block")
        traced = int(tm.family_total("oap_serve_traced_total"))
        check(traced >= 92, f"oap_serve_traced_total {traced} < 92")
        print(f"  80-request storm: p50 {p50 * 1e3:.2f} ms, p99 "
              f"{p99 * 1e3:.2f} ms, coverage {attr.get('coverage')}, "
              f"0 compiles")
    finally:
        set_config(serve_trace_sample=0.0, serve_slo_p99_ms=0.0)
        slo_mod._reset_for_tests()

    # -- leg 2: sampling is a pure hash — identical across processes ----
    print("== slo gate: deterministic sampling across processes "
          "(sample=0.37, no RNG) ==")
    local = "".join(
        "1" if reqtrace.is_sampled(reqtrace.make_trace_id(r, s), 0.37)
        else "0"
        for r in (0, 1, 2) for s in range(400)
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = (
        "import sys; sys.path.insert(0, sys.argv[1]); "
        "from oap_mllib_tpu.serving.reqtrace import is_sampled, "
        "make_trace_id; "
        "print(''.join('1' if is_sampled(make_trace_id(r, s), 0.37) "
        "else '0' for r in (0, 1, 2) for s in range(400)))"
    )
    env = dict(os.environ)
    env.pop("PYTHONHASHSEED", None)  # the decision must not depend on it
    remote = subprocess.run(
        [sys.executable, "-c", prog, repo],
        capture_output=True, text=True, env=env, timeout=120,
    ).stdout.strip()
    check(local == remote,
          "a fresh process sampled a DIFFERENT id set (sampling must "
          "be a pure hash of the trace id)")
    frac = local.count("1") / len(local)
    check(0.25 <= frac <= 0.50,
          f"sample=0.37 selected fraction {frac:.3f} (hash badly "
          "skewed)")
    print(f"  1200 ids: {local.count('1')} sampled ({frac:.3f}), "
          "identical in a fresh process")

    # -- leg 3: induced breach moves the burn gauges; decisions record --
    print("== slo gate: multi-window burn under an induced breach, "
          "decisions record SLO state ==")
    clock = [0.0]
    eng = serving.SLOEngine(
        p99_ms=100.0, availability=0.99, window_s=600.0,
        clock=lambda: clock[0],
    )
    for _ in range(200):  # healthy baseline
        clock[0] += 0.1
        eng.observe(0.010, ok=True)
    check(eng.burn_rate(eng.fast_window_s) == 0.0,
          "healthy baseline burns error budget")
    check(eng.budget_remaining() == 1.0,
          "healthy baseline drained the error budget")
    for _ in range(50):  # the breach: every request blows the target
        clock[0] += 0.1
        eng.observe(0.500, ok=True)
    st = eng.state()
    check(st["burn_rate_fast"] > 1.0,
          f"fast burn {st['burn_rate_fast']} not > 1.0 under breach")
    check(st["burn_rate_slow"] > 1.0,
          f"slow burn {st['burn_rate_slow']} not > 1.0 under breach")
    check(st["breach"] is True, "multi-window breach flag never flipped")
    check(st["error_budget_remaining"] < 1.0,
          "error budget untouched by a 50-request breach")
    check(tm.family_total("oap_slo_burn_rate") > 1.0,
          "oap_slo_burn_rate gauges never moved under the breach")
    set_config(serve_slo_p99_ms=100.0, serve_slo_availability=0.99,
               serve_slo_window_s=600.0)
    try:
        for _ in range(20):
            slo_mod.observe_request(0.5, ok=False)
        bc = serving.BrownoutController("auto")
        for _ in range(12):
            bc.observe(200, 100)  # sustained 2x over-budget: steps fire
        check(bc.steps and all("slo" in s for s in bc.steps),
              "brownout steps do not record the witnessed SLO state")
        sc = serving.ScaleController(1)
        d = sc.observe(queue_depth=0)
        check("slo" in d and d["slo"].get("breach") is True,
              f"scale decision lacks breach-state SLO record: {d}")
        check(slo_mod.slo_state().get("armed") is True,
              "slo_state() not armed with serve_slo_p99_ms set")
    finally:
        set_config(serve_slo_p99_ms=0.0, serve_slo_availability=0.999,
                   serve_slo_window_s=3600.0, serve_brownout="auto")
        traffic_mod._reset_for_tests()
        slo_mod._reset_for_tests()
    print(f"  breach: fast burn {st['burn_rate_fast']}, slow burn "
          f"{st['burn_rate_slow']}, budget "
          f"{st['error_budget_remaining']}; decisions carry slo records")

    # -- leg 4: 2-replica trace world merges through oaptrace -----------
    print("== slo gate: 2-replica traced fleet -> oaptrace request "
          "lanes + ring-hop flow arrows ==")
    _trace_world_leg()

    # -- leg 5: disarmed seam prices at <1% of the microbench -----------
    print("== slo gate: tracing-off seam vs the 20-predict "
          "microbench ==")
    set_config(serve_trace_sample=0.0, serve_slo_p99_ms=0.0)
    xs = x[:256]
    hk.predict(xs)  # warm
    t0 = time.perf_counter()
    for _ in range(20):
        hk.predict(xs)
    predict_wall = time.perf_counter() - t0
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        # one of each disarmed hook per request — a large overestimate
        # (submit checks the knob once; the rest are misses)
        reqtrace.armed()
        reqtrace.begin(0.0, 0, 1, 0.0)
        reqtrace.note_flush("bucket_pad", 0.0)
        reqtrace.note_event("ring_hop", "", 0.0)
        reqtrace.exemplar_trace_id()
        reqtrace.finalize(None, "answered", 0.0)
        slo_mod.observe_request(0.0, True)
    seam_wall = (time.perf_counter() - t0) * (20.0 / reps)
    pct = 100.0 * seam_wall / predict_wall
    print(f"  20-predict wall {predict_wall * 1e3:.1f} ms; disarmed "
          f"hooks {seam_wall * 1e3:.3f} ms (~{pct:.2f}%)")
    check(seam_wall < max(0.01 * predict_wall, 0.005),
          f"disarmed tracing seam measurable: {seam_wall:.4f}s vs "
          f"{predict_wall:.4f}s predict wall")

    if failures:
        print(f"\nslo gate: {len(failures)} failure(s)")
        return 1
    print("\nslo gate: OK")
    return 0


def _trace_world_leg():
    with tempfile.TemporaryDirectory() as crash_dir:
        sink = os.path.join(crash_dir, "trace.jsonl")
        spawned = _spawn_traffic_world(
            "trace", 2, crash_dir, timeout=240,
            env_extra={"TRAFFIC_TRACE_SINK": sink},
        )
        if spawned is None:
            return
        procs, outs = spawned
        sweep_ok = True
        for r in range(2):
            check(procs[r].returncode == 0,
                  f"trace-world rank {r} failed:\n{outs[r][-1500:]}")
            fields = _traffic_fields(outs[r], f"TRACE_OK rank={r}")
            check(fields is not None,
                  f"rank {r} never finished the traced storm")
            if fields is not None:
                check(fields["missing"] == "0",
                      f"rank {r}: {fields['missing']} futures lack "
                      "finalized ledgers")
                check(fields["bad_cov"] == "0",
                      f"rank {r}: {fields['bad_cov']} ledgers miss the "
                      "5% sum-to-wall bound")
                check(int(fields["sampled"]) == int(fields["reqs"]),
                      f"rank {r}: sample=1.0 sampled "
                      f"{fields['sampled']}/{fields['reqs']}")
                # the worker degrades to a collective-free traced storm
                # on hosts whose backend cannot RUN sharded programs
                # (worlds form, computations don't) — ring-hop flows
                # are only expected where the sweep actually ran
                sweep_ok = sweep_ok and fields.get("sweep") == "1"
        import oaptrace

        paths = oaptrace.expand_paths([sink])
        check(len(paths) == 2, f"expected 2 per-rank sinks, got {paths}")
        trace = oaptrace.merge_trace(paths)
        problems = oaptrace.validate_trace(trace)
        check(problems == [],
              f"merged trace fails schema validation: {problems[:5]}")
        check(trace["otherData"]["mode"] == "recorder",
              "trace world merged without recorder events")
        check(trace["otherData"]["requests"] > 0,
              "no request-ledger records reached the sinks")
        lanes = [
            e for e in trace["traceEvents"]
            if e.get("cat") == "request" and e.get("ph") == "X"
        ]
        check({e["pid"] for e in lanes} == {0, 1},
              "request stage lanes missing from a replica track")
        ring = [
            e for e in trace["traceEvents"]
            if e.get("cat") == "ring_hop" and e.get("ph") in ("s", "t", "f")
        ]
        if sweep_ok:
            check(len(ring) >= 2, "no ring-hop flow arrows in the merge")
            check(len({e["pid"] for e in ring}) == 2,
                  "ring-hop flow arrows do not span both replica tracks")
            ring_note = (f"{len(ring)} ring-hop flow endpoints across "
                         "2 replica tracks")
        else:
            ring_note = ("ring hops skipped — this backend cannot run "
                         "sharded programs (tests/test_oaptrace.py "
                         "covers the flow chains synthetically)")
        print(f"  merged {trace['otherData']['requests']} request "
              f"ledgers, {len(lanes)} stage slices, {ring_note}")


if __name__ == "__main__":
    sys.exit(main())
