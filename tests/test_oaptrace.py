"""dev/oaptrace.py + dev/bench_regress.py units (ISSUE 11): merged
Chrome-trace timelines from per-rank JSONL sinks, and the perf
trajectory regression gate."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dev")
)

import bench_regress  # noqa: E402
import oaptrace  # noqa: E402


def _flightrec_record(rank, events, seq=0):
    return {
        "type": "flightrec", "rank": rank, "seq": seq,
        "events": events, "fit": "kmeans.fit",
    }


def _event(seq, t, kind, name, detail="", tid=1):
    return {"seq": seq, "t": t, "tid": tid, "kind": kind,
            "name": name, "detail": detail}


def _write_sink(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestRecorderMode:
    def _two_rank_sinks(self, tmp_path, rank1_offset=100.0, skew=0.0):
        """Two ranks running the same two-pass fit; rank 1's monotonic
        clock starts at +offset and its pass is `skew` seconds slower —
        the alignment must recover the offset from the collective
        sequence."""
        base = str(tmp_path / "fits.jsonl")
        for rank, off, lag in ((0, 0.0, 0.0), (1, rank1_offset, skew)):
            t = off + 10.0
            events = [
                _event(0, t, "span_open", "lloyd_loop"),
                _event(1, t + 0.1, "chunk", "prefetch", "#0"),
                _event(2, t + 0.4 + lag, "collective",
                       "process_allgather", "(2,2)"),
                _event(3, t + 0.5 + lag, "span_close", "lloyd_loop",
                       "0.5s"),
                _event(4, t + 0.6 + lag, "collective",
                       "process_allgather", "(2,2)"),
            ]
            _write_sink(f"{base}.rank{rank}", [
                _flightrec_record(rank, events),
                {"type": "metrics", "rank": rank, "seq": 99,
                 "metrics": {}},
            ])
        return base

    def test_merges_one_track_per_rank(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert trace["otherData"]["mode"] == "recorder"
        assert trace["otherData"]["ranks"] == [0, 1]
        pids = {e["pid"] for e in trace["traceEvents"]}
        assert pids == {0, 1}
        assert oaptrace.validate_trace(trace) == []

    def test_clock_alignment_via_collective_seqs(self, tmp_path):
        """Rank 1's raw clock is +100 s — aligned via the collective
        sequence, its span must land within the trace near rank 0's,
        not 100 s later."""
        base = self._two_rank_sinks(tmp_path, rank1_offset=100.0)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        spans = {
            e["pid"]: e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "lloyd_loop"
        }
        assert set(spans) == {0, 1}
        # identical workloads + alignment => near-identical start times
        assert abs(spans[0]["ts"] - spans[1]["ts"]) < 1e5  # < 100 ms
        assert spans[0]["dur"] == pytest.approx(0.5e6, rel=0.01)

    def test_skewed_rank_reads_staircased(self, tmp_path):
        base = self._two_rank_sinks(tmp_path, skew=1.0)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        spans = {
            e["pid"]: e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "lloyd_loop"
        }
        # the slow rank's span is visibly longer
        assert spans[1]["dur"] > spans[0]["dur"] + 0.5e6

    def test_cross_rank_flow_arrows_per_collective(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
        finishes = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
        assert len(starts) == 2  # one flow per collective index
        assert len(finishes) == 2
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        assert all(e["cat"] == "collective" for e in starts + finishes)

    def test_cli_writes_validated_file(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        out = str(tmp_path / "trace.json")
        assert oaptrace.main([base, "-o", out]) == 0
        trace = json.load(open(out))
        assert oaptrace.validate_trace(trace) == []


class TestSynthesizedMode:
    def test_span_only_sink_lays_out_tree(self, tmp_path):
        path = str(tmp_path / "solo.jsonl")
        _write_sink(path, [
            {"type": "span", "fit": "pca.fit", "path": "pca.fit",
             "name": "pca.fit", "duration_s": 1.0, "count": 1,
             "rank": 0, "seq": 0},
            {"type": "span", "fit": "pca.fit",
             "path": "pca.fit/covariance", "name": "covariance",
             "duration_s": 0.6, "count": 1, "rank": 0, "seq": 1},
            {"type": "span", "fit": "pca.fit", "path": "pca.fit/eigh",
             "name": "eigh", "duration_s": 0.4, "count": 1,
             "rank": 0, "seq": 2},
            {"type": "metrics", "rank": 0, "seq": 3, "metrics": {}},
        ])
        trace = oaptrace.merge_trace([path])
        assert trace["otherData"]["mode"] == "synthesized"
        assert oaptrace.validate_trace(trace) == []
        by_name = {e["name"]: e for e in trace["traceEvents"]
                   if e.get("ph") == "X"}
        assert by_name["pca.fit"]["ts"] == 0
        # children lay out sequentially inside the parent
        assert by_name["covariance"]["ts"] == 0
        assert by_name["eigh"]["ts"] == pytest.approx(0.6e6)

    def test_missing_files_raise(self):
        with pytest.raises(FileNotFoundError):
            oaptrace.expand_paths(["/nonexistent/sink.jsonl"])


class TestBenchRegress:
    def _round(self, tmp_path, n, metrics):
        path = str(tmp_path / f"BENCH_r{n:02d}.json")
        tail = "\n".join(json.dumps(m) for m in metrics)
        with open(path, "w") as f:
            json.dump({"n": n, "cmd": "python bench.py", "rc": 0,
                       "tail": tail, "parsed": metrics[-1]}, f)
        return path

    def _metric(self, name, value, unit="iters/sec", backend="tpu"):
        return {"metric": name, "value": value, "unit": unit,
                "backend": backend}

    def test_single_round_warns_only(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("m", 10.0)])
        failures, warnings, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert any("only one bench round" in w for w in warnings)

    def test_regression_fails_naming_metric(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("kmeans_ips", 100.0)])
        self._round(tmp_path, 2, [self._metric("kmeans_ips", 80.0)])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1
        assert "kmeans_ips" in failures[0]
        assert "REGRESSION" in failures[0]

    def test_improvement_and_small_drift_pass(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("a", 100.0),
                                  self._metric("w", 2.0, unit="sec")])
        self._round(tmp_path, 2, [self._metric("a", 95.0),
                                  self._metric("w", 1.5, unit="sec")])
        failures, _, report = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert len(report) == 2

    def test_sec_units_are_lower_is_better(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("w", 1.0, unit="sec/iter")])
        self._round(tmp_path, 2, [self._metric("w", 1.5, unit="sec/iter")])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1

    def test_best_prior_not_just_previous(self, tmp_path):
        """The gate compares against the BEST prior round, so two slow
        rounds in a row cannot ratchet the bar down."""
        self._round(tmp_path, 1, [self._metric("a", 100.0)])
        self._round(tmp_path, 2, [self._metric("a", 85.0)])
        self._round(tmp_path, 3, [self._metric("a", 85.0)])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1  # 85 vs best=100 is -15%

    def test_backends_never_cross_compare(self, tmp_path):
        self._round(tmp_path, 1, [
            self._metric("kmeans_ips", 100.0, backend="tpu")])
        self._round(tmp_path, 2, [
            self._metric("kmeans_ips_cpuproxy", 2.0, backend="cpu")])
        failures, warnings, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert any("cpuproxy" in w and "skipped" in w for w in warnings)

    def test_cli_exit_codes(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("a", 100.0)])
        assert bench_regress.main(["--dir", str(tmp_path)]) == 0
        self._round(tmp_path, 2, [self._metric("a", 50.0)])
        assert bench_regress.main(["--dir", str(tmp_path)]) == 1

    def test_repo_trajectory_is_currently_clean(self):
        """The live repo's recorded rounds must pass the gate — this is
        the tier-1 mirror of the ci.sh soft gate."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        failures, _, _ = bench_regress.compare(root, 0.10)
        assert failures == [], failures

    def test_real_fit_sink_merges(self, tmp_path):
        """End-to-end: a real streamed fit's JSONL sink (recorder armed)
        merges into a validated recorder-mode timeline."""
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        sink = str(tmp_path / "real.jsonl")
        set_config(flight_recorder=256, telemetry_log=sink)
        try:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(600, 4)).astype(np.float32)

            def gen():
                for lo in range(0, 600, 200):
                    yield x[lo:lo + 200]

            src = ChunkSource(gen, 4, 200, n_rows=600)
            KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        finally:
            set_config(flight_recorder=0, telemetry_log="")
        trace = oaptrace.merge_trace(oaptrace.expand_paths([sink]))
        assert trace["otherData"]["mode"] == "recorder"
        assert oaptrace.validate_trace(trace) == []
        assert any(e.get("ph") == "X" for e in trace["traceEvents"])


class TestRequestFlows:
    """ISSUE 19: request-ledger records merge into per-replica stage
    lanes, ring-hop recorder events become cross-replica flow arrows,
    and both survive clock alignment."""

    def _request(self, rank, seq, t0, outcome="answered", events=()):
        return {
            "type": "request", "rank": rank, "seq": seq,
            "trace_id": f"{rank:02x}-{seq:08x}", "deadline_ms": 50.0,
            "sampled": True, "t0": t0, "wall_s": 0.45,
            "outcome": outcome, "model": "kmeans", "retries": 0,
            "stages": {
                "admission": 0.05, "queue_wait": 0.1, "batch_form": 0.05,
                "bucket_pad": 0.0, "compile": 0.0, "execute": 0.2,
                "dispatch": 0.05,
            },
            "events": list(events),
        }

    def _aligned_sinks(self, tmp_path, rank1_offset=100.0):
        """Two ranks, rank 1's clock at +offset, one collective each
        for alignment, one traced request each."""
        base = str(tmp_path / "serve.jsonl")
        for rank, off in ((0, 0.0), (1, rank1_offset)):
            t = off + 10.0
            events = [
                _event(0, t, "collective", "process_allgather", "(2,3)"),
            ]
            _write_sink(f"{base}.rank{rank}", [
                _flightrec_record(rank, events),
                self._request(
                    rank, rank, t + 0.2,
                    events=[{"kind": "retry", "t": t + 0.3,
                             "detail": "n=1"}],
                ),
            ])
        return base

    def test_request_lanes_merge_clock_true(self, tmp_path):
        base = self._aligned_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert trace["otherData"]["mode"] == "recorder"
        assert trace["otherData"]["requests"] == 2
        assert oaptrace.validate_trace(trace) == []
        lanes = [e for e in trace["traceEvents"]
                 if e.get("cat") == "request" and e.get("ph") == "X"]
        assert {e["pid"] for e in lanes} == {0, 1}
        # rank 1's +100 s clock is recovered via the collective: both
        # requests land near each other on the merged timeline
        t0s = {e["pid"]: e["ts"] for e in lanes
               if e["name"] == "admission"}
        assert abs(t0s[0] - t0s[1]) < 1e5  # < 100 ms apart
        # lanes are high tids, grouped below the real threads
        assert all(e["tid"] >= 900_000 for e in lanes)

    def test_stage_slices_lay_out_in_ledger_order(self, tmp_path):
        base = str(tmp_path / "solo.jsonl")
        _write_sink(base + ".rank0",
                    [_flightrec_record(0, [_event(0, 5.0, "collective",
                                                  "g", "")]),
                     self._request(0, 3, 5.5)])
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        lane = [e for e in trace["traceEvents"]
                if e.get("cat") == "request" and e.get("ph") == "X"]
        names = [e["name"] for e in sorted(lane, key=lambda e: e["ts"])]
        # zero-duration stages are skipped; the rest keep STAGES order
        assert names == ["admission", "queue_wait", "batch_form",
                         "execute", "dispatch"]
        starts = sorted(e["ts"] for e in lane)
        durs = [e["dur"] for e in sorted(lane, key=lambda e: e["ts"])]
        for i in range(1, len(starts)):
            assert starts[i] == pytest.approx(
                starts[i - 1] + durs[i - 1], abs=0.2
            )
        args = lane[0]["args"]
        assert args["trace_id"] == "00-00000003"
        assert args["outcome"] == "answered"

    def test_lifecycle_events_become_instants(self, tmp_path):
        base = self._aligned_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        instants = [e for e in trace["traceEvents"]
                    if e.get("ph") == "i" and e.get("cat") == "request"]
        assert {e["name"] for e in instants} == {"request:retry"}
        assert {e["pid"] for e in instants} == {0, 1}

    def test_ring_hops_chain_the_right_replica_rotation_pairs(
            self, tmp_path):
        """The ring schedule: block b sits on rank (b - t) mod world at
        hop t — each block's flow must step through exactly that rank
        sequence, in hop order."""
        world = 3
        base = str(tmp_path / "ring.jsonl")
        for r in range(world):
            events = [
                _event(t, 10.0 + 0.1 * t, "ring_hop", f"hop{t}",
                       f"rank={r} hop={t} block={(r + t) % world} "
                       f"world={world}")
                for t in range(world)
            ]
            _write_sink(f"{base}.rank{r}", [_flightrec_record(r, events)])
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert oaptrace.validate_trace(trace) == []
        flows = {}
        for e in trace["traceEvents"]:
            if e.get("cat") == "ring_hop" and e.get("ph") in ("s", "t",
                                                             "f"):
                flows.setdefault(e["name"], []).append(e)
        assert set(flows) == {f"ring:block{b}" for b in range(world)}
        for b in range(world):
            chain = sorted(flows[f"ring:block{b}"], key=lambda e: e["ts"])
            assert [e["ph"] for e in chain] == ["s", "t", "f"]
            assert [e["pid"] for e in chain] == [
                (b - t) % world for t in range(world)
            ]
            assert len({e["id"] for e in chain}) == 1
        # the per-hop instants still render alongside the flows
        assert sum(
            1 for e in trace["traceEvents"]
            if e.get("ph") == "i" and e.get("cat") == "ring_hop"
        ) == world * world

    def test_second_sweep_occurrence_gets_its_own_flows(self, tmp_path):
        """hop=0 restarts an occurrence counter: two sweeps on one rank
        pair up independently instead of cross-linking."""
        base = str(tmp_path / "two.jsonl")
        for r in range(2):
            events = []
            seq = 0
            for occ in range(2):
                for t in range(2):
                    events.append(_event(
                        seq, 10.0 + 5.0 * occ + 0.1 * t, "ring_hop",
                        f"hop{t}",
                        f"rank={r} hop={t} block={(r + t) % 2} world=2",
                    ))
                    seq += 1
            _write_sink(f"{base}.rank{r}", [_flightrec_record(r, events)])
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        starts = [e for e in trace["traceEvents"]
                  if e.get("cat") == "ring_hop" and e.get("ph") == "s"]
        # 2 occurrences x 2 blocks, each its own flow id
        assert len(starts) == 4
        assert len({e["id"] for e in starts}) == 4

    def test_synthesized_fallback_lays_request_lanes(self, tmp_path):
        """Recorder off: request records alone still merge — per-rank
        layout from each rank's earliest admission — and validate."""
        base = str(tmp_path / "noflight.jsonl")
        for r in range(2):
            _write_sink(f"{base}.rank{r}", [
                self._request(r, 0, 50.0 + r * 7.0),
                self._request(r, 1, 50.4 + r * 7.0, outcome="shed",
                              events=[{"kind": "shed", "t": 50.6,
                                       "detail": "deadline"}]),
            ])
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert trace["otherData"]["mode"] == "synthesized"
        assert trace["otherData"]["requests"] == 4
        assert oaptrace.validate_trace(trace) == []
        lanes = [e for e in trace["traceEvents"]
                 if e.get("cat") == "request" and e.get("ph") == "X"]
        assert {e["pid"] for e in lanes} == {0, 1}
        # each rank laid out from ITS earliest admission: no negative
        # timestamps, first slice at ~0 per rank
        per_rank_min = {}
        for e in lanes:
            per_rank_min[e["pid"]] = min(
                per_rank_min.get(e["pid"], float("inf")), e["ts"]
            )
        assert all(ts == pytest.approx(0.0, abs=1.0)
                   for ts in per_rank_min.values())
        assert any(e["name"] == "request:shed"
                   for e in trace["traceEvents"] if e.get("ph") == "i")

    def test_requests_count_lands_in_other_data(self, tmp_path):
        base = self._aligned_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert trace["otherData"]["requests"] == 2
