"""dev/oaptrace.py + dev/bench_regress.py units (ISSUE 11): merged
Chrome-trace timelines from per-rank JSONL sinks, and the perf
trajectory regression gate."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dev")
)

import bench_regress  # noqa: E402
import oaptrace  # noqa: E402


def _flightrec_record(rank, events, seq=0):
    return {
        "type": "flightrec", "rank": rank, "seq": seq,
        "events": events, "fit": "kmeans.fit",
    }


def _event(seq, t, kind, name, detail="", tid=1):
    return {"seq": seq, "t": t, "tid": tid, "kind": kind,
            "name": name, "detail": detail}


def _write_sink(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestRecorderMode:
    def _two_rank_sinks(self, tmp_path, rank1_offset=100.0, skew=0.0):
        """Two ranks running the same two-pass fit; rank 1's monotonic
        clock starts at +offset and its pass is `skew` seconds slower —
        the alignment must recover the offset from the collective
        sequence."""
        base = str(tmp_path / "fits.jsonl")
        for rank, off, lag in ((0, 0.0, 0.0), (1, rank1_offset, skew)):
            t = off + 10.0
            events = [
                _event(0, t, "span_open", "lloyd_loop"),
                _event(1, t + 0.1, "chunk", "prefetch", "#0"),
                _event(2, t + 0.4 + lag, "collective",
                       "process_allgather", "(2,2)"),
                _event(3, t + 0.5 + lag, "span_close", "lloyd_loop",
                       "0.5s"),
                _event(4, t + 0.6 + lag, "collective",
                       "process_allgather", "(2,2)"),
            ]
            _write_sink(f"{base}.rank{rank}", [
                _flightrec_record(rank, events),
                {"type": "metrics", "rank": rank, "seq": 99,
                 "metrics": {}},
            ])
        return base

    def test_merges_one_track_per_rank(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        assert trace["otherData"]["mode"] == "recorder"
        assert trace["otherData"]["ranks"] == [0, 1]
        pids = {e["pid"] for e in trace["traceEvents"]}
        assert pids == {0, 1}
        assert oaptrace.validate_trace(trace) == []

    def test_clock_alignment_via_collective_seqs(self, tmp_path):
        """Rank 1's raw clock is +100 s — aligned via the collective
        sequence, its span must land within the trace near rank 0's,
        not 100 s later."""
        base = self._two_rank_sinks(tmp_path, rank1_offset=100.0)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        spans = {
            e["pid"]: e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "lloyd_loop"
        }
        assert set(spans) == {0, 1}
        # identical workloads + alignment => near-identical start times
        assert abs(spans[0]["ts"] - spans[1]["ts"]) < 1e5  # < 100 ms
        assert spans[0]["dur"] == pytest.approx(0.5e6, rel=0.01)

    def test_skewed_rank_reads_staircased(self, tmp_path):
        base = self._two_rank_sinks(tmp_path, skew=1.0)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        spans = {
            e["pid"]: e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "lloyd_loop"
        }
        # the slow rank's span is visibly longer
        assert spans[1]["dur"] > spans[0]["dur"] + 0.5e6

    def test_cross_rank_flow_arrows_per_collective(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        trace = oaptrace.merge_trace(oaptrace.expand_paths([base]))
        starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
        finishes = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
        assert len(starts) == 2  # one flow per collective index
        assert len(finishes) == 2
        assert {e["id"] for e in starts} == {e["id"] for e in finishes}
        assert all(e["cat"] == "collective" for e in starts + finishes)

    def test_cli_writes_validated_file(self, tmp_path):
        base = self._two_rank_sinks(tmp_path)
        out = str(tmp_path / "trace.json")
        assert oaptrace.main([base, "-o", out]) == 0
        trace = json.load(open(out))
        assert oaptrace.validate_trace(trace) == []


class TestSynthesizedMode:
    def test_span_only_sink_lays_out_tree(self, tmp_path):
        path = str(tmp_path / "solo.jsonl")
        _write_sink(path, [
            {"type": "span", "fit": "pca.fit", "path": "pca.fit",
             "name": "pca.fit", "duration_s": 1.0, "count": 1,
             "rank": 0, "seq": 0},
            {"type": "span", "fit": "pca.fit",
             "path": "pca.fit/covariance", "name": "covariance",
             "duration_s": 0.6, "count": 1, "rank": 0, "seq": 1},
            {"type": "span", "fit": "pca.fit", "path": "pca.fit/eigh",
             "name": "eigh", "duration_s": 0.4, "count": 1,
             "rank": 0, "seq": 2},
            {"type": "metrics", "rank": 0, "seq": 3, "metrics": {}},
        ])
        trace = oaptrace.merge_trace([path])
        assert trace["otherData"]["mode"] == "synthesized"
        assert oaptrace.validate_trace(trace) == []
        by_name = {e["name"]: e for e in trace["traceEvents"]
                   if e.get("ph") == "X"}
        assert by_name["pca.fit"]["ts"] == 0
        # children lay out sequentially inside the parent
        assert by_name["covariance"]["ts"] == 0
        assert by_name["eigh"]["ts"] == pytest.approx(0.6e6)

    def test_missing_files_raise(self):
        with pytest.raises(FileNotFoundError):
            oaptrace.expand_paths(["/nonexistent/sink.jsonl"])


class TestBenchRegress:
    def _round(self, tmp_path, n, metrics):
        path = str(tmp_path / f"BENCH_r{n:02d}.json")
        tail = "\n".join(json.dumps(m) for m in metrics)
        with open(path, "w") as f:
            json.dump({"n": n, "cmd": "python bench.py", "rc": 0,
                       "tail": tail, "parsed": metrics[-1]}, f)
        return path

    def _metric(self, name, value, unit="iters/sec", backend="tpu"):
        return {"metric": name, "value": value, "unit": unit,
                "backend": backend}

    def test_single_round_warns_only(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("m", 10.0)])
        failures, warnings, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert any("only one bench round" in w for w in warnings)

    def test_regression_fails_naming_metric(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("kmeans_ips", 100.0)])
        self._round(tmp_path, 2, [self._metric("kmeans_ips", 80.0)])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1
        assert "kmeans_ips" in failures[0]
        assert "REGRESSION" in failures[0]

    def test_improvement_and_small_drift_pass(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("a", 100.0),
                                  self._metric("w", 2.0, unit="sec")])
        self._round(tmp_path, 2, [self._metric("a", 95.0),
                                  self._metric("w", 1.5, unit="sec")])
        failures, _, report = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert len(report) == 2

    def test_sec_units_are_lower_is_better(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("w", 1.0, unit="sec/iter")])
        self._round(tmp_path, 2, [self._metric("w", 1.5, unit="sec/iter")])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1

    def test_best_prior_not_just_previous(self, tmp_path):
        """The gate compares against the BEST prior round, so two slow
        rounds in a row cannot ratchet the bar down."""
        self._round(tmp_path, 1, [self._metric("a", 100.0)])
        self._round(tmp_path, 2, [self._metric("a", 85.0)])
        self._round(tmp_path, 3, [self._metric("a", 85.0)])
        failures, _, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert len(failures) == 1  # 85 vs best=100 is -15%

    def test_backends_never_cross_compare(self, tmp_path):
        self._round(tmp_path, 1, [
            self._metric("kmeans_ips", 100.0, backend="tpu")])
        self._round(tmp_path, 2, [
            self._metric("kmeans_ips_cpuproxy", 2.0, backend="cpu")])
        failures, warnings, _ = bench_regress.compare(str(tmp_path), 0.10)
        assert failures == []
        assert any("cpuproxy" in w and "skipped" in w for w in warnings)

    def test_cli_exit_codes(self, tmp_path):
        self._round(tmp_path, 1, [self._metric("a", 100.0)])
        assert bench_regress.main(["--dir", str(tmp_path)]) == 0
        self._round(tmp_path, 2, [self._metric("a", 50.0)])
        assert bench_regress.main(["--dir", str(tmp_path)]) == 1

    def test_repo_trajectory_is_currently_clean(self):
        """The live repo's recorded rounds must pass the gate — this is
        the tier-1 mirror of the ci.sh soft gate."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        failures, _, _ = bench_regress.compare(root, 0.10)
        assert failures == [], failures

    def test_real_fit_sink_merges(self, tmp_path):
        """End-to-end: a real streamed fit's JSONL sink (recorder armed)
        merges into a validated recorder-mode timeline."""
        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        sink = str(tmp_path / "real.jsonl")
        set_config(flight_recorder=256, telemetry_log=sink)
        try:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(600, 4)).astype(np.float32)

            def gen():
                for lo in range(0, 600, 200):
                    yield x[lo:lo + 200]

            src = ChunkSource(gen, 4, 200, n_rows=600)
            KMeans(k=2, seed=0, init_mode="random", max_iter=2).fit(src)
        finally:
            set_config(flight_recorder=0, telemetry_log="")
        trace = oaptrace.merge_trace(oaptrace.expand_paths([sink]))
        assert trace["otherData"]["mode"] == "recorder"
        assert oaptrace.validate_trace(trace) == []
        assert any(e.get("ph") == "X" for e in trace["traceEvents"])
