"""Traffic-plane tests (ISSUE 16): async ingestion, deadline-aware
batch formation, admission control, and the replica scale controller.

Contracts under test:

- deadline-ordered dispatch: out-of-order arrivals flush in deadline
  order (deterministic injected clock — no wall-clock in the loop);
- expired requests are shed BEFORE dispatch (their future raises a
  ``ShedError`` naming the deadline) and never reach the handle;
- every future resolves or raises EXACTLY once, including under a
  racing dispatcher thread and at close();
- admission sheds loudly — queue-full and budget sheds raise at
  ``submit`` with queue depth / priced-bytes detail and book
  ``oap_serve_shed_total{reason=}``;
- async answers are bit-identical to direct ``handle.predict`` calls;
- ``oap_serve_queue_depth`` is delta-folded under a tracked lock —
  race-safe under the dispatcher thread and clean with
  ``sanitizers="locks"`` armed;
- the scale controller votes out on sustained per-replica depth,
  in on idleness, books ``oap_serve_scale_*``, lands its decision in
  ``serving_summary()``, and posts the supervisor sideband hint.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.serving import registry, traffic
from oap_mllib_tpu.telemetry import metrics as tm


@pytest.fixture(autouse=True)
def _clear_serving():
    from oap_mllib_tpu.serving import ha
    from oap_mllib_tpu.utils import faults

    registry.clear()
    traffic._reset_for_tests()
    ha._reset_for_tests()
    faults.reset()  # fresh injection counters per test
    yield
    registry.clear()
    traffic._reset_for_tests()
    ha._reset_for_tests()
    faults.reset()


class FakeClock:
    """Injected monotonic clock: deadline logic is tested without a
    single wall-clock read."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class SpyHandle:
    """Records each flush's per-request row counts and tags results so
    tests can match answers back to requests."""

    def __init__(self, fail: Exception | None = None):
        self.flushes: list[list[int]] = []
        self.fail = fail

    def predict_many(self, batches):
        self.flushes.append([b.shape[0] for b in batches])
        if self.fail is not None:
            raise self.fail
        return [np.full(b.shape[0], b.shape[0], np.int32) for b in batches]


def _kmeans_handle(rng, n=300, d=8, k=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    model = KMeans(k=k, seed=3, max_iter=3).fit(x)
    return serving.serve(model), x


def _shed_total(reason: str) -> int:
    reg = tm.registry()
    with tm._LOCK:
        return int(sum(
            m.value for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_shed_total"
            and dict(labels).get("reason") == reason
        ))


class TestAdmission:
    def test_needs_predict_many(self):
        with pytest.raises(TypeError, match="predict_many"):
            serving.TrafficQueue(object(), start=False)

    def test_knob_typos_raise_at_submit(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        set_config(serve_queue_depth=0)
        with pytest.raises(ValueError, match="serve_queue_depth"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_queue_depth=4, serve_shed_headroom=1.5)
        with pytest.raises(ValueError, match="serve_shed_headroom"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_shed_headroom=0.5, serve_deadline_ms=-1.0)
        with pytest.raises(ValueError, match="serve_deadline_ms"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_deadline_ms=0.0)
        with pytest.raises(ValueError, match="deadline_ms"):
            q.submit(np.zeros((1, 2)), deadline_ms=-5)

    def test_queue_full_sheds_loudly_at_submit(self):
        set_config(serve_queue_depth=2)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((1, 2)))
        q.submit(np.zeros((1, 2)))
        before = _shed_total("queue_full")
        with pytest.raises(serving.ShedError) as ei:
            q.submit(np.zeros((1, 2)), deadline_ms=25.0)
        e = ei.value
        assert e.reason == "queue_full"
        assert e.queue_depth == 2
        msg = str(e)
        # loud like scale_policy: the message names depth and deadline
        assert "serve_queue_depth=2" in msg
        assert "queue depth 2" in msg and "25.0 ms" in msg
        assert _shed_total("queue_full") == before + 1
        q.pump()
        q.close()

    def test_budget_shed_prices_against_membudget(self):
        # 4 KiB budget x 0.5 headroom = 2048 B allowance; one 100x8 f32
        # request (3200 B) x the planner fudge prices over it
        set_config(memory_budget_hbm="4K", serve_shed_headroom=0.5)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        before = _shed_total("budget")
        with pytest.raises(serving.ShedError) as ei:
            q.submit(np.zeros((100, 8), np.float32))
        e = ei.value
        assert e.reason == "budget"
        assert e.budget_bytes == 2048
        assert e.priced_bytes > e.budget_bytes
        assert "budget" in str(e) and "OOM" in str(e)
        assert _shed_total("budget") == before + 1
        # under the allowance is admitted: pending bytes accumulate
        f = q.submit(np.zeros((10, 8), np.float32))  # 320 B * 1.25
        with pytest.raises(serving.ShedError):
            # (320 + 1600) * 1.25 = 2400 B > the 2048 B allowance
            q.submit(np.zeros((50, 8), np.float32))
        q.pump()
        assert f.result(timeout=5) is not None
        q.close()

    def test_unbounded_budget_prices_nothing(self):
        set_config(memory_budget_hbm="0")  # explicit unlimited
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((5000, 8), np.float32))
        q.pump()
        q.close()

    def test_submit_after_close_raises(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.close()
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(np.zeros((1, 2)))


class TestDeadlineBatching:
    def test_out_of_order_arrivals_flush_in_deadline_order(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        # arrival order: loose, tight, middle — dispatch must invert it
        q.submit(np.zeros((3, 2)), deadline_ms=5000)
        q.submit(np.zeros((7, 2)), deadline_ms=100)
        q.submit(np.zeros((5, 2)), deadline_ms=1000)
        q.pump()
        assert spy.flushes == [[7, 5, 3]]
        q.close()

    def test_no_deadline_sorts_last_by_arrival(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        q.submit(np.zeros((2, 2)))  # inf deadline
        q.submit(np.zeros((9, 2)), deadline_ms=50)
        q.submit(np.zeros((4, 2)))  # inf deadline, later arrival
        q.pump()
        assert spy.flushes == [[9, 2, 4]]
        q.close()

    def test_default_deadline_comes_from_config(self):
        set_config(serve_deadline_ms=10.0)
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        f = q.submit(np.zeros((1, 2)))  # inherits the 10 ms default
        clock.advance(1.0)
        q.pump()
        assert isinstance(f.exception(), serving.ShedError)
        assert f.exception().reason == "deadline"
        assert spy.flushes == []  # never dispatched
        q.close()

    def test_expired_shed_before_dispatch_live_still_answered(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        dead = q.submit(np.zeros((6, 2)), deadline_ms=10)
        live = q.submit(np.zeros((4, 2)), deadline_ms=60_000)
        before = _shed_total("deadline")
        clock.advance(0.5)  # past 10 ms, well under 60 s
        n = q.pump()
        assert n == 2
        exc = dead.exception()
        assert isinstance(exc, serving.ShedError)
        assert exc.reason == "deadline"
        assert "expired" in str(exc) and "10.0 ms" in str(exc)
        assert _shed_total("deadline") == before + 1
        assert live.result(timeout=5)[0] == 4
        assert spy.flushes == [[4]]  # the dead request never dispatched
        q.close()

    def test_max_batch_rows_splits_flushes_in_deadline_order(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(
            spy, start=False, clock=clock, max_batch_rows=10
        )
        q.submit(np.zeros((6, 2)), deadline_ms=300)
        q.submit(np.zeros((6, 2)), deadline_ms=100)
        q.submit(np.zeros((6, 2)), deadline_ms=200)
        q.pump()
        # tightest-deadline pair would overflow 10 rows: greedy split,
        # still deadline-ordered across flushes
        assert spy.flushes == [[6], [6], [6]] or spy.flushes == [[6, 6], [6]]
        q.close()

    def test_futures_resolve_exactly_once(self):
        clock = FakeClock()
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        f = q.submit(np.zeros((2, 2)))
        assert q.pump() == 1
        first = f.result(timeout=5)
        # a second cycle has nothing to do and cannot re-resolve
        assert q.pump() == 0
        assert f.result() is first
        q.close()

    def test_cancelled_future_dropped_without_dispatch(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        f = q.submit(np.zeros((2, 2)))
        assert f.cancel()
        q.pump()
        assert spy.flushes == []
        assert f.cancelled()
        q.close()

    def test_handle_exception_lands_on_every_future_of_the_flush(self):
        clock = FakeClock()
        boom = RuntimeError("scoring failed")
        q = serving.TrafficQueue(
            SpyHandle(fail=boom), start=False, clock=clock
        )
        f1 = q.submit(np.zeros((2, 2)))
        f2 = q.submit(np.zeros((3, 2)))
        q.pump()
        assert f1.exception() is boom and f2.exception() is boom
        q.close()


class TestAsyncDispatch:
    def test_storm_answers_match_direct_predict(self, rng):
        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        batches = [
            rng.normal(size=(int(s), 8)).astype(np.float32)
            for s in rng.integers(3, 60, size=40)
        ]
        with serving.TrafficQueue(handle) as q:
            futs = [q.submit(b, deadline_ms=60_000) for b in batches]
            got = [f.result(timeout=60) for f in futs]
        for b, ids in zip(batches, got):
            np.testing.assert_array_equal(ids, handle.predict(b))

    def test_close_drains_pending(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        futs = [q.submit(np.zeros((2, 2))) for _ in range(5)]
        q.close()  # final inline pump resolves everything
        assert all(f.done() for f in futs)
        assert all(f.exception() is None for f in futs)

    def test_dispatcher_thread_is_daemon_and_joined(self):
        q = serving.TrafficQueue(SpyHandle())
        t = q._thread
        assert t is not None and t.daemon
        q.close()
        assert not t.is_alive()
        assert q._thread is None


class TestQueueDepthGauge:
    def _gauge(self):
        reg = tm.registry()
        with tm._LOCK:
            for (name, _), m in reg._metrics.items():
                if name == "oap_serve_queue_depth":
                    return m.value
        return None

    def test_gauge_tracks_pending_and_returns_to_zero(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        for _ in range(3):
            q.submit(np.zeros((2, 2)))
        assert self._gauge() == 3
        assert q.depth() == 3
        q.pump()
        assert self._gauge() == 0
        assert q.depth() == 0
        q.close()

    def test_delta_folding_is_race_safe(self):
        # the bug the seam fixes: concurrent set() calls clobber each
        # other; delta folding under the tracked lock cannot
        n, per = 8, 200
        start = threading.Barrier(n)

        def hammer():
            start.wait()
            for _ in range(per):
                registry.note_queue_depth(1)
                registry.note_queue_depth(-1)

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert self._gauge() == 0

    def test_clean_under_locks_sanitizer(self, rng):
        from oap_mllib_tpu.utils import locktrace

        locktrace._reset_for_tests()
        set_config(sanitizers="locks")
        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        # armed tracked locks raise LockOrderError on any live
        # inversion across submit / dispatcher / flush seams
        with serving.TrafficQueue(handle) as q:
            futs = [
                q.submit(
                    rng.normal(size=(5, 8)).astype(np.float32),
                    deadline_ms=60_000,
                )
                for _ in range(30)
            ]
            for f in futs:
                assert f.result(timeout=60) is not None
        set_config(sanitizers="")
        locktrace._reset_for_tests()


class TestScaleController:
    def test_validates_knobs(self):
        with pytest.raises(ValueError, match="replicas"):
            serving.ScaleController(0)
        set_config(serve_scale_high=0.0)
        with pytest.raises(ValueError, match="serve_scale_high"):
            serving.ScaleController(1)
        set_config(serve_scale_high=32.0, serve_scale_idle_s=-1.0)
        with pytest.raises(ValueError, match="serve_scale_idle_s"):
            serving.ScaleController(1)

    def test_scales_out_on_sustained_depth(self):
        set_config(serve_scale_high=4.0)
        clock = FakeClock()
        sc = serving.ScaleController(2, clock=clock)
        before = int(tm.family_total("oap_serve_scale_out_total"))
        decisions = [
            sc.observe(queue_depth=40, p99_s=0.2) for _ in range(4)
        ]
        assert [d["action"] for d in decisions[:-1]] == ["hold"] * 3
        last = decisions[-1]
        assert last["action"] == "out"
        assert last["replicas"] == 3
        assert "serve_scale_high=4" in last["reason"]
        assert int(tm.family_total("oap_serve_scale_out_total")) \
            == before + 1
        summary = registry.serving_summary()
        assert summary["scale"]["action"] == "out"

    def test_holds_while_depth_trend_falls(self):
        set_config(serve_scale_high=4.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        # mean depth/replica is over the bar, but falling fast: a
        # draining queue must not trigger growth
        for depth in (100, 90, 10, 5):
            d = sc.observe(queue_depth=depth)
        assert d["action"] == "hold"
        assert d["depth_trend"] == "falling"

    def test_scales_in_on_idleness(self):
        set_config(serve_scale_idle_s=5.0)
        clock = FakeClock()
        sc = serving.ScaleController(3, min_replicas=1, clock=clock)
        before = int(tm.family_total("oap_serve_scale_in_total"))
        d = sc.observe(queue_depth=0)
        assert d["action"] == "hold"
        clock.advance(6.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "in" and d["replicas"] == 2
        # traffic resets the idle clock
        clock.advance(6.0)
        d = sc.observe(queue_depth=3)
        assert d["action"] == "hold"
        assert int(tm.family_total("oap_serve_scale_in_total")) \
            == before + 1

    def test_growth_caps_and_floor(self):
        set_config(serve_scale_high=1.0, serve_scale_idle_s=1.0)
        clock = FakeClock()
        sc = serving.ScaleController(1, max_replicas=2, clock=clock)
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["replicas"] == 2
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["action"] == "hold" and d["replicas"] == 2  # capped
        clock.advance(10.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "in" and d["replicas"] == 1
        clock.advance(10.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "hold" and d["replicas"] == 1  # floored

    def test_observe_view_folds_fleet_heartbeat(self):
        set_config(serve_scale_high=4.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        view = {"world": 2, "queue_depth": [30.0, 20.0],
                "requests": [100.0, 90.0]}
        d = sc.observe_view(view, p99_s=0.1)
        assert sc.replicas == 2
        assert d["queue_depth"] == 50

    def test_write_scale_hint_roundtrip(self, tmp_path):
        set_config(serve_scale_high=1.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["action"] == "out"
        path = serving.write_scale_hint(str(tmp_path), d)
        assert path is not None
        import json

        with open(path) as f:
            assert json.load(f)["action"] == "out"
        # hold decisions post nothing
        hold = dict(d, action="hold")
        assert serving.write_scale_hint(str(tmp_path / "x"), hold) is None


class TestSummary:
    def test_serving_summary_grows_traffic_blocks(self):
        set_config(serve_queue_depth=1)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((1, 2)))
        with pytest.raises(serving.ShedError):
            q.submit(np.zeros((1, 2)))
        q.pump()
        q.close()
        s = registry.serving_summary()
        assert s["queue_depth"] == 0
        assert s["shed"]["total"] >= 1
        assert s["shed"]["queue_full"] >= 1


# -- ISSUE 18: request-lifecycle fault tolerance ------------------------------


def _total(name: str) -> int:
    return int(tm.family_total(name))


class FlakyHandle:
    """Fails the first ``fail_times`` flushes with ``exc_factory()``,
    then answers like SpyHandle — the durable-future retry drill."""

    def __init__(self, fail_times: int,
                 exc_factory=lambda: ConnectionError("peer reset")):
        self.flushes: list[list[int]] = []
        self.fail_times = fail_times
        self.exc_factory = exc_factory
        self.calls = 0

    def predict_many(self, batches):
        self.flushes.append([b.shape[0] for b in batches])
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc_factory()
        return [np.full(b.shape[0], b.shape[0], np.int32) for b in batches]


class PoisonSpy:
    """Mimics registry._flush_many's finite-guard: a flush containing
    any nonfinite row raises NonFiniteError — the data-driven poison
    that re-faults deterministically in whichever bisection half holds
    it."""

    def __init__(self):
        self.flushes: list[list[int]] = []

    def predict_many(self, batches):
        from oap_mllib_tpu.utils.resilience import NonFiniteError

        self.flushes.append([b.shape[0] for b in batches])
        if any(not np.isfinite(b).all() for b in batches):
            raise NonFiniteError("poison flush")
        return [np.full(b.shape[0], b.shape[0], np.int32) for b in batches]


def _pump_until_done(q, futs, clock, rounds=60):
    """Drive a start=False queue until every future resolves —
    advancing the injected clock past retry backoffs each round."""
    for _ in range(rounds):
        if all(f.done() for f in futs):
            return
        clock.advance(1.0)
        try:
            q.pump()
        except Exception:
            pass  # dispatcher-crash cycles already landed their futures
    raise AssertionError(
        f"unresolved futures after {rounds} pump rounds: "
        f"{sum(not f.done() for f in futs)}"
    )


class TestDurableFutures:
    def test_transient_fault_retries_then_answers(self):
        set_config(serve_retry_limit=2, serve_retry_backoff=0.01)
        clock = FakeClock()
        h = FlakyHandle(fail_times=1)
        q = serving.TrafficQueue(h, start=False, clock=clock)
        before = _total("oap_serve_retries_total")
        f = q.submit(np.zeros((3, 2)))
        q.pump()  # transient fault -> requeued, future still pending
        assert not f.done()
        _pump_until_done(q, [f], clock)
        assert f.result()[0] == 3  # answered after the retry
        assert _total("oap_serve_retries_total") == before + 1
        assert h.calls == 2
        q.close()

    def test_retries_exhausted_fails_classified(self):
        set_config(serve_retry_limit=1, serve_retry_backoff=0.0)
        clock = FakeClock()
        q = serving.TrafficQueue(
            FlakyHandle(fail_times=99), start=False, clock=clock
        )
        f = q.submit(np.zeros((2, 2)))
        _pump_until_done(q, [f], clock)
        exc = f.exception()
        assert isinstance(exc, serving.ServeError)
        assert exc.reason == "retries-exhausted"
        assert exc.retries == 1
        assert "serve_retry_limit" in str(exc)
        q.close()

    def test_retry_limit_zero_fails_immediately(self):
        set_config(serve_retry_limit=0)
        clock = FakeClock()
        q = serving.TrafficQueue(
            FlakyHandle(fail_times=99), start=False, clock=clock
        )
        f = q.submit(np.zeros((2, 2)))
        q.pump()
        assert isinstance(f.exception(), serving.ServeError)
        assert f.exception().reason == "retries-exhausted"
        q.close()

    def test_retry_preserves_deadline_priority(self):
        # the retried pair must flush tight-deadline-first again, not
        # in requeue order
        set_config(serve_retry_limit=2, serve_retry_backoff=0.0)
        clock = FakeClock()
        h = FlakyHandle(fail_times=1)
        q = serving.TrafficQueue(h, start=False, clock=clock)
        fa = q.submit(np.zeros((3, 2)), deadline_ms=500_000)  # loose
        fb = q.submit(np.zeros((7, 2)), deadline_ms=100_000)  # tight
        _pump_until_done(q, [fa, fb], clock)
        assert h.flushes[0] == [7, 3] and h.flushes[-1] == [7, 3]
        assert fa.result()[0] == 3 and fb.result()[0] == 7
        q.close()

    def test_dispatcher_crash_fails_futures_and_restarts(self):
        # an injected serve.dispatch fault (kind err = unclassified
        # crash) fails the in-cycle futures with a classified
        # ServeError, books the crash counter, and the queue keeps
        # working afterwards
        set_config(fault_spec="serve.dispatch:err=1")
        clock = FakeClock()
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        before = _total("oap_serve_dispatch_crashes_total")
        f = q.submit(np.zeros((2, 2)))
        with pytest.raises(Exception, match="serve.dispatch"):
            q.pump()
        exc = f.exception()
        assert isinstance(exc, serving.ServeError)
        assert exc.reason == "dispatcher-crash"
        assert _total("oap_serve_dispatch_crashes_total") == before + 1
        # the fault is spent: the next cycle answers normally
        f2 = q.submit(np.zeros((4, 2)))
        q.pump()
        assert f2.result()[0] == 4
        q.close()

    def test_dispatcher_thread_survives_crash(self):
        # with the live thread, the crash is absorbed by _run (warned,
        # loop restarts) and later submissions still answer
        set_config(fault_spec="serve.dispatch:err=1")
        q = serving.TrafficQueue(SpyHandle(), poll_s=0.005)
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("ignore", RuntimeWarning)
            f1 = q.submit(np.zeros((2, 2)))
            with pytest.raises(Exception):
                f1.result(timeout=10)
            f2 = q.submit(np.zeros((4, 2)))
            assert f2.result(timeout=10)[0] == 4
        assert q._thread.is_alive()  # the loop restarted, not died
        q.close()

    def test_transient_dispatcher_crash_requeues(self):
        set_config(fault_spec="serve.dispatch:fail=1",
                   serve_retry_limit=2, serve_retry_backoff=0.0)
        clock = FakeClock()
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        f = q.submit(np.zeros((3, 2)))
        with pytest.raises(Exception):
            q.pump()
        assert not f.done()  # requeued, not failed: retries remain
        _pump_until_done(q, [f], clock)
        assert f.result()[0] == 3
        q.close()


class TestPoisonBisection:
    def test_poison_isolated_innocents_answered(self):
        clock = FakeClock()
        spy = PoisonSpy()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        poison_before = _total("oap_serve_poison_total")
        bisect_before = _total("oap_serve_bisect_total")
        futs = [q.submit(np.full((3, 2), float(i))) for i in range(3)]
        bad = np.full((5, 2), np.nan)
        fp = q.submit(bad)
        futs2 = [q.submit(np.full((4, 2), 9.0))]
        q.pump()
        # every innocent answered despite sharing the poisoned flush
        for f in futs + futs2:
            assert f.exception() is None, f.exception()
        exc = fp.exception()
        assert isinstance(exc, serving.ServeError)
        assert exc.reason == "poison"
        assert exc.fault_class == "nonfinite"
        assert "digest" in str(exc)
        assert _total("oap_serve_poison_total") == poison_before + 1
        assert _total("oap_serve_bisect_total") > bisect_before
        q.close()

    def test_two_poisons_both_isolated(self):
        clock = FakeClock()
        q = serving.TrafficQueue(PoisonSpy(), start=False, clock=clock)
        before = _total("oap_serve_poison_total")
        good = [q.submit(np.full((2, 2), float(i))) for i in range(4)]
        bad = [q.submit(np.full((2, 2), np.nan)) for _ in range(2)]
        q.pump()
        for f in good:
            assert f.exception() is None
        for f in bad:
            assert isinstance(f.exception(), serving.ServeError)
            assert f.exception().reason == "poison"
        assert _total("oap_serve_poison_total") == before + 2
        q.close()

    def test_end_to_end_kmeans_flush_guard_zero_compiles(self, rng):
        # the real registry._flush_many finite-guard + bisection: the
        # poison request quarantines, innocents match direct predict,
        # and the bisection halves re-coalesce on the warm bucket
        # family — zero new XLA compiles
        from oap_mllib_tpu.serving import batcher

        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        clock = FakeClock()
        q = serving.TrafficQueue(handle, start=False, clock=clock)
        innocents = [
            rng.normal(size=(int(s), 8)).astype(np.float32)
            for s in (5, 12, 30)
        ]
        bad = np.full((7, 8), np.nan, np.float32)
        snap = batcher.xla_snapshot()
        futs = [q.submit(b) for b in innocents]
        fp = q.submit(bad)
        q.pump()
        assert batcher.xla_snapshot() == snap  # bisection compiled nothing
        assert isinstance(fp.exception(), serving.ServeError)
        assert fp.exception().reason == "poison"
        for b, f in zip(innocents, futs):
            np.testing.assert_array_equal(f.result(), handle.predict(b))
        q.close()

    def test_injected_batch_fault_triggers_bisection(self, rng):
        # fault_spec-driven serve.batch poison: the first flush faults,
        # bisection rescoring answers everyone once the count is spent
        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        set_config(fault_spec="serve.batch:nan=1")
        clock = FakeClock()
        q = serving.TrafficQueue(handle, start=False, clock=clock)
        before = _total("oap_serve_bisect_total")
        futs = [
            q.submit(rng.normal(size=(4, 8)).astype(np.float32))
            for _ in range(4)
        ]
        q.pump()
        assert _total("oap_serve_bisect_total") > before
        for f in futs:
            assert f.exception() is None
        q.close()


class TestDrain:
    def test_drain_flushes_then_sheds_draining(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        before = _total("oap_serve_drains_total")
        futs = [q.submit(np.zeros((2, 2))) for _ in range(4)]
        stats = q.drain(timeout_s=2.0)
        assert stats["drained"] and stats["failed"] == 0
        assert stats["answered"] == 4
        assert all(f.exception() is None for f in futs)
        assert _total("oap_serve_drains_total") == before + 1
        shed_before = _shed_total("draining")
        with pytest.raises(serving.ShedError) as ei:
            q.submit(np.zeros((1, 2)))
        assert ei.value.reason == "draining"
        assert _shed_total("draining") == shed_before + 1
        q.close()

    def test_drain_deadline_fails_leftovers_loudly(self):
        # a handle that keeps transient-faulting + a frozen clock: the
        # retries' backoff never elapses, so the wall deadline expires
        # and every leftover future fails with drain-deadline
        set_config(serve_retry_limit=5, serve_retry_backoff=0.05)
        clock = FakeClock()
        q = serving.TrafficQueue(
            FlakyHandle(fail_times=99), start=False, clock=clock
        )
        futs = [q.submit(np.zeros((2, 2))) for _ in range(3)]
        stats = q.drain(timeout_s=0.2)
        assert not stats["drained"] and stats["failed"] == 3
        for f in futs:
            exc = f.exception()
            assert isinstance(exc, serving.ServeError)
            assert exc.reason == "drain-deadline"
        assert q.depth() == 0
        q.close()

    def test_drain_posts_sideband_report(self, tmp_path):
        set_config(crash_dir=str(tmp_path))
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((2, 2)))
        q.drain(timeout_s=1.0)
        q.close()
        import json

        path = tmp_path / "serve.drain.done.rank0.json"
        assert path.exists()
        with open(path) as f:
            rep = json.load(f)
        assert rep["rank"] == 0 and rep["answered"] == 1

    def test_supervisor_consumes_drain_reports(self, tmp_path):
        from oap_mllib_tpu.utils.supervisor import Supervisor

        set_config(crash_dir=str(tmp_path))
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((2, 2)))
        q.drain(timeout_s=1.0)
        q.close()
        sup = Supervisor(
            lambda rank, world, coord, local: ["true"],
            1, str(tmp_path),
        )
        reports = sup._read_drain_reports()
        assert len(reports) == 1 and reports[0]["answered"] == 1
        # read-and-remove: a second read finds nothing
        assert sup._read_drain_reports() == []

    def test_scale_in_drains_attached_queue(self):
        set_config(serve_scale_idle_s=5.0)
        clock = FakeClock()
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        f = q.submit(np.zeros((2, 2)))
        sc = serving.ScaleController(
            2, min_replicas=1, clock=clock, queue=q
        )
        sc.observe(queue_depth=0)
        clock.advance(6.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "in"
        assert d["drained"]["drained"] is True
        assert f.exception() is None  # flushed by the drain
        with pytest.raises(serving.ShedError, match="draining"):
            q.submit(np.zeros((1, 2)))
        q.close()

    def test_drain_fault_site_armed(self):
        set_config(fault_spec="serve.drain:err=1")
        q = serving.TrafficQueue(SpyHandle(), start=False)
        with pytest.raises(Exception, match="serve.drain"):
            q.drain(timeout_s=0.1)
        q.close()


class TestCloseFailOrFlush:
    def test_wedged_scoring_callable_fails_futures_not_hangs(self):
        # satellite 2: a scoring callable that never returns must not
        # strand pending futures behind the daemon flag — close(...)
        # with a join timeout fails every unresolved future explicitly
        gate = threading.Event()
        release = threading.Event()

        class WedgedHandle:
            def predict_many(self, batches):
                gate.set()
                release.wait(30)  # wedged until the test frees it
                return [np.zeros(b.shape[0], np.int32) for b in batches]

        q = serving.TrafficQueue(WedgedHandle(), poll_s=0.005)
        before = _total("oap_serve_close_wedged_total")
        f_wedged = q.submit(np.zeros((2, 2)))
        assert gate.wait(10)  # dispatcher is now stuck scoring it
        f_pending = q.submit(np.zeros((3, 2)))
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("ignore", RuntimeWarning)
            q.close(timeout_s=0.2)
        for f in (f_wedged, f_pending):
            exc = f.exception(timeout=1)
            assert isinstance(exc, serving.ServeError)
            assert exc.reason == "shutdown"
        assert _total("oap_serve_close_wedged_total") == before + 1
        # free the wedge: its late set_result lands on an already-
        # failed future and is swallowed (exactly-once preserved)
        release.set()
        t = q._thread
        if t is not None:
            t.join(5)
        assert isinstance(f_wedged.exception(), serving.ServeError)

    def test_close_fails_unresolvable_retries(self):
        # pending retries at close get the final pump; if they fault
        # again the closing queue fails them with reason=shutdown
        # instead of leaking them
        set_config(serve_retry_limit=10, serve_retry_backoff=0.05)
        clock = FakeClock()
        q = serving.TrafficQueue(
            FlakyHandle(fail_times=99), start=False, clock=clock
        )
        f = q.submit(np.zeros((2, 2)))
        q.pump()  # -> requeued with a backoff the FakeClock never meets
        assert not f.done()
        q.close()
        exc = f.exception()
        assert isinstance(exc, serving.ServeError)
        assert exc.reason == "shutdown"

    def test_queue_depth_zero_after_failed_close(self):
        set_config(serve_retry_limit=10, serve_retry_backoff=0.05)
        clock = FakeClock()
        q = serving.TrafficQueue(
            FlakyHandle(fail_times=99), start=False, clock=clock
        )
        for _ in range(3):
            q.submit(np.zeros((2, 2)))
        q.pump()
        q.close()
        s = registry.serving_summary()
        assert s["queue_depth"] == 0


class TestEvictionFutureAccounting:
    def test_no_future_leaks_across_eviction_and_release(self, rng):
        # satellite 3: a jittered storm exercising shed, retry, and
        # answer paths; mid-storm the replica evicts; release() must
        # leave EVERY submitted future resolved and the depth gauge at 0
        from oap_mllib_tpu.serving import ha
        from oap_mllib_tpu.utils import recovery

        set_config(serve_queue_depth=6, serve_retry_limit=1,
                   serve_retry_backoff=0.0)
        clock = FakeClock()
        h = FlakyHandle(fail_times=2)
        q = serving.TrafficQueue(h, start=False, clock=clock)
        guard = serving.ReplicaGuard(queue=q)
        futs = []
        sheds = 0
        for i in range(30):
            try:
                futs.append(
                    q.submit(rng.normal(size=(1 + i % 4, 2)))
                )
            except serving.ShedError:
                sheds += 1
            if i == 10:
                with guard.leg():
                    raise recovery.RecoveryError("peer died mid-storm")
            if i % 5 == 4:
                clock.advance(1.0)
                q.pump()
        assert guard.local_only and serving.fleet_evicted()
        assert sheds > 0  # the storm really exercised the shed path
        stats = guard.release(timeout_s=2.0)
        assert stats is not None
        for f in futs:
            assert f.done(), "future leaked across eviction"
        s = registry.serving_summary()
        assert s["queue_depth"] == 0
        assert s["evictions"] >= 1


class TestBrownout:
    def test_grammar_validates_at_submit(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        set_config(serve_brownout="bogus")
        with pytest.raises(ValueError, match="serve_brownout"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_brownout="pin:bogus")
        with pytest.raises(ValueError, match="serve_brownout"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_brownout="pin:topk")
        q.submit(np.zeros((1, 2)))
        q.pump()
        q.close()

    def test_ladder_steps_up_under_sustained_pressure(self):
        b = traffic.BrownoutController("auto")
        before = _total("oap_serve_brownout_steps_total")
        # three full windows of 2x pressure walk the ladder to the top
        decisions = [b.observe(200, 100) for _ in range(12)]
        assert b.rung == 3
        assert [s["to"] for s in b.steps] == ["topk", "bf16", "stale"]
        assert _total("oap_serve_brownout_steps_total") == before + 3
        # breaches at intermediate rungs (and on the step itself) were
        # absorbed rather than shed
        assert any(d["absorb"] for d in decisions)
        # at the top rung with pressure still sustained, absorb stops:
        # the budget shed resumes as the OOM backstop
        tail = [b.observe(200, 100) for _ in range(4)]
        assert b.rung == 3 and not any(d["absorb"] for d in tail)

    def test_falling_trend_blocks_the_step(self):
        b = traffic.BrownoutController("auto")
        for ratio in (4.0, 3.0, 1.2, 1.1):  # mean > 1 but falling
            d = b.observe(int(ratio * 100), 100)
        assert b.rung == 0 and d["stepped"] == 0

    def test_ladder_steps_down_when_pressure_clears(self):
        b = traffic.BrownoutController("auto")
        for _ in range(4):
            b.observe(200, 100)
        assert b.rung == 1
        for _ in range(4):
            d = b.observe(10, 100)
        assert b.rung == 0 and d["stepped"] == -1

    def test_pinned_rung_never_absorbs(self):
        set_config(serve_brownout="pin:bf16")
        b = traffic.brownout()
        assert b.rung == 2
        d = b.observe(500, 100)
        assert d["absorb"] is False  # pinned quality, intact admission

    def test_off_never_steps(self):
        set_config(serve_brownout="off")
        b = traffic.brownout()
        for _ in range(8):
            d = b.observe(500, 100)
        assert b.rung == 0 and d["absorb"] is False

    def test_topk_depth_halves_at_rung(self):
        set_config(serve_brownout="pin:topk")
        traffic._reset_for_tests()
        assert traffic.brownout_topk(8) == 4
        assert traffic.brownout_topk(1) == 1  # floor
        set_config(serve_brownout="off")
        assert traffic.brownout_topk(8) == 8

    def test_bf16_rung_overrides_precision_with_parity_bound(self):
        from oap_mllib_tpu.serving import batcher

        set_config(serve_brownout="pin:bf16")
        traffic._reset_for_tests()
        assert batcher.resolve_policy("kmeans").name == "bf16"
        # an explicit operator pin always beats the rung
        set_config(serving_precision="f32")
        assert batcher.resolve_policy("kmeans").name == "f32"
        set_config(serving_precision="", serve_brownout="auto")
        assert batcher.resolve_policy("kmeans").name != "bf16"

    def test_stale_rung_answers_from_previous_pin(self):
        set_config(serve_brownout="pin:stale")
        traffic._reset_for_tests()
        before = _total("oap_serve_stale_pins_total")
        cache: dict = {}
        a1 = np.ones((4, 2), np.float32)
        a2 = 2 * np.ones((4, 2), np.float32)
        d1 = registry.pin(cache, "t", a1)
        stale = registry.pin(cache, "t", a2, allow_stale=True)
        assert stale is d1  # the previous pin answered
        assert _total("oap_serve_stale_pins_total") == before + 1
        set_config(serve_brownout="off")
        traffic._reset_for_tests()
        fresh = registry.pin(cache, "t", a2, allow_stale=True)
        assert fresh is not d1  # off the rung: re-pins fresh

    def test_submit_absorbs_breach_at_active_rung(self):
        # 4 KiB x 0.5 headroom = 2048 B allowance; a 100x8 f32 request
        # prices over it — at an active rung the breach is ABSORBED
        set_config(memory_budget_hbm="4K", serve_shed_headroom=0.5)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        set_config(serve_brownout="auto")
        b = traffic.brownout()
        b.rung = 1  # an active intermediate rung
        before = _total("oap_serve_brownout_absorbed_total")
        f = q.submit(np.zeros((100, 8), np.float32))  # no ShedError
        assert _total("oap_serve_brownout_absorbed_total") == before + 1
        q.pump()
        assert f.exception() is None
        q.close()

    def test_summary_and_gauge_are_loud(self):
        set_config(serve_brownout="pin:stale")
        traffic._reset_for_tests()
        traffic.brownout()
        s = registry.serving_summary()
        assert s["brownout"]["rung"] == "stale"
        assert s["brownout"]["policy"] == "pin:stale"
        reg = tm.registry()
        with tm._LOCK:
            rungs = [
                m.value for (name, _), m in reg._metrics.items()
                if name == "oap_serve_brownout_rung"
            ]
        assert rungs == [3.0]


class TestServingChaos:
    def _storm_outcomes(self, handle, rng_seed: int):
        """One seeded storm under armed chaos; returns the per-request
        outcome tags (deterministic iff the chaos schedule is)."""
        clock = FakeClock()
        q = serving.TrafficQueue(handle, start=False, clock=clock)
        r = np.random.default_rng(rng_seed)
        futs = [
            q.submit(r.normal(size=(int(s), 8)).astype(np.float32))
            for s in r.integers(2, 20, size=16)
        ]
        _pump_until_done(q, futs, clock)
        q.close()
        out = []
        for f in futs:
            exc = f.exception()
            if exc is None:
                out.append("ok")
            elif isinstance(exc, serving.ServeError):
                out.append(f"serve:{exc.reason}")
            else:
                out.append(type(exc).__name__)
        return out

    def test_seeded_serving_chaos_is_deterministic(self, rng):
        # satellite 1: chaos over the serve.* sites, same seed + same
        # call sequence -> identical per-request outcome vector
        handle, _ = _kmeans_handle(rng)
        handle.warmup(32)
        set_config(serve_retry_limit=1, serve_retry_backoff=0.0)
        from oap_mllib_tpu.utils import faults

        spec = "1234:0.35:fail+nan"
        set_config(chaos=spec)
        run1 = self._storm_outcomes(handle, rng_seed=7)
        faults.reset()  # restart the schedule's call counters
        run2 = self._storm_outcomes(handle, rng_seed=7)
        set_config(chaos="")
        assert run1 == run2
        assert any(tag != "ok" for tag in run1)  # chaos really fired
