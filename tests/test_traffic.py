"""Traffic-plane tests (ISSUE 16): async ingestion, deadline-aware
batch formation, admission control, and the replica scale controller.

Contracts under test:

- deadline-ordered dispatch: out-of-order arrivals flush in deadline
  order (deterministic injected clock — no wall-clock in the loop);
- expired requests are shed BEFORE dispatch (their future raises a
  ``ShedError`` naming the deadline) and never reach the handle;
- every future resolves or raises EXACTLY once, including under a
  racing dispatcher thread and at close();
- admission sheds loudly — queue-full and budget sheds raise at
  ``submit`` with queue depth / priced-bytes detail and book
  ``oap_serve_shed_total{reason=}``;
- async answers are bit-identical to direct ``handle.predict`` calls;
- ``oap_serve_queue_depth`` is delta-folded under a tracked lock —
  race-safe under the dispatcher thread and clean with
  ``sanitizers="locks"`` armed;
- the scale controller votes out on sustained per-replica depth,
  in on idleness, books ``oap_serve_scale_*``, lands its decision in
  ``serving_summary()``, and posts the supervisor sideband hint.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from oap_mllib_tpu import serving
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.serving import registry, traffic
from oap_mllib_tpu.telemetry import metrics as tm


@pytest.fixture(autouse=True)
def _clear_serving():
    registry.clear()
    traffic._reset_for_tests()
    yield
    registry.clear()
    traffic._reset_for_tests()


class FakeClock:
    """Injected monotonic clock: deadline logic is tested without a
    single wall-clock read."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class SpyHandle:
    """Records each flush's per-request row counts and tags results so
    tests can match answers back to requests."""

    def __init__(self, fail: Exception | None = None):
        self.flushes: list[list[int]] = []
        self.fail = fail

    def predict_many(self, batches):
        self.flushes.append([b.shape[0] for b in batches])
        if self.fail is not None:
            raise self.fail
        return [np.full(b.shape[0], b.shape[0], np.int32) for b in batches]


def _kmeans_handle(rng, n=300, d=8, k=4):
    x = rng.normal(size=(n, d)).astype(np.float32)
    model = KMeans(k=k, seed=3, max_iter=3).fit(x)
    return serving.serve(model), x


def _shed_total(reason: str) -> int:
    reg = tm.registry()
    with tm._LOCK:
        return int(sum(
            m.value for (name, labels), m in reg._metrics.items()
            if name == "oap_serve_shed_total"
            and dict(labels).get("reason") == reason
        ))


class TestAdmission:
    def test_needs_predict_many(self):
        with pytest.raises(TypeError, match="predict_many"):
            serving.TrafficQueue(object(), start=False)

    def test_knob_typos_raise_at_submit(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        set_config(serve_queue_depth=0)
        with pytest.raises(ValueError, match="serve_queue_depth"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_queue_depth=4, serve_shed_headroom=1.5)
        with pytest.raises(ValueError, match="serve_shed_headroom"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_shed_headroom=0.5, serve_deadline_ms=-1.0)
        with pytest.raises(ValueError, match="serve_deadline_ms"):
            q.submit(np.zeros((1, 2)))
        set_config(serve_deadline_ms=0.0)
        with pytest.raises(ValueError, match="deadline_ms"):
            q.submit(np.zeros((1, 2)), deadline_ms=-5)

    def test_queue_full_sheds_loudly_at_submit(self):
        set_config(serve_queue_depth=2)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((1, 2)))
        q.submit(np.zeros((1, 2)))
        before = _shed_total("queue_full")
        with pytest.raises(serving.ShedError) as ei:
            q.submit(np.zeros((1, 2)), deadline_ms=25.0)
        e = ei.value
        assert e.reason == "queue_full"
        assert e.queue_depth == 2
        msg = str(e)
        # loud like scale_policy: the message names depth and deadline
        assert "serve_queue_depth=2" in msg
        assert "queue depth 2" in msg and "25.0 ms" in msg
        assert _shed_total("queue_full") == before + 1
        q.pump()
        q.close()

    def test_budget_shed_prices_against_membudget(self):
        # 4 KiB budget x 0.5 headroom = 2048 B allowance; one 100x8 f32
        # request (3200 B) x the planner fudge prices over it
        set_config(memory_budget_hbm="4K", serve_shed_headroom=0.5)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        before = _shed_total("budget")
        with pytest.raises(serving.ShedError) as ei:
            q.submit(np.zeros((100, 8), np.float32))
        e = ei.value
        assert e.reason == "budget"
        assert e.budget_bytes == 2048
        assert e.priced_bytes > e.budget_bytes
        assert "budget" in str(e) and "OOM" in str(e)
        assert _shed_total("budget") == before + 1
        # under the allowance is admitted: pending bytes accumulate
        f = q.submit(np.zeros((10, 8), np.float32))  # 320 B * 1.25
        with pytest.raises(serving.ShedError):
            # (320 + 1600) * 1.25 = 2400 B > the 2048 B allowance
            q.submit(np.zeros((50, 8), np.float32))
        q.pump()
        assert f.result(timeout=5) is not None
        q.close()

    def test_unbounded_budget_prices_nothing(self):
        set_config(memory_budget_hbm="0")  # explicit unlimited
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((5000, 8), np.float32))
        q.pump()
        q.close()

    def test_submit_after_close_raises(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.close()
        with pytest.raises(RuntimeError, match="closed"):
            q.submit(np.zeros((1, 2)))


class TestDeadlineBatching:
    def test_out_of_order_arrivals_flush_in_deadline_order(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        # arrival order: loose, tight, middle — dispatch must invert it
        q.submit(np.zeros((3, 2)), deadline_ms=5000)
        q.submit(np.zeros((7, 2)), deadline_ms=100)
        q.submit(np.zeros((5, 2)), deadline_ms=1000)
        q.pump()
        assert spy.flushes == [[7, 5, 3]]
        q.close()

    def test_no_deadline_sorts_last_by_arrival(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        q.submit(np.zeros((2, 2)))  # inf deadline
        q.submit(np.zeros((9, 2)), deadline_ms=50)
        q.submit(np.zeros((4, 2)))  # inf deadline, later arrival
        q.pump()
        assert spy.flushes == [[9, 2, 4]]
        q.close()

    def test_default_deadline_comes_from_config(self):
        set_config(serve_deadline_ms=10.0)
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        f = q.submit(np.zeros((1, 2)))  # inherits the 10 ms default
        clock.advance(1.0)
        q.pump()
        assert isinstance(f.exception(), serving.ShedError)
        assert f.exception().reason == "deadline"
        assert spy.flushes == []  # never dispatched
        q.close()

    def test_expired_shed_before_dispatch_live_still_answered(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        dead = q.submit(np.zeros((6, 2)), deadline_ms=10)
        live = q.submit(np.zeros((4, 2)), deadline_ms=60_000)
        before = _shed_total("deadline")
        clock.advance(0.5)  # past 10 ms, well under 60 s
        n = q.pump()
        assert n == 2
        exc = dead.exception()
        assert isinstance(exc, serving.ShedError)
        assert exc.reason == "deadline"
        assert "expired" in str(exc) and "10.0 ms" in str(exc)
        assert _shed_total("deadline") == before + 1
        assert live.result(timeout=5)[0] == 4
        assert spy.flushes == [[4]]  # the dead request never dispatched
        q.close()

    def test_max_batch_rows_splits_flushes_in_deadline_order(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(
            spy, start=False, clock=clock, max_batch_rows=10
        )
        q.submit(np.zeros((6, 2)), deadline_ms=300)
        q.submit(np.zeros((6, 2)), deadline_ms=100)
        q.submit(np.zeros((6, 2)), deadline_ms=200)
        q.pump()
        # tightest-deadline pair would overflow 10 rows: greedy split,
        # still deadline-ordered across flushes
        assert spy.flushes == [[6], [6], [6]] or spy.flushes == [[6, 6], [6]]
        q.close()

    def test_futures_resolve_exactly_once(self):
        clock = FakeClock()
        q = serving.TrafficQueue(SpyHandle(), start=False, clock=clock)
        f = q.submit(np.zeros((2, 2)))
        assert q.pump() == 1
        first = f.result(timeout=5)
        # a second cycle has nothing to do and cannot re-resolve
        assert q.pump() == 0
        assert f.result() is first
        q.close()

    def test_cancelled_future_dropped_without_dispatch(self):
        clock = FakeClock()
        spy = SpyHandle()
        q = serving.TrafficQueue(spy, start=False, clock=clock)
        f = q.submit(np.zeros((2, 2)))
        assert f.cancel()
        q.pump()
        assert spy.flushes == []
        assert f.cancelled()
        q.close()

    def test_handle_exception_lands_on_every_future_of_the_flush(self):
        clock = FakeClock()
        boom = RuntimeError("scoring failed")
        q = serving.TrafficQueue(
            SpyHandle(fail=boom), start=False, clock=clock
        )
        f1 = q.submit(np.zeros((2, 2)))
        f2 = q.submit(np.zeros((3, 2)))
        q.pump()
        assert f1.exception() is boom and f2.exception() is boom
        q.close()


class TestAsyncDispatch:
    def test_storm_answers_match_direct_predict(self, rng):
        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        batches = [
            rng.normal(size=(int(s), 8)).astype(np.float32)
            for s in rng.integers(3, 60, size=40)
        ]
        with serving.TrafficQueue(handle) as q:
            futs = [q.submit(b, deadline_ms=60_000) for b in batches]
            got = [f.result(timeout=60) for f in futs]
        for b, ids in zip(batches, got):
            np.testing.assert_array_equal(ids, handle.predict(b))

    def test_close_drains_pending(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        futs = [q.submit(np.zeros((2, 2))) for _ in range(5)]
        q.close()  # final inline pump resolves everything
        assert all(f.done() for f in futs)
        assert all(f.exception() is None for f in futs)

    def test_dispatcher_thread_is_daemon_and_joined(self):
        q = serving.TrafficQueue(SpyHandle())
        t = q._thread
        assert t is not None and t.daemon
        q.close()
        assert not t.is_alive()
        assert q._thread is None


class TestQueueDepthGauge:
    def _gauge(self):
        reg = tm.registry()
        with tm._LOCK:
            for (name, _), m in reg._metrics.items():
                if name == "oap_serve_queue_depth":
                    return m.value
        return None

    def test_gauge_tracks_pending_and_returns_to_zero(self):
        q = serving.TrafficQueue(SpyHandle(), start=False)
        for _ in range(3):
            q.submit(np.zeros((2, 2)))
        assert self._gauge() == 3
        assert q.depth() == 3
        q.pump()
        assert self._gauge() == 0
        assert q.depth() == 0
        q.close()

    def test_delta_folding_is_race_safe(self):
        # the bug the seam fixes: concurrent set() calls clobber each
        # other; delta folding under the tracked lock cannot
        n, per = 8, 200
        start = threading.Barrier(n)

        def hammer():
            start.wait()
            for _ in range(per):
                registry.note_queue_depth(1)
                registry.note_queue_depth(-1)

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert self._gauge() == 0

    def test_clean_under_locks_sanitizer(self, rng):
        from oap_mllib_tpu.utils import locktrace

        locktrace._reset_for_tests()
        set_config(sanitizers="locks")
        handle, _ = _kmeans_handle(rng)
        handle.warmup(64)
        # armed tracked locks raise LockOrderError on any live
        # inversion across submit / dispatcher / flush seams
        with serving.TrafficQueue(handle) as q:
            futs = [
                q.submit(
                    rng.normal(size=(5, 8)).astype(np.float32),
                    deadline_ms=60_000,
                )
                for _ in range(30)
            ]
            for f in futs:
                assert f.result(timeout=60) is not None
        set_config(sanitizers="")
        locktrace._reset_for_tests()


class TestScaleController:
    def test_validates_knobs(self):
        with pytest.raises(ValueError, match="replicas"):
            serving.ScaleController(0)
        set_config(serve_scale_high=0.0)
        with pytest.raises(ValueError, match="serve_scale_high"):
            serving.ScaleController(1)
        set_config(serve_scale_high=32.0, serve_scale_idle_s=-1.0)
        with pytest.raises(ValueError, match="serve_scale_idle_s"):
            serving.ScaleController(1)

    def test_scales_out_on_sustained_depth(self):
        set_config(serve_scale_high=4.0)
        clock = FakeClock()
        sc = serving.ScaleController(2, clock=clock)
        before = int(tm.family_total("oap_serve_scale_out_total"))
        decisions = [
            sc.observe(queue_depth=40, p99_s=0.2) for _ in range(4)
        ]
        assert [d["action"] for d in decisions[:-1]] == ["hold"] * 3
        last = decisions[-1]
        assert last["action"] == "out"
        assert last["replicas"] == 3
        assert "serve_scale_high=4" in last["reason"]
        assert int(tm.family_total("oap_serve_scale_out_total")) \
            == before + 1
        summary = registry.serving_summary()
        assert summary["scale"]["action"] == "out"

    def test_holds_while_depth_trend_falls(self):
        set_config(serve_scale_high=4.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        # mean depth/replica is over the bar, but falling fast: a
        # draining queue must not trigger growth
        for depth in (100, 90, 10, 5):
            d = sc.observe(queue_depth=depth)
        assert d["action"] == "hold"
        assert d["depth_trend"] == "falling"

    def test_scales_in_on_idleness(self):
        set_config(serve_scale_idle_s=5.0)
        clock = FakeClock()
        sc = serving.ScaleController(3, min_replicas=1, clock=clock)
        before = int(tm.family_total("oap_serve_scale_in_total"))
        d = sc.observe(queue_depth=0)
        assert d["action"] == "hold"
        clock.advance(6.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "in" and d["replicas"] == 2
        # traffic resets the idle clock
        clock.advance(6.0)
        d = sc.observe(queue_depth=3)
        assert d["action"] == "hold"
        assert int(tm.family_total("oap_serve_scale_in_total")) \
            == before + 1

    def test_growth_caps_and_floor(self):
        set_config(serve_scale_high=1.0, serve_scale_idle_s=1.0)
        clock = FakeClock()
        sc = serving.ScaleController(1, max_replicas=2, clock=clock)
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["replicas"] == 2
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["action"] == "hold" and d["replicas"] == 2  # capped
        clock.advance(10.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "in" and d["replicas"] == 1
        clock.advance(10.0)
        d = sc.observe(queue_depth=0)
        assert d["action"] == "hold" and d["replicas"] == 1  # floored

    def test_observe_view_folds_fleet_heartbeat(self):
        set_config(serve_scale_high=4.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        view = {"world": 2, "queue_depth": [30.0, 20.0],
                "requests": [100.0, 90.0]}
        d = sc.observe_view(view, p99_s=0.1)
        assert sc.replicas == 2
        assert d["queue_depth"] == 50

    def test_write_scale_hint_roundtrip(self, tmp_path):
        set_config(serve_scale_high=1.0)
        sc = serving.ScaleController(1, clock=FakeClock())
        for _ in range(4):
            d = sc.observe(queue_depth=50)
        assert d["action"] == "out"
        path = serving.write_scale_hint(str(tmp_path), d)
        assert path is not None
        import json

        with open(path) as f:
            assert json.load(f)["action"] == "out"
        # hold decisions post nothing
        hold = dict(d, action="hold")
        assert serving.write_scale_hint(str(tmp_path / "x"), hold) is None


class TestSummary:
    def test_serving_summary_grows_traffic_blocks(self):
        set_config(serve_queue_depth=1)
        q = serving.TrafficQueue(SpyHandle(), start=False)
        q.submit(np.zeros((1, 2)))
        with pytest.raises(serving.ShedError):
            q.submit(np.zeros((1, 2)))
        q.pump()
        q.close()
        s = registry.serving_summary()
        assert s["queue_depth"] == 0
        assert s["shed"]["total"] >= 1
        assert s["shed"]["queue_full"] >= 1
