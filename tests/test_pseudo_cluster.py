"""2-process pseudo-cluster integration test.

The reference's single most important distributed test is the 2-executor
pseudo-YARN cluster that forms a real 2-rank oneCCL world on one machine
(reference dev/ci-test.sh:60-62, dev/test-cluster/).  This is its analog:
two subprocesses join a real ``jax.distributed`` world over 127.0.0.1 (CPU
backend, 2 local devices each -> a 4-device global mesh), ingest
process-local data shards via ``DenseTable.from_process_local``, fit
K-Means (unweighted + weighted) and PCA, and the parent asserts the global
results equal the single-process oracle.

Runs unconditionally in dev/ci.sh as part of the suite.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "pseudo_cluster_worker.py")
_WORKER3 = os.path.join(os.path.dirname(__file__), "pseudo_cluster_worker3.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env():
    env = dict(os.environ)
    # workers pick their own device count; strip the parent suite's 8-device
    # forcing and pin the platform via env too (belt and braces)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split() if "xla_force_host_platform_device_count" not in f
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


# Environment-incapability signatures: a worker that died on one of these
# means this HOST cannot form a multiprocess jax world at all (a jax build
# whose CPU backend lacks multiprocess collectives, a sandbox that blocks
# the coordinator socket) — not a code regression.  _launch_world skips
# the suite with the captured output instead of erroring 16 tests.
_ENV_FAILURE_MARKERS = (
    "Multiprocess computations aren't implemented",
    "UNIMPLEMENTED",
    "Unable to initialize backend",
    "failed to join world",
    "DEADLINE_EXCEEDED",
    "Failed to connect to coordinator",
)


def _skip_if_environment_cannot_spawn(procs, outs):
    """pytest.skip (with the worker's captured stderr) when any worker hit
    a known environment-incapability signature.  Checked regardless of
    exit code: the error-injection worker catches exceptions itself and
    exits 0 even when what it caught was the environment, not the fault
    under test.  A worker that fails any OTHER way falls through to the
    caller's assertions — genuine regressions must still fail loudly."""
    for p, out in zip(procs, outs):
        if any(m in out for m in _ENV_FAILURE_MARKERS):
            pytest.skip(
                "pseudo-cluster world cannot run in this environment "
                f"(worker exit {p.returncode}); captured output:\n"
                + out[-2000:]
            )


def _launch_world(nproc=2, local_dev=2, timeout=300, worker=_WORKER,
                  env_extra=None):
    """Spawn an nproc world and collect (procs, outs, elapsed_sec) —
    the shared plumbing; callers interpret success/failure (the happy
    -path suites demand RESULT lines, the error-injection test demands
    prompt collective failure).  Worlds this environment cannot spawn
    at all skip the calling test instead of erroring it.  ``env_extra``
    rides into the workers' environment (worker mode switches)."""
    import time

    from oap_mllib_tpu.parallel.bootstrap import free_port

    coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
    env = _worker_env()
    if env_extra:
        env.update(env_extra)
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), str(nproc), coord, str(local_dev)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    _skip_if_environment_cannot_spawn(procs, outs)
    return procs, outs, time.monotonic() - t0


def _run_world(nproc=2, local_dev=2, timeout=300, worker=_WORKER,
               env_extra=None):
    procs, outs, _ = _launch_world(nproc, local_dev, timeout, worker,
                                   env_extra)
    results = {}
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, f"no RESULT line in worker output:\n{out}"
        r = json.loads(line[-1][len("RESULT "):])
        results[r["rank"]] = r
    return results


@pytest.fixture(scope="module")
def world_results():
    return _run_world()


@pytest.fixture(scope="module")
def world3_results():
    """3-process world, 1 device each, uneven thirds (1300/1300/1400)."""
    return _run_world(nproc=3, local_dev=1, worker=_WORKER3)


def _oracle_data():
    rng = np.random.default_rng(123)  # must match pseudo_cluster_worker.py
    proto = rng.normal(size=(5, 12)).astype(np.float32) * 3.0
    x = (proto[rng.integers(5, size=4000)]
         + rng.normal(size=(4000, 12)).astype(np.float32) * 0.25)
    return x


def _als_oracle_ratings():
    rng = np.random.default_rng(77)  # must match pseudo_cluster_worker.py
    nu, ni, rank = 60, 40, 3
    xt = rng.normal(size=(nu, rank)).astype(np.float32)
    yt = rng.normal(size=(ni, rank)).astype(np.float32)
    u = rng.integers(nu, size=1200).astype(np.int64)
    i = rng.integers(ni, size=1200).astype(np.int64)
    u[0], i[0] = nu - 1, ni - 1
    r = ((xt[u] * yt[i]).sum(1)
         + rng.normal(size=1200).astype(np.float32) * 0.1).astype(np.float32)
    return u, i, r


class TestPseudoCluster:
    def test_kmeans_matches_single_process(self, world_results):
        """Default (k-means||) init: the device-side rounds run multi-host
        and the converged objective matches the single-process fit."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _oracle_data()
        oracle = KMeans(k=5, seed=7, max_iter=30).fit(x)
        for rank in (0, 1):
            r = world_results[rank]
            assert r["kmeans_iters"] == oracle.summary.num_iter
            np.testing.assert_allclose(
                r["kmeans_cost"], oracle.summary.training_cost, rtol=1e-4
            )

    def test_uneven_shards_match_single_process(self, world_results):
        """1999 + 2000 valid rows: per-process padding sits mid-array, and
        random init must map valid indices around it (a padding row as a
        centroid, or an unreachable tail row, would shift the cost)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _oracle_data()[:3999]
        oracle = KMeans(k=5, seed=11, init_mode="random", max_iter=15).fit(x)
        for rank in (0, 1):
            np.testing.assert_allclose(
                world_results[rank]["uneven_cost"],
                oracle.summary.training_cost,
                rtol=1e-4,
            )

    def test_weighted_kmeans_matches_single_process(self, world_results):
        """sample_weight through the collective per-process path (the
        round-1 multi-host weighted fit was a shape-mismatch crash)."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _oracle_data()
        w = np.ones((4000,), np.float32)
        w[:100] = 2.5  # rank 0's first 100 rows
        w[2000:2100] = 2.5  # rank 1's first 100 rows
        oracle = KMeans(k=5, seed=7, init_mode="random", max_iter=10).fit(
            x, sample_weight=w
        )
        for rank in (0, 1):
            np.testing.assert_allclose(
                world_results[rank]["weighted_cost"],
                oracle.summary.training_cost,
                rtol=1e-4,
            )

    def test_model_axis_matches_single_process(self, world_results):
        """model_parallel=2 across the 2-process world: the feature-sharded
        K-Means Lloyd and model-sharded PCA Gram agree with single-process
        model_parallel=1 oracles."""
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.models.pca import PCA

        x = _oracle_data()
        km = KMeans(k=5, seed=7, init_mode="random", max_iter=15).fit(x)
        pc = PCA(k=4).fit(x)
        for rank in (0, 1):
            r = world_results[rank]
            assert r["kmeans_mp_iters"] == km.summary.num_iter
            np.testing.assert_allclose(
                r["kmeans_mp_cost"], km.summary.training_cost, rtol=1e-3
            )
            np.testing.assert_allclose(
                r["pca_mp_var"], np.asarray(pc.explained_variance_), rtol=1e-3
            )

    def test_pca_matches_single_process(self, world_results):
        from oap_mllib_tpu.models.pca import PCA

        x = _oracle_data()
        oracle = PCA(k=4).fit(x)
        for rank in (0, 1):
            r = world_results[rank]
            np.testing.assert_allclose(
                r["pca_var"], np.asarray(oracle.explained_variance_), rtol=1e-3
            )
            # eigenvector sign is arbitrary: compare |PC0| (the reference's
            # sign-insensitive pattern, IntelPCASuite.scala:80-86)
            np.testing.assert_allclose(
                r["pca_pc0_abs"],
                np.abs(np.asarray(oracle.components_)[:, 0]),
                atol=1e-4,
            )

    @pytest.mark.parametrize("tag,implicit", [("imp", True), ("exp", False)])
    def test_als_matches_single_process(self, world_results, tag, implicit):
        """Each rank fed only its local ratings shard (590/610 uneven
        split); factors must match the single-process fit.  Exercises the
        multi-process branches of exchange_ratings, the allgathered
        id-maxima, and the rank-local sharded-factor gather.  Tolerance is
        2x the block-vs-oracle bar since both sides carry f32 error."""
        from oap_mllib_tpu.models.als import ALS

        u, i, r = _als_oracle_ratings()
        oracle = ALS(rank=3, max_iter=3, reg_param=0.1, alpha=0.8,
                     implicit_prefs=implicit, seed=3).fit(u, i, r)
        for rank in (0, 1):
            res = world_results[rank]
            np.testing.assert_allclose(
                res[f"als_{tag}_uf"], oracle.user_factors_,
                atol=4e-3, rtol=4e-3,
            )
            np.testing.assert_allclose(
                res[f"als_{tag}_if"], oracle.item_factors_,
                atol=4e-3, rtol=4e-3,
            )

    def test_als_item_sharded_matches_single_process(self, world_results):
        """als_item_layout="sharded" across the real 2-process world: the
        second (item-block) shuffle, the all_gather exchange loop, and
        the collective item-factor gather must land on the same factors
        as the single-process fit."""
        from oap_mllib_tpu.models.als import ALS

        u, i, r = _als_oracle_ratings()
        oracle = ALS(rank=3, max_iter=3, reg_param=0.1, alpha=0.8,
                     implicit_prefs=True, seed=3).fit(u, i, r)
        for rank in (0, 1):
            res = world_results[rank]
            np.testing.assert_allclose(
                res["als_sh_uf"], oracle.user_factors_, atol=4e-3, rtol=4e-3
            )
            np.testing.assert_allclose(
                res["als_sh_if"], oracle.item_factors_, atol=4e-3, rtol=4e-3
            )
        assert world_results[0]["als_sh_if"] == world_results[1]["als_sh_if"]

    def test_streamed_kmeans_matches_single_process(self, world_results):
        """Each rank streams its local half as a ChunkSource; the
        host-mediated cross-process reductions must land on the same
        clustering quality as the single-process streamed fit (init RNG
        merges differ across world sizes, so compare cost — survey §7.3)."""
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _oracle_data()
        oracle = KMeans(k=5, seed=7, max_iter=30).fit(
            ChunkSource.from_array(x, chunk_rows=512)
        )
        for rank in (0, 1):
            r = world_results[rank]
            # well-separated blobs: both reach the same optimum
            np.testing.assert_allclose(
                r["streamed_cost"], oracle.summary.training_cost, rtol=1e-3
            )
            np.testing.assert_allclose(
                r["streamed_rand_cost"], oracle.summary.training_cost,
                rtol=1e-3,
            )

    def test_streamed_pca_matches_single_process(self, world_results):
        """Streamed PCA over per-process shards == streamed PCA over the
        full table (exact moments, fp tolerance only)."""
        from oap_mllib_tpu.models.pca import PCA

        x = _oracle_data()
        oracle = PCA(k=4).fit(x)
        for rank in (0, 1):
            r = world_results[rank]
            np.testing.assert_allclose(
                r["streamed_pca_var"],
                np.asarray(oracle.explained_variance_), rtol=1e-3,
            )
            np.testing.assert_allclose(
                r["streamed_pca_pc0_abs"],
                np.abs(np.asarray(oracle.components_)[:, 0]), atol=1e-4,
            )

    def test_three_process_world(self, world3_results):
        """Uneven thirds over 3 processes (a world size the reference
        never tested): in-memory mesh AND streamed per-process-source
        fits match the single-process oracles; all ranks agree."""
        from oap_mllib_tpu.models.kmeans import KMeans
        from oap_mllib_tpu.models.pca import PCA

        x = _oracle_data()
        km = KMeans(k=5, seed=7, max_iter=30).fit(x)
        pc = PCA(k=4).fit(x)
        for rank in (0, 1, 2):
            r = world3_results[rank]
            np.testing.assert_allclose(
                r["kmeans_cost"], km.summary.training_cost, rtol=1e-3
            )
            np.testing.assert_allclose(
                r["pca_var"], np.asarray(pc.explained_variance_), rtol=1e-3
            )
            np.testing.assert_allclose(
                r["streamed_cost"], km.summary.training_cost, rtol=1e-3
            )
            np.testing.assert_allclose(
                r["streamed_pca_var"],
                np.asarray(pc.explained_variance_), rtol=1e-3,
            )
        assert world3_results[0] == {**world3_results[0], **{
            k: v for k, v in world3_results[1].items() if k != "rank"
        }}
        assert (
            world3_results[1]["streamed_cost"]
            == world3_results[2]["streamed_cost"]
        )

    def test_three_process_item_sharded_als(self, world3_results):
        """als_item_layout="sharded" over 3 ranks (a block count that is
        neither 2 nor a power of two — the last item block is short):
        factors match the single-process fit on the same global edges."""
        from oap_mllib_tpu.models.als import ALS

        rng_als = np.random.default_rng(77)
        nu, ni = 60, 40
        u = rng_als.integers(nu, size=1200).astype(np.int64)
        i = rng_als.integers(ni, size=1200).astype(np.int64)
        u[0], i[0] = nu - 1, ni - 1
        r = rng_als.random(1200).astype(np.float32) * 4 + 1
        oracle = ALS(rank=3, max_iter=3, reg_param=0.1,
                     implicit_prefs=True, seed=3).fit(u, i, r)
        for rank in (0, 1, 2):
            np.testing.assert_allclose(
                world3_results[rank]["als_sh_if"], oracle.item_factors_,
                atol=4e-3, rtol=4e-3,
            )
            # streamed-block 2-D over the same 3-rank world (short last
            # item block through the cross-process double redistribution)
            np.testing.assert_allclose(
                world3_results[rank]["als_st3_if"], oracle.item_factors_,
                atol=4e-3, rtol=4e-3,
            )

    def test_streamed_block_als_two_process(self, world_results):
        """Out-of-core ALS composed with a REAL 2-process world: each
        rank streamed only its local triples; the block redistribution
        ran over the process boundary and the chunked uploads + block
        collectives must land on the single-process factors."""
        from oap_mllib_tpu.models.als import ALS

        u, i, r = _als_oracle_ratings()
        oracle = ALS(rank=3, max_iter=3, reg_param=0.1, alpha=0.8,
                     implicit_prefs=True, seed=3).fit(u, i, r)
        for rank in (0, 1):
            res = world_results[rank]
            np.testing.assert_allclose(
                res["als_st_uf"], oracle.user_factors_,
                atol=4e-3, rtol=4e-3,
            )
            np.testing.assert_allclose(
                res["als_st_if"], oracle.item_factors_,
                atol=4e-3, rtol=4e-3,
            )
            # 2-D item-sharded streamed composition (double
            # redistribution + cross-process replicate + collective
            # factor gathers) lands on the same factors
            np.testing.assert_allclose(
                res["als_st_sh_uf"], oracle.user_factors_,
                atol=4e-3, rtol=4e-3,
            )
            np.testing.assert_allclose(
                res["als_st_sh_if"], oracle.item_factors_,
                atol=4e-3, rtol=4e-3,
            )
        assert world_results[0]["als_st_if"] == world_results[1]["als_st_if"]
        assert (
            world_results[0]["als_st_sh_if"]
            == world_results[1]["als_st_sh_if"]
        )

    def test_adapter_partitioned_kmeans(self, world_results):
        """The PySpark adapter's multi-process ingestion: each rank
        materialized only its partitions of a mocked partitioned
        DataFrame (pid % world == rank) and fed them as its local shard;
        the converged cost must match the single-process fit on the full
        data, and both ranks must agree exactly."""
        from oap_mllib_tpu.models.kmeans import KMeans

        x = _oracle_data()
        oracle = KMeans(k=5, seed=7, max_iter=30).fit(x)
        for rank in (0, 1):
            np.testing.assert_allclose(
                world_results[rank]["adapter_mp_cost"],
                oracle.summary.training_cost, rtol=1e-3,
            )
        assert (
            world_results[0]["adapter_mp_cost"]
            == world_results[1]["adapter_mp_cost"]
        )

    def test_adapter_partitioned_als(self, world_results):
        """Adapter ALS over partitioned ratings: factors match the
        single-process fit, and the cold-start seen-user sets are
        WORLD-consistent (global uniques, not rank-local) — rank-local
        sets would drop different rows on different ranks."""
        from oap_mllib_tpu.models.als import ALS

        u, i, r = _als_oracle_ratings()
        oracle = ALS(rank=3, max_iter=3, reg_param=0.1, alpha=0.8,
                     implicit_prefs=True, seed=3).fit(u, i, r)
        expect_seen = sorted(int(v) for v in np.unique(u))
        for rank in (0, 1):
            res = world_results[rank]
            np.testing.assert_allclose(
                res["adapter_als_uf"], oracle.user_factors_,
                atol=4e-3, rtol=4e-3,
            )
            assert res["adapter_seen_users"] == expect_seen
        assert (
            world_results[0]["adapter_als_uf"]
            == world_results[1]["adapter_als_uf"]
        )

    def test_source_error_fails_world_fast(self):
        """The _PassGuard contract in a REAL 2-process world: rank 1's
        source errors mid-pass, and BOTH ranks must raise out of the
        same fit promptly — not hang in process_allgather until the
        distributed timeout (the pre-round-4 behavior)."""
        worker = os.path.join(
            os.path.dirname(__file__), "pseudo_cluster_worker_err.py"
        )
        procs, outs, elapsed = _launch_world(
            nproc=2, local_dev=1, timeout=120, worker=worker
        )
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker did not see the error:\n{out}"
            assert "EXPECTED_ERROR" in out, out
        # rank 0's source is consistent — its failure can only be the
        # guard flag riding the collective (the mechanism under test)
        assert "RuntimeError: streamed pass failed" in outs[0], outs[0]
        assert "deterministic" in outs[1], outs[1]  # the original error
        # both ranks failed together, well under any distributed timeout
        assert elapsed < 90, f"world took {elapsed:.0f}s to fail"

    def test_ranks_agree(self, world_results):
        """Replicated results must be bitwise-identical across ranks."""
        assert world_results[0]["kmeans_cost"] == world_results[1]["kmeans_cost"]
        assert world_results[0]["pca_var"] == world_results[1]["pca_var"]
        assert world_results[0]["als_imp_if"] == world_results[1]["als_imp_if"]
        assert world_results[0]["streamed_cost"] == world_results[1]["streamed_cost"]
        assert (
            world_results[0]["streamed_pca_var"]
            == world_results[1]["streamed_pca_var"]
        )


_SANITIZER_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_sanitizer.py"
)
_CKPT_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_ckpt.py"
)


class TestElasticWorlds:
    """ISSUE 8 acceptance: kill-and-resume across a REAL 2-process world
    (utils/checkpoint.py), plus the 2->1 resharded restore."""

    def _launch_kill_world(self, ckdir, timeout=240):
        """Victim world: rank 1 hard-kills itself mid-pass; rank 0 is
        left in the pass collective and reaped by this watchdog — the
        preemption the elastic-worlds subsystem exists for."""
        import time

        from oap_mllib_tpu.parallel.bootstrap import free_port

        coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
        env = _worker_env()
        env.update({
            "CKPT_WORKER_MODE": "victim", "CKPT_CHECKPOINT_DIR": ckdir,
        })
        procs = [
            subprocess.Popen(
                [sys.executable, _CKPT_WORKER, str(r), "2", coord, "1"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=_REPO,
            )
            for r in range(2)
        ]
        deadline = time.monotonic() + timeout
        while procs[1].poll() is None and time.monotonic() < deadline:
            time.sleep(0.5)
        # rank 0 has lost its peer; give it a moment, then reap it
        grace = time.monotonic() + 20
        while procs[0].poll() is None and time.monotonic() < grace:
            time.sleep(0.5)
        outs = []
        for p in procs:
            if p.poll() is None:
                p.kill()
            out, _ = p.communicate(timeout=60)
            outs.append(out)
        _skip_if_environment_cannot_spawn(procs, outs)
        return procs, outs

    def test_kill_and_resume_matches_uninterrupted(self, tmp_path):
        full_dir = str(tmp_path / "full")
        kill_dir = str(tmp_path / "kill")
        # leg 1: the uninterrupted checkpoint-armed world (the oracle)
        full = _run_world(
            nproc=2, local_dev=1, worker=_CKPT_WORKER,
            env_extra={"CKPT_WORKER_MODE": "full",
                       "CKPT_CHECKPOINT_DIR": full_dir},
        )
        assert full[0]["decision"] == "fresh"
        assert full[0]["ladder"] == "bypassed(static-world)"
        assert full[0]["centers_hex"] == full[1]["centers_hex"]

        # leg 2: the same fit, rank 1 preempted mid-pass-3
        procs, outs = self._launch_kill_world(kill_dir)
        assert procs[1].returncode == 9, outs[1]  # genuinely killed
        # passes 1-2 are durable: both rank shards + the manifest
        mdirs = os.listdir(kill_dir)
        assert len(mdirs) == 1
        manifest = json.load(
            open(os.path.join(kill_dir, mdirs[0], "manifest.json"))
        )
        assert manifest["step"] == 2 and manifest["world"] == 2

        # leg 3: a RELAUNCHED 2-process world resumes and must match the
        # uninterrupted run bit-for-bit
        resumed = _run_world(
            nproc=2, local_dev=1, worker=_CKPT_WORKER,
            env_extra={"CKPT_WORKER_MODE": "resume",
                       "CKPT_CHECKPOINT_DIR": kill_dir},
        )
        for rank in (0, 1):
            assert resumed[rank]["decision"] == "found"
            assert resumed[rank]["restored_step"] == 2
            assert resumed[rank]["centers_hex"] == full[rank]["centers_hex"]
            assert resumed[rank]["cost"] == full[rank]["cost"]

        # leg 4: 2 -> 1 resharded restore — THIS process (a 1-process
        # world) consumes the 2-rank checkpoint and must land within fp
        # tolerance of the 2-process run (reduction order changes)
        import numpy as _np

        from oap_mllib_tpu.config import set_config
        from oap_mllib_tpu.data.stream import ChunkSource
        from oap_mllib_tpu.models.kmeans import KMeans

        rng = _np.random.default_rng(321)  # must match the worker
        x = rng.normal(size=(3000, 8)).astype(_np.float32)
        set_config(checkpoint_dir=kill_dir)
        try:
            m1 = KMeans(
                k=4, seed=7, init_mode="random", max_iter=6, tol=0.0
            ).fit(ChunkSource.from_array(x, chunk_rows=500))
        finally:
            set_config(checkpoint_dir="")
        assert m1.summary.checkpoint["decision"] == "resharded"
        assert m1.summary.checkpoint["old_world"] == 2
        _np.testing.assert_allclose(
            m1.summary.training_cost, full[0]["cost"], rtol=1e-5
        )


_RECOVERY_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_recovery.py"
)


class TestLiveWorldRecovery:
    """ISSUE 10 acceptance: the recovery plane across a REAL 2-process
    world — a SIGKILLed rank converts every survivor's hang into a
    prompt CollectiveTimeoutError, and a poisoned sideband aborts peers
    out of their collectives (utils/recovery.py)."""

    def _launch_recovery_world(self, mode, crash_dir, timeout=120):
        """Spawn the 2-rank drill world.  Unlike the elastic-worlds kill
        leg, the parent never reaps the survivor: the plane under test
        is that EVERY rank exits on its own, within the deadline."""
        import time

        from oap_mllib_tpu.parallel.bootstrap import free_port

        coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
        env = _worker_env()
        env.update({
            "RECOVERY_WORKER_MODE": mode, "RECOVERY_CRASH_DIR": crash_dir,
        })
        t0 = time.monotonic()
        procs = [
            subprocess.Popen(
                [sys.executable, _RECOVERY_WORKER, str(r), "2", coord, "1"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=_REPO,
            )
            for r in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        _skip_if_environment_cannot_spawn(procs, outs)
        return procs, outs, time.monotonic() - t0

    def test_rank_kill_raises_timeout_on_survivors(self, tmp_path):
        """Satellite leg: rank 1 is SIGKILLed mid-collective; rank 0
        must raise CollectiveTimeoutError within collective_timeout —
        exiting BY ITSELF, well inside the 120 s watchdog — with its
        crash record (fault class, last-completed fingerprint) in the
        sideband for the supervisor to classify."""
        crash_dir = str(tmp_path / "sideband")
        procs, outs, elapsed = self._launch_recovery_world(
            "hang", crash_dir
        )
        assert procs[1].returncode == -9, outs[1]  # genuinely SIGKILLed
        assert procs[0].returncode == 0, f"survivor did not self-exit:\n{outs[0]}"
        assert "TIMEOUT_CAUGHT" in outs[0], outs[0]
        # the survivor's diagnosis landed in the sideband, machine-readable
        rec_path = os.path.join(crash_dir, "crash.rank0.json")
        assert os.path.exists(rec_path), os.listdir(crash_dir)
        rec = json.load(open(rec_path))
        assert rec["fault_class"] == "collective_timeout"
        assert rec["rank"] == 0 and rec["world"] == 2
        assert rec["last_checkpoint_step"] == -1  # no checkpointing armed
        assert "telemetry" in rec
        # the whole drill completed well under the distributed timeout
        assert elapsed < 90, f"world took {elapsed:.0f}s to diagnose"

    def test_peer_crash_record_aborts_collectives(self, tmp_path):
        """Coordinated abort: rank 1's fatal fault never reaches a
        collective — only the sideband can tell rank 0, which must
        raise PeerAbortError promptly instead of burning the full
        deadline."""
        crash_dir = str(tmp_path / "sideband")
        procs, outs, elapsed = self._launch_recovery_world(
            "abort", crash_dir
        )
        assert procs[1].returncode == 3, outs[1]
        assert "ABORT_RECORDED" in outs[1], outs[1]
        assert procs[0].returncode == 0, f"survivor did not self-exit:\n{outs[0]}"
        assert "PEER_ABORT_CAUGHT" in outs[0], outs[0]
        assert "peer=1" in outs[0], outs[0]
        # both ranks' records in the sideband: the culprit's fault and
        # the victim's abort
        recs = {
            f: json.load(open(os.path.join(crash_dir, f)))
            for f in os.listdir(crash_dir) if f.endswith(".json")
        }
        assert recs["crash.rank1.json"]["fault_class"] == "unclassified"
        assert recs["crash.rank0.json"]["fault_class"] == "peer_abort"
        assert elapsed < 90, f"world took {elapsed:.0f}s to abort"


class TestSanitizerPlane:
    """The runtime sanitizer plane (utils/sanitizers.py) across a REAL
    2-process world — the configuration it exists for."""

    def test_collective_sanitizer_names_divergence_instead_of_hanging(self):
        """ISSUE 7 acceptance: rank 0 dispatches allreduce_sum while
        rank 1 dispatches allgather_rows — without the sanitizer this
        wedges both ranks inside mismatched collectives until the
        distributed timeout; with `collective` armed, BOTH ranks must
        raise a CollectiveDivergenceError naming both ops, promptly
        (the watchdog is the 120 s world timeout)."""
        procs, outs, elapsed = _launch_world(
            nproc=2, local_dev=1, timeout=120, worker=_SANITIZER_WORKER,
            env_extra={"SANITIZER_WORKER_MODE": "diverge"},
        )
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"divergence not caught:\n{out}"
            assert "DIVERGENCE_CAUGHT" in out, out
        assert elapsed < 100, f"world took {elapsed:.0f}s to diagnose"

    @pytest.fixture(scope="class")
    def probe_results(self):
        return _run_world(
            nproc=2, local_dev=2, worker=_SANITIZER_WORKER,
            env_extra={"SANITIZER_WORKER_MODE": "probe"},
        )

    def test_facade_books_per_shard_bytes(self, probe_results):
        """ISSUE 7 satellite: the facade must book each PROCESS's shard
        bytes (half the global array here), not the unsharded abstract
        shape — so the world's byte counters sum to the wire traffic
        instead of world × payload."""
        for rank in (0, 1):
            r = probe_results[rank]
            assert r["booked_bytes"] == r["global_bytes"] / 2, r

    def test_sanitized_streamed_fit_clean_and_fingerprint_agrees(
            self, probe_results):
        """All three sanitizers armed over a streamed multi-process fit:
        the fit must succeed (no false positives from the transfer/
        retrace guards), the collective fingerprint must be world-checked
        and identical across ranks, and the costs must agree exactly."""
        r0, r1 = probe_results[0], probe_results[1]
        assert r0["san_ops"] > 0
        assert r0["san_world_checked"] and r1["san_world_checked"]
        assert r0["san_fingerprint"] == r1["san_fingerprint"]
        assert r0["streamed_cost"] == r1["streamed_cost"]


_FLEET_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_fleet.py"
)


class TestFleetObservability:
    """ISSUE 11 acceptance: the fleet control plane across a REAL
    2-process world — per-pass rollups agree on every rank, a
    deliberately slowed rank is named with skew > 1.5, the live
    /metrics endpoint serves oap_fleet_* mid-fit, and a SIGKILL
    drill's crash records carry >= 32-event flight-recorder tails."""

    def _launch_fleet_world(self, mode, env_extra=None, timeout=180):
        import time

        from oap_mllib_tpu.parallel.bootstrap import free_port

        coord = f"127.0.0.1:{free_port('127.0.0.1', 4000)}"
        env = _worker_env()
        env["FLEET_WORKER_MODE"] = mode
        env.update(env_extra or {})
        t0 = time.monotonic()
        procs = [
            subprocess.Popen(
                [sys.executable, _FLEET_WORKER, str(r), "2", coord, "1"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=_REPO,
            )
            for r in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        _skip_if_environment_cannot_spawn(procs, outs)
        return procs, outs, time.monotonic() - t0

    @staticmethod
    def _tagged_json(out, tag, rank):
        line = [
            ln for ln in out.splitlines()
            if ln.startswith(f"{tag} rank={rank} ")
        ]
        assert line, f"no {tag} line for rank {rank}:\n{out}"
        return json.loads(line[0].split(" ", 2)[2])

    def test_skewed_rank_named_and_rollups_agree(self):
        """A slowed rank 1 must show up in every rank's identical fleet
        window, the summary block must name it with skew > 1.5, and
        rank 0's live endpoint must serve oap_fleet_* families while
        the fit is running."""
        from oap_mllib_tpu.parallel.bootstrap import free_port

        port = free_port("127.0.0.1", 9400)
        procs, outs, _ = self._launch_fleet_world(
            "skew", {"FLEET_METRICS_PORT": str(port)}
        )
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out}"
        blocks = [self._tagged_json(outs[r], "FLEETBLOCK", r)
                  for r in range(2)]
        windows = [self._tagged_json(outs[r], "WINDOW", r)
                   for r in range(2)]
        # the gathered per-pass frames are identical on every rank (the
        # rollup is a rank-uniform allgather) ...
        assert windows[0] == windows[1]
        assert len(windows[0]) >= 4  # per-pass granularity: >= max_iter
        # ... and rank 0's fold equals a hand-fold of the per-rank rows
        for w in windows[0]:
            frames = np.asarray(w["frames"])
            assert frames.shape[0] == 2
            for i, field in enumerate([
                "pass_wall_s", "stage_s", "transfer_s", "compute_s",
                "bytes_staged", "retries", "kernel_dispatch_s",
            ]):
                got = w["fields"][field]
                col = frames[:, i]
                assert abs(got["mean"] - col.mean()) < 1e-9
                assert abs(got["min"] - col.min()) < 1e-9
                assert abs(got["max"] - col.max()) < 1e-9
        # the straggler analytics name the slowed rank with real skew
        for block in blocks:
            assert block["enabled"] and block["passes"] >= 4
            assert block["slowest_rank"] == 1, block
            assert block["fit_skew_ratio"] > 1.5, block
        # the live endpoint served fleet families mid-fit on rank 0
        assert "SCRAPE OK rank=0" in outs[0], outs[0]

    def test_sigkill_crash_record_carries_recorder_tail(self, tmp_path):
        """A SIGKILLed rank 1 mid-pass: the surviving rank's v2 crash
        record must embed a >= 32-event flight-recorder tail whose
        events cover chunk progress and collective dispatches — the
        "what happened just before" a post-mortem needs."""
        crash_dir = str(tmp_path / "sideband")
        procs, outs, elapsed = self._launch_fleet_world(
            "kill", {"FLEET_CRASH_DIR": crash_dir}, timeout=120
        )
        assert procs[1].returncode == -9, outs[1]
        assert procs[0].returncode == 0, outs[0]
        assert "TIMEOUT_CAUGHT" in outs[0], outs[0]
        rec = json.load(
            open(os.path.join(crash_dir, "crash.rank0.json"))
        )
        assert rec["version"] == 2
        tail = rec["flight_recorder"]
        assert len(tail) >= 32, f"only {len(tail)} recorder events"
        kinds = {e["kind"] for e in tail}
        assert "chunk" in kinds and "collective" in kinds, kinds
        seqs = [e["seq"] for e in tail]
        assert seqs == sorted(seqs)  # tails are seq-ordered
        assert elapsed < 90, f"world took {elapsed:.0f}s to diagnose"


_SERVING_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_serving.py"
)


def _answer_digests(out):
    """leg -> digest from a serving worker's ANSWER lines."""
    digests = {}
    for ln in out.splitlines():
        if ln.startswith("ANSWER "):
            parts = dict(p.split("=") for p in ln.split()[1:])
            digests[int(parts["leg"])] = parts["digest"]
    return digests


class TestServingPlane:
    """ISSUE 13 serving availability: a REAL 2-replica serving fleet —
    the replica that misses its collective deadline is EVICTED, the
    survivor keeps answering bit-identical results in local-only mode,
    and the supervisor's relaunched replacement answers exactly the
    same requests (serving/ha.py composed with utils/recovery.py)."""

    def test_replica_eviction_survivors_unchanged(self, tmp_path):
        crash_dir = str(tmp_path / "sideband")
        os.makedirs(crash_dir, exist_ok=True)
        procs, outs, elapsed = _launch_world(
            nproc=2, local_dev=1, timeout=120, worker=_SERVING_WORKER,
            env_extra={
                "SERVING_WORKER_MODE": "evict",
                "SERVING_CRASH_DIR": crash_dir,
            },
        )
        # rank 1 was genuinely preempted; rank 0 survived, evicted the
        # fleet, and finished EVERY serving leg
        assert procs[1].returncode == -9, outs[1]
        assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
        assert "EVICTED rank=0" in outs[0], outs[0]
        assert "CollectiveTimeoutError" in outs[0], outs[0]
        assert "SERVE_OK rank=0 legs=6 local_only=True" in outs[0], outs[0]
        assert "FLEET rank=0 world=2" in outs[0], outs[0]
        survivor = _answer_digests(outs[0])
        assert sorted(survivor) == list(range(6)), survivor
        # the evicted replica answered identically while it lived
        victim = _answer_digests(outs[1])
        for leg, dig in victim.items():
            assert survivor[leg] == dig, (leg, survivor, victim)
        # the survivor's diagnosis is in the sideband for the
        # supervisor's classification
        rec = json.load(
            open(os.path.join(crash_dir, "crash.rank0.json"))
        )
        assert rec["fault_class"] == "collective_timeout"
        assert elapsed < 90, f"fleet took {elapsed:.0f}s to evict"

        # the supervisor's relaunch: a replacement replica (fresh
        # 1-process world) serves the SAME requests and answers exactly
        # what the survivor answered — eviction never changed results
        procs2, outs2, _ = _launch_world(
            nproc=1, local_dev=1, timeout=120, worker=_SERVING_WORKER,
            env_extra={
                "SERVING_WORKER_MODE": "relaunched",
                "SERVING_CRASH_DIR": crash_dir,
            },
        )
        assert procs2[0].returncode == 0, outs2[0]
        assert "SERVE_OK rank=0 legs=6" in outs2[0], outs2[0]
        relaunched = _answer_digests(outs2[0])
        assert relaunched == survivor, (relaunched, survivor)


_TRAFFIC_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_traffic.py"
)


def _traffic_fields(out, tag):
    """``tag k=v ...`` line -> {k: v} from a traffic worker's output."""
    line = [ln for ln in out.splitlines() if ln.startswith(tag + " ")]
    assert line, f"no {tag} line in worker output:\n{out}"
    return dict(p.split("=", 1) for p in line[-1].split()[1:])


class TestTrafficPlane:
    """ISSUE 16 acceptance: the async traffic plane across a REAL
    2-replica serving fleet — the factor-sharded sweep is bit-identical
    to the single-process reference on a live multi-process mesh, a
    jittered storm through the TrafficQueue holds the zero-steady-
    compile and p99-vs-p50 contracts, sheds stay loud, and a SIGKILLed
    replica is evicted while the survivor keeps the same contracts in
    local-only mode (serving/traffic.py + serving/ha.py)."""

    def _launch_traffic_world(self, mode, crash_dir, timeout=180):
        os.makedirs(crash_dir, exist_ok=True)
        return _launch_world(
            nproc=2, local_dev=1, timeout=timeout, worker=_TRAFFIC_WORKER,
            env_extra={
                "TRAFFIC_WORKER_MODE": mode,
                "TRAFFIC_CRASH_DIR": crash_dir,
            },
        )

    @staticmethod
    def _check_storm(out, rank, expect_local_only):
        storm = _traffic_fields(out, f"STORM_OK rank={rank}")
        assert storm["compiles"] == "0", storm
        assert storm["local_only"] == str(expect_local_only), storm
        p50, p99 = float(storm["p50_ms"]), float(storm["p99_ms"])
        # same tail bound as dev/serve_gate.py leg 5: a compile or
        # re-upload in the tail costs 100x+, scheduler jitter does not
        assert p99 <= max(50.0 * p50, 250.0), storm
        return storm

    def test_healthy_fleet_parity_storm_and_sheds(self, tmp_path):
        procs, outs, elapsed = self._launch_traffic_world(
            "healthy", str(tmp_path / "sideband")
        )
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out}"
        # the sharded sweep agreed with each rank's IN-PROCESS single
        # -process reference, and both ranks answered identical bits
        digs = [_traffic_fields(outs[r], f"PARITY_OK rank={r}")["digest"]
                for r in range(2)]
        assert digs[0] == digs[1], digs
        for r in range(2):
            assert f"FLEET rank={r} world=2" in outs[r], outs[r]
            self._check_storm(outs[r], r, expect_local_only=False)
        assert "SHED_OK rank=0 sheds=3" in outs[0], outs[0]
        assert elapsed < 150, f"fleet took {elapsed:.0f}s"

    def test_evicted_replica_survivor_keeps_contracts(self, tmp_path):
        crash_dir = str(tmp_path / "sideband")
        procs, outs, elapsed = self._launch_traffic_world(
            "evict", crash_dir
        )
        # rank 1 genuinely preempted mid-storm; rank 0 evicted the
        # fleet and finished every wave + the shed legs on its own
        assert procs[1].returncode == -9, outs[1]
        assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
        assert "EVICTED rank=0" in outs[0], outs[0]
        assert "err=CollectiveTimeoutError" in outs[0], outs[0]
        self._check_storm(outs[0], 0, expect_local_only=True)
        assert "SHED_OK rank=0 sheds=3" in outs[0], outs[0]
        # the survivor's diagnosis is in the sideband for the
        # supervisor's classification + relaunch
        rec = json.load(
            open(os.path.join(crash_dir, "crash.rank0.json"))
        )
        assert rec["fault_class"] == "collective_timeout"
        assert elapsed < 150, f"fleet took {elapsed:.0f}s to evict"


_BALANCE_WORKER = os.path.join(
    os.path.dirname(__file__), "pseudo_cluster_worker_balance.py"
)


class TestHeteroFleet:
    """ISSUE 15 acceptance: capability-weighted sharding across a REAL
    2-process world with one deliberately slowed rank — the weighted
    layout beats the equal layout end-to-end, results stay within 1e-5,
    the decision trail lands in summary.balance, and the live straggler
    controller re-plans an initially-equal world mid-fit."""

    # per-chunk sleep on rank 1: equal layout pays ~12 chunks x sleep
    # per pass, the 1:0.25-weighted layout ~5 — a wide, scheduler-noise
    # -proof gap across the fit's 9 rollup passes
    _SLEEP = "0.05"

    def _launch_balance_world(self, mode, timeout=120):
        procs, outs, elapsed = _launch_world(
            nproc=2, local_dev=1, timeout=timeout, worker=_BALANCE_WORKER,
            env_extra={
                "BALANCE_WORKER_MODE": mode,
                "BALANCE_CHUNK_SLEEP": self._SLEEP,
            },
        )
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out}"
        return outs

    @staticmethod
    def _tagged_json(out, tag, rank):
        line = [
            ln for ln in out.splitlines()
            if ln.startswith(f"{tag} rank={rank} ")
        ]
        assert line, f"no {tag} line for rank {rank}:\n{out}"
        return json.loads(line[0].split(" ", 2)[2])

    def test_weighted_layout_beats_equal_with_parity(self):
        """The capability-weighted world must finish measurably faster
        than the equal-shard world on the same slowed rank, with
        centers within 1e-5 and the plan visible in summary.balance."""
        eq = self._launch_balance_world("equal")
        wt = self._launch_balance_world("weighted")

        eq_res = [self._tagged_json(eq[r], "RESULT", r) for r in range(2)]
        wt_res = [self._tagged_json(wt[r], "RESULT", r) for r in range(2)]
        # world wall = the slowest rank's wall (the pass barrier)
        eq_wall = max(r["wall_s"] for r in eq_res)
        wt_wall = max(r["wall_s"] for r in wt_res)
        assert wt_wall < 0.75 * eq_wall, (
            f"weighted layout ({wt_wall:.2f}s) did not beat equal "
            f"({eq_wall:.2f}s) by the required margin"
        )
        # parity: same optimization, different reduction grouping
        c_eq = np.asarray(eq_res[0]["centers"])
        c_wt = np.asarray(wt_res[0]["centers"])
        assert np.max(np.abs(c_eq - c_wt)) <= 1e-5
        assert abs(eq_res[0]["cost"] - wt_res[0]["cost"]) <= 1e-3 * max(
            abs(eq_res[0]["cost"]), 1.0
        )
        # every rank computed the identical plan (rank-uniform contract)
        blocks = [self._tagged_json(wt[r], "BALANCE", r) for r in range(2)]
        assert blocks[0] == blocks[1]
        block = blocks[0]
        assert block["origin"] == "pinned"
        assert block["enabled"] is True
        extents = block["extents"]
        assert sum(r for _, r in extents) == 6000
        # rank 1 (capability 0.25) must hold the smaller extent
        assert extents[1][1] < extents[0][1]
        # fleet block shows assignment vs achievement side by side
        rows = self._tagged_json(wt[0], "FLEETROWS", 0)
        assert rows["per_rank_capability"] is not None
        assert rows["per_rank_rows"] is not None
        assert rows["per_rank_rows"][0] > rows["per_rank_rows"][1]

    def test_live_rebalance_shrinks_straggler_extent(self):
        """An initially-equal world (equal pinned capabilities) must
        detect the slowed rank from the fleet rollups and re-plan its
        extents mid-fit — the decision trail in summary.balance."""
        outs = self._launch_balance_world("rebalance")
        blocks = [self._tagged_json(outs[r], "BALANCE", r)
                  for r in range(2)]
        assert blocks[0] == blocks[1]  # identical decisions on every rank
        block = blocks[0]
        replans = block["replans"]
        assert replans, f"no replan recorded: {json.dumps(block)[:500]}"
        first = replans[0]
        assert first["slowest_rank"] == 1
        assert first["skew_ratio"] > 1.3
        # the re-planned extent moved rows OFF the straggler
        assert first["new_extents"][1][1] < first["old_extents"][1][1]
        final = block["extents"]
        assert final[1][1] < final[0][1]
        assert sum(r for _, r in final) == 6000
        # parity against the equal-shard oracle survives the re-plans
        eq = self._launch_balance_world("equal")
        c_eq = np.asarray(
            self._tagged_json(eq[0], "RESULT", 0)["centers"])
        c_rb = np.asarray(
            self._tagged_json(outs[0], "RESULT", 0)["centers"])
        assert np.max(np.abs(c_eq - c_rb)) <= 1e-5
