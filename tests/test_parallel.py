"""Parallel-layer tests: mesh/sharding helpers, collective facade, the
distributed ratings shuffle, and bootstrap discovery — all on the 8-device
CPU pseudo-cluster (a stronger analog of the reference's 2-executor
pseudo-YARN cluster, survey §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from oap_mllib_tpu.parallel import (
    allgather_rows,
    allreduce_sum,
    alltoall_rows,
    broadcast,
    get_mesh,
    pad_rows,
    shard_rows,
)
from oap_mllib_tpu.parallel.mesh import data_sharding


class TestMesh:
    def test_mesh_shape(self):
        mesh = get_mesh()
        assert mesh.shape["data"] == 8
        assert mesh.shape["model"] == 1

    def test_model_parallel_split(self):
        mesh = get_mesh(model_parallel=2)
        assert mesh.shape["data"] == 4
        assert mesh.shape["model"] == 2

    def test_indivisible_model_parallel_raises(self):
        with pytest.raises(ValueError):
            get_mesh(model_parallel=3)

    def test_pad_rows(self):
        x = np.ones((5, 2))
        padded, n = pad_rows(x, 4)
        assert padded.shape == (8, 2) and n == 5
        assert padded[5:].sum() == 0

    def test_shard_rows_placement(self, rng):
        mesh = get_mesh()
        x = rng.normal(size=(16, 4)).astype(np.float32)
        arr = shard_rows(x, mesh)
        assert arr.shape == (16, 4)
        assert len(arr.sharding.device_set) == 8
        np.testing.assert_array_equal(np.asarray(arr), x)


class TestCollectives:
    def test_broadcast_root_shard(self, rng):
        mesh = get_mesh()
        x = rng.normal(size=(16, 3)).astype(np.float32)
        arr = shard_rows(x, mesh)
        out = np.asarray(broadcast(arr, mesh, root=2))
        # every rank's shard should equal root 2's shard, tiled
        expected = np.tile(x[4:6], (8, 1))
        np.testing.assert_allclose(out, expected)

    def test_allgather_rows(self, rng):
        mesh = get_mesh()
        x = rng.normal(size=(16, 3)).astype(np.float32)
        out = np.asarray(allgather_rows(shard_rows(x, mesh), mesh))
        np.testing.assert_allclose(out, x)

    def test_allreduce_sum(self, rng):
        mesh = get_mesh()
        x = rng.normal(size=(8, 4)).astype(np.float32)  # one row per rank
        out = np.asarray(allreduce_sum(shard_rows(x, mesh), mesh))
        # per-shard (1, 4) values psum'd -> replicated (1, 4) global sum
        np.testing.assert_allclose(out, x.sum(0, keepdims=True), rtol=1e-6)

    def test_alltoall_rows_transposes_blocks(self):
        mesh = get_mesh()
        world = 8
        # rank s holds rows [s*8, (s+1)*8); block j inside = value s*10+j
        x = np.zeros((world * world, 1), np.float32)
        for s in range(world):
            for j in range(world):
                x[s * world + j] = s * 10 + j
        out = np.asarray(alltoall_rows(jax.device_put(
            jnp.asarray(x), data_sharding(mesh, 2)), mesh))
        # after exchange rank j holds s*10+j for all s
        for j in range(world):
            got = sorted(out[j * world:(j + 1) * world, 0].tolist())
            assert got == [s * 10 + j for s in range(world)]


class TestShuffle:
    def test_blocks_land_on_their_rank(self, rng):
        from oap_mllib_tpu.parallel.shuffle import shuffle_to_blocks

        mesh = get_mesh()
        n_users, n_items, n = 64, 32, 500
        users = rng.integers(0, n_users, n)
        items = rng.integers(0, n_items, n)
        ratings = rng.random(n).astype(np.float32)
        sb = shuffle_to_blocks(users, items, ratings, mesh, n_users, n_items)
        assert len(sb.blocks) == 8
        # reassemble: every rating must appear exactly once, in its block
        seen = []
        for b, tbl in enumerate(sb.blocks):
            lo, hi = sb.block_offsets[b], sb.block_offsets[b + 1]
            r = np.asarray(tbl.rows)[: tbl.nnz]
            c = np.asarray(tbl.cols)[: tbl.nnz]
            v = np.asarray(tbl.values)[: tbl.nnz]
            assert tbl.n_rows >= (hi - lo) or hi == lo
            assert np.all(r >= 0) and np.all(r < max(hi - lo, 1))
            for rr, cc, vv in zip(r, c, v):
                seen.append((int(rr) + lo, int(cc), float(np.float32(vv))))
        expected = sorted(
            (int(u), int(i), float(np.float32(v)))
            for u, i, v in zip(users, items, ratings)
        )
        assert sorted(seen) == expected

    def test_csr_offsets_consistent(self, rng):
        from oap_mllib_tpu.parallel.shuffle import shuffle_to_blocks

        mesh = get_mesh()
        users = rng.integers(0, 16, 100)
        items = rng.integers(0, 8, 100)
        ratings = np.ones(100, np.float32)
        sb = shuffle_to_blocks(users, items, ratings, mesh, 16, 8)
        for tbl in sb.blocks:
            ro = np.asarray(tbl.row_offsets)
            assert ro[0] == 0 and ro[-1] == tbl.nnz
            assert np.all(np.diff(ro) >= 0)


class TestBootstrap:
    def test_local_ip_and_port(self):
        from oap_mllib_tpu.parallel import bootstrap

        ip = bootstrap.local_ip()
        assert isinstance(ip, str) and ip.count(".") == 3
        port = bootstrap.free_port(start=41000)
        assert 41000 <= port <= 65535
        coord = bootstrap.default_coordinator(start_port=41000)
        assert ":" in coord

    def test_single_process_noop(self):
        from oap_mllib_tpu.parallel import bootstrap

        assert bootstrap.initialize_distributed() is False

    def test_nonzero_rank_requires_address(self):
        from oap_mllib_tpu.parallel import bootstrap

        with pytest.raises(ValueError):
            bootstrap.initialize_distributed(num_processes=2, process_id=1)
