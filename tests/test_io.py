"""Data IO tests: native and pure-Python parsers agree on all formats."""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, "..", "examples", "data")


def test_readers_native_python_equivalence(monkeypatch):
    """read_* must give identical results with and without the native lib."""
    from oap_mllib_tpu.data import io as io_mod

    l1, x1 = io_mod.read_libsvm(os.path.join(DATA, "sample_kmeans_data.txt"))
    c1 = io_mod.read_csv(os.path.join(DATA, "pca_data.csv"))
    u1, i1, r1 = io_mod.read_ratings(os.path.join(DATA, "sample_als_ratings.txt"))

    # run the pure-python variants via the env escape hatch (read per call)
    monkeypatch.setenv("OAP_MLLIB_TPU_PURE_PYTHON_IO", "1")
    l2, x2 = io_mod.read_libsvm(os.path.join(DATA, "sample_kmeans_data.txt"))
    c2 = io_mod.read_csv(os.path.join(DATA, "pca_data.csv"))
    u2, i2, r2 = io_mod.read_ratings(os.path.join(DATA, "sample_als_ratings.txt"))

    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(l1, l2)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(u1, u2)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(r1, r2)


def test_libsvm_n_features_override():
    from oap_mllib_tpu.data import io as io_mod

    _, x = io_mod.read_libsvm(os.path.join(DATA, "sample_kmeans_data.txt"), n_features=7)
    assert x.shape[1] == 7
