"""Elastic-worlds checkpoint/resume (utils/checkpoint.py, ISSUE 8).

Covers the subsystem bottom-up: the atomic write primitives, the
manifest/shard protocol and its corruption tiers, same-world
bit-identical continuation on every fit path of all three estimators,
cross-world-size resharded restores (block-layout changes through the
collective resharding pass), the ``ckpt.*`` fault sites, and a genuine
kill-and-resume subprocess leg (a fit hard-killed mid-pass by its own
source, relaunched, and required to match the uninterrupted run
bit-for-bit).  The 2-process pseudo-cluster leg lives in
tests/test_pseudo_cluster.py; the CI gate is dev/checkpoint_gate.py.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from oap_mllib_tpu.config import get_config, set_config
from oap_mllib_tpu.data import io as data_io
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.als import ALS
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.models.pca import PCA
from oap_mllib_tpu.utils import checkpoint as ckpt_mod
from oap_mllib_tpu.utils import faults
from oap_mllib_tpu.utils.checkpoint import CheckpointError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def blobs(rng):
    proto = rng.normal(size=(4, 10)).astype(np.float32) * 3.0
    return (proto[rng.integers(4, size=1600)]
            + rng.normal(size=(1600, 10)).astype(np.float32) * 0.2)


@pytest.fixture
def noise(rng):
    # structureless data: Lloyd never hits an exact fixpoint, so pass
    # counts equal max_iter — the iterate-count assertions stay exact
    return rng.normal(size=(1600, 10)).astype(np.float32)


@pytest.fixture
def ratings(rng):
    nu, ni = 50, 30
    u = rng.integers(nu, size=900).astype(np.int64)
    i = rng.integers(ni, size=900).astype(np.int64)
    v = (rng.random(900).astype(np.float32) * 4 + 1)
    u[0], i[0] = nu - 1, ni - 1
    return u, i, v


class TestAtomicIO:
    def test_json_roundtrip_and_no_tmp_left(self, tmp_path):
        p = str(tmp_path / "m.json")
        n = data_io.atomic_write_json(p, {"a": 1, "b": [2, 3]})
        assert n > 0
        assert data_io.read_json(p) == {"a": 1, "b": [2, 3]}
        assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []

    def test_npz_roundtrip(self, tmp_path):
        p = str(tmp_path / "s.npz")
        arrays = {"x": np.arange(6).reshape(2, 3).astype(np.float32)}
        assert data_io.atomic_save_npz(p, arrays) == os.path.getsize(p)
        out = data_io.load_npz(p)
        np.testing.assert_array_equal(out["x"], arrays["x"])

    def test_replace_is_atomic_generation_flip(self, tmp_path):
        p = str(tmp_path / "m.json")
        data_io.atomic_write_json(p, {"gen": 1})
        data_io.atomic_write_json(p, {"gen": 2})
        assert data_io.read_json(p) == {"gen": 2}


class TestCheckpointerCore:
    def test_off_by_default_zero_objects(self):
        assert ckpt_mod.maybe_open("kmeans", {"k": 2}) is None

    def test_resume_typo_raises(self, tmp_path):
        set_config(checkpoint_dir=str(tmp_path), resume="sometimes")
        with pytest.raises(ValueError, match="resume must be"):
            ckpt_mod.maybe_open("kmeans", {"k": 2})

    def test_interval_gates_writes(self, tmp_path, noise):
        set_config(checkpoint_dir=str(tmp_path), checkpoint_interval=2)
        m = KMeans(k=3, seed=1, max_iter=5, tol=0.0).fit(
            ChunkSource.from_array(noise, chunk_rows=512)
        )
        # passes 2 and 4 land; pass 5 is not a boundary and not converged
        assert m.summary.checkpoint["writes"] == 2
        assert m.summary.checkpoint["last_step"] == 4

    def test_signature_mismatch_is_fresh(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path))
        src = ChunkSource.from_array(blobs, chunk_rows=512)
        KMeans(k=3, seed=1, max_iter=3).fit(src)
        m = KMeans(k=4, seed=1, max_iter=3).fit(src)  # different k
        assert m.summary.checkpoint["decision"] == "fresh"

    def test_manifest_names_world_and_signature(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path))
        m = KMeans(k=3, seed=1, max_iter=3).fit(
            ChunkSource.from_array(blobs, chunk_rows=512)
        )
        mdir = m.summary.checkpoint["dir"]
        man = data_io.read_json(os.path.join(mdir, "manifest.json"))
        assert man["world"] == 1 and man["algo"] == "kmeans"
        assert man["signature"]["k"] == 3
        assert man["step"] == m.summary.num_iter

    def test_gc_keeps_two_generations(self, tmp_path, noise):
        set_config(checkpoint_dir=str(tmp_path))
        m = KMeans(k=3, seed=1, max_iter=6, tol=0.0).fit(
            ChunkSource.from_array(noise, chunk_rows=512)
        )
        mdir = m.summary.checkpoint["dir"]
        shards = [f for f in os.listdir(mdir) if f.endswith(".npz")]
        assert len(shards) == 2  # newest two of six writes


class TestSameWorldContinuation:
    """Kill-free continuation units: a fit stopped at step N (via
    max_iter) and re-run to completion must equal the uninterrupted
    checkpoint-armed run bit-for-bit, on every wired path."""

    def _continue_equals_full(self, tmp_a, tmp_b, fit_fn, get_state):
        set_config(checkpoint_dir=str(tmp_a))
        full = fit_fn(max_iter=6)
        set_config(checkpoint_dir=str(tmp_b))
        fit_fn(max_iter=3)
        resumed = fit_fn(max_iter=6)
        ck = (resumed.summary.checkpoint
              if not isinstance(resumed.summary, dict)
              else resumed.summary["checkpoint"])
        assert ck["decision"] == "found"
        assert ck["restored_step"] == 3
        for a, b in zip(get_state(full), get_state(resumed)):
            np.testing.assert_array_equal(a, b)
        return full, resumed

    def test_streamed_kmeans(self, tmp_path, noise):
        def fit(max_iter):
            return KMeans(k=3, seed=2, max_iter=max_iter, tol=0.0).fit(
                ChunkSource.from_array(noise, chunk_rows=512)
            )

        full, resumed = self._continue_equals_full(
            tmp_path / "a", tmp_path / "b", fit,
            lambda m: [m.cluster_centers_],
        )
        assert full.summary.training_cost == resumed.summary.training_cost

    def test_in_memory_kmeans_segmented(self, tmp_path, noise):
        def fit(max_iter):
            return KMeans(k=3, seed=2, max_iter=max_iter, tol=0.0).fit(noise)

        self._continue_equals_full(
            tmp_path / "a", tmp_path / "b", fit,
            lambda m: [m.cluster_centers_],
        )

    def test_in_memory_kmeans_checkpointed_matches_unarmed(self, blobs,
                                                          tmp_path):
        """Segmentation must not change the iterate sequence: a
        checkpoint-armed in-memory fit equals the checkpoint-off fit
        (tol=0 keeps convergence off segment boundaries)."""
        base = KMeans(k=3, seed=2, max_iter=5, tol=0.0).fit(blobs)
        set_config(checkpoint_dir=str(tmp_path))
        armed = KMeans(k=3, seed=2, max_iter=5, tol=0.0).fit(blobs)
        np.testing.assert_array_equal(
            base.cluster_centers_, armed.cluster_centers_
        )

    def test_streamed_pca_resumes_past_colsum(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path))
        src = ChunkSource.from_array(blobs, chunk_rows=512)
        full = PCA(k=3).fit(src)
        resumed = PCA(k=3).fit(src)
        assert resumed.summary["checkpoint"]["decision"] == "found"
        np.testing.assert_array_equal(full.components_, resumed.components_)

    def test_in_memory_pca_resumes_past_covariance(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path))
        full = PCA(k=3).fit(blobs)
        resumed = PCA(k=3).fit(blobs)
        assert resumed.summary["checkpoint"]["decision"] == "found"
        np.testing.assert_array_equal(full.components_, resumed.components_)

    @pytest.mark.parametrize("implicit", [True, False])
    def test_block_als(self, tmp_path, ratings, implicit):
        u, i, v = ratings

        def fit(max_iter):
            return ALS(rank=3, max_iter=max_iter, reg_param=0.1, alpha=0.8,
                       implicit_prefs=implicit, seed=3).fit(u, i, v)

        self._continue_equals_full(
            tmp_path / "a", tmp_path / "b", fit,
            lambda m: [m.user_factors_, m.item_factors_],
        )

    def test_single_device_als(self, tmp_path, ratings):
        u, i, v = ratings

        def fit(max_iter):
            return ALS(rank=3, max_iter=max_iter, reg_param=0.1, seed=3,
                       num_user_blocks=1).fit(u, i, v)

        self._continue_equals_full(
            tmp_path / "a", tmp_path / "b", fit,
            lambda m: [m.user_factors_, m.item_factors_],
        )

    def test_streamed_block_als_sharded_items(self, tmp_path, ratings):
        u, i, v = ratings
        set_config(als_kernel="grouped", als_item_layout="sharded")
        trip = np.stack([u.astype(np.float64), i.astype(np.float64),
                         v.astype(np.float64)], axis=1)

        def fit(max_iter):
            return ALS(rank=3, max_iter=max_iter, reg_param=0.1, alpha=0.8,
                       implicit_prefs=True, seed=3).fit(
                ChunkSource.from_array(trip, chunk_rows=256)
            )

        self._continue_equals_full(
            tmp_path / "a", tmp_path / "b", fit,
            lambda m: [m.user_factors_, m.item_factors_],
        )


class TestReshardedRestore:
    """Cross-world restores: the collective resharding pass must land the
    resumed fit within fp tolerance of the uninterrupted oracle."""

    def test_block_layout_shrink_and_grow(self, tmp_path, ratings):
        u, i, v = ratings
        base = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3).fit(u, i, v)
        # 8 blocks -> 2 blocks
        set_config(checkpoint_dir=str(tmp_path / "s"))
        ALS(rank=3, max_iter=2, reg_param=0.1, seed=3).fit(u, i, v)
        m2 = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3,
                 num_user_blocks=2).fit(u, i, v)
        assert m2.summary["checkpoint"]["decision"] == "resharded"
        np.testing.assert_allclose(
            m2.user_factors_, base.user_factors_, atol=1e-5, rtol=1e-5
        )
        # 2 blocks -> 8 blocks
        set_config(checkpoint_dir=str(tmp_path / "g"))
        ALS(rank=3, max_iter=2, reg_param=0.1, seed=3,
            num_user_blocks=2).fit(u, i, v)
        m8 = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3).fit(u, i, v)
        assert m8.summary["checkpoint"]["decision"] == "resharded"
        np.testing.assert_allclose(
            m8.user_factors_, base.user_factors_, atol=1e-5, rtol=1e-5
        )

    def test_single_device_to_blocks_and_back(self, tmp_path, ratings):
        u, i, v = ratings
        base = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3).fit(u, i, v)
        set_config(checkpoint_dir=str(tmp_path / "up"))
        ALS(rank=3, max_iter=2, reg_param=0.1, seed=3,
            num_user_blocks=1).fit(u, i, v)
        up = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3).fit(u, i, v)
        assert up.summary["checkpoint"]["decision"] == "resharded"
        np.testing.assert_allclose(
            up.user_factors_, base.user_factors_, atol=1e-5, rtol=1e-5
        )
        set_config(checkpoint_dir=str(tmp_path / "down"))
        ALS(rank=3, max_iter=2, reg_param=0.1, seed=3).fit(u, i, v)
        down = ALS(rank=3, max_iter=4, reg_param=0.1, seed=3,
                   num_user_blocks=1).fit(u, i, v)
        assert down.summary["checkpoint"]["decision"] == "resharded"
        np.testing.assert_allclose(
            down.user_factors_, base.user_factors_, atol=1e-5, rtol=1e-5
        )

    def test_fabricated_two_rank_checkpoint_restores_single_process(
            self, tmp_path, blobs):
        """A manifest recording world=2 (per-rank shards fabricated as a
        2-rank world would write them) must restore in THIS 1-process
        world with decision 'resharded' and exact centroids (replicated
        state)."""
        set_config(checkpoint_dir=str(tmp_path))
        sig = KMeans(k=3, seed=2, max_iter=5)._ckpt_signature(
            blobs.shape[1], get_config()
        )
        centers = np.asarray(blobs[:3], np.float32)
        ck = ckpt_mod.Checkpointer("kmeans", sig)
        ck.world = 2  # fabricate the 2-rank world's write
        for rank in (0, 1):
            ck.rank = rank
            ck._write_shard(2, {"centers": centers}, {})
        ck.rank = 0
        ck._write_manifest(2, ["centers"], {"converged": False}, [], {})
        m = KMeans(k=3, seed=2, max_iter=2).fit(
            ChunkSource.from_array(blobs, chunk_rows=512)
        )
        assert m.summary.checkpoint["decision"] == "resharded"
        assert m.summary.checkpoint["old_world"] == 2
        assert m.summary.checkpoint["new_world"] == 1


class TestElasticWorldEdges:
    """Hardening around the grow path and the commit protocol: the
    empty-shard placeholder's width, the manifest-flip agreement,
    vanished-rank garbage collection, and reshard preconditions."""

    def test_grown_world_shardless_rank_gets_true_width(self, tmp_path):
        """A rank assigned no old shards (the world GREW past old_world)
        must build its empty placeholder with the manifest-recorded
        value width, not a guessed width 1: every restore collective
        derives record widths from vals.shape[1] per-process, and
        rank-divergent widths crash or hang the world."""
        set_config(checkpoint_dir=str(tmp_path))
        ids = np.arange(4, dtype=np.int64)
        vals = np.arange(12, dtype=np.float32).reshape(4, 3)
        ck = ckpt_mod.Checkpointer("als", {"rank": 3})
        ck.world = 2
        for rank in (0, 1):
            ck.rank = rank
            ck._write_shard(5, {}, {"x": (ids + 4 * rank, vals + rank)})
        ck.rank = 0
        ck._write_manifest(5, [], {}, {"x": (ids, vals)}, {})
        man = data_io.read_json(os.path.join(ck.dir, "manifest.json"))
        assert man["widths"] == {"x": 3}

        grown = ckpt_mod.Checkpointer("als", {"rank": 3})
        grown.world, grown.rank = 3, 2  # no old rank maps to rank 2
        res = grown._load()
        gids, gvals = res.sharded["x"]
        assert gids.shape == (0,)
        assert gvals.shape == (0, 3) and gvals.dtype == np.float32
        assert res.decision == "resharded" and res.old_world == 2
        # a data-bearing rank of the same grown world agrees on width
        bearing = ckpt_mod.Checkpointer("als", {"rank": 3})
        bearing.world, bearing.rank = 3, 0
        _, bvals = bearing._load().sharded["x"]
        assert bvals.shape[1] == gvals.shape[1]

    def test_manifest_flip_failure_is_rank_uniform(self, tmp_path,
                                                   monkeypatch):
        """A peer rank must not count a write as durable when rank 0's
        manifest flip failed — the second agreement carries the flip
        outcome to every rank before writes/last_step advance."""
        set_config(checkpoint_dir=str(tmp_path))
        ck = ckpt_mod.Checkpointer("kmeans", {"k": 2})
        ck.world, ck.rank = 2, 1
        outcomes = []
        monkeypatch.setattr(
            ck, "_sync_ok",
            lambda ok: outcomes.append(ok) or len(outcomes) == 1,
        )
        ok = ck.maybe_write(
            1, {"c": np.zeros((2, 2), np.float32)}, force=True
        )
        assert ok is False
        assert outcomes == [True, True]  # shard landed; flip agreement ran
        assert ck.writes == 0 and ck.last_step == -1

    def test_rank0_flip_failure_counts_failed_write(self, tmp_path,
                                                    monkeypatch):
        from oap_mllib_tpu.telemetry import metrics as tm

        set_config(checkpoint_dir=str(tmp_path))
        ck = ckpt_mod.Checkpointer("kmeans", {"k": 2})

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(ck, "_write_manifest", boom)
        before = tm.snapshot().get(
            "oap_checkpoint_write_failures_total", {}
        ).get("algo=kmeans", 0.0)
        ok = ck.maybe_write(
            1, {"c": np.zeros((2, 2), np.float32)}, force=True
        )
        assert ok is False and ck.writes == 0
        after = tm.snapshot()[
            "oap_checkpoint_write_failures_total"]["algo=kmeans"]
        assert after - before == 1

    def test_gc_reaps_vanished_ranks_stale_shards(self, tmp_path):
        """After a restore onto a smaller world, the vanished ranks'
        shards must not accumulate forever: rank 0 reaps ranks >= the
        current world once their generation ages out of the kept set."""
        set_config(checkpoint_dir=str(tmp_path))
        ck = ckpt_mod.Checkpointer("kmeans", {"k": 2})
        z = {"c": np.zeros((2, 2), np.float32)}
        ck.rank = 1  # the old 2-rank world's history
        for step in (1, 2, 4):
            ck._write_shard(step, z, {})
        ck.rank = 0
        for step in (1, 2, 3, 4):
            ck._write_shard(step, z, {})
        ck.world = 1
        ck._gc()
        assert sorted(os.listdir(ck.dir)) == [
            "step00000003.rank0.npz",
            "step00000004.rank0.npz",
            "step00000004.rank1.npz",  # kept generation: not stale yet
        ]

    def test_reshard_rejects_indivisible_world(self, monkeypatch):
        """A data axis not divisible by the process count would silently
        misassign rows through the bucket round-robin and the counts
        reshape — reshard_factor_rows must refuse it at entry."""
        import jax

        from oap_mllib_tpu.parallel.mesh import get_mesh
        from oap_mllib_tpu.parallel.shuffle import reshard_factor_rows

        mesh = get_mesh()  # 8-way data axis on the suite mesh
        monkeypatch.setattr(jax, "process_count", lambda: 3)
        with pytest.raises(ValueError, match="multiple of process_count"):
            reshard_factor_rows(
                np.arange(4, dtype=np.int64),
                np.zeros((4, 3), np.float32),
                mesh, np.array([0, 4, 8]), 4,
            )


class TestCorruptionTiers:
    def _arm(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path))
        src = ChunkSource.from_array(blobs, chunk_rows=512)
        m = KMeans(k=3, seed=1, max_iter=3).fit(src)
        return src, m.summary.checkpoint["dir"]

    def test_corrupt_manifest_auto_is_fresh(self, tmp_path, blobs):
        src, mdir = self._arm(tmp_path, blobs)
        with open(os.path.join(mdir, "manifest.json"), "w") as f:
            f.write("{torn")
        m = KMeans(k=3, seed=1, max_iter=3).fit(src)
        assert m.summary.checkpoint["decision"] == "fresh"
        assert "corrupt" in m.summary.checkpoint["reason"]

    def test_corrupt_manifest_require_raises(self, tmp_path, blobs):
        src, mdir = self._arm(tmp_path, blobs)
        with open(os.path.join(mdir, "manifest.json"), "w") as f:
            f.write("{torn")
        set_config(resume="require")
        with pytest.raises(CheckpointError, match="require"):
            KMeans(k=3, seed=1, max_iter=3).fit(src)

    def test_stale_shard_step_is_corrupt(self, tmp_path, blobs):
        """Manifest pointing at a step whose shard carries another step
        (the torn multi-rank write the barrier defends against) must be
        treated as corrupt, not silently restored."""
        src, mdir = self._arm(tmp_path, blobs)
        man = data_io.read_json(os.path.join(mdir, "manifest.json"))
        shard = [f for f in os.listdir(mdir) if f.endswith(".npz")][-1]
        man["step"] = 99
        os.rename(
            os.path.join(mdir, shard),
            os.path.join(mdir, f"step{99:08d}.rank0.npz"),
        )
        data_io.atomic_write_json(os.path.join(mdir, "manifest.json"), man)
        m = KMeans(k=3, seed=1, max_iter=3).fit(src)
        assert m.summary.checkpoint["decision"] == "fresh"

    def test_require_without_any_checkpoint_raises(self, tmp_path, blobs):
        set_config(checkpoint_dir=str(tmp_path), resume="require")
        with pytest.raises(CheckpointError, match="no usable checkpoint"):
            KMeans(k=3, seed=1, max_iter=3).fit(
                ChunkSource.from_array(blobs, chunk_rows=512)
            )

    def test_resume_off_writes_but_never_reads(self, tmp_path, noise):
        set_config(checkpoint_dir=str(tmp_path), resume="off")
        src = ChunkSource.from_array(noise, chunk_rows=512)
        KMeans(k=3, seed=1, max_iter=3).fit(src)
        m = KMeans(k=3, seed=1, max_iter=3).fit(src)
        assert m.summary.checkpoint["decision"] == "fresh"
        assert m.summary.checkpoint["reason"] == "resume=off"
        assert m.summary.checkpoint["writes"] == 3


class TestFaultSites:
    def test_write_fault_warns_and_fit_survives(self, tmp_path, noise):
        from oap_mllib_tpu.telemetry import metrics as tm

        set_config(
            checkpoint_dir=str(tmp_path), fault_spec="ckpt.write:fail=2"
        )
        faults.reset()
        before = tm.snapshot().get(
            "oap_checkpoint_write_failures_total", {}
        ).get("algo=kmeans", 0.0)
        m = KMeans(k=3, seed=1, max_iter=4, tol=0.0).fit(
            ChunkSource.from_array(noise, chunk_rows=512)
        )
        assert m.summary.accelerated
        assert m.summary.checkpoint["writes"] == 2  # 2 of 4 failed
        after = tm.snapshot()[
            "oap_checkpoint_write_failures_total"]["algo=kmeans"]
        assert after - before == 2

    def test_restore_fault_auto_fresh_require_raises(self, tmp_path, blobs):
        src = ChunkSource.from_array(blobs, chunk_rows=512)
        set_config(checkpoint_dir=str(tmp_path))
        KMeans(k=3, seed=1, max_iter=3).fit(src)
        set_config(fault_spec="ckpt.restore:err=1")
        faults.reset()
        m = KMeans(k=3, seed=1, max_iter=3).fit(src)
        assert m.summary.checkpoint["decision"] == "fresh"
        set_config(resume="require")
        faults.reset()
        with pytest.raises(CheckpointError):
            KMeans(k=3, seed=1, max_iter=3).fit(src)

    def test_ckpt_sites_registered(self):
        assert "ckpt.write" in faults.SITES
        assert "ckpt.restore" in faults.SITES
        parsed = faults.parse_spec("ckpt.write:fail=1,ckpt.restore:err=*")
        assert parsed["ckpt.write"].kind == faults.KIND_FAIL
        assert parsed["ckpt.restore"].limit == -1


class TestHardenedModelPersistence:
    def test_kmeans_save_atomic_and_validated(self, tmp_path, blobs):
        from oap_mllib_tpu.models.kmeans import KMeansModel

        m = KMeans(k=3, seed=1, max_iter=2).fit(blobs)
        p = str(tmp_path / "km")
        m.save(p)
        assert [f for f in os.listdir(p) if f.endswith(".tmp")] == []
        meta = data_io.read_json(os.path.join(p, "metadata.json"))
        assert meta["shape"] == [3, blobs.shape[1]]
        # torn directory: centers from a different save
        np.save(os.path.join(p, "centers.npy"), np.zeros((7, 2), np.float32))
        with pytest.raises(ValueError) as e:
            KMeansModel.load(p)
        assert "centers.npy" in str(e.value) and "(3," in str(e.value)

    def test_pca_save_validated(self, tmp_path, blobs):
        from oap_mllib_tpu.models.pca import PCAModel

        m = PCA(k=3).fit(blobs)
        p = str(tmp_path / "pc")
        m.save(p)
        np.save(os.path.join(p, "components.npy"),
                np.zeros((blobs.shape[1], 9), np.float32))
        with pytest.raises(ValueError, match="components.npy"):
            PCAModel.load(p)

    def test_als_save_validated(self, tmp_path, ratings):
        from oap_mllib_tpu.models.als import ALSModel

        u, i, v = ratings
        m = ALS(rank=3, max_iter=2, seed=3).fit(u, i, v)
        p = str(tmp_path / "als")
        m.save(p)
        np.save(os.path.join(p, "user_factors.npy"),
                np.zeros((5, 9), np.float32))
        with pytest.raises(ValueError, match="user_factors.npy"):
            ALSModel.load(p)


class TestLadderVisibility:
    def test_single_process_fit_reports_active_ladder(self, blobs):
        m = KMeans(k=3, seed=1, max_iter=2).fit(blobs)
        assert m.summary.resilience["ladder"] == "active"


_KILL_SCRIPT = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans

mode, ckdir = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(11)
proto = rng.normal(size=(4, 8)).astype(np.float32) * 3.0
x = (proto[rng.integers(4, size=1500)]
     + rng.normal(size=(1500, 8)).astype(np.float32) * 0.2)

passes = {"n": 0}

def gen():
    passes["n"] += 1
    # source walk 1 = the random-init reservoir pass; Lloyd passes are
    # walks 2+.  The victim dies mid-read of Lloyd pass 3 (walk 4),
    # with passes 1 and 2 checkpointed durably.
    if mode == "victim" and passes["n"] == 4:
        os._exit(9)  # hard kill: no cleanup, no atexit — a preemption
    for lo in range(0, x.shape[0], 500):
        yield x[lo:lo + 500]

src = ChunkSource(gen, x.shape[1], 500, n_rows=x.shape[0])
set_config(checkpoint_dir=ckdir)
m = KMeans(k=4, seed=7, init_mode="random", max_iter=8, tol=0.0).fit(src)
ck = m.summary.checkpoint
print("RESULT", repr((float(m.summary.training_cost),
                      m.cluster_centers_.tobytes().hex(),
                      ck["decision"], ck["restored_step"])))
"""


class TestKillAndResume:
    def test_hard_killed_fit_resumes_bit_identical(self, tmp_path):
        """The acceptance leg, single-process form: a fit hard-killed
        (os._exit inside its own source, no cleanup) at pass 3 is
        relaunched with the same config and must produce the
        uninterrupted run's model bit-for-bit."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

        def run(mode, ckdir):
            return subprocess.run(
                [sys.executable, "-c", _KILL_SCRIPT, mode, ckdir],
                capture_output=True, text=True, env=env, cwd=_REPO,
                timeout=240,
            )

        full = run("full", str(tmp_path / "full"))
        assert full.returncode == 0, full.stdout + full.stderr
        victim = run("victim", str(tmp_path / "kill"))
        assert victim.returncode == 9  # genuinely killed mid-pass
        resumed = run("resume", str(tmp_path / "kill"))
        assert resumed.returncode == 0, resumed.stdout + resumed.stderr

        def parse(out):
            line = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
            return eval(line[-1][len("RESULT "):])  # noqa: S307 — own output

        cost_f, centers_f, dec_f, _ = parse(full.stdout)
        cost_r, centers_r, dec_r, step_r = parse(resumed.stdout)
        assert dec_f == "fresh" and dec_r == "found"
        assert step_r == 2  # killed mid-pass-3 -> pass 2 is durable
        assert centers_r == centers_f  # bit-identical continuation
        assert cost_r == cost_f
