"""Fleet observability pseudo-cluster worker (ISSUE 11).

One rank of a real ``jax.distributed`` world driving the fleet control
plane (telemetry/fleet.py + telemetry/flightrec.py).  Modes (env
``FLEET_WORKER_MODE``):

- ``skew`` — rank 1's chunk source sleeps per chunk (a deliberately
  slowed rank).  Every rank runs a streamed K-Means fit with per-pass
  fleet rollups armed (auto + 2-process world) and prints its fleet
  WINDOW (the gathered per-pass frames) and FLEETBLOCK (the summary's
  fleet block); rank 0 additionally scrapes its OWN live /metrics
  endpoint from a background thread WHILE the fit runs and prints
  SCRAPE_OK once ``oap_fleet_*`` families appear mid-fit.  The parent
  asserts the windows agree across ranks, the hand-fold matches, and
  the block names rank 1 with skew > 1.5.
- ``kill`` — flight recorder + collective deadline + crash sideband
  armed; rank 1 SIGKILLs itself mid-read of Lloyd pass 2.  Rank 0 must
  raise CollectiveTimeoutError within the deadline, leaving a v2 crash
  record whose ``flight_recorder`` tail carries >= 32 events.

Invoked as:  python pseudo_cluster_worker_fleet.py RANK NPROC COORD LOCAL_DEV
"""

import json
import os
import sys
import threading
import time

rank, nproc = int(sys.argv[1]), int(sys.argv[2])
coord, local_dev = sys.argv[3], int(sys.argv[4])
mode = os.environ["FLEET_WORKER_MODE"]

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={local_dev}"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", local_dev)

import numpy as np

from oap_mllib_tpu.parallel import bootstrap

ran = bootstrap.initialize_distributed(coord, nproc, rank)
assert ran, "initialize_distributed returned False"

from oap_mllib_tpu.config import set_config
from oap_mllib_tpu.data.stream import ChunkSource
from oap_mllib_tpu.models.kmeans import KMeans
from oap_mllib_tpu.telemetry import fleet
from oap_mllib_tpu.utils import recovery

rng = np.random.default_rng(99)
rows, chunk = 3000, 300
x = rng.normal(size=(rows * nproc, 8)).astype(np.float32)
shard = x[rank * rows: (rank + 1) * rows]

walks = {"n": 0}


def gen():
    walks["n"] += 1
    for lo in range(0, rows, chunk):
        if mode == "skew" and rank == 1:
            time.sleep(0.03)  # the deliberately slowed rank
        if (mode == "kill" and rank == 1 and walks["n"] == 3
                and lo >= chunk * 4):
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        yield shard[lo: lo + chunk]


src = ChunkSource(gen, 8, chunk, n_rows=rows)

if mode == "kill":
    crash_dir = os.environ["FLEET_CRASH_DIR"]
    set_config(
        flight_recorder=256, collective_timeout=10.0, crash_dir=crash_dir,
    )
    try:
        KMeans(k=4, seed=7, init_mode="random", max_iter=6, tol=0.0).fit(src)
    except recovery.CollectiveTimeoutError as e:
        print(f"TIMEOUT_CAUGHT rank={rank} op={e.op}", flush=True)
        os._exit(0)  # crash record written; peer is gone
    except recovery.PeerAbortError:
        print(f"PEER_ABORT rank={rank}", flush=True)
        os._exit(0)
    except Exception as e:  # noqa: BLE001 — surface env markers
        print(f"WORKER_ERROR rank={rank} {type(e).__name__}: {e}",
              flush=True)
        os._exit(4)
    print(f"RESULT_UNEXPECTED rank={rank}", flush=True)
    os._exit(5)

# -- skew mode ----------------------------------------------------------------
port = int(os.environ.get("FLEET_METRICS_PORT", "0"))
set_config(flight_recorder=256, metrics_port=port)

scrape = {"ok": False}


def _scraper():
    import urllib.request

    url = f"http://127.0.0.1:{port + rank}/metrics"
    for _ in range(600):  # poll while the fit runs
        try:
            text = urllib.request.urlopen(url, timeout=2).read().decode()
            if "oap_fleet_pass_seconds" in text:
                scrape["ok"] = True
                return
        except OSError:
            pass
        time.sleep(0.1)


if rank == 0 and port:
    threading.Thread(target=_scraper, daemon=True).start()

window = {}
_orig_finalize = fleet.finalize_fit


def _capturing_finalize(summary, root):
    # the per-fit window resets at finalization — keep a copy for the
    # parent's cross-rank consistency assertions
    window["passes"] = fleet.last_window()
    _orig_finalize(summary, root)


fleet.finalize_fit = _capturing_finalize

try:
    m = KMeans(k=4, seed=7, init_mode="random", max_iter=4, tol=0.0).fit(src)
except Exception as e:  # noqa: BLE001 — surface env markers
    print(f"WORKER_ERROR rank={rank} {type(e).__name__}: {e}", flush=True)
    os._exit(4)

block = m.summary.fleet
print(f"FLEETBLOCK rank={rank} {json.dumps(block, sort_keys=True)}",
      flush=True)
print(
    "WINDOW rank=%d %s" % (
        rank,
        json.dumps(
            [
                {"phase": w["phase"], "frames": w["frames"],
                 "fields": w["fields"],
                 "slowest_rank": w["slowest_rank"],
                 "skew_ratio": w["skew_ratio"]}
                for w in window.get("passes", [])
            ],
            sort_keys=True,
        ),
    ),
    flush=True,
)
if rank == 0 and port:
    # give the scraper a beat in case the fit finished between polls
    for _ in range(20):
        if scrape["ok"]:
            break
        time.sleep(0.1)
    print(f"SCRAPE {'OK' if scrape['ok'] else 'MISSED'} rank=0", flush=True)
print(f"RESULT rank={rank} ok=1", flush=True)
